"""Suggestion algorithms as standalone services + the client-side proxy.

The reference runs every algorithm as a per-experiment gRPC Deployment
(composer ``composer.go:72``) that the controller dials through
``SyncAssignments`` (``suggestionclient.go:83``: convert CRDs→proto, call
``GetSuggestions``, write mutated algorithm settings back).  TPU-native the
default is in-process (suggest/base.py), but the service form still matters:
a long-lived ENAS controller on its own TPU, one suggester shared by many
orchestrators, or isolation of heavyweight algorithm state.

This module keeps the same three-call contract over plain HTTP/JSON:

- ``POST /api/v1/validate``     {spec}                       ↔ ValidateAlgorithmSettings
- ``POST /api/v1/suggestions``  {spec, trials, settings, count} ↔ GetSuggestions
- ``GET  /healthz``                                          ↔ gRPC health servicer

The server is **stateful per experiment** (hyperopt-store/ENAS-session/PBT-
queue analogs live as the real Suggester instance keyed by experiment name);
the reply carries the mutated ``algorithm_settings`` so stateless algorithms
(Hyperband) round-trip their state through the caller exactly like the
reference's state-in-CR trick (``suggestionclient.go:194-196``).

Client side, ``RemoteSuggester`` registers as algorithm ``"remote"``::

    algorithm:
      name: remote
      settings: {endpoint: "http://host:6789", algorithm: tpe}

so the orchestrator treats a remote service like any other suggester,
including its NotReady/Exhausted flow control (HTTP 409/410).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from katib_tpu.core.types import (
    ComparisonOp,
    EarlyStoppingRule,
    Experiment,
    ExperimentSpec,
    Metric,
    Observation,
    ParameterAssignment,
    Trial,
    TrialAssignmentSet,
    TrialCondition,
    TrialSpec,
)
from katib_tpu.suggest.base import (
    SearchExhausted,
    Suggester,
    SuggesterError,
    SuggestionsNotReady,
    make_suggester,
    register,
)
from katib_tpu.utils import observability as obs
from katib_tpu.utils import tracing

# ---------------------------------------------------------------------------
# wire format (flat dict shapes; spec side reuses sdk.yaml_spec's parser)
# ---------------------------------------------------------------------------


def _param_to_wire(p) -> dict:
    fs: dict[str, Any] = {"distribution": p.feasible.distribution.value}
    if p.feasible.list is not None:
        fs["list"] = list(p.feasible.list)
    if p.feasible.min is not None:
        fs["min"] = p.feasible.min
    if p.feasible.max is not None:
        fs["max"] = p.feasible.max
    if p.feasible.step is not None:
        fs["step"] = p.feasible.step
    return {"name": p.name, "parameterType": p.type.value, "feasibleSpace": fs}


def spec_to_wire(spec: ExperimentSpec) -> dict:
    """Flat mapping accepted by ``experiment_spec_from_dict`` — the analog of
    the controller's CRD→proto conversion (``suggestionclient.go:111-116``)."""
    params = [_param_to_wire(p) for p in spec.parameters]
    objective: dict[str, Any] = {
        "type": spec.objective.type.value,
        "objectiveMetricName": spec.objective.objective_metric_name,
        "additionalMetricNames": list(spec.objective.additional_metric_names),
        "metricStrategies": [
            {"name": s.name, "value": s.value.value}
            for s in spec.objective.metric_strategies
        ],
    }
    if spec.objective.goal is not None:
        objective["goal"] = spec.objective.goal
    wire = {
        "name": spec.name,
        "objective": objective,
        "algorithm": {"name": spec.algorithm.name, "settings": dict(spec.algorithm.settings)},
        "parameters": params,
        "parallelTrialCount": spec.parallel_trial_count,
        "maxTrialCount": spec.max_trial_count,
        "maxFailedTrialCount": spec.max_failed_trial_count,
    }
    if spec.nas_config is not None:
        nc = spec.nas_config
        wire["nasConfig"] = {
            "graphConfig": {
                "numLayers": nc.graph_config.num_layers,
                "inputSizes": list(nc.graph_config.input_sizes),
                "outputSizes": list(nc.graph_config.output_sizes),
            },
            "operations": [
                {
                    "operationType": op.operation_type,
                    "parameters": [
                        _param_to_wire(p) for p in op.parameters
                    ],
                }
                for op in nc.operations
            ],
        }
    return wire


def trial_to_wire(t: Trial) -> dict:
    return {
        "name": t.name,
        "condition": t.condition.value,
        "assignments": [{"name": a.name, "value": a.value} for a in t.spec.assignments],
        "labels": dict(t.spec.labels),
        "start_time": t.start_time,
        "observation": (
            None
            if t.observation is None
            else [
                {"name": m.name, "value": m.value, "min": m.min, "max": m.max, "latest": m.latest}
                for m in t.observation.metrics
            ]
        ),
    }


def trial_from_wire(d: dict) -> Trial:
    obs = None
    if d.get("observation") is not None:
        obs = Observation(
            metrics=[
                Metric(
                    name=m["name"],
                    value=m["value"],
                    min=m.get("min", float("nan")),
                    max=m.get("max", float("nan")),
                    latest=m.get("latest", float("nan")),
                )
                for m in d["observation"]
            ]
        )
    return Trial(
        name=d["name"],
        spec=TrialSpec(
            assignments=[
                ParameterAssignment(a["name"], a["value"])
                for a in d.get("assignments") or ()
            ],
            labels=dict(d.get("labels") or {}),
        ),
        condition=TrialCondition(d.get("condition", "Created")),
        observation=obs,
        start_time=d.get("start_time", 0.0),
    )


def proposal_to_wire(p: TrialAssignmentSet) -> dict:
    return {
        "name": p.name,
        "assignments": [{"name": a.name, "value": a.value} for a in p.assignments],
        "labels": dict(p.labels),
        "early_stopping_rules": [
            {
                "name": r.name,
                "value": r.value,
                "comparison": r.comparison.value,
                "start_step": r.start_step,
            }
            for r in p.early_stopping_rules
        ],
    }


def proposal_from_wire(d: dict) -> TrialAssignmentSet:
    return TrialAssignmentSet(
        assignments=[
            ParameterAssignment(a["name"], a["value"]) for a in d.get("assignments") or ()
        ],
        name=d.get("name"),
        labels=dict(d.get("labels") or {}),
        early_stopping_rules=[
            EarlyStoppingRule(
                name=r["name"],
                value=r["value"],
                comparison=ComparisonOp(r["comparison"]),
                start_step=r.get("start_step", 0),
            )
            for r in d.get("early_stopping_rules") or ()
        ],
    )


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _Entry:
    def __init__(self, suggester: Suggester, fingerprint: str):
        self.suggester = suggester
        self.fingerprint = fingerprint
        # serializes get_suggestions per experiment: stateful suggesters
        # (TPE store / PBT queue / ENAS controller) are not thread-safe, and
        # ThreadingHTTPServer handles each POST on its own thread
        self.lock = threading.Lock()
        # set (under lock) when the suggester has been torn down; a request
        # that raced the teardown sees it and backs off instead of calling
        # into a closed suggester
        self.closed = False
        # idempotency: a retried POST whose first response was lost must not
        # advance stateful suggesters (grid/sobol/hyperband) a second time —
        # the last request id replays its stored reply instead
        self.last_request_id: str | None = None
        self.last_response: tuple[int, dict] | None = None


class SuggestionService:
    """Holds the per-experiment suggester instances (the stateful analog of
    one algorithm Deployment per experiment).  ``forget()`` /
    ``DELETE /api/v1/experiment/<name>`` is the teardown path (the reference
    deletes the Deployment on experiment completion,
    ``suggestion_controller.go:132-143``)."""

    def __init__(self):
        self._entries: dict[str, _Entry] = {}
        self._lock = threading.Lock()

    def _spec_from_wire(self, payload: dict) -> ExperimentSpec:
        from katib_tpu.sdk.yaml_spec import experiment_spec_from_dict

        return experiment_spec_from_dict(payload["spec"])

    @staticmethod
    def _fingerprint(wire_spec: dict) -> str:
        return json.dumps(wire_spec, sort_keys=True, default=str)

    @staticmethod
    def _reject_nested_remote(spec: ExperimentSpec) -> None:
        # a service serving algorithm "remote" would proxy to yet another
        # service — and its composer mode would let any network caller spawn
        # subprocesses on this host.  The reference equally has no
        # suggestion image that dials a second suggestion service.
        if spec.algorithm.name == "remote":
            raise SuggesterError(
                "algorithm 'remote' cannot be served by a suggestion service; "
                "point the client at the real algorithm instead"
            )

    def validate(self, payload: dict) -> tuple[int, dict]:
        from katib_tpu.suggest.base import validate_spec

        try:
            spec = self._spec_from_wire(payload)
            self._reject_nested_remote(spec)
            # class-level validate: MUST NOT instantiate (construction can
            # spawn composer subprocesses the validate path would then leak)
            validate_spec(spec)
        except (SuggesterError, KeyError, ValueError) as e:
            return 400, {"ok": False, "error": str(e)}
        return 200, {"ok": True}

    @staticmethod
    def _close_entry(entry: "_Entry | None") -> None:
        """Best-effort resource teardown for an evicted/forgotten suggester
        (anything holding processes/sockets exposes ``close``).  Caller must
        hold ``entry.lock``; the ``closed`` flag tells a request thread that
        looked the entry up before the pop/evict not to use it."""
        if entry is None:
            return
        entry.closed = True
        close = getattr(entry.suggester, "close", None)
        if close is None:
            return
        try:
            close(Experiment(spec=entry.suggester.spec))
        except Exception:
            pass

    def forget(self, name: str) -> tuple[int, dict]:
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is not None:
            # serialize teardown behind any in-flight get_suggestions on
            # the same entry (its lock is taken for the whole call)
            with entry.lock:
                self._close_entry(entry)
        return (200, {"ok": True}) if entry else (404, {"error": f"unknown experiment {name!r}"})

    def suggestions(self, payload: dict) -> tuple[int, dict]:
        try:
            spec = self._spec_from_wire(payload)
            count = int(payload.get("count", 1))
        except (KeyError, ValueError) as e:
            return 400, {"error": f"bad request: {e}"}
        fingerprint = self._fingerprint(payload["spec"])
        evicted: "_Entry | None" = None
        try:
            self._reject_nested_remote(spec)
            with self._lock:
                entry = self._entries.get(spec.name)
                # a re-used experiment name with a different spec gets a
                # fresh suggester, not the stale one
                if entry is None or entry.fingerprint != fingerprint:
                    evicted = entry
                    entry = _Entry(make_suggester(spec), fingerprint)
                    self._entries[spec.name] = entry
        except SuggesterError as e:
            return 400, {"error": str(e)}
        if evicted is not None:
            with evicted.lock:  # wait out any in-flight call on the old entry
                self._close_entry(evicted)
        exp = Experiment(spec=spec)
        exp.trials = {
            t["name"]: trial_from_wire(t) for t in payload.get("trials") or ()
        }
        if payload.get("settings"):
            exp.algorithm_settings = {
                str(k): str(v) for k, v in payload["settings"].items()
            }
        request_id = payload.get("request_id")
        with entry.lock:
            if entry.closed:
                # raced a forget()/evict between the registry lookup and
                # here; the registry no longer holds this entry, so a retry
                # builds a fresh suggester (409 → client NotReady → retry)
                return 409, {
                    "error": "suggester was torn down concurrently; retry",
                    "code": "not_ready",
                }
            if (
                request_id is not None
                and request_id == entry.last_request_id
                and entry.last_response is not None
            ):
                # retried delivery of a request already applied: replay the
                # stored reply, do not advance suggester state again
                return entry.last_response
            # server-side latency: the algorithm's own think time, without
            # the client's HTTP round-trip (which the orchestrator measures)
            t_sug = time.perf_counter()
            try:
                with tracing.span(
                    "suggest.service", algorithm=spec.algorithm.name, count=count
                ):
                    proposals = entry.suggester.get_suggestions(exp, count)
            except SuggestionsNotReady as e:
                return 409, {"error": str(e), "code": "not_ready"}
            except SearchExhausted as e:
                return 410, {"error": str(e), "code": "exhausted"}
            except SuggesterError as e:
                return 400, {"error": str(e)}
            finally:
                obs.suggestion_latency.observe(
                    time.perf_counter() - t_sug, algorithm=spec.algorithm.name
                )
            response = (
                200,
                {
                    "suggestions": [proposal_to_wire(p) for p in proposals],
                    "algorithm_settings": dict(exp.algorithm_settings),
                },
            )
            if request_id is not None:
                entry.last_request_id = request_id
                entry.last_response = response
            return response

    # -- lifecycle -----------------------------------------------------------

    def serve(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        token: str | None = None,
        ssl_context=None,
    ) -> "RunningService":
        """``token`` enables shared-token auth: every API request must carry
        ``Authorization: Bearer <token>`` (the TPU-native stand-in for the
        reference's RBAC-gated service account, ``suggestion_controller.go:
        209-224``; ``/healthz`` stays open like a readiness probe).
        ``ssl_context`` (from ``utils.certgen.server_ssl_context``) serves the
        API over TLS, the analog of the reference webhook's rotated serving
        cert (``certgenerator/generator.go:37``)."""
        svc = self

        class Handler(BaseHTTPRequestHandler):
            # bounds a stalled peer (incl. a deferred TLS handshake that
            # never arrives) to this per-connection thread, not the server
            timeout = 60

            def _reply(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                from katib_tpu.utils.http import bearer_authorized

                return bearer_authorized(self.headers, token)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    self._reply(200, {"status": "serving"})
                else:
                    self._reply(404, {"error": "not found"})

            def _write_guards(self) -> bool:
                """CSRF + DNS-rebinding guards, mirroring ui/backend.py."""
                from katib_tpu.utils.http import json_content_type, local_host_allowed

                if self.command == "POST" and not json_content_type(self.headers):
                    self._reply(415, {"error": "Content-Type must be application/json"})
                    return False
                if token is None and not local_host_allowed(self.headers):
                    self._reply(403, {
                        "error": "Host not recognized (DNS-rebinding guard); "
                        "set a bearer token to accept requests on other hosts"
                    })
                    return False
                if not self._authorized():
                    self._reply(401, {"error": "missing or bad bearer token"})
                    return False
                return True

            def do_POST(self):  # noqa: N802
                if not self._write_guards():
                    return
                from katib_tpu.utils.http import read_json_body

                try:
                    payload = read_json_body(self)
                except (ValueError, OSError) as e:
                    self._reply(400, {"error": f"bad payload: {e}"})
                    return
                if self.path == "/api/v1/suggestions":
                    self._reply(*svc.suggestions(payload))
                elif self.path == "/api/v1/validate":
                    self._reply(*svc.validate(payload))
                else:
                    self._reply(404, {"error": "not found"})

            def do_DELETE(self):  # noqa: N802
                if not self._write_guards():
                    return
                prefix = "/api/v1/experiment/"
                if self.path.startswith(prefix):
                    self._reply(*svc.forget(self.path[len(prefix):]))
                else:
                    self._reply(404, {"error": "not found"})

            def log_message(self, *args):
                pass

        server = ThreadingHTTPServer((host, port), Handler)
        if ssl_context is not None:
            from katib_tpu.utils.certgen import wrap_server_socket

            server.socket = wrap_server_socket(ssl_context, server.socket)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return RunningService(server, thread)


class RunningService:
    def __init__(self, server: ThreadingHTTPServer, thread: threading.Thread):
        self._server = server
        self._thread = thread

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def serve_suggestions(
    port: int = 0,
    host: str = "127.0.0.1",
    token: str | None = None,
    ssl_context=None,
) -> RunningService:
    return SuggestionService().serve(
        port=port, host=host, token=token, ssl_context=ssl_context
    )


# ---------------------------------------------------------------------------
# composer: per-experiment suggester process lifecycle
# ---------------------------------------------------------------------------


class LocalSuggesterProcess:
    """Spawn → readiness-gate → tear down a suggester service subprocess;
    the in-process analog of the reference composer building the algorithm
    Deployment + Service and waiting for availability
    (``composer/composer.go:72-296``, ``suggestion_controller.go:229-238``).

    A fresh auth token is generated per process and passed via environment
    (never argv, which is world-readable in /proc).  With ``tls=True`` the
    composer also provisions a private CA + serving cert for the child and
    the client pins that CA — the reference webhook's rotated-cert setup
    (``certgenerator/generator.go:37``) collapsed to one handshake."""

    def __init__(self, readiness_timeout: float = 60.0, tls: bool = True):
        import secrets
        import socket
        import subprocess
        import sys
        import tempfile

        self.token = secrets.token_hex(16)
        # bind-then-release to pick a free port for the child; the tiny race
        # window is acceptable for a localhost helper process
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            self.port = s.getsockname()[1]
        self.ca_cert: str | None = None
        self._ssl = None
        extra_args: list[str] = []
        if tls:
            # TLS needs the optional `cryptography` extra; a base install
            # degrades to the pre-TLS localhost behavior instead of crashing
            # mid-experiment (the token still gates the child either way)
            try:
                from katib_tpu.utils.certgen import client_ssl_context, ensure_certs
                import cryptography  # noqa: F401
            except ImportError:
                import warnings

                warnings.warn(
                    "cryptography not installed; composer suggester will "
                    "serve plain HTTP on 127.0.0.1 (install katib-tpu[tls])",
                    RuntimeWarning,
                    stacklevel=2,
                )
                tls = False
        if tls:
            self._cert_dir = tempfile.mkdtemp(prefix="katib-suggest-certs-")
            bundle = ensure_certs(self._cert_dir)
            self.ca_cert = bundle.ca_cert
            self._ssl = client_ssl_context(bundle.ca_cert)
            extra_args = ["--cert-dir", self._cert_dir]
            # connect by IP: the child binds IPv4 only, and the leaf carries
            # an IP SAN for 127.0.0.1 so verification still holds
            self.endpoint = f"https://127.0.0.1:{self.port}"
        else:
            self.endpoint = f"http://127.0.0.1:{self.port}"
        import os as _os

        env = dict(_os.environ)
        env["KATIB_SUGGEST_TOKEN"] = self.token
        # the suggester service runs algorithm math on CPU; keep the child
        # off the TPU so it never contends for the chip grant
        env["JAX_PLATFORMS"] = "cpu"
        # the child must import katib_tpu regardless of the caller's cwd
        # (callers often sys.path-hack rather than install the package)
        import katib_tpu as _pkg

        pkg_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(_pkg.__file__)))
        env["PYTHONPATH"] = (
            pkg_root + _os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_root
        )
        # keep the child's output: a child that dies before readiness is
        # undiagnosable if its traceback went to /dev/null
        self._log = tempfile.NamedTemporaryFile(
            mode="w+b", prefix="katib-suggest-", suffix=".log", delete=False
        )
        self._proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "katib_tpu",
                "suggest-server",
                "--host",
                "127.0.0.1",
                "--port",
                str(self.port),
                *extra_args,
            ],
            env=env,
            stdout=self._log,
            stderr=subprocess.STDOUT,
        )
        self._wait_healthy(readiness_timeout)

    def _wait_healthy(self, timeout: float) -> None:
        import time as _time

        deadline = _time.monotonic() + timeout
        last: Exception | None = None
        while _time.monotonic() < deadline:
            if self._proc.poll() is not None:
                rc = self._proc.returncode
                tail = self._log_tail()
                self.stop()  # reclaims the cert dir + log like the timeout path
                raise RuntimeError(
                    f"suggester process exited rc={rc} before ready; "
                    f"output tail:\n{tail}"
                )
            try:
                with urllib.request.urlopen(
                    f"{self.endpoint}/healthz", timeout=2, context=self._ssl
                ) as r:
                    if r.status == 200:
                        return
            except OSError as e:
                last = e
            _time.sleep(0.1)
        tail = self._log_tail()
        self.stop()
        raise RuntimeError(
            f"suggester service never became healthy: {last}; output tail:\n{tail}"
        )

    def _log_tail(self, n: int = 2000) -> str:
        import os as _os

        try:
            self._log.flush()
            with open(self._log.name, "rb") as f:
                f.seek(max(0, _os.path.getsize(self._log.name) - n))
                return f.read().decode(errors="replace")
        except OSError:
            return "<unavailable>"

    def stop(self) -> None:
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=10)
            except Exception:
                self._proc.kill()
                self._proc.wait(timeout=10)
        cert_dir = getattr(self, "_cert_dir", None)
        if cert_dir is not None:
            import shutil

            shutil.rmtree(cert_dir, ignore_errors=True)
        log = getattr(self, "_log", None)
        if log is not None:
            import os as _os

            try:
                log.close()
                _os.unlink(log.name)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# client proxy
# ---------------------------------------------------------------------------


@register("remote")
class RemoteSuggester(Suggester):
    """Proxy to a suggestion service — the orchestrator-side analog of
    ``SyncAssignments`` (``suggestionclient.go:83``): ships spec + trial
    history, receives assignments, writes mutated settings back."""

    RETRIES = 3  # the reference's retry middleware does 10 @ 3s linear

    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        if not spec.algorithm.setting("endpoint"):
            raise SuggesterError("remote requires setting 'endpoint'")
        if not spec.algorithm.setting("algorithm"):
            raise SuggesterError("remote requires setting 'algorithm' (the real name)")
        if spec.algorithm.setting("algorithm") == "pbt":
            # PBT's exploit step copies checkpoint directories, which live on
            # the orchestrator host; a remote PbtSuggester would allocate its
            # lineage on the service host and children would silently cold-
            # start (reference PBT equally requires the shared RWX PVC)
            raise SuggesterError(
                "pbt cannot run behind 'remote': checkpoint lineage requires "
                "the suggester and trials to share a filesystem — run pbt "
                "in-process"
            )

    def __init__(self, spec: ExperimentSpec):
        super().__init__(spec)
        endpoint = spec.algorithm.setting("endpoint")
        self._local: LocalSuggesterProcess | None = None
        self._ssl = None
        if endpoint == "auto":
            # composer mode: spawn a private suggester service subprocess
            # (TLS + fresh token), readiness-gated; torn down in close() with
            # the experiment (``composer.go:72-296`` deploy + ``:132-143``)
            self._local = LocalSuggesterProcess()
            endpoint = self._local.endpoint
            self.token: str | None = self._local.token
            self._ssl = self._local._ssl
        else:
            import os as _os

            self.token = spec.algorithm.setting("token") or _os.environ.get(
                "KATIB_SUGGEST_TOKEN"
            )
            # ``ca_cert`` pins a private CA for an https endpoint (the
            # CABundle the reference injects into webhook clientConfig)
            ca = spec.algorithm.setting("ca_cert") or _os.environ.get(
                "KATIB_SUGGEST_CA"
            )
            if ca:
                from katib_tpu.utils.certgen import client_ssl_context

                self._ssl = client_ssl_context(ca)
        self.endpoint = endpoint.rstrip("/")
        self.algorithm = spec.algorithm.setting("algorithm")

    def _wire_spec(self) -> dict:
        wire = spec_to_wire(self.spec)
        settings = {
            k: v
            for k, v in wire["algorithm"]["settings"].items()
            if k not in ("endpoint", "algorithm", "token", "ca_cert")
        }
        wire["algorithm"] = {"name": self.algorithm, "settings": settings}
        return wire

    def _headers(self) -> dict:
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    def _post(self, path: str, payload: dict) -> tuple[int, dict]:
        data = json.dumps(payload).encode()
        req = urllib.request.Request(
            f"{self.endpoint}{path}", data=data, headers=self._headers()
        )
        def safe_json(raw: bytes) -> dict:
            # a proxy's HTML error page must not escape as JSONDecodeError
            try:
                out = json.loads(raw or b"{}")
                return out if isinstance(out, dict) else {"error": str(out)}
            except ValueError:
                return {"error": raw[:200].decode(errors="replace")}

        import http.client

        last: Exception | None = None
        for _ in range(self.RETRIES):
            try:
                with urllib.request.urlopen(req, timeout=30, context=self._ssl) as r:
                    return r.status, safe_json(r.read())
            except urllib.error.HTTPError as e:
                return e.code, safe_json(e.read())
            except (OSError, http.client.HTTPException) as e:
                # half-closed connections raise BadStatusLine (not OSError);
                # both are transient — retry, then surface as NotReady so a
                # glitch never fails the experiment
                last = e
        raise SuggestionsNotReady(f"suggestion service unreachable: {last}")

    def get_suggestions(self, experiment: Experiment, count: int):
        import uuid

        payload = {
            "spec": self._wire_spec(),
            "trials": [trial_to_wire(t) for t in experiment.trials.values()],
            "settings": {
                k: v
                for k, v in experiment.algorithm_settings.items()
                if k not in ("endpoint", "algorithm", "token", "ca_cert")
            },
            "count": count,
            # constant across transport retries: the service replays its
            # stored reply instead of advancing stateful suggesters twice
            "request_id": uuid.uuid4().hex,
        }
        status, reply = self._post("/api/v1/suggestions", payload)
        if status == 409:
            raise SuggestionsNotReady(reply.get("error", "not ready"))
        if status == 410:
            raise SearchExhausted(reply.get("error", "exhausted"))
        if status != 200:
            raise SuggesterError(reply.get("error", f"service error {status}"))
        for k, v in (reply.get("algorithm_settings") or {}).items():
            experiment.algorithm_settings[str(k)] = str(v)
        return [proposal_from_wire(p) for p in reply.get("suggestions") or ()]

    def close(self, experiment: Experiment) -> None:
        """Teardown on experiment completion: evict the server-side suggester
        (the reference deletes the per-experiment Deployment,
        ``suggestion_controller.go:132-143``).  Best-effort — the service may
        already be gone."""
        import http.client

        req = urllib.request.Request(
            f"{self.endpoint}/api/v1/experiment/{self.spec.name}",
            method="DELETE",
            headers=self._headers(),
        )
        try:
            urllib.request.urlopen(req, timeout=10, context=self._ssl).close()
        except (OSError, urllib.error.HTTPError, http.client.HTTPException):
            pass
        if self._local is not None:
            self._local.stop()
