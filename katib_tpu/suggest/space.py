"""Unified numeric encoding of the search space.

Every numeric algorithm (TPE, GP-BO, CMA-ES, Sobol) works over the same
encoded view: continuous/int parameters map to the unit interval (log-warped
when the distribution is logUniform/logNormal), discrete/categorical map to
index space.  This replaces the per-library domain conversions scattered
through the reference (hyperopt ``base_service.py:54``, skopt/optuna
converters, ``hyperband/parsing_util.py``) with one encoder.

All methods are vectorized numpy; nothing here touches JAX — suggesters run
on host CPU while trials own the TPU.
"""

from __future__ import annotations

import math
from typing import Any, Mapping, Sequence

import numpy as np

from katib_tpu.core.types import (
    ParameterAssignment,
    ParameterSpec,
    ParameterType,
)

__all__ = ["SpaceEncoder"]


class SpaceEncoder:
    """Bijection between parameter dicts and points in the unit hypercube.

    One dimension per parameter.  Categorical/discrete dimensions carry the
    value's index scaled to [0, 1]; ``n_choices`` exposes their cardinality so
    algorithms that need special categorical handling (TPE's smoothed counts,
    GP one-hot expansion) can branch on it.
    """

    def __init__(self, params: Sequence[ParameterSpec]):
        if not params:
            raise ValueError("empty search space")
        self.params = list(params)
        self.names = [p.name for p in self.params]

    # -- introspection -----------------------------------------------------

    @property
    def n_dims(self) -> int:
        return len(self.params)

    def is_categorical(self, dim: int) -> bool:
        return self.params[dim].type in (
            ParameterType.CATEGORICAL,
            ParameterType.DISCRETE,
        )

    def n_choices(self, dim: int) -> int:
        p = self.params[dim]
        if not self.is_categorical(dim):
            raise ValueError(f"{p.name} is not categorical")
        return len(p.feasible.list)

    # -- scalar transforms -------------------------------------------------

    def _to_unit(self, dim: int, value: Any) -> float:
        p = self.params[dim]
        f = p.feasible
        if self.is_categorical(dim):
            values = list(f.list)
            try:
                idx = values.index(p.cast(value))
            except ValueError:
                idx = 0
            if len(values) == 1:
                return 0.0
            return idx / (len(values) - 1)
        lo, hi = float(f.min), float(f.max)
        v = float(value)
        if f.is_log_scaled():
            lo, hi, v = math.log(lo), math.log(hi), math.log(max(v, 1e-300))
        if hi <= lo:
            return 0.0
        return min(1.0, max(0.0, (v - lo) / (hi - lo)))

    def _from_unit(self, dim: int, u: float) -> Any:
        p = self.params[dim]
        f = p.feasible
        u = min(1.0, max(0.0, float(u)))
        if self.is_categorical(dim):
            values = list(f.list)
            idx = min(len(values) - 1, int(round(u * (len(values) - 1))))
            return values[idx]
        lo, hi = float(f.min), float(f.max)
        if f.is_log_scaled():
            v = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
        else:
            v = lo + u * (hi - lo)
        if f.step:
            v = lo + round((v - lo) / f.step) * f.step
            v = min(hi, max(lo, v))
        return p.cast(v)

    # -- vector API --------------------------------------------------------

    def encode(self, assignment: Mapping[str, Any]) -> np.ndarray:
        return np.array(
            [self._to_unit(i, assignment[p.name]) for i, p in enumerate(self.params)],
            dtype=np.float64,
        )

    def decode(self, u: np.ndarray) -> dict[str, Any]:
        return {
            p.name: self._from_unit(i, u[i]) for i, p in enumerate(self.params)
        }

    def encode_categorical_index(self, dim: int, value: Any) -> int:
        p = self.params[dim]
        values = list(p.feasible.list)
        try:
            return values.index(p.cast(value))
        except ValueError:
            return 0

    def decode_categorical_index(self, dim: int, idx: int) -> Any:
        values = list(self.params[dim].feasible.list)
        return values[int(idx) % len(values)]

    # -- sampling ----------------------------------------------------------

    def sample(self, rng: np.random.Generator) -> dict[str, Any]:
        """Sample one configuration from the prior (uniform in encoded space,
        i.e. log-uniform in value space for log-scaled params)."""
        out: dict[str, Any] = {}
        for i, p in enumerate(self.params):
            if self.is_categorical(i):
                out[p.name] = self.decode_categorical_index(
                    i, rng.integers(self.n_choices(i))
                )
            else:
                out[p.name] = self._from_unit(i, rng.random())
        return out

    def sample_assignments(self, rng: np.random.Generator) -> list[ParameterAssignment]:
        return self.to_assignments(self.sample(rng))

    def to_assignments(self, d: Mapping[str, Any]) -> list[ParameterAssignment]:
        return [ParameterAssignment(p.name, p.cast(d[p.name])) for p in self.params]

    # -- one-hot view for GP models ---------------------------------------

    def onehot_dims(self) -> int:
        n = 0
        for i in range(self.n_dims):
            n += self.n_choices(i) if self.is_categorical(i) else 1
        return n

    def encode_onehot(self, assignment: Mapping[str, Any]) -> np.ndarray:
        parts: list[np.ndarray] = []
        for i, p in enumerate(self.params):
            if self.is_categorical(i):
                vec = np.zeros(self.n_choices(i))
                vec[self.encode_categorical_index(i, assignment[p.name])] = 1.0
                parts.append(vec)
            else:
                parts.append(np.array([self._to_unit(i, assignment[p.name])]))
        return np.concatenate(parts)
