"""Population-Based Training.

Capability parity with the reference's ``pbt`` service
(``pkg/suggestion/v1beta1/pbt/service.py``): a job queue seeded from the
search space, truncation selection per generation — the bottom quantile
*exploits* (restarts from a top-quantile member's checkpoint + hyperparams),
the rest *explore* (perturb x0.8/x1.2 or resample with
``resample_probability``) — failed/killed members re-queued with identical
parameters, and generation/parent lineage carried in trial labels.

Design changes vs the reference:
- Checkpoint lineage uses the trial runner's per-trial checkpoint directories
  under the experiment workdir (Orbax pytrees for JAX trials) instead of a
  ReadWriteMany PVC mounted into pods; the exploit copy is still a directory
  copy (``pbt/service.py:259-268``) but initiated by the suggester in-process.
- The reference's exploit step copies the *loser's* checkpoint while taking
  the winner's hyperparameters (``service.py:383-389``: ``parent=job.uid`` for
  the below-threshold job).  Standard PBT — and this implementation — clones
  the winner's checkpoint AND hyperparameters, which is the behavior the PBT
  paper specifies and what actually transfers learned weights.
"""

from __future__ import annotations

import os
import shutil
import uuid

import numpy as np

from katib_tpu.core.types import (
    COHORT_KEY_LABEL,
    Experiment,
    ExperimentSpec,
    ParameterAssignment,
    Trial,
    TrialAssignmentSet,
)
from katib_tpu.suggest.base import Suggester, SuggesterError, register
from katib_tpu.suggest.space import SpaceEncoder

GENERATION_LABEL = "pbt-generation"
PARENT_LABEL = "pbt-parent"

#: cohort key stamped on every pbt-ondevice member so the orchestrator
#: groups the whole population into ONE vmapped program
ONDEVICE_COHORT_KEY = "pbt-ondevice"


def resolve_pbt_ondevice(spec: ExperimentSpec) -> bool:
    """Whether ``pbt-ondevice`` actually evolves on device.  Escape-hatch
    precedence: ``KATIB_PBT_ONDEVICE`` env > ``spec.pbt_ondevice``
    (``pbtOnDevice`` YAML knob) > the ``on_device`` algorithm setting >
    default ON."""
    env = os.environ.get("KATIB_PBT_ONDEVICE")
    if env:
        return env.strip().lower() not in ("0", "false", "no", "off")
    if getattr(spec, "pbt_ondevice", None) is not None:
        return bool(spec.pbt_ondevice)
    raw = spec.algorithm.settings.get("on_device")
    if raw is not None:
        return str(raw).strip().lower() not in ("0", "false", "no", "off")
    return True


class _PbtJob:
    def __init__(self, uid: str, params: dict, generation: int, parent: str | None):
        self.uid = uid
        self.params = params
        self.generation = generation
        self.parent = parent
        self.score: float | None = None  # scaled so higher is better


@register("pbt")
class PbtSuggester(Suggester):
    """Stateful population manager (in-memory, like the reference service);
    completed-trial sync is idempotent so repeated calls are safe."""

    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        s = spec.algorithm.settings
        for key in ("n_population", "truncation_threshold"):
            if key not in s:
                raise SuggesterError(f"pbt requires setting {key}")
        if int(s["n_population"]) < 5:
            raise SuggesterError("n_population should be >= 5")
        if not 0.0 <= float(s["truncation_threshold"]) <= 0.5:
            raise SuggesterError("truncation_threshold should be in [0, 0.5]")
        if "resample_probability" in s and not 0.0 <= float(s["resample_probability"]) <= 1.0:
            raise SuggesterError("resample_probability should be in [0, 1]")

    def __init__(self, spec: ExperimentSpec):
        super().__init__(spec)
        s = spec.algorithm.settings
        self.population = int(s["n_population"])
        self.truncation = float(s["truncation_threshold"])
        self.resample_p = (
            float(s["resample_probability"]) if "resample_probability" in s else None
        )
        self.checkpoint_root = s.get(
            "suggestion_trial_dir", os.path.join("katib_runs", spec.name, "pbt")
        )
        self._rng = self.rng()
        self._space = SpaceEncoder(spec.parameters)
        self.pending: list[_PbtJob] = []
        self.running: dict[str, _PbtJob] = {}
        self.completed: dict[str, _PbtJob] = {}
        self.pool_current: list[str] = []
        self.pool_previous: list[str] = []
        self._seed_population(self.population)

    # -- perturbation (reference HyperParameterSampler.perturb) -------------

    def _perturb(self, name: str, value) -> object:
        p = self.spec.parameter(name)
        f = p.feasible
        if p.type.value in ("double", "int"):
            factor = float(self._rng.choice([0.8, 1.2]))
            v = float(value) * factor
            v = min(float(f.max), max(float(f.min), v))
            return p.cast(v)
        # discrete/categorical: step to a neighbor, wrapping at the end
        values = list(f.list)
        idx = values.index(p.cast(value)) + int(self._rng.choice([-1, 1]))
        return values[idx % len(values)]

    # -- queue management ---------------------------------------------------

    def _new_uid(self) -> str:
        return f"{self.spec.name}-{uuid.uuid4().hex[:8]}"

    def _ckpt_dir(self, uid: str) -> str:
        return os.path.join(self.checkpoint_root, uid)

    def _append(self, params: dict, generation: int, parent: str | None) -> _PbtJob:
        job = _PbtJob(self._new_uid(), dict(params), generation, parent)
        self.pending.append(job)
        new_dir = self._ckpt_dir(job.uid)
        if os.path.isdir(new_dir):
            shutil.rmtree(new_dir)
        if parent is None:
            os.makedirs(new_dir, exist_ok=True)
        else:
            parent_dir = self._ckpt_dir(parent)
            if os.path.isdir(parent_dir):
                shutil.copytree(parent_dir, new_dir)
            else:
                os.makedirs(new_dir, exist_ok=True)
        return job

    def _seed_population(self, count: int) -> None:
        for _ in range(count):
            self._append(self._space.sample(self._rng), generation=0, parent=None)

    def _sync(self, experiment: Experiment) -> None:
        """Fold newly-terminal trials into the population state."""
        obj = self.spec.objective
        sign = 1.0 if obj.type.value == "maximize" else -1.0
        for t in experiment.trials.values():
            if t.name not in self.running or not t.condition.is_terminal():
                continue
            job = self.running.pop(t.name)
            self.completed[job.uid] = job
            if t.condition.is_completed_ok():
                v = t.objective_value(obj)
                job.score = sign * v if v is not None else None
                if job.score is not None:
                    self.pool_current.append(job.uid)
            else:
                # retry failed/killed members with identical params+lineage
                # (reference ``pbt/service.py:303-322``)
                self._append(job.params, job.generation, job.parent)

    # -- generation logic ---------------------------------------------------

    def _segment(self, pool: list[str], count: int):
        jobs = [self.completed[uid] for uid in pool if self.completed[uid].score is not None]
        scores = np.array([j.score for j in jobs])
        lo, hi = np.quantile(scores, (self.truncation, 1.0 - self.truncation))
        exploit = [j for j in jobs if j.score < lo]
        explore = [j for j in jobs if j.score >= lo]
        upper = [j for j in jobs if j.score >= hi]
        self._rng.shuffle(exploit)
        self._rng.shuffle(explore)
        # round half-up with a floor of 1 whenever anyone actually fell
        # below the quantile: plain int() floors to 0 for
        # count < 1/truncation, silently turning PBT into random search
        # for small populations / partial refills
        n_exploit = int(count * self.truncation + 0.5)
        if n_exploit == 0 and exploit:
            n_exploit = 1
        exploit = exploit[:n_exploit]
        explore = explore[: count - len(exploit)]
        return exploit, explore, upper

    def _generate(self, min_count: int) -> None:
        # strict '<': the generation turns over as soon as a full population
        # has completed (the reference's '<=', ``pbt/service.py:355``, needs
        # population+1 completions before it rolls over)
        if len(self.pool_current) < self.population:
            if not self.pool_previous:
                self._seed_population(min_count)
                return
            exploit, explore, upper = self._segment(self.pool_previous, min_count)
        else:
            exploit, explore, upper = self._segment(self.pool_current, self.population)
            self.pool_previous = self.pool_current
            self.pool_current = []

        # exploit: clone a top-quantile winner (checkpoint + hyperparameters)
        for job in exploit:
            winner = upper[int(self._rng.integers(len(upper)))] if upper else job
            self._append(winner.params, job.generation + 1, parent=winner.uid)
        # explore: continue own checkpoint with perturbed/resampled params
        for job in explore:
            new_params = {}
            for p in self.spec.parameters:
                if self.resample_p is None:
                    new_params[p.name] = self._perturb(p.name, job.params[p.name])
                elif self._rng.random() < self.resample_p:
                    new_params[p.name] = self._space.sample(self._rng)[p.name]
                else:
                    new_params[p.name] = job.params[p.name]
            self._append(new_params, job.generation + 1, parent=job.uid)

    # -- Suggester API ------------------------------------------------------

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        self._sync(experiment)
        while len(self.pending) < count:
            self._generate(count)
        out = []
        for _ in range(count):
            job = self.pending.pop(0)
            self.running[job.uid] = job
            labels = {GENERATION_LABEL: str(job.generation)}
            if job.parent is not None:
                labels[PARENT_LABEL] = job.parent
            out.append(
                TrialAssignmentSet(
                    name=job.uid,
                    assignments=[
                        ParameterAssignment(k, v) for k, v in job.params.items()
                    ],
                    labels=labels,
                )
            )
        return out

    def checkpoint_dir_for(self, trial_name: str) -> str:
        """The runner mounts this as the trial's checkpoint directory (parity
        with the webhook mounting the PBT PVC, ``inject_webhook.go:334-365``)."""
        return self._ckpt_dir(trial_name)

    # -- persistence hooks (orchestrator journals these across restarts;
    # the reference's PVC held only the checkpoints — its in-memory queue
    # was lost on service restart, an acknowledged gap) -----------------

    @staticmethod
    def _job_dict(job: _PbtJob) -> dict:
        return {
            "uid": job.uid,
            "params": dict(job.params),
            "generation": job.generation,
            "parent": job.parent,
            "score": job.score,
        }

    @staticmethod
    def _job_from(d: dict) -> _PbtJob:
        job = _PbtJob(d["uid"], dict(d["params"]), d["generation"], d["parent"])
        job.score = d["score"]
        return job

    def state_dict(self) -> dict:
        return {
            "rng": self._rng.bit_generator.state,
            "pending": [self._job_dict(j) for j in self.pending],
            "running": {k: self._job_dict(j) for k, j in self.running.items()},
            "completed": {k: self._job_dict(j) for k, j in self.completed.items()},
            "pool_current": list(self.pool_current),
            "pool_previous": list(self.pool_previous),
        }

    def load_state_dict(self, data: dict) -> None:
        # parse everything BEFORE mutating, so a schema mismatch leaves the
        # freshly-seeded suggester intact (the caller falls back to it)
        rng_state = data["rng"]
        pending = [self._job_from(d) for d in data["pending"]]
        running = {k: self._job_from(d) for k, d in data["running"].items()}
        completed = {k: self._job_from(d) for k, d in data["completed"].items()}
        pool_current = list(data["pool_current"])
        pool_previous = list(data["pool_previous"])
        # discard the freshly-seeded boot population (and its just-created
        # empty checkpoint dirs) in favor of the journaled queue
        for job in self.pending:
            shutil.rmtree(self._ckpt_dir(job.uid), ignore_errors=True)
        self._rng.bit_generator.state = rng_state
        self.pending = pending
        self.running = running
        self.completed = completed
        self.pool_current = pool_current
        self.pool_previous = pool_previous


@register("pbt-ondevice")
class PbtOnDeviceSuggester(PbtSuggester):
    """PBT whose generations run ON DEVICE: the whole population dispatches
    once as a single cohort and evolves inside one compiled program
    (``parallel/pbt.py``) — exploit is a ``jnp.take`` permutation over the
    stacked ``[K, ...]`` member axis, explore is an in-kernel perturbation,
    and the host sees only generation-boundary summaries.

    Additional settings over ``pbt``: ``generations`` (evolution rounds per
    dispatch, default 8), ``steps_per_generation`` (train steps between
    selections, default 60), ``on_device`` ("false" falls back to the exact
    host ``PbtSuggester`` exchange — the escape hatch, also reachable via
    ``spec.pbt_ondevice`` / ``KATIB_PBT_ONDEVICE``).

    Requires a cohort-capable train_fn whose cohort twin understands the
    ``pbt_*`` shared assignments (e.g.
    ``katib_tpu.models.pbt_digits.pbt_digits_trial``).  Lineage labels
    (generation, parent) are settled onto the member trials by the cohort
    fn at every generation boundary, and per-generation ``pbt_parent`` /
    ``pbt_exploit`` metric rows land in the ObservationStore, so journal
    and UI see the same history the host exchange would produce.
    """

    @classmethod
    def validate(cls, spec: ExperimentSpec) -> None:
        super().validate(spec)
        s = spec.algorithm.settings
        for key in ("generations", "steps_per_generation"):
            if key in s and int(s[key]) < 1:
                raise SuggesterError(f"{key} must be >= 1")
        if resolve_pbt_ondevice(spec):
            pop = int(s["n_population"])
            if spec.max_trial_count is not None and spec.max_trial_count < pop:
                raise SuggesterError(
                    "pbt-ondevice dispatches the whole population as one "
                    f"cohort: max_trial_count ({spec.max_trial_count}) must "
                    f"be >= n_population ({pop})"
                )

    def __init__(self, spec: ExperimentSpec):
        super().__init__(spec)
        s = spec.algorithm.settings
        self.generations = int(s.get("generations", 8))
        self.steps_per_generation = int(s.get("steps_per_generation", 60))
        self.on_device = resolve_pbt_ondevice(spec)
        self._dispatched = False
        if self.on_device:
            # the population is ONE cohort: widen the orchestrator's
            # grouping window so it never splits the members
            spec.cohort_width = max(spec.cohort_width, self.population)

    def get_suggestions(
        self, experiment: Experiment, count: int
    ) -> list[TrialAssignmentSet]:
        if not self.on_device:
            # escape hatch: exact host checkpoint-exchange semantics
            return super().get_suggestions(experiment, count)
        self._sync(experiment)
        if self._dispatched:
            return []  # one dispatch per experiment -> exhausted
        self._dispatched = True
        from katib_tpu.parallel.pbt import specs_from_parameters, specs_to_json

        space_json = specs_to_json(specs_from_parameters(self.spec.parameters))
        jobs = self.pending[: self.population]
        self.pending = self.pending[self.population :]
        out = []
        for slot, job in enumerate(jobs):
            self.running[job.uid] = job
            assignments = [
                ParameterAssignment(k, v) for k, v in job.params.items()
            ]
            # generation-step config rides as shared assignments: the
            # cohort fn reads them via cctx.shared() so the whole
            # population provably agrees on the compiled program
            assignments += [
                ParameterAssignment("pbt_slot", slot),
                ParameterAssignment("pbt_population", self.population),
                ParameterAssignment("pbt_generations", self.generations),
                ParameterAssignment(
                    "pbt_steps_per_generation", self.steps_per_generation
                ),
                ParameterAssignment("pbt_truncation", self.truncation),
                ParameterAssignment("pbt_seed", int(self.seed() % (2**31))),
                ParameterAssignment("pbt_space", space_json),
            ]
            if self.resample_p is not None:
                assignments.append(
                    ParameterAssignment("pbt_resample_p", self.resample_p)
                )
            out.append(
                TrialAssignmentSet(
                    name=job.uid,
                    assignments=assignments,
                    labels={
                        GENERATION_LABEL: "0",
                        COHORT_KEY_LABEL: ONDEVICE_COHORT_KEY,
                    },
                )
            )
        return out

    def state_dict(self) -> dict:
        data = super().state_dict()
        data["dispatched"] = self._dispatched
        return data

    def load_state_dict(self, data: dict) -> None:
        super().load_state_dict(data)
        self._dispatched = bool(data.get("dispatched", False))
