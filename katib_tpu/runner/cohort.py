"""Vectorized trial cohorts: K compatible trials, one compiled program.

Small-model hyperparameter sweeps are dominated by per-trial overhead —
XLA recompiles the same training step once per trial and Python dispatch
is paid K times per step.  A *cohort* lifts the K members' hyperparameters
into dynamic array operands (a stacked ``[K, ...]`` state pytree whose
opt-state carries per-member learning rates via
``optax.inject_hyperparams``) and trains all members in ONE jitted
``vmap``'d step with donated carried state (see
``parallel/train.py:make_cohort_train_step``).  The first member pays the
trace; members 2..K — and every later cohort of the same shapes — reuse
the executable.

The cohort is an *execution* batch, not a semantic one: each member keeps
its own trial identity.  Metric rows are unstacked per member into the
normal ``ObservationStore`` path, early-stopping rules evaluate per
member, and a member whose objective goes non-finite fails alone
(``Permanent``, "diverged") while its lane is frozen in-step so it cannot
poison the rest (the ``jnp.where`` guard in ``make_cohort_train_step``).

A train function opts in by attaching a cohort-capable twin::

    def my_trial(ctx): ...            # normal TrialContext path
    def my_cohort(cctx): ...          # CohortContext path, trains all K
    attach_cohort_fn(my_trial, my_cohort)

``run_cohort`` falls back to per-member serial ``run_trial`` whenever the
cohort path is unavailable (K == 1, no cohort fn) or blows up mid-flight —
cohort mode is never worse than serial, just slower on the fallback.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from katib_tpu.core.types import COHORT_KEY_LABEL, MetricLog, Trial, TrialCondition
from katib_tpu.earlystop.rules import RuleEvaluator
from katib_tpu.runner.trial_runner import TrialResult, _finalize, run_trial
from katib_tpu.store.base import ObservationStore
from katib_tpu.utils import observability as obs
from katib_tpu.utils import tracing
from katib_tpu.utils.faults import FailureKind, classify_exception

_COHORT_ATTR = "__cohort_fn__"


def attach_cohort_fn(train_fn: Callable, cohort_fn: Callable) -> Callable:
    """Declare ``cohort_fn(cctx)`` as the vectorized twin of ``train_fn(ctx)``.
    Returns ``train_fn`` so it can be used as a decorator-style one-liner."""
    setattr(train_fn, _COHORT_ATTR, cohort_fn)
    return train_fn


def cohort_fn_of(train_fn: Callable | None) -> Callable | None:
    """The cohort-capable twin of ``train_fn``, or None when it never
    opted in (black-box commands and plain train_fns stay serial)."""
    if train_fn is None:
        return None
    return getattr(train_fn, _COHORT_ATTR, None)


class CohortContext:
    """What a cohort_fn sees: the members' hyperparameters (stackable into
    ``[K]`` operand arrays), a batched ``report`` that unstacks metric rows
    per member, and per-member failure/early-stop bookkeeping."""

    def __init__(
        self,
        members: Sequence[Trial],
        store: ObservationStore,
        objective,
        mesh: Any = None,
        stop_event: threading.Event | None = None,
        drain_event: threading.Event | None = None,
        hang_event: threading.Event | None = None,
        heartbeat: Any = None,
        buckets: bool = False,
    ):
        self.members = list(members)
        self.params_list = [t.params() for t in self.members]
        self.labels_list = [dict(t.spec.labels) for t in self.members]
        self.checkpoint_dirs = [t.checkpoint_dir for t in self.members]
        self.mesh = mesh
        # devices on the mesh's reserved `trial` axis: the stacked member
        # dimension shards over them, so K is padded up to a multiple with
        # inert ghost members whose metric rows never reach the store
        if mesh is not None:
            from katib_tpu.parallel.mesh import trial_axis_size

            self.trial_devices = trial_axis_size(mesh)
        else:
            self.trial_devices = 1
        # shape bucketing (ExperimentSpec.cohort_buckets): quantize the
        # padded member dimension to the next power of two so cohorts of
        # heterogeneous K share one cached executable (katib_tpu/compile)
        self.buckets = buckets
        self._store = store
        self._objective = objective
        self._stop_event = stop_event
        # drain (orchestrator preemption) + hang-watchdog plumbing, same
        # semantics as TrialContext: the whole cohort checkpoints-and-exits
        # at its next step boundary / is classified hung as one program
        self._drain_event = drain_event
        self._hang_event = hang_event
        self._heartbeat = heartbeat
        self._evaluators = [
            RuleEvaluator(t.spec.early_stopping_rules, objective)
            for t in self.members
        ]
        k = len(self.members)
        self._failed: list[tuple[str, FailureKind] | None] = [None] * k
        self._early_stopped: list[bool] = [False] * k
        self._step = 0
        # cooperative wall-clock bound like TrialContext: the tightest
        # member deadline bounds the whole cohort (members share one program)
        runtimes = [
            t.spec.max_runtime_seconds
            for t in self.members
            if t.spec.max_runtime_seconds is not None
        ]
        self._deadline = time.monotonic() + min(runtimes) if runtimes else None

    # -- member hyperparameters -------------------------------------------

    def __len__(self) -> int:
        return len(self.members)

    @property
    def padded_size(self) -> int:
        """K rounded up to a multiple of the trial-axis size — the leading
        dimension the stacked state pytree must carry on a sharded mesh.
        With ``buckets`` on, K is first quantized to the next power of two
        so different-K cohorts collapse onto one cached executable.
        Rows ``[K:]`` are ghost members: they train (on member 0's
        hyperparameters, so they stay finite) but their metric rows are
        dropped by ``report`` before the ObservationStore."""
        t = self.trial_devices
        if self.buckets:
            from katib_tpu.compile.buckets import bucket_size

            return bucket_size(len(self.members), t)
        return -(-len(self.members) // t) * t

    @property
    def cohort_mesh(self):
        """The mesh the cohort step should shard over, or None when the
        experiment mesh carries no trial axis (single-device vmap)."""
        return self.mesh if self.trial_devices > 1 else None

    def stacked(self, name: str, default: Any = None, dtype=None):
        """Per-member values of parameter ``name`` as a ``[padded_size]``
        jnp array — the dynamic operand that rides inside the vmapped
        program.  Ghost rows repeat member 0's value (inert but finite)."""
        import jax.numpy as jnp

        vals = [p.get(name, default) for p in self.params_list]
        vals += [vals[0]] * (self.padded_size - len(vals))
        return jnp.asarray(vals, dtype=dtype)

    def place_members(self, tree):
        """Device-put a stacked ``[padded_size, ...]`` pytree onto the
        trial-sharded layout (identity without a trial axis, so cohort fns
        call it unconditionally)."""
        if self.trial_devices <= 1:
            return tree
        from katib_tpu.parallel.mesh import shard_members

        return shard_members(tree, self.mesh)

    def place_shared(self, tree):
        """Device-put member-shared arrays (batches, eval sets) — replicated
        across the mesh, or the default single-device placement without one."""
        import jax

        if self.trial_devices <= 1:
            return jax.device_put(tree)
        from katib_tpu.parallel.mesh import replicate

        return replicate(tree, self.mesh)

    def shared(self, name: str, default: Any = None) -> Any:
        """A parameter every member must agree on (model shape, batch size —
        anything that changes the compiled program).  Raises when members
        disagree: such trials belong in different cohorts."""
        vals = [p.get(name, default) for p in self.params_list]
        if any(v != vals[0] for v in vals[1:]):
            raise ValueError(
                f"cohort members disagree on structural parameter {name!r}: {vals} "
                "(group them under different cohort keys)"
            )
        return vals[0]

    # -- reporting ---------------------------------------------------------

    def report(self, step: int | None = None, **metrics) -> bool:
        """Report one ``[K]`` row per metric; returns True while any member
        is still alive and the cohort should keep training.

        Row ``i`` of each value belongs to member ``i``.  A member whose
        objective metric comes back non-finite is failed ``Permanent``
        ("diverged" — the identical re-run would diverge again); non-finite
        values are never written to the store so reductions stay clean.
        """
        if self._heartbeat is not None:
            self._heartbeat()  # cohort step boundary = watchdog progress
        if step is None:
            step = self._step
            self._step += 1
        else:
            self._step = step + 1
        k = len(self.members)
        rows: dict[str, np.ndarray] = {}
        for name, value in metrics.items():
            arr = np.asarray(value, dtype=float).reshape(-1)
            if arr.size == 1:
                arr = np.full(k, arr[0])
            if arr.size == self.padded_size and self.padded_size != k:
                # ghost-member rows (sharded-mesh padding) are dropped
                # before they can reach the store
                arr = arr[:k]
            if arr.size != k:
                raise ValueError(
                    f"metric {name!r} has {arr.size} rows for a {k}-member cohort"
                )
            rows[name] = arr
        obj_name = self._objective.objective_metric_name
        now = time.time()
        for i, trial in enumerate(self.members):
            if not self.alive(i):
                continue
            if obj_name in rows and not np.isfinite(rows[obj_name][i]):
                self.fail_member(
                    i,
                    f"objective metric {obj_name!r} went non-finite at step "
                    f"{step} (diverged)",
                )
                continue
            logs = [
                MetricLog(metric_name=n, value=float(v[i]), timestamp=now, step=step)
                for n, v in rows.items()
                if np.isfinite(v[i])
            ]
            if logs:
                self._store.report(trial.name, logs)
            ev = self._evaluators[i]
            for log in logs:
                ev.observe(log.metric_name, log.value)
            if ev.should_stop():
                self._early_stopped[i] = True
        return not self.should_stop()

    # -- member lifecycle --------------------------------------------------

    def alive(self, i: int) -> bool:
        """True while member ``i`` still wants training steps."""
        return self._failed[i] is None and not self._early_stopped[i]

    def fail_member(self, i: int, message: str, transient: bool = False) -> None:
        """Fail member ``i`` alone; the rest of the cohort keeps training.
        ``transient=True`` marks it retryable (the orchestrator re-runs it
        as a singleton trial)."""
        if self._failed[i] is None:
            kind = FailureKind.TRANSIENT if transient else FailureKind.PERMANENT
            self._failed[i] = (message, kind)

    def should_stop(self) -> bool:
        """True when the whole cohort should wind down: every member is
        done (failed/early-stopped), the experiment hit a terminal state,
        or the wall-clock bound passed."""
        if not any(self.alive(i) for i in range(len(self.members))):
            return True
        if self.deadline_exceeded():
            return True
        if self.hang_flagged() or self.drain_requested():
            return True
        return self._stop_event is not None and self._stop_event.is_set()

    def deadline_exceeded(self) -> bool:
        return self._deadline is not None and time.monotonic() > self._deadline

    def drain_requested(self) -> bool:
        """True once the orchestrator wants the cohort to checkpoint and
        return at its next step boundary (preemption drain)."""
        return self._drain_event is not None and self._drain_event.is_set()

    def hang_flagged(self) -> bool:
        return self._hang_event is not None and self._hang_event.is_set()

    # -- settlement (run_cohort internals) ---------------------------------

    def _settle(self, i: int) -> TrialResult:
        """Terminal condition for member ``i`` after the cohort fn returned,
        mirroring the serial ``_run_whitebox`` postamble ordering."""
        if self._failed[i] is not None:
            message, kind = self._failed[i]
            return TrialResult(TrialCondition.FAILED, message, failure_kind=kind)
        if self._early_stopped[i]:
            triggered = self._evaluators[i].triggered
            return TrialResult(
                TrialCondition.EARLY_STOPPED,
                triggered.describe() if triggered is not None else "early stopped",
            )
        if self.hang_flagged():
            # retryable: the member rejoins as a singleton from its last
            # checkpoint through the orchestrator's retry machinery
            return TrialResult(
                TrialCondition.FAILED,
                "hang watchdog: cohort made no step progress past "
                "progress_deadline_seconds",
                failure_kind=FailureKind.HANG,
            )
        if self.deadline_exceeded():
            return TrialResult(
                TrialCondition.FAILED,
                "cohort exceeded max_runtime_seconds",
                failure_kind=FailureKind.PERMANENT,
            )
        if self._stop_event is not None and self._stop_event.is_set():
            return TrialResult(
                TrialCondition.KILLED, "experiment reached terminal state"
            )
        if self.drain_requested():
            return TrialResult(
                TrialCondition.DRAINED, "checkpointed and exited for drain"
            )
        return _finalize(self.members[i], self._store, self._objective)


def run_cohort(
    trials: Sequence[Trial],
    store: ObservationStore,
    objective,
    mesh=None,
    stop_event: threading.Event | None = None,
    injector=None,
    watchdog=None,
    drain_event: threading.Event | None = None,
    buckets: bool = False,
) -> dict[str, TrialResult]:
    """Execute K trials as one vectorized cohort; returns a per-trial-name
    result map.  Never raises: a cohort-path failure falls back to serial
    per-member execution, and member failures are isolated results.
    """
    results: dict[str, TrialResult] = {}
    if not trials:
        return results
    cohort_fn = cohort_fn_of(trials[0].spec.train_fn)
    if len(trials) == 1 or cohort_fn is None:
        for t in trials:
            results[t.name] = run_trial(
                t, store, objective, mesh, stop_event, injector,
                watchdog=watchdog, drain_event=drain_event,
            )
        return results

    # chaos seam parity with run_trial: injected faults fire per member and
    # fail only that member; survivors still train as a (smaller) cohort
    survivors: list[Trial] = []
    for t in trials:
        if injector is not None:
            try:
                injector.on_trial_attempt(t)
                injector.apply_metrics_delay(t, stop_event)
            except Exception as e:
                results[t.name] = TrialResult(
                    TrialCondition.FAILED,
                    traceback.format_exc(limit=20),
                    failure_kind=classify_exception(e),
                )
                continue
        survivors.append(t)
    if not survivors:
        return results
    if len(survivors) == 1:
        t = survivors[0]
        results[t.name] = run_trial(
            t, store, objective, mesh, stop_event,
            watchdog=watchdog, drain_event=drain_event,
        )
        return results

    k = len(survivors)
    key = survivors[0].spec.labels.get(COHORT_KEY_LABEL, "")
    # one heartbeat for the whole cohort (members share one compiled
    # program, so they stall together): tightest member deadline wins
    hang_event = threading.Event()
    heartbeat = None
    deadlines = [
        t.spec.progress_deadline_seconds
        for t in survivors
        if t.spec.progress_deadline_seconds
    ]
    if watchdog is not None and deadlines:
        heartbeat = watchdog.register(
            f"cohort:{key or survivors[0].name}",
            min(deadlines),
            on_hang=lambda _name: hang_event.set(),
        )
    # compile watchdog: one budget for the cohort's shared trace/compile/first
    # dispatch, disarmed by the first step-boundary beat.  Re-armed per
    # degradation tier (a rebuilt mesh means a fresh compile).
    compile_hang_event = threading.Event()
    compile_deadlines = [
        t.spec.compile_deadline_seconds
        for t in survivors
        if t.spec.compile_deadline_seconds
    ]
    compile_hb_holder: list = [None]

    def _on_compile_hang(_name: str) -> None:
        obs.compile_hangs.inc()
        compile_hang_event.set()
        hang_event.set()  # cooperative unwind through the hang path

    # warm/cold first-step classification: the cohort's first step-boundary
    # report closes the trace+compile+first-dispatch window; the shape
    # registry (katib_tpu/compile) decides whether that compile should have
    # hit the cache and feeds the hit/miss counters
    from katib_tpu import costmodel
    from katib_tpu.compile import registry as compile_registry

    sig_holder: list = [None]
    first_step_at: list[float] = [0.0]
    # classification drains sig_holder on the first beat; roofline
    # publication and cost persistence keep using the tier's signature
    cost_sig_holder: list = [None]
    last_beat: list[float] = [0.0]
    cost_attrs: dict = {}

    def _beat() -> None:
        now = time.perf_counter()
        sig = sig_holder[0]
        if sig is not None:
            sig_holder[0] = None
            try:
                dt = now - first_step_at[0]
                label = compile_registry.REGISTRY.note_first_step(sig, dt)
                obs.trial_first_step_seconds.set(
                    dt, phase="first_report", cache=label, workload=sig.program
                )
            except Exception:
                pass  # classification is telemetry, never a cohort failure
        else:
            # steady-state interval (the first one folds compile — skip):
            # the cohort program's observed cost over the report cadence
            active = costmodel.active_cost()
            csig = cost_sig_holder[0]
            if active is not None and csig is not None:
                rec, per_report = active
                interval = now - last_beat[0]
                steps = max(1, rec.steps * per_report)
                attrs = costmodel.publish_dispatch(
                    rec, interval / steps, workload=csig.program
                )
                if attrs:
                    cost_attrs.update(attrs)
        active = costmodel.active_cost()
        if active is not None and cost_sig_holder[0] is not None:
            try:
                compile_registry.REGISTRY.record_cost(
                    cost_sig_holder[0], active[0].as_dict()
                )
            except Exception:
                pass
        last_beat[0] = now
        hb = compile_hb_holder[0]
        if hb is not None:
            # first step-boundary report = first dispatch done
            hb.close()
            compile_hb_holder[0] = None
        if heartbeat is not None:
            heartbeat.beat()

    # elastic degradation: a DEVICE-classified cohort failure probes the
    # mesh, rebuilds it from survivors with a narrower trial axis, and
    # re-runs the cohort (members resume from their checkpoints).  The loop
    # terminates because each pass strictly shrinks the trial axis — the
    # final tier is mesh=None (single-device vmap); anything past that falls
    # back to serial per-member execution.
    from katib_tpu.parallel.mesh import trial_axis_size

    cur_mesh = mesh
    started = time.perf_counter()
    tier = 0
    try:
        while True:
            ctx = CohortContext(
                survivors, store, objective, mesh=cur_mesh, stop_event=stop_event,
                drain_event=drain_event, hang_event=hang_event,
                # always wired: _beat also closes the warm/cold first-step
                # classification window above
                heartbeat=_beat,
                buckets=buckets,
            )
            devices = ctx.trial_devices
            if watchdog is not None and compile_deadlines:
                compile_hb_holder[0] = watchdog.register(
                    f"compile:cohort:{key or survivors[0].name}",
                    min(compile_deadlines),
                    on_hang=_on_compile_hang,
                )
            try:
                if injector is not None and cur_mesh is not None:
                    injector.on_cohort_execute(
                        survivors, [d.id for d in cur_mesh.devices.flat]
                    )
                # (re)arm classification per tier — a rebuilt mesh means a
                # fresh program with its own signature
                sig_holder[0] = compile_registry.cohort_signature(
                    cohort_fn, survivors, ctx.padded_size, ctx.cohort_mesh
                )
                cost_sig_holder[0] = sig_holder[0]
                # dispatch tries a fetch before tracing: published
                # executables for this cohort signature load (warm +
                # resolve() adoption) instead of compiling — best-effort
                try:
                    from katib_tpu.compile.artifacts import ARTIFACTS

                    ARTIFACTS.fetch_family(sig_holder[0])
                except Exception:
                    pass
                costmodel.clear_active()  # fresh tier = fresh program cost
                first_step_at[0] = time.perf_counter()
                last_beat[0] = first_step_at[0]
                with tracing.span(
                    "cohort",
                    size=k,
                    key=key,
                    devices=devices,
                    members_per_device=ctx.padded_size // devices,
                    tier=tier,
                ) as cohort_sp:
                    cohort_fn(ctx)
                    if cost_attrs:
                        cohort_sp.set(**cost_attrs)
                break
            except Exception as e:
                kind = classify_exception(e)
                if kind is FailureKind.DEVICE and trial_axis_size(cur_mesh) > 1:
                    from katib_tpu.parallel.mesh import narrowed_trial_mesh
                    from katib_tpu.utils import meshhealth

                    devs = list(cur_mesh.devices.flat)
                    report = meshhealth.probe_devices(
                        devs,
                        deadline=min(10.0, meshhealth.default_deadline()),
                        injector=injector,
                    )
                    for d in report.devices:
                        obs.device_healthy.set(
                            1.0 if d.status == meshhealth.HEALTHY else 0.0,
                            device=d.device,
                            platform=d.platform,
                        )
                    alive_devs = meshhealth.healthy_devices(devs, report)
                    cur_mesh = narrowed_trial_mesh(cur_mesh, alive_devs)
                    obs.mesh_degraded.inc()
                    tier += 1
                    continue  # retry: narrower sharded mesh, or vmap when None
                # the vectorized path is an optimization, never a correctness
                # dependency: re-run every member serially (duplicate metric
                # rows from the partial cohort are tolerated by the store's
                # reduction)
                obs.cohort_fallbacks.inc()
                for t in survivors:
                    results[t.name] = run_trial(
                        t, store, objective, None, stop_event,
                        watchdog=watchdog, drain_event=drain_event,
                    )
                return results
            finally:
                hb = compile_hb_holder[0]
                if hb is not None:
                    hb.close()
                    compile_hb_holder[0] = None
    finally:
        if heartbeat is not None:
            heartbeat.close()
    elapsed = max(time.perf_counter() - started, 1e-9)

    obs.cohorts_executed.inc()
    obs.cohort_size.observe(float(k))
    obs.cohort_trials_per_sec.set(k / elapsed)
    obs.cohort_devices.set(float(devices))
    per_member = elapsed / k
    for i, t in enumerate(survivors):
        member_result = ctx._settle(i)
        if (
            compile_hang_event.is_set()
            and member_result.failure_kind is FailureKind.HANG
        ):
            # the hang the watchdog flagged was the compile budget, not
            # step-progress: reclassify so retry telemetry stays honest
            member_result = TrialResult(
                TrialCondition.FAILED,
                "compile watchdog: cohort jit compile / first dispatch "
                "exceeded compileDeadlineSeconds",
                failure_kind=FailureKind.COMPILE_HANG,
            )
        results[t.name] = member_result
        # per-member span so trial-level trace analysis (and the CI
        # observability smoke) sees cohort members as ordinary trials
        tracing.record_span(
            "trial",
            per_member,
            trial=t.name,
            condition=results[t.name].condition.value,
            cohort=key,
            cohort_size=k,
            **cost_attrs,
        )
    return results
