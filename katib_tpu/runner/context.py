"""Trial execution context — the white-box replacement for the reference's
pod machinery.

In the reference, a trial is an opaque container: parameters arrive as CLI
args rendered from a template (``manifest/generator.go:79-99``), metrics leave
via stdout scraping by an injected sidecar (``pod/inject_webhook.go:123``),
and early stopping is a SIGTERM from that sidecar.  Here a trial is a
function ``train_fn(ctx)`` and ``TrialContext`` is its whole contract:

- ``ctx.params``           — suggested hyperparameters (typed, not strings)
- ``ctx.report(...)``      — metrics straight into the observation store
- ``ctx.should_stop()``    — cooperative early-stopping check
- ``ctx.checkpoint_dir``   — per-trial checkpoint directory (PBT lineage
                             pre-populated by the suggester)
- ``ctx.mesh``             — the JAX device mesh the trial should train on
"""

from __future__ import annotations

import os
import time
from typing import Any, Mapping

from katib_tpu.core.types import MetricLog
from katib_tpu.earlystop.rules import RuleEvaluator
from katib_tpu.store.base import ObservationStore


class TrialEarlyStopped(Exception):
    """Raised by ``report(..., check_stop=True)`` / ``raise_if_stopped`` to
    unwind a training loop when a stop rule fires."""


class TrialContext:
    def __init__(
        self,
        trial_name: str,
        params: Mapping[str, Any],
        store: ObservationStore,
        evaluator: RuleEvaluator | None = None,
        checkpoint_dir: str | None = None,
        mesh: Any = None,
        labels: Mapping[str, str] | None = None,
        stop_event: Any = None,
        max_runtime_seconds: float | None = None,
        drain_event: Any = None,
        hang_event: Any = None,
        heartbeat: Any = None,
    ):
        self.trial_name = trial_name
        self.params = dict(params)
        self._store = store
        self._evaluator = evaluator
        self.checkpoint_dir = checkpoint_dir
        self.mesh = mesh
        self.labels = dict(labels or {})
        self._stop_event = stop_event
        # orchestrator drain (preemption SIGTERM): checkpoint-and-exit at the
        # next step boundary — report()/should_stop() turn the flag into a
        # cooperative unwind, the runner settles the trial DRAINED
        self._drain_event = drain_event
        # hang watchdog verdict (utils/watchdog.py): set by the monitor
        # thread when no heartbeat landed for progress_deadline_seconds
        self._hang_event = hang_event
        # called on every report() — the watchdog heartbeat
        self._heartbeat = heartbeat
        self._step = 0
        self._checkpointer = None
        # cooperative wall-clock deadline: report()/should_stop() turn False/
        # True past it and the runner classifies the trial FAILED (a Python
        # train_fn cannot be preempted; the black-box path SIGTERMs instead)
        self._deadline = (
            time.monotonic() + max_runtime_seconds
            if max_runtime_seconds is not None
            else None
        )

    # -- metrics -----------------------------------------------------------

    def report(self, step: int | None = None, **metrics: float) -> bool:
        """Report metric values; returns True while the trial may continue.

        ``ctx.report(accuracy=0.91, loss=0.3, step=epoch)`` replaces the
        reference's ``print("accuracy=0.91")`` + sidecar regex scrape.
        """
        if self._heartbeat is not None:
            self._heartbeat()
        if step is None:
            step = self._step
            self._step += 1
        else:
            self._step = step + 1
        now = time.time()
        logs = [
            MetricLog(metric_name=k, value=float(v), timestamp=now, step=step)
            for k, v in metrics.items()
        ]
        self._store.report(self.trial_name, logs)
        if self._evaluator is not None:
            for log in logs:
                self._evaluator.observe(log.metric_name, log.value)
        return not self.should_stop()

    # -- early stopping ------------------------------------------------------

    def should_stop(self) -> bool:
        """True when an early-stopping rule fired OR the experiment reached a
        terminal state (goal hit / failure budget) and wants trials to wind
        down OR the trial blew its wall-clock deadline."""
        if self._evaluator is not None and self._evaluator.should_stop():
            return True
        if self.deadline_exceeded():
            return True
        if self.hang_flagged() or self.drain_requested():
            return True
        return self._stop_event is not None and self._stop_event.is_set()

    def deadline_exceeded(self) -> bool:
        return self._deadline is not None and time.monotonic() > self._deadline

    def drain_requested(self) -> bool:
        """True once the orchestrator received SIGTERM/SIGINT and wants this
        trial to checkpoint and return at its next step boundary.  A trial
        that saves each epoch before ``report()`` needs no extra code — the
        report's False return unwinds it after the save."""
        return self._drain_event is not None and self._drain_event.is_set()

    def hang_flagged(self) -> bool:
        """True once the hang watchdog classified this trial as stalled (the
        runner settles it ``FailureKind.HANG`` when the train_fn unwinds)."""
        return self._hang_event is not None and self._hang_event.is_set()

    def raise_if_stopped(self) -> None:
        if self._evaluator is not None and self._evaluator.should_stop():
            raise TrialEarlyStopped(self._evaluator.triggered.describe())
        if self.deadline_exceeded():
            raise TrialEarlyStopped("trial max_runtime exceeded")
        if self.hang_flagged():
            raise TrialEarlyStopped("hang watchdog interrupted the trial")
        if self.drain_requested():
            raise TrialEarlyStopped("orchestrator draining (preemption)")
        if self._stop_event is not None and self._stop_event.is_set():
            raise TrialEarlyStopped("experiment reached terminal state")

    # -- checkpoints ---------------------------------------------------------

    def ensure_checkpoint_dir(self) -> str:
        if self.checkpoint_dir is None:
            raise RuntimeError("trial has no checkpoint directory configured")
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        return self.checkpoint_dir

    def checkpointer(self, max_to_keep: int = 3):
        """Orbax-backed pytree checkpointer on this trial's directory (PBT
        lineage arrives pre-populated: the suggester copies the parent's
        tree here before the trial starts)."""
        if self._checkpointer is None:
            from katib_tpu.utils.checkpoint import TrialCheckpointer

            self._checkpointer = TrialCheckpointer(
                self.ensure_checkpoint_dir(), max_to_keep=max_to_keep
            )
        return self._checkpointer

    def save_checkpoint(self, pytree, step: int) -> str:
        return self.checkpointer().save(pytree, step)

    def restore_checkpoint(self, template=None, step: int | None = None):
        """Latest (or given-step) checkpoint as ``(pytree, step)``; ``None``
        on a cold start."""
        if self.checkpoint_dir is None or not os.path.isdir(self.checkpoint_dir):
            return None
        return self.checkpointer().restore(template, step)
