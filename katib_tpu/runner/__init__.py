from katib_tpu.runner.context import TrialContext, TrialEarlyStopped  # noqa: F401
from katib_tpu.runner.trial_runner import TrialResult, run_trial  # noqa: F401
