"""TensorFlow event-file metrics collector — no TensorFlow dependency.

Parity with the reference's TFEvent metrics-collector sidecar
(``cmd/metricscollector/v1beta1/tfevent-metricscollector/main.py:47-79`` +
``tfevent_loader.py``), which tails a trial's summary directory with TF's
EventAccumulator and reports scalar metrics once the trial exits.  Here the
TFRecord framing (u64 length + masked crc32c, payload + masked crc32c) and
the Event/Summary protobuf wire format are decoded directly, so JAX trials
and arbitrary black-box trainers that emit TensorBoard event files work
without TF installed.

Scalars are read from both summary encodings:
- TF1 ``Summary.Value.simple_value`` (field 2, float)
- TF2 ``Summary.Value.tensor`` (field 8) carrying a scalar DT_FLOAT/DT_DOUBLE
  TensorProto (``float_val``/``double_val`` or packed ``tensor_content``)

A minimal writer is included (valid framing + simple_value summaries) so the
framework can export its own metrics for TensorBoard and so tests can
fabricate real files — the reference generates fixtures by running a real TF
trainer (``Makefile:172-175``); we synthesize them instead.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Sequence

from katib_tpu.core.types import MetricLog

# -- crc32c (Castagnoli), table-driven --------------------------------------

_CRC_TABLE: tuple[int, ...] | None = None


def _crc_table() -> tuple[int, ...]:
    # built as a local and published in one assignment: concurrent trial
    # threads either see None (and rebuild identically) or the full table —
    # never a partially filled one
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = tuple(table)
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# -- protobuf wire-format primitives ----------------------------------------


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        result |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes) -> Iterator[tuple[int, int, bytes | int]]:
    """Yield (field_number, wire_type, value) skipping unknown types."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:  # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 1:  # fixed64
            value = buf[pos : pos + 8]
            pos += 8
        elif wire == 2:  # length-delimited
            length, pos = _read_varint(buf, pos)
            value = buf[pos : pos + length]
            pos += length
        elif wire == 5:  # fixed32
            value = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


# TensorProto dtype codes (tensorflow/core/framework/types.proto)
_DT_FLOAT, _DT_DOUBLE = 1, 2


def _tensor_scalar(buf: bytes) -> float | None:
    """Extract a scalar float from a TensorProto (TF2 scalar summaries)."""
    dtype = None
    content = b""
    float_val: float | None = None
    for field, wire, value in _iter_fields(buf):
        if field == 1 and wire == 0:  # dtype
            dtype = value
        elif field == 4 and wire == 2:  # tensor_content
            content = value
        elif field == 5:  # float_val (packed or single fixed32)
            raw = value
            if isinstance(raw, bytes) and len(raw) >= 4:
                float_val = struct.unpack("<f", raw[:4])[0]
        elif field == 6:  # double_val
            raw = value
            if isinstance(raw, bytes) and len(raw) >= 8:
                float_val = struct.unpack("<d", raw[:8])[0]
    if float_val is not None:
        return float(float_val)
    if dtype == _DT_FLOAT and len(content) >= 4:
        return float(struct.unpack("<f", content[:4])[0])
    if dtype == _DT_DOUBLE and len(content) >= 8:
        return float(struct.unpack("<d", content[:8])[0])
    return None


def _parse_summary(buf: bytes, wall_time: float, step: int) -> list[MetricLog]:
    out: list[MetricLog] = []
    for field, wire, value in _iter_fields(buf):
        if field != 1 or wire != 2:  # repeated Summary.Value
            continue
        tag: str | None = None
        scalar: float | None = None
        for vfield, vwire, vvalue in _iter_fields(value):
            if vfield == 1 and vwire == 2:  # tag
                tag = vvalue.decode(errors="replace")
            elif vfield == 2 and vwire == 5:  # simple_value
                scalar = float(struct.unpack("<f", vvalue)[0])
            elif vfield == 8 and vwire == 2:  # tensor
                got = _tensor_scalar(vvalue)
                if got is not None:
                    scalar = got
        if tag is not None and scalar is not None:
            out.append(
                MetricLog(metric_name=tag, value=scalar, timestamp=wall_time, step=step)
            )
    return out


def _parse_event(buf: bytes) -> list[MetricLog]:
    wall_time = 0.0
    step = -1
    summaries: list[bytes] = []
    for field, wire, value in _iter_fields(buf):
        if field == 1 and wire == 1:  # wall_time double
            wall_time = struct.unpack("<d", value)[0]
        elif field == 2 and wire == 0:  # step
            step = value
        elif field == 5 and wire == 2:  # summary
            summaries.append(value)
    out: list[MetricLog] = []
    for s in summaries:
        out.extend(_parse_summary(s, wall_time, step))
    return out


# -- tfrecord framing --------------------------------------------------------


def read_tfrecords(path: str, verify_crc: bool = True) -> Iterator[bytes]:
    """Yield raw record payloads; stops cleanly at a truncated tail (a live
    trial may still be appending)."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if verify_crc and _masked_crc(header[:8]) != len_crc:
                return  # corrupt frame: stop rather than misparse
            data = f.read(length)
            footer = f.read(4)
            if len(data) < length or len(footer) < 4:
                return
            (data_crc,) = struct.unpack("<I", footer)
            if verify_crc and _masked_crc(data) != data_crc:
                return
            yield data


def parse_tfevent_file(path: str, metric_names: Sequence[str] | None = None) -> list[MetricLog]:
    tracked = set(metric_names) if metric_names is not None else None
    out: list[MetricLog] = []
    for record in read_tfrecords(path):
        try:
            logs = _parse_event(record)
        except (ValueError, IndexError, struct.error):
            continue  # skip undecodable events, keep scanning
        for log in logs:
            if tracked is None or log.metric_name in tracked:
                out.append(log)
    return out


def parse_tfevent_dir(path: str, metric_names: Sequence[str] | None = None) -> list[MetricLog]:
    """Scan a summary directory tree for ``*tfevents*`` files (the reference
    loader walks the whole dir, ``tfevent_loader.py`` MetricsCollector) and
    merge their scalars in (wall_time, step) order."""
    out: list[MetricLog] = []
    for root, _, files in os.walk(path):
        for name in sorted(files):
            if "tfevents" not in name:
                continue
            out.extend(parse_tfevent_file(os.path.join(root, name), metric_names))
    out.sort(key=lambda l: (l.timestamp, l.step))
    return out


# -- writer ------------------------------------------------------------------


def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _field(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


class TFEventWriter:
    """Append scalar summaries to a TensorBoard-compatible event file."""

    def __init__(self, logdir: str, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        import time as _time

        name = f"events.out.tfevents.{int(_time.time())}.katib{filename_suffix}"
        self._path = os.path.join(logdir, name)
        self._f = open(self._path, "ab")

    @property
    def path(self) -> str:
        return self._path

    def _write_record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, step: int, wall_time: float) -> None:
        tag_b = tag.encode()
        summary_value = (
            _field(1, 2) + _varint(len(tag_b)) + tag_b
            + _field(2, 5) + struct.pack("<f", value)
        )
        summary = _field(1, 2) + _varint(len(summary_value)) + summary_value
        event = (
            _field(1, 1) + struct.pack("<d", wall_time)
            + _field(2, 0) + _varint(step if step >= 0 else 0)
            + _field(5, 2) + _varint(len(summary)) + summary
        )
        self._write_record(event)

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()
