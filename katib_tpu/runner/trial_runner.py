"""Trial runners: white-box (Python/JAX function) and black-box (subprocess).

The white-box path collapses the reference's trial pipeline (trial controller
creates Job -> pod webhook injects sidecar -> sidecar PNS-waits and scrapes
stdout -> gRPC to DB-manager -> controller polls observation,
``trial_controller.go:147-306`` + ``inject_webhook.go`` + ``pns.go``) into a
function call with a metrics callback.

The black-box path keeps parity with arbitrary-language trials: the command
template's ``${trialParameters.X}`` placeholders are substituted
(``manifest/generator.go:99``), metrics are scraped live — from stdout for
StdOut collectors, by tailing the metrics file for File/JsonLines collectors
(the sidecar's watch loop, ``file-metricscollector/main.go:143``) — and
early-stopping rules terminate the process on trigger (the sidecar's SIGTERM
dance, ``main.go:262-306``).

Both paths honor a shared ``stop_event``: when the orchestrator reaches a
terminal verdict (goal hit, failure budget blown) it sets the event and
in-flight trials wind down as ``Killed`` (the reference deletes running trial
jobs on experiment completion, ``experiment_controller.go:362-403``).
"""

from __future__ import annotations

import dataclasses
import os
import re
import signal
import subprocess
import threading
import traceback

from katib_tpu.utils.clock import get_clock
from katib_tpu.core.types import (
    MetricsCollectorKind,
    Trial,
    TrialCondition,
)
from katib_tpu.earlystop.rules import RuleEvaluator
from katib_tpu.runner.context import TrialContext, TrialEarlyStopped
from katib_tpu.runner.metrics import parse_json_lines, parse_text_lines_fast
from katib_tpu.store.base import ObservationStore
from katib_tpu.utils import observability as obs
from katib_tpu.utils import tracing
from katib_tpu.utils.faults import (
    FailureKind,
    classify_exception,
    classify_exit_code,
)


# process-global: the JAX compilation-cache config is a singleton, so the
# first caller to wire a directory wins for the life of the process
_COMPILE_CACHE_DIR: str | None = None


def init_compile_cache(cache_dir: str | None = None) -> str | None:
    """Wire JAX's persistent compilation cache, once per process.

    Resolution: ``KATIB_COMPILE_CACHE`` env var, then the ``cache_dir``
    argument (``ExperimentSpec.compile_cache``), else disabled.  With the
    cache wired, identical programs compile once per *cache* instead of
    once per process — restarts, ``--resume``, and repeated sweeps of the
    same shapes skip straight to executable deserialization, which shows
    up as the compile phase of ``katib_trial_first_step_seconds``
    collapsing.  Returns the effective directory (None = disabled);
    best-effort — an unwritable dir or an old jax never fails the run.
    """
    global _COMPILE_CACHE_DIR
    if _COMPILE_CACHE_DIR is not None:
        requested = os.environ.get("KATIB_COMPILE_CACHE") or cache_dir
        if requested and os.path.abspath(requested) != _COMPILE_CACHE_DIR:
            # first caller wins (the jax config is process-global), but a
            # second experiment asking for a DIFFERENT directory deserves to
            # know its setting is inert — its executables land in (and hit
            # from) the first directory
            import warnings

            warnings.warn(
                "persistent compilation cache already wired to "
                f"{_COMPILE_CACHE_DIR!r}; ignoring the requested "
                f"{os.path.abspath(requested)!r} (the jax cache config is "
                "process-global — first caller wins)",
                RuntimeWarning,
                stacklevel=2,
            )
        return _COMPILE_CACHE_DIR
    resolved = os.environ.get("KATIB_COMPILE_CACHE") or cache_dir
    if not resolved:
        return None
    resolved = os.path.abspath(resolved)
    try:
        os.makedirs(resolved, exist_ok=True)
    except OSError:
        return None
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", resolved)
    except Exception:
        return None
    try:
        # default jax threshold skips sub-second compiles — exactly the
        # small-model sweep programs this repo batches; cache everything
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass
    _COMPILE_CACHE_DIR = resolved
    from katib_tpu.utils import observability as obs

    obs.compile_cache_enabled.set(1.0)
    return resolved


class TrialResult:
    def __init__(
        self,
        condition: TrialCondition,
        message: str = "",
        failure_kind: FailureKind | None = None,
    ):
        self.condition = condition
        self.message = message
        # why the attempt failed (``utils.faults`` taxonomy) — the
        # orchestrator's retry loop re-runs TRANSIENT failures only
        self.failure_kind = failure_kind


def run_trial(
    trial: Trial,
    store: ObservationStore,
    objective,
    mesh=None,
    stop_event: threading.Event | None = None,
    injector=None,
    watchdog=None,
    drain_event: threading.Event | None = None,
) -> TrialResult:
    """Execute one trial to a terminal condition.  Never raises: failures
    become ``TrialCondition.FAILED`` with the traceback in ``message`` and
    their ``FailureKind`` classified (budget accounting needs failed trials
    recorded, not exceptions — reference ``experiment_controller.go:274-330``).

    ``injector`` (a ``faults.FaultInjector``) is the chaos seam: it fires
    inside this classification try-block, so injected faults take exactly
    the path a real preemption or shape error would.

    ``watchdog`` (``utils.watchdog.Watchdog``) arms hang detection when the
    trial carries ``progress_deadline_seconds``; ``drain_event`` is the
    orchestrator's checkpoint-and-exit request (preemption SIGTERM) — both
    observable to the train_fn through its context."""
    if mesh is not None:
        # a trial-axis-only mesh partitions cohort MEMBERS, not tensors: a
        # singleton (cohort fallback, transient-member rejoin) has no data
        # axis to shard over, so it trains on the default device layout
        from katib_tpu.parallel.mesh import serial_mesh

        mesh = serial_mesh(mesh)
    evaluator = RuleEvaluator(trial.spec.early_stopping_rules, objective)
    try:
        if injector is not None:
            injector.on_trial_attempt(trial)
            injector.apply_metrics_delay(trial, stop_event)
        if trial.spec.train_fn is not None:
            return _run_whitebox(
                trial, store, evaluator, objective, mesh, stop_event,
                injector=injector, watchdog=watchdog, drain_event=drain_event,
            )
        if trial.spec.command:
            return _run_blackbox(
                trial, store, evaluator, objective, stop_event,
                watchdog=watchdog, drain_event=drain_event,
            )
        return TrialResult(
            TrialCondition.FAILED,
            "trial has neither train_fn nor command",
            failure_kind=FailureKind.PERMANENT,
        )
    except Exception as e:
        return TrialResult(
            TrialCondition.FAILED,
            traceback.format_exc(limit=20),
            failure_kind=classify_exception(e),
        )


def _finalize(trial: Trial, store: ObservationStore, objective) -> TrialResult:
    """Post-run observation check: succeeded-but-no-objective-metric becomes
    MetricsUnavailable (reference ``newObservationLog`` +
    ``trial_controller.go:249-252``)."""
    obs = store.observation_for(trial.name, objective)
    if obs is None:
        return TrialResult(
            TrialCondition.METRICS_UNAVAILABLE,
            f"objective metric {objective.objective_metric_name!r} was never reported",
        )
    return TrialResult(TrialCondition.SUCCEEDED)


def _run_whitebox(
    trial: Trial,
    store: ObservationStore,
    evaluator: RuleEvaluator,
    objective,
    mesh,
    stop_event: threading.Event | None,
    injector=None,
    watchdog=None,
    drain_event: threading.Event | None = None,
) -> TrialResult:
    hang_event = threading.Event()
    compile_hang_event = threading.Event()
    heartbeat = None
    if watchdog is not None and trial.spec.progress_deadline_seconds:
        heartbeat = watchdog.register(
            trial.name,
            trial.spec.progress_deadline_seconds,
            on_hang=lambda _name: hang_event.set(),
        )
    # compile watchdog: the progress watchdog only measures step-to-step
    # cadence, so a jit compile (or first dispatch) that never completes
    # looks identical to a wedge.  Arm a one-shot budget that covers trace
    # -> compile -> first ctx.report(); the first beat disarms it.
    compile_hb = None
    if watchdog is not None and trial.spec.compile_deadline_seconds:

        def _on_compile_hang(_name: str) -> None:
            obs.compile_hangs.inc()
            compile_hang_event.set()
            hang_event.set()  # reuse the cooperative hang unwind path

        compile_hb = watchdog.register(
            f"compile:{trial.name}",
            trial.spec.compile_deadline_seconds,
            on_hang=_on_compile_hang,
        )

    # warm/cold first-step classification: the first ctx.report() marks the
    # first step boundary (trace + compile + first dispatch behind it); the
    # shape registry decides whether that compile should have been a cache
    # hit and feeds the hit/miss counters + warm-vs-cold histogram
    from katib_tpu import costmodel
    from katib_tpu.compile import registry as compile_registry

    first_step_sig = compile_registry.trial_signature(
        trial.spec.train_fn, trial, mesh
    )
    # dispatch tries a fetch before tracing: executables another host (or
    # an earlier process) published for this signature load here — marking
    # it warm and arming the model's resolve() seam — instead of compiling.
    # Best-effort: a miss, an unreadable tier, or no tier at all just
    # means the ordinary trace-and-compile path below.
    try:
        from katib_tpu.compile.artifacts import ARTIFACTS

        ARTIFACTS.fetch_family(first_step_sig)
    except Exception:
        pass
    started_holder = [get_clock().perf_counter()]
    first_step_seen = [False]
    last_beat = [0.0]
    cost_attrs: dict = {}

    def _beat() -> None:
        now = get_clock().perf_counter()
        if not first_step_seen[0]:
            first_step_seen[0] = True
            try:
                dt = now - started_holder[0]
                label = compile_registry.REGISTRY.note_first_step(
                    first_step_sig, dt
                )
                obs.trial_first_step_seconds.set(
                    dt,
                    phase="first_report",
                    cache=label,
                    workload=first_step_sig.program,
                )
            except Exception:
                pass  # classification is telemetry, never a trial failure
        else:
            # steady-state report interval (first interval folds compile —
            # skip it): combine the model's observed program cost with the
            # measured cadence into the live roofline gauges
            active = costmodel.active_cost()
            if active is not None:
                rec, per_report = active
                interval = now - last_beat[0]
                steps = max(1, rec.steps * per_report)
                attrs = costmodel.publish_dispatch(
                    rec, interval / steps, workload=first_step_sig.program
                )
                if attrs:
                    cost_attrs.update(attrs)
        # persist the program's XLA cost next to its compile signature
        # (idempotent; the model may observe only after its first epoch)
        active = costmodel.active_cost()
        if active is not None:
            try:
                compile_registry.REGISTRY.record_cost(
                    first_step_sig, active[0].as_dict()
                )
            except Exception:
                pass
        last_beat[0] = now
        if compile_hb is not None:
            # first metric report = first dispatch completed: compile is done
            compile_hb.close()
        if heartbeat is not None:
            heartbeat.beat()

    ctx = TrialContext(
        trial_name=trial.name,
        params=trial.params(),
        store=store,
        evaluator=evaluator,
        checkpoint_dir=trial.checkpoint_dir,
        mesh=mesh,
        labels=trial.spec.labels,
        stop_event=stop_event,
        max_runtime_seconds=trial.spec.max_runtime_seconds,
        drain_event=drain_event,
        hang_event=hang_event,
        # always wired: _beat also timestamps the first step boundary for
        # the warm/cold classification above
        heartbeat=_beat,
    )

    def _deadline_result() -> TrialResult:
        # a deadline blown once will blow again on an identical re-run —
        # never worth a transient retry
        return TrialResult(
            TrialCondition.FAILED,
            f"trial exceeded max_runtime_seconds={trial.spec.max_runtime_seconds}",
            failure_kind=FailureKind.PERMANENT,
        )

    def _hang_result() -> TrialResult:
        if compile_hang_event.is_set():
            return TrialResult(
                TrialCondition.FAILED,
                "compile watchdog: jit compile / first dispatch exceeded "
                f"compileDeadlineSeconds={trial.spec.compile_deadline_seconds}",
                failure_kind=FailureKind.COMPILE_HANG,
            )
        return TrialResult(
            TrialCondition.FAILED,
            "hang watchdog: no progress for "
            f"progress_deadline_seconds={trial.spec.progress_deadline_seconds}",
            failure_kind=FailureKind.HANG,
        )

    try:
        if injector is not None:
            # chaos 'compile-hang' action: wedge *before* the first report,
            # inside the compile budget — only the compile watchdog (or
            # stop/drain) can unwedge it
            injector.maybe_compile_hang(
                trial, events=(compile_hang_event, hang_event, stop_event, drain_event)
            )
            # chaos 'hang' action: wedge here like a stuck step; only the
            # watchdog / stop / drain machinery can unwedge it — and whichever
            # did decides the settlement (HANG / KILLED / DRAINED)
            injector.maybe_hang(trial, events=(hang_event, stop_event, drain_event))
            ctx.raise_if_stopped()
        # executor threads are reused: a previous trial's observed cost
        # must not leak into this trial's heartbeat publications
        costmodel.clear_active()
        started_holder[0] = get_clock().perf_counter()  # first-step clock starts here
        last_beat[0] = started_holder[0]
        with tracing.span("train_fn", trial=trial.name) as sp:
            trial.spec.train_fn(ctx)
            if cost_attrs:
                sp.set(**cost_attrs)
    except TrialEarlyStopped as e:
        if evaluator.triggered is not None:
            return TrialResult(TrialCondition.EARLY_STOPPED, str(e))
        if hang_event.is_set():
            return _hang_result()
        if ctx.deadline_exceeded():
            return _deadline_result()
        if ctx.drain_requested() and not (stop_event is not None and stop_event.is_set()):
            return TrialResult(
                TrialCondition.DRAINED, "checkpointed and exited for drain"
            )
        return TrialResult(TrialCondition.KILLED, str(e))
    except Exception as e:
        return TrialResult(
            TrialCondition.FAILED,
            traceback.format_exc(limit=20),
            failure_kind=classify_exception(e),
        )
    finally:
        if compile_hb is not None:
            compile_hb.close()
        if heartbeat is not None:
            heartbeat.close()
    if evaluator.should_stop():
        return TrialResult(TrialCondition.EARLY_STOPPED, evaluator.triggered.describe())
    if hang_event.is_set():
        return _hang_result()
    if ctx.deadline_exceeded():
        return _deadline_result()
    if stop_event is not None and stop_event.is_set():
        return TrialResult(TrialCondition.KILLED, "experiment reached terminal state")
    if ctx.drain_requested():
        # the train_fn unwound at a step boundary; its last checkpoint (if
        # any) is on disk and the resumed run re-submits this trial
        return TrialResult(TrialCondition.DRAINED, "checkpointed and exited for drain")
    return _finalize(trial, store, objective)


# one pattern for BOTH placeholder families so substitution is a single
# simultaneous pass over the template text — substituted values can never
# be re-expanded (a parameter value containing "${trialSpec...}" stays
# verbatim, and a label value containing "${trialParameters...}" does too)
_PLACEHOLDER = re.compile(
    r"\$\{trialParameters\.([^}]+)\}"
    r"|\$\{trialSpec\.([A-Za-z]+)(?:\[([^\]]+)\])?\}"
)


def _resolve_meta_ref(key: str, idx: str | None, raw: str, trial: Trial) -> str:
    """Trial-metadata references (reference ``manifest/generator.go:148-171``:
    Name/Namespace/Kind/APIVersion/Labels[k]/Annotations[k]).  TPU-native
    mapping: Namespace -> the owning experiment (the closest scoping
    construct), Kind/APIVersion -> this framework's type identity, and
    Annotations resolve from the same label map (trials here carry one
    metadata map, not two)."""
    if key == "Name":
        return trial.name
    if key == "Namespace":
        return trial.experiment_name
    if key == "Kind":
        return "Trial"
    if key == "APIVersion":
        return "katib-tpu/v1beta1"
    if key in ("Labels", "Annotations"):
        if idx is None or idx not in trial.spec.labels:
            raise ValueError(
                f"illegal trial metadata reference {raw}: "
                f"trial has no label {idx!r}"
            )
        return trial.spec.labels[idx]
    raise ValueError(f"illegal trial metadata reference {raw}")


def substitute_command(
    command: list[str], params: dict, trial: Trial | None = None
) -> list[str]:
    """Render ``${trialParameters.X}`` placeholders and — when the trial is
    given — ``${trialSpec.*}`` metadata references (reference
    ``manifest/generator.go:99`` applyParameters + meta keys :148-171)."""

    def sub(m: "re.Match[str]") -> str:
        if m.group(1) is not None:  # ${trialParameters.X}
            name = m.group(1)
            return str(params[name]) if name in params else m.group(0)
        if trial is None:
            return m.group(0)
        return _resolve_meta_ref(m.group(2), m.group(3), m.group(0), trial)

    return [_PLACEHOLDER.sub(sub, arg) for arg in command]


class _LineSource:
    """Incremental metric-line source for a running black-box trial."""

    def poll(self) -> list[str]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class _StdoutSource(_LineSource):
    """Drains the process's stdout on a reader thread (never blocks poll).

    When ``log_path`` is given every line is also persisted there — the
    analog of the reference wrapping the trainer as ``<cmd>
    1>/var/log/katib/metrics.log 2>&1`` (``pod/utils.go:199``) so the UI
    can serve trial logs after the pod is gone."""

    def __init__(self, proc: subprocess.Popen, log_path: str | None = None):
        self._lines: list[str] = []
        self._lock = threading.Lock()
        self._log = None
        if log_path:
            try:
                # line-buffered: each line reaches disk as it's drained, so
                # the log is servable while the trial runs and survives a
                # reader thread that never reaches EOF (orphaned pipe)
                self._log = open(log_path, "w", buffering=1, errors="replace")
            except OSError:
                self._log = None  # log capture is best-effort
        self._thread = get_clock().spawn(
            lambda: self._drain(proc), name="katib-stdout-drain", daemon=True
        )

    def _drain(self, proc: subprocess.Popen) -> None:
        assert proc.stdout is not None
        for line in proc.stdout:
            if self._log is not None:
                try:
                    self._log.write(line)
                except OSError:
                    pass
            with self._lock:
                self._lines.append(line)
        if self._log is not None:
            try:
                self._log.close()
            except OSError:
                pass

    def poll(self) -> list[str]:
        with self._lock:
            out, self._lines = self._lines, []
        return out

    def join(self, timeout: float) -> None:
        """Wait for the reader to hit EOF so the final poll sees every line
        the process printed before exiting."""
        self._thread.join(timeout)


class _FileTailSource(_LineSource):
    """Tails the metrics file the trial writes (sidecar watch parity,
    ``file-metricscollector/main.go:143``)."""

    def __init__(self, path: str):
        self._path = path
        self._offset = 0
        self._buffer = ""

    def poll(self) -> list[str]:
        if not os.path.exists(self._path):
            return []
        try:
            with open(self._path, errors="replace") as f:
                f.seek(self._offset)
                chunk = f.read()
                self._offset = f.tell()
        except OSError:
            return []
        self._buffer += chunk
        if "\n" not in self._buffer:
            return []
        *complete, self._buffer = self._buffer.split("\n")
        return complete

    def drain(self) -> list[str]:
        """Flush a trailing line without a newline (process has exited)."""
        rest, self._buffer = self._buffer, ""
        return [rest] if rest.strip() else []


class _PrometheusScraper:
    """Polls the trial's exposition endpoint at the configured cadence;
    reports a sample only when its value changed since the last scrape (each
    scrape is a snapshot, not a stream — dedup keeps the store a series)."""

    def __init__(self, collector, metric_names: list[str]):
        path = collector.path or "/metrics"
        if not path.startswith("/"):
            path = "/" + path
        port = collector.port or 8080
        self.url = f"http://127.0.0.1:{port}{path}"
        self.interval = max(0.05, collector.scrape_interval)
        self.metric_names = metric_names
        self._last_values: dict[str, float] = {}
        self._next_scrape = 0.0

    def poll(self):
        from katib_tpu.runner.metrics import parse_prometheus_samples

        now = get_clock().monotonic()
        if now < self._next_scrape:
            return []
        self._next_scrape = now + self.interval
        import urllib.request

        import http.client

        try:
            with urllib.request.urlopen(self.url, timeout=0.5) as r:
                text = r.read().decode(errors="replace")
        except (OSError, http.client.HTTPException):
            # endpoint not up yet / shutting down / half-closed socket
            # (BadStatusLine is not an OSError) — never fail the trial
            return []
        out = []
        # dedup per labelled series: two series of one base metric must not
        # re-emit each other's snapshots every scrape
        for key, log in parse_prometheus_samples(text, self.metric_names):
            if self._last_values.get(key) != log.value:
                self._last_values[key] = log.value
                out.append(log)
        return out


def _run_blackbox(
    trial: Trial,
    store: ObservationStore,
    evaluator: RuleEvaluator,
    objective,
    stop_event: threading.Event | None,
    watchdog=None,
    drain_event: threading.Event | None = None,
) -> TrialResult:
    collector = trial.spec.metrics_collector
    # the collector path renders like the command (per-trial file paths via
    # ${trialSpec.Name} keep parallel trials from clobbering each other's
    # metrics; the reference gets this isolation from per-pod emptyDirs)
    if collector.path:
        collector = dataclasses.replace(
            collector,
            path=substitute_command([collector.path], trial.params(), trial)[0],
        )
    metric_names = list(objective.all_metric_names())
    argv = substitute_command(trial.spec.command, trial.params(), trial)
    filters = [collector.filter] if collector.filter else []
    use_file = collector.path and collector.kind in (
        MetricsCollectorKind.FILE,
        MetricsCollectorKind.JSONL,
    )
    # TFEvent summaries are parsed once after exit (reference tfevent
    # collector semantics, ``tfevent-metricscollector/main.py:47-79``):
    # event files are binary, so there is no live line stream to tail
    tfevent_dir = (
        collector.path if collector.kind is MetricsCollectorKind.TFEVENT else None
    )

    prom = (
        _PrometheusScraper(collector, metric_names)
        if collector.kind is MetricsCollectorKind.PROMETHEUS
        else None
    )

    def parse(lines: list[str]):
        if (
            tfevent_dir
            or prom is not None
            or collector.kind is MetricsCollectorKind.NONE
        ):
            return []  # metrics come from event files / the endpoint, not stdout
        if collector.kind is MetricsCollectorKind.JSONL:
            # per-line so one malformed line (partial flush, stray diagnostic)
            # doesn't discard the valid lines polled in the same batch
            out = []
            for line in lines:
                try:
                    out.extend(parse_json_lines([line], metric_names))
                except ValueError:
                    continue
            return out
        return parse_text_lines_fast(lines, metric_names, filters)

    try:
        # start_new_session puts the trial in its own process group/session:
        # terminate/kill below signal the WHOLE group, so a trainer that
        # forks workers (data loaders, launchers) can't leave grandchildren
        # holding TPU devices after the trial is reaped
        proc = subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            errors="replace",
            bufsize=1,
            start_new_session=(os.name == "posix"),
        )
    except OSError as e:
        return TrialResult(
            TrialCondition.FAILED,
            f"failed to launch {argv[0]}: {e}",
            failure_kind=classify_exception(e),
        )
    launched_at = get_clock().perf_counter()

    # metrics come from exactly one source: the file when configured, else
    # stdout (no double-reporting); stdout is always drained to avoid blocking
    log_path = None
    if trial.checkpoint_dir:
        try:
            os.makedirs(trial.checkpoint_dir, exist_ok=True)
            log_path = os.path.join(trial.checkpoint_dir, "trial.log")
        except OSError:
            log_path = None
    stdout_source = _StdoutSource(proc, log_path=log_path)
    source: _LineSource = _FileTailSource(collector.path) if use_file else stdout_source

    early_stopped = False
    killed = False
    deadline_hit = False
    hanged = False
    drained = False
    deadline = (
        get_clock().monotonic() + trial.spec.max_runtime_seconds
        if trial.spec.max_runtime_seconds is not None
        else None
    )
    # hang watchdog: progress = any polled metric line OR the metrics file's
    # mtime moving (a trainer mid-epoch appends without completing a line);
    # a stall past progress_deadline_seconds SIGTERMs through the same
    # escalation as the deadline, classified FailureKind.HANG
    hang_event = threading.Event()
    heartbeat = None
    if watchdog is not None and trial.spec.progress_deadline_seconds:
        heartbeat = watchdog.register(
            trial.name,
            trial.spec.progress_deadline_seconds,
            on_hang=lambda _name: hang_event.set(),
        )
    last_mtime: float | None = None
    terminate_at: float | None = None
    try:
        while True:
            raw = source.poll()
            polled = parse(raw)
            if prom is not None:
                polled += prom.poll()
            if heartbeat is not None:
                progressed = bool(raw) or bool(polled)
                if use_file and not progressed:
                    try:
                        mtime = os.stat(collector.path).st_mtime
                        progressed = mtime != last_mtime
                        last_mtime = mtime
                    except OSError:
                        pass
                if progressed:
                    heartbeat.beat()
            for log in polled:
                store.report(trial.name, [log])
                if evaluator.observe(log.metric_name, log.value):
                    early_stopped = True
            if stop_event is not None and stop_event.is_set():
                killed = True
            if hang_event.is_set():
                hanged = True
            if drain_event is not None and drain_event.is_set():
                # ask the trainer to exit (its own SIGTERM handler may
                # checkpoint); the escalation below bounds a deaf one
                drained = True
            if deadline is not None and get_clock().monotonic() > deadline:
                # per-trial wall-clock bound: SIGTERM (then SIGKILL below) the
                # hung trial instead of pinning an orchestrator slot forever
                deadline_hit = True
            if (
                early_stopped or killed or deadline_hit or hanged or drained
            ) and terminate_at is None:
                _signal_group(proc, signal.SIGTERM)
                terminate_at = get_clock().monotonic()
            if terminate_at is not None and get_clock().monotonic() - terminate_at > 10.0:
                # SIGTERM ignored; escalate (classification unchanged)
                _signal_group(proc, signal.SIGKILL)
                terminate_at = float("inf")
            if proc.poll() is not None:
                break
            get_clock().sleep(0.05)
    finally:
        if heartbeat is not None:
            heartbeat.close()
    rc = proc.wait()
    tracing.record_span(
        "subprocess", get_clock().perf_counter() - launched_at, trial=trial.name, rc=rc
    )

    # final sweep for lines written right before exit (including a last line
    # with no trailing newline); the reader thread must reach EOF first or
    # buffered lines race the sweep and a reported metric is lost
    stdout_source.join(timeout=5.0)
    final_lines = source.poll()
    if isinstance(source, _FileTailSource):
        final_lines += source.drain()
    for log in parse(final_lines):
        store.report(trial.name, [log])
    if tfevent_dir:
        from katib_tpu.runner.tfevent import parse_tfevent_dir

        logs = parse_tfevent_dir(tfevent_dir, metric_names)
        if logs:
            store.report(trial.name, logs)

    if early_stopped:
        return TrialResult(TrialCondition.EARLY_STOPPED, evaluator.triggered.describe())
    if hanged:
        return TrialResult(
            TrialCondition.FAILED,
            "hang watchdog: no metric progress for "
            f"progress_deadline_seconds={trial.spec.progress_deadline_seconds}",
            failure_kind=FailureKind.HANG,
        )
    if deadline_hit:
        return TrialResult(
            TrialCondition.FAILED,
            f"trial exceeded max_runtime_seconds={trial.spec.max_runtime_seconds}",
        )
    if killed:
        return TrialResult(TrialCondition.KILLED, "experiment reached terminal state")
    if drained:
        return TrialResult(
            TrialCondition.DRAINED, "terminated for drain (resume re-runs it)"
        )
    if rc != 0:
        return TrialResult(
            TrialCondition.FAILED,
            f"exit code {rc}",
            failure_kind=classify_exit_code(rc),
        )
    return _finalize(trial, store, objective)


def _signal_group(proc: subprocess.Popen, sig: int) -> None:
    """Signal the trial's whole process group (the child is its own session
    leader, so ``pid == pgid``); fall back to the child alone when the group
    is already gone or group signalling is unsupported."""
    if os.name == "posix":
        try:
            os.killpg(proc.pid, sig)
            return
        except (ProcessLookupError, PermissionError, OSError):
            pass
    try:
        if sig == getattr(signal, "SIGKILL", None):
            proc.kill()
        else:
            proc.terminate()
    except OSError:
        pass
