"""Metrics-line parsing for black-box trials.

White-box JAX trials report metrics through a direct callback, so they never
touch this module.  Black-box subprocess trials (arbitrary-language training
scripts) write lines to stdout or a file, and this parser extracts metric
points — functional parity with the reference's file/stdout metrics-collector
sidecar (``pkg/metricscollector/v1beta1/file-metricscollector/file-metricscollector.go:45``)
minus the pod machinery (no shared-PID-namespace scans, ``$$$$.pid`` completion
markers or SIGTERM dances: the runner owns the subprocess handle directly).

Formats:
- TEXT: ``name=value`` pairs matched by a filter regex, optional leading
  RFC3339 timestamp (reference ``parseLogsInTextFormat``; default filter
  ``common/const.go:47``).
- JSON lines: one object per line; metric keys map to values, optional
  ``timestamp`` key (reference ``parseLogsInJsonFormat``).
"""

from __future__ import annotations

import json
import math
import re
import time
from datetime import datetime
from typing import Sequence

from katib_tpu.core.types import MetricLog

# Reference default filter (``pkg/metricscollector/v1beta1/common/const.go:47``):
# word-ish metric name, '=', float with optional sign/decimals/exponent.
DEFAULT_TEXT_FILTER = r"([\w|-]+)\s*=\s*([+-]?\d*(?:\.\d+)?(?:[Ee][+-]?\d+)?)"

# Reported when the objective metric never appears in the logs (reference
# ``consts.UnavailableMetricValue``); the orchestrator turns this into the
# MetricsUnavailable trial condition.
UNAVAILABLE_METRIC_VALUE = "unavailable"


def _parse_rfc3339(token: str) -> float | None:
    # RFC3339 requires a zone offset (the reference parses with Go
    # time.RFC3339); naive/date-only tokens are rejected, which also keeps
    # this parser in lockstep with the native C++ one.
    try:
        dt = datetime.fromisoformat(token.replace("Z", "+00:00"))
    except ValueError:
        return None
    if dt.tzinfo is None:
        return None
    return dt.timestamp()


def parse_text_lines(
    lines: Sequence[str],
    metric_names: Sequence[str],
    filters: Sequence[str] = (),
) -> list[MetricLog]:
    """Parse TEXT-format log lines into metric points.

    Only lines containing a tracked metric name are inspected; each filter
    regex must expose (name, value) capture groups; names not in
    ``metric_names`` are dropped (reference ``parseLogsInTextFormat``).
    """
    regs = [re.compile(f) for f in (filters or [DEFAULT_TEXT_FILTER])]
    names = set(metric_names)
    out: list[MetricLog] = []
    for line in lines:
        if not any(m in line for m in names):
            continue
        ts = 0.0
        head = line.split(" ", 1)[0]
        parsed = _parse_rfc3339(head) if head else None
        if parsed is not None:
            ts = parsed
        for reg in regs:
            for match in reg.finditer(line):
                if match.lastindex is None or match.lastindex < 2:
                    continue
                name = match.group(1).strip()
                raw = match.group(2).strip()
                if name not in names or not raw:
                    continue
                try:
                    value = float(raw)
                except ValueError:
                    continue
                out.append(MetricLog(metric_name=name, value=value, timestamp=ts))
    return out


def parse_json_lines(
    lines: Sequence[str], metric_names: Sequence[str]
) -> list[MetricLog]:
    """Parse JSON-lines logs; each line is an object whose keys may include
    tracked metric names and an optional ``timestamp`` (string RFC3339 or
    epoch number)."""
    out: list[MetricLog] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"failed to parse json log line: {line[:120]!r}") from e
        if not isinstance(obj, dict):
            continue
        ts = 0.0
        raw_ts = obj.get("timestamp")
        if isinstance(raw_ts, (int, float)):
            ts = float(raw_ts)
        elif isinstance(raw_ts, str):
            ts = _parse_rfc3339(raw_ts) or 0.0
        step = obj.get("step", -1)
        if not isinstance(step, int):
            step = -1
        for name in metric_names:
            if name not in obj:
                continue
            try:
                value = float(obj[name])
            except (TypeError, ValueError):
                continue
            out.append(MetricLog(metric_name=name, value=value, timestamp=ts, step=step))
    return out


_native_parser = None
_native_checked = False


def parse_text_lines_fast(
    lines: Sequence[str],
    metric_names: Sequence[str],
    filters: Sequence[str] = (),
) -> list[MetricLog]:
    """``parse_text_lines`` with the C++ fast path: the native parser handles
    the default filter; custom regex filters stay in Python."""
    global _native_parser, _native_checked
    if filters:
        return parse_text_lines(lines, metric_names, filters)
    if not _native_checked:
        _native_checked = True
        try:
            from katib_tpu.native import native_available

            if native_available():
                from katib_tpu.native.store import parse_text_lines_native

                _native_parser = parse_text_lines_native
        except Exception:
            _native_parser = None
    if _native_parser is not None:
        return _native_parser(lines, metric_names)
    return parse_text_lines(lines, metric_names)


_PROM_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([^\s]+)(?:\s+\d+)?$"
)


def parse_prometheus_samples(
    text: str, metric_names: Sequence[str]
) -> list[tuple[str, MetricLog]]:
    """Parse Prometheus exposition format (reference Prometheus collector
    kind, ``common_types.go:216-219``): ``name{labels} value [timestamp]``
    samples; comment/HELP/TYPE lines skipped; only tracked base names kept;
    NaN samples dropped like garbage TEXT values.

    Returns ``(series_key, log)`` pairs where the key includes the label set
    — scrapers must dedup per series, not per base name, or two labelled
    series of one metric re-emit forever."""
    names = set(metric_names)
    out: list[tuple[str, MetricLog]] = []
    ts = time.time()
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_SAMPLE.match(line)
        if m is None or m.group(1) not in names:
            continue
        try:
            value = float(m.group(3))
        except ValueError:
            continue
        if not math.isfinite(value):
            continue
        key = m.group(1) + (m.group(2) or "")
        out.append(
            (key, MetricLog(metric_name=m.group(1), value=value, timestamp=ts))
        )
    return out


def parse_prometheus_text(text: str, metric_names: Sequence[str]) -> list[MetricLog]:
    return [log for _, log in parse_prometheus_samples(text, metric_names)]


def objective_reported(logs: Sequence[MetricLog], objective_metric: str) -> bool:
    """Reference ``newObservationLog``: logs must contain at least one finite
    objective point, else the trial is MetricsUnavailable."""
    return any(
        l.metric_name == objective_metric and math.isfinite(l.value) for l in logs
    )


def now_metric(name: str, value: float, step: int = -1) -> MetricLog:
    return MetricLog(metric_name=name, value=value, timestamp=time.time(), step=step)
