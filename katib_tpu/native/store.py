"""ctypes wrappers over the native observation-log engine.

``NativeObservationStore`` is the in-RAM hot-path backend (same
Report/Get/Delete contract as the reference DB-manager,
``pkg/db/v1beta1/common/kdb.go:23``); ``parse_text_lines_native`` is the C++
TEXT parser used by the black-box metrics tail when the filter is the
reference default (custom regex filters fall back to the Python parser).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Callable, Iterable, Sequence

from katib_tpu.core.types import MetricLog
from katib_tpu.native.build import load_lib
from katib_tpu.store.base import ObservationStore


def _drain_query(lib, q) -> list[MetricLog]:
    try:
        n = lib.kt_query_len(q)
        if n == 0:
            return []
        blob = lib.kt_query_names_blob(q).decode()
        names = blob.split("\n")
        values = (ctypes.c_double * n)()
        ts = (ctypes.c_double * n)()
        steps = (ctypes.c_int64 * n)()
        lib.kt_query_values(q, values)
        lib.kt_query_timestamps(q, ts)
        lib.kt_query_steps(q, steps)
        return [
            MetricLog(metric_name=names[i], value=values[i], timestamp=ts[i], step=steps[i])
            for i in range(n)
        ]
    finally:
        lib.kt_query_free(q)


class NativeObservationStore(ObservationStore):
    """C++ append-log backend; thread safety lives in the C++ mutex, the
    Python side only guards its subscriber list."""

    def __init__(self) -> None:
        self._lib = load_lib()
        self._handle = self._lib.kt_store_new()
        self._sub_lock = threading.Lock()
        self._subscribers: list[Callable[[str, MetricLog], None]] = []

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kt_store_free(handle)
            self._handle = None

    def subscribe(self, fn: Callable[[str, MetricLog], None]) -> None:
        with self._sub_lock:
            self._subscribers.append(fn)

    def report(self, trial_name: str, logs: Iterable[MetricLog]) -> None:
        logs = list(logs)
        if not logs:
            return
        n = len(logs)
        metrics = (ctypes.c_char_p * n)(*[l.metric_name.encode() for l in logs])
        values = (ctypes.c_double * n)(*[l.value for l in logs])
        ts = (ctypes.c_double * n)(*[l.timestamp for l in logs])
        steps = (ctypes.c_int64 * n)(*[l.step for l in logs])
        self._lib.kt_store_report_batch(
            self._handle, trial_name.encode(), n, metrics, values, ts, steps
        )
        with self._sub_lock:
            subs = list(self._subscribers)
        for fn in subs:
            for log in logs:
                fn(trial_name, log)

    def get(self, trial_name: str, metric_name: str | None = None) -> list[MetricLog]:
        q = self._lib.kt_store_get(
            self._handle,
            trial_name.encode(),
            metric_name.encode() if metric_name else b"",
        )
        return _drain_query(self._lib, q)

    def delete(self, trial_name: str) -> None:
        self._lib.kt_store_delete(self._handle, trial_name.encode())

    def total_points(self) -> int:
        return self._lib.kt_store_total(self._handle)

    def trial_names(self) -> list[str]:
        q = self._lib.kt_store_trial_names(self._handle)
        return [l.metric_name for l in _drain_query(self._lib, q)]


def parse_text_lines_native(
    lines: Sequence[str], metric_names: Sequence[str]
) -> list[MetricLog]:
    """Native counterpart of ``runner.metrics.parse_text_lines`` for the
    default filter (``common/const.go:47`` semantics)."""
    lib = load_lib()
    # kt_parse_text takes a C string: strip stray NUL bytes (binary progress
    # bars, corrupted output) so they can't truncate the buffer mid-line
    text = "\n".join(lines).replace("\0", "").encode(errors="replace")
    tracked = "\n".join(metric_names).replace("\0", "").encode(errors="replace")
    return _drain_query(lib, lib.kt_parse_text(text, tracked))
