/* C ABI for the native observation-log engine.
 *
 * TPU-native equivalent of the reference's DB-manager storage core
 * (pkg/db/v1beta1/common/kdb.go:23 — Report/Get/DeleteObservationLog over
 * one table observation_logs(trial_name, id, time, metric_name, value)),
 * rebuilt as an in-process C++ append log: interned metric names, per-trial
 * insertion-ordered entry vectors, mutex-guarded for concurrent trial
 * runners.  Also hosts the TEXT metrics-line parser (the hot path of the
 * reference's file/stdout metrics-collector sidecar,
 * pkg/metricscollector/v1beta1/file-metricscollector/file-metricscollector.go:45).
 *
 * Query objects snapshot matching entries under the store lock, so readers
 * never see torn state; their pointers stay valid until kt_query_free.
 */
#ifndef KATIB_TPU_OBSLOG_H
#define KATIB_TPU_OBSLOG_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* kt_store_t;
typedef void* kt_query_t;

/* -- store ------------------------------------------------------------- */
kt_store_t kt_store_new(void);
void kt_store_free(kt_store_t s);

void kt_store_report(kt_store_t s, const char* trial, const char* metric,
                     double value, double ts, int64_t step);
void kt_store_report_batch(kt_store_t s, const char* trial, int32_t n,
                           const char** metrics, const double* values,
                           const double* ts, const int64_t* steps);

/* metric == NULL or "" -> all metrics, in report order */
kt_query_t kt_store_get(kt_store_t s, const char* trial, const char* metric);
void kt_store_delete(kt_store_t s, const char* trial);
int64_t kt_store_total(kt_store_t s);
/* query whose names are the trial names, in first-report order */
kt_query_t kt_store_trial_names(kt_store_t s);

/* -- query accessors ---------------------------------------------------- */
int32_t kt_query_len(kt_query_t q);
/* '\n'-joined names, built lazily, owned by the query */
const char* kt_query_names_blob(kt_query_t q);
void kt_query_values(kt_query_t q, double* out);
void kt_query_timestamps(kt_query_t q, double* out);
void kt_query_steps(kt_query_t q, int64_t* out);
void kt_query_free(kt_query_t q);

/* -- TEXT metrics parser ------------------------------------------------ */
/* Parse newline-separated log lines for `name=value` pairs where name is in
 * the '\n'-separated tracked set; leading RFC3339 token becomes the
 * timestamp.  Semantics match the reference default filter
 * ([\w|-]+)\s*=\s*([+-]?float). Returns a query (step = -1). */
kt_query_t kt_parse_text(const char* text, const char* tracked_names);

#ifdef __cplusplus
}
#endif

#endif /* KATIB_TPU_OBSLOG_H */
