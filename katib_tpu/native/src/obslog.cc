// Native observation-log engine + TEXT metrics parser.  See obslog.h.

#include "obslog.h"

#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Entry {
  int32_t name_id;
  double value;
  double ts;
  int64_t step;
};

struct Store {
  std::mutex mu;
  std::vector<std::string> names;  // id -> metric name
  std::unordered_map<std::string, int32_t> name_ids;
  std::unordered_map<std::string, std::vector<Entry>> trials;
  std::vector<std::string> trial_order;
  int64_t total = 0;

  int32_t intern(const std::string& name) {
    auto it = name_ids.find(name);
    if (it != name_ids.end()) return it->second;
    int32_t id = static_cast<int32_t>(names.size());
    names.push_back(name);
    name_ids.emplace(name, id);
    return id;
  }
};

struct Query {
  std::vector<std::string> names;
  std::vector<double> values;
  std::vector<double> ts;
  std::vector<int64_t> steps;
  std::string blob;
  bool blob_built = false;
};

Store* as_store(kt_store_t s) { return static_cast<Store*>(s); }
Query* as_query(kt_query_t q) { return static_cast<Query*>(q); }

}  // namespace

extern "C" {

kt_store_t kt_store_new(void) { return new Store(); }

void kt_store_free(kt_store_t s) { delete as_store(s); }

static void report_locked(Store* st, const char* trial, const char* metric,
                          double value, double ts, int64_t step) {
  auto it = st->trials.find(trial);
  if (it == st->trials.end()) {
    it = st->trials.emplace(trial, std::vector<Entry>()).first;
    st->trial_order.push_back(trial);
  }
  it->second.push_back(Entry{st->intern(metric), value, ts, step});
  st->total++;
}

void kt_store_report(kt_store_t s, const char* trial, const char* metric,
                     double value, double ts, int64_t step) {
  Store* st = as_store(s);
  std::lock_guard<std::mutex> lk(st->mu);
  report_locked(st, trial, metric, value, ts, step);
}

void kt_store_report_batch(kt_store_t s, const char* trial, int32_t n,
                           const char** metrics, const double* values,
                           const double* ts, const int64_t* steps) {
  Store* st = as_store(s);
  std::lock_guard<std::mutex> lk(st->mu);
  for (int32_t i = 0; i < n; ++i)
    report_locked(st, trial, metrics[i], values[i], ts[i], steps[i]);
}

kt_query_t kt_store_get(kt_store_t s, const char* trial, const char* metric) {
  Store* st = as_store(s);
  Query* q = new Query();
  bool filter = metric != nullptr && metric[0] != '\0';
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->trials.find(trial);
  if (it == st->trials.end()) return q;
  int32_t want = -1;
  if (filter) {
    auto nit = st->name_ids.find(metric);
    if (nit == st->name_ids.end()) return q;
    want = nit->second;
  }
  for (const Entry& e : it->second) {
    if (filter && e.name_id != want) continue;
    q->names.push_back(st->names[e.name_id]);
    q->values.push_back(e.value);
    q->ts.push_back(e.ts);
    q->steps.push_back(e.step);
  }
  return q;
}

void kt_store_delete(kt_store_t s, const char* trial) {
  Store* st = as_store(s);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->trials.find(trial);
  if (it == st->trials.end()) return;
  st->total -= static_cast<int64_t>(it->second.size());
  st->trials.erase(it);
  for (auto t = st->trial_order.begin(); t != st->trial_order.end(); ++t) {
    if (*t == trial) {
      st->trial_order.erase(t);
      break;
    }
  }
}

int64_t kt_store_total(kt_store_t s) {
  Store* st = as_store(s);
  std::lock_guard<std::mutex> lk(st->mu);
  return st->total;
}

kt_query_t kt_store_trial_names(kt_store_t s) {
  Store* st = as_store(s);
  Query* q = new Query();
  std::lock_guard<std::mutex> lk(st->mu);
  for (const std::string& t : st->trial_order) {
    q->names.push_back(t);
    q->values.push_back(0.0);
    q->ts.push_back(0.0);
    q->steps.push_back(0);
  }
  return q;
}

int32_t kt_query_len(kt_query_t q) {
  return static_cast<int32_t>(as_query(q)->names.size());
}

const char* kt_query_names_blob(kt_query_t q) {
  Query* qq = as_query(q);
  if (!qq->blob_built) {
    size_t total = 0;
    for (const std::string& n : qq->names) total += n.size() + 1;
    qq->blob.reserve(total);
    for (size_t i = 0; i < qq->names.size(); ++i) {
      if (i) qq->blob.push_back('\n');
      qq->blob += qq->names[i];
    }
    qq->blob_built = true;
  }
  return qq->blob.c_str();
}

void kt_query_values(kt_query_t q, double* out) {
  Query* qq = as_query(q);
  std::memcpy(out, qq->values.data(), qq->values.size() * sizeof(double));
}

void kt_query_timestamps(kt_query_t q, double* out) {
  Query* qq = as_query(q);
  std::memcpy(out, qq->ts.data(), qq->ts.size() * sizeof(double));
}

void kt_query_steps(kt_query_t q, int64_t* out) {
  Query* qq = as_query(q);
  std::memcpy(out, qq->steps.data(), qq->steps.size() * sizeof(int64_t));
}

void kt_query_free(kt_query_t q) { delete as_query(q); }

}  // extern "C"

// ---------------------------------------------------------------------------
// TEXT metrics parser
// ---------------------------------------------------------------------------

namespace {

bool is_wordish(char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_' || c == '|' || c == '-';
}

// Parse the float subset the reference filter accepts:
// [+-]? digits* (.digits+)? ([eE][+-]?digits+)? with >=1 mantissa digit.
// Returns chars consumed (0 = no match) and writes the value.
size_t parse_float(const char* p, const char* end, double* out) {
  const char* q = p;
  if (q < end && (*q == '+' || *q == '-')) q++;
  const char* mant = q;
  while (q < end && *q >= '0' && *q <= '9') q++;
  bool digits = q > mant;
  if (q < end && *q == '.') {
    const char* frac = q + 1;
    const char* r = frac;
    while (r < end && *r >= '0' && *r <= '9') r++;
    if (r > frac) {
      q = r;
      digits = true;
    }
  }
  if (!digits) return 0;
  if (q < end && (*q == 'e' || *q == 'E')) {
    const char* e = q + 1;
    if (e < end && (*e == '+' || *e == '-')) e++;
    const char* ed = e;
    while (e < end && *e >= '0' && *e <= '9') e++;
    if (e > ed) q = e;
  }
  std::string tok(p, q - p);
  *out = std::strtod(tok.c_str(), nullptr);
  return static_cast<size_t>(q - p);
}

// RFC3339 subset: YYYY-MM-DDThh:mm:ss[.frac](Z|±hh:mm).  Returns true and
// writes the epoch timestamp; matches the Python datetime.fromisoformat path
// for the full timestamp format log lines actually carry.
bool parse_rfc3339(const std::string& tok, double* out) {
  int y, mo, d, h, mi, s, n = 0;
  if (std::sscanf(tok.c_str(), "%4d-%2d-%2dT%2d:%2d:%2d%n", &y, &mo, &d, &h,
                  &mi, &s, &n) != 6 ||
      n < 19)
    return false;
  size_t i = static_cast<size_t>(n);
  double frac = 0.0;
  if (i < tok.size() && tok[i] == '.') {
    size_t fs = ++i;
    while (i < tok.size() && tok[i] >= '0' && tok[i] <= '9') i++;
    if (i == fs) return false;
    frac = std::strtod(("0." + tok.substr(fs, i - fs)).c_str(), nullptr);
  }
  long offset = 0;
  if (i < tok.size() && (tok[i] == 'Z' || tok[i] == 'z')) {
    i++;
  } else if (i < tok.size() && (tok[i] == '+' || tok[i] == '-')) {
    int oh, om;
    if (std::sscanf(tok.c_str() + i + 1, "%2d:%2d", &oh, &om) != 2)
      return false;
    offset = (oh * 3600L + om * 60L) * (tok[i] == '-' ? -1 : 1);
    i += 6;
  } else {
    return false;  // naive timestamps are ambiguous; treat as no timestamp
  }
  if (i != tok.size()) return false;
  std::tm tm{};
  tm.tm_year = y - 1900;
  tm.tm_mon = mo - 1;
  tm.tm_mday = d;
  tm.tm_hour = h;
  tm.tm_min = mi;
  tm.tm_sec = s;
  *out = static_cast<double>(timegm(&tm)) + frac - static_cast<double>(offset);
  return true;
}

}  // namespace

extern "C" kt_query_t kt_parse_text(const char* text,
                                    const char* tracked_names) {
  Query* q = new Query();
  std::unordered_map<std::string, bool> tracked;
  {
    const char* p = tracked_names;
    while (*p) {
      const char* nl = std::strchr(p, '\n');
      size_t len = nl ? static_cast<size_t>(nl - p) : std::strlen(p);
      if (len) tracked.emplace(std::string(p, len), true);
      if (!nl) break;
      p = nl + 1;
    }
  }

  const char* line = text;
  while (*line) {
    const char* nl = std::strchr(line, '\n');
    const char* end = nl ? nl : line + std::strlen(line);

    // leading whitespace-delimited token as RFC3339 timestamp
    double ts = 0.0;
    const char* sp = line;
    while (sp < end && *sp != ' ') sp++;
    if (sp > line) parse_rfc3339(std::string(line, sp - line), &ts);

    const char* p = line;
    while (p < end) {
      if (!is_wordish(*p)) {
        p++;
        continue;
      }
      const char* name_start = p;
      while (p < end && is_wordish(*p)) p++;
      std::string name(name_start, p - name_start);
      const char* after = p;
      while (after < end && (*after == ' ' || *after == '\t')) after++;
      if (after >= end || *after != '=') continue;  // resume after the token
      after++;
      while (after < end && (*after == ' ' || *after == '\t')) after++;
      double value;
      size_t used = parse_float(after, end, &value);
      if (used == 0) continue;
      p = after + used;
      if (tracked.find(name) == tracked.end()) continue;
      q->names.push_back(std::move(name));
      q->values.push_back(value);
      q->ts.push_back(ts);
      q->steps.push_back(-1);
    }
    if (!nl) break;
    line = nl + 1;
  }
  return q;
}
