// katib-db-manager — standalone native metrics daemon.
//
// TPU-native equivalent of the reference's Go DB-manager gRPC service
// (cmd/db-manager/v1beta1/main.go:51-70): a network front-end over the
// observation-log engine so trials in *other processes/hosts* (multi-host
// slice workers, black-box subprocess trials) can report metrics centrally.
// In-process trials skip this entirely and call the store directly.
//
// Protocol (all little-endian, one frame per request/response):
//   frame    := u32 payload_len, payload
//   request  := u8 op, body
//     op=1 REPORT: str16 trial, u32 n, n * (str16 metric, f64 value,
//                  f64 timestamp, i64 step)
//     op=2 GET:    str16 trial, str16 metric ("" = all)
//     op=3 DELETE: str16 trial
//     op=4 PING
//   response := u8 status (0=ok, 1=bad request), body
//     GET ok:  u32 n, n * (str16 metric, f64 value, f64 timestamp, i64 step)
//   str16    := u16 len, bytes
//
// Thread-per-connection over one mutex-guarded store; connections are
// long-lived (the Python client keeps one socket open per process).
//
// Durability (--db <path>): the reference's daemon fronts a persisted SQL
// table (pkg/db/v1beta1/mysql/mysql.go:67, schema mysql/init.go:35) — a
// crash loses nothing.  This daemon gets the same guarantee with an
// append-only frame journal: a mutation (REPORT/DELETE) first appends its
// raw request frame to the journal and flushes, then applies to the store
// — durable-before-applied-before-acked; startup replays the journal
// through the same request handler before listening.
// One serialization format for wire and disk, zero translation code.  A
// truncated tail frame (crash mid-append) is detected and trimmed on
// replay.  Without --db the daemon is the round-2 in-RAM service.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <csignal>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "obslog.h"

namespace {

kt_store_t g_store;
std::FILE* g_journal = nullptr;  // append handle; null = in-RAM mode
std::string g_journal_path;
bool g_journal_broken = false;  // unrecoverable append failure: reject writes
std::mutex g_journal_mu;

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  template <typename T>
  T get() {
    T v{};
    if (p + sizeof(T) > end) {
      ok = false;
      return v;
    }
    std::memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    return v;
  }

  std::string str16() {
    uint16_t n = get<uint16_t>();
    if (!ok || p + n > end) {
      ok = false;
      return {};
    }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

struct Writer {
  std::vector<uint8_t> buf;

  template <typename T>
  void put(T v) {
    size_t at = buf.size();
    buf.resize(at + sizeof(T));
    std::memcpy(buf.data() + at, &v, sizeof(T));
  }

  void str16(const std::string& s) {
    put<uint16_t>(static_cast<uint16_t>(s.size()));
    buf.insert(buf.end(), s.begin(), s.end());
  }
};

void handle_request(const std::vector<uint8_t>& req, Writer* out) {
  Reader r{req.data(), req.data() + req.size()};
  uint8_t op = r.get<uint8_t>();
  switch (op) {
    case 1: {  // REPORT
      std::string trial = r.str16();
      uint32_t n = r.get<uint32_t>();
      std::vector<std::string> metrics;
      std::vector<double> values, ts;
      std::vector<int64_t> steps;
      for (uint32_t i = 0; i < n && r.ok; ++i) {
        metrics.push_back(r.str16());
        values.push_back(r.get<double>());
        ts.push_back(r.get<double>());
        steps.push_back(r.get<int64_t>());
      }
      if (!r.ok) break;
      std::vector<const char*> cnames;
      for (const std::string& m : metrics) cnames.push_back(m.c_str());
      kt_store_report_batch(g_store, trial.c_str(),
                            static_cast<int32_t>(n), cnames.data(),
                            values.data(), ts.data(), steps.data());
      out->put<uint8_t>(0);
      return;
    }
    case 2: {  // GET
      std::string trial = r.str16();
      std::string metric = r.str16();
      if (!r.ok) break;
      kt_query_t q = kt_store_get(g_store, trial.c_str(), metric.c_str());
      int32_t n = kt_query_len(q);
      std::vector<double> values(n), ts(n);
      std::vector<int64_t> steps(n);
      if (n > 0) {
        kt_query_values(q, values.data());
        kt_query_timestamps(q, ts.data());
        kt_query_steps(q, steps.data());
      }
      const char* blob = kt_query_names_blob(q);
      out->put<uint8_t>(0);
      out->put<uint32_t>(static_cast<uint32_t>(n));
      const char* name = blob;
      for (int32_t i = 0; i < n; ++i) {
        const char* nl = std::strchr(name, '\n');
        size_t len = nl ? static_cast<size_t>(nl - name) : std::strlen(name);
        out->str16(std::string(name, len));
        out->put<double>(values[i]);
        out->put<double>(ts[i]);
        out->put<int64_t>(steps[i]);
        name = nl ? nl + 1 : name + len;
      }
      kt_query_free(q);
      return;
    }
    case 3: {  // DELETE
      std::string trial = r.str16();
      if (!r.ok) break;
      kt_store_delete(g_store, trial.c_str());
      out->put<uint8_t>(0);
      return;
    }
    case 4:  // PING
      out->put<uint8_t>(0);
      out->put<int64_t>(kt_store_total(g_store));
      return;
    default:
      break;
  }
  out->buf.clear();
  out->put<uint8_t>(1);
}

// Appends one frame; caller holds g_journal_mu.  Returns false when the
// append could not be made durable — the caller must NOT ack the request
// (acked == journaled is the whole guarantee).  A short write (ENOSPC,
// I/O error) is rolled back by truncating to the pre-write offset so it
// can't become a corrupt tail that replay would trim LATER good frames
// behind; if even the rollback fails the journal is marked broken and all
// further mutations are rejected while reads keep serving.
bool append_journal_locked(const std::vector<uint8_t>& frame) {
  if (g_journal_broken) return false;
  long start = std::ftell(g_journal);
  uint32_t len = static_cast<uint32_t>(frame.size());
  bool ok = std::fwrite(&len, sizeof(len), 1, g_journal) == 1 &&
            std::fwrite(frame.data(), 1, frame.size(), g_journal) ==
                frame.size() &&
            // flush to the OS so a killed daemon loses nothing (page cache
            // survives process death; only power loss needs fdatasync)
            std::fflush(g_journal) == 0;
  if (ok) return true;
  std::fprintf(stderr, "journal: append failed, rolling back\n");
  if (start < 0 || std::fflush(g_journal) != 0 ||
      ::truncate(g_journal_path.c_str(), start) != 0 ||
      std::fseek(g_journal, start, SEEK_SET) != 0) {
    std::fprintf(stderr, "journal: rollback failed — rejecting writes\n");
    g_journal_broken = true;
  }
  return false;
}

// Replays mutation frames from the journal into the fresh store.  Returns
// the byte offset of the last complete frame; a truncated tail (crash
// mid-append) is trimmed so subsequent appends can't corrupt the file.
void replay_journal(const char* path) {
  std::FILE* f = std::fopen(path, "rb");
  long valid_end = 0;
  long replayed = 0;
  if (f) {
    for (;;) {
      uint32_t len;
      if (std::fread(&len, sizeof(len), 1, f) != 1) break;
      if (len == 0 || len > (64u << 20)) break;  // corrupt header
      std::vector<uint8_t> req(len);
      if (std::fread(req.data(), 1, len, f) != len) break;
      Writer ignored;
      handle_request(req, &ignored);
      valid_end = std::ftell(f);
      ++replayed;
    }
    long file_end = 0;
    if (std::fseek(f, 0, SEEK_END) == 0) file_end = std::ftell(f);
    std::fclose(f);
    if (file_end != valid_end) {
      std::fprintf(stderr, "journal: trimming truncated tail (%ld -> %ld)\n",
                   file_end, valid_end);
      if (::truncate(path, valid_end) != 0) std::perror("truncate");
    }
  }
  g_journal_path = path;
  g_journal = std::fopen(path, "ab");
  if (!g_journal) {
    std::perror("journal open");
    std::exit(1);
  }
  std::printf("JOURNAL %ld frames, %lld points\n", replayed,
              static_cast<long long>(kt_store_total(g_store)));
}

void serve_connection(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint32_t len;
    if (!read_exact(fd, &len, sizeof(len))) break;
    if (len == 0 || len > (64u << 20)) break;  // 64 MiB frame cap
    std::vector<uint8_t> req(len);
    if (!read_exact(fd, req.data(), len)) break;
    Writer out;
    bool is_mutation = !req.empty() && (req[0] == 1 || req[0] == 3);
    if (g_journal && is_mutation) {
      // Journal-append and store-apply form ONE critical section, in that
      // order.  One lock: with concurrent connections, separate locks
      // could journal B's DELETE before A's REPORT while the store applied
      // them the other way — replay would then resurrect deleted points.
      // Journal FIRST: if the append fails nothing was applied, so the
      // live store never diverges from what a restart would rebuild (a
      // malformed frame that journals then no-ops replays as the same
      // no-op).  Reads bypass this lock (the store has its own mutex).
      std::lock_guard<std::mutex> lock(g_journal_mu);
      if (append_journal_locked(req)) {
        handle_request(req, &out);
      } else {
        out.put<uint8_t>(1);  // not durable -> not applied -> not acked
      }
    } else {
      handle_request(req, &out);
    }
    uint32_t olen = static_cast<uint32_t>(out.buf.size());
    if (!write_exact(fd, &olen, sizeof(olen)) ||
        !write_exact(fd, out.buf.data(), olen))
      break;
  }
  ::close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = "127.0.0.1";
  const char* db_path = nullptr;
  int port = 0;
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "--port")) port = std::atoi(argv[i + 1]);
    if (!std::strcmp(argv[i], "--host")) host = argv[i + 1];
    if (!std::strcmp(argv[i], "--db")) db_path = argv[i + 1];
  }
  ::signal(SIGPIPE, SIG_IGN);
  g_store = kt_store_new();
  if (db_path) replay_journal(db_path);

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) {
    std::perror("socket");
    return 1;
  }
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    std::fprintf(stderr, "bad host %s\n", host);
    return 1;
  }
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    std::perror("bind");
    return 1;
  }
  if (::listen(lfd, 64) < 0) {
    std::perror("listen");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  // the spawn helper reads this line to learn the ephemeral port
  std::printf("LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);

  for (;;) {
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) continue;
    std::thread(serve_connection, cfd).detach();
  }
}
