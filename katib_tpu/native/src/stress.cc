// Race-detection stress harness for the native runtime, built with
// ThreadSanitizer (`make tsan` -> build/katib-native-stress).
//
// The reference ships no race detection at all (its `make test` runs
// without -race — SURVEY §5); here the two concurrent-by-design native
// components get hammered under TSan:
//
//   1. observation store: N writer threads reporting interleaved with
//      reader threads snapshotting queries and a deleter thread — the
//      exact shape of parallel trial runners + UI reads + retention.
//   2. batch loader: gather workers racing the consumer across epoch
//      turnovers (permutation rebuild) and shutdown mid-stream.
//
// Exit 0 = no data race reported (TSan aborts the process otherwise).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "obslog.h"

extern "C" {
void* ktl_open(const char* path, uint64_t record_bytes, uint64_t n_records,
               uint64_t batch, uint64_t seed, uint32_t n_threads,
               uint32_t queue_cap);
int64_t ktl_next(void* h, uint8_t* out);
uint64_t ktl_batches_per_epoch(void* h);
void ktl_close(void* h);
}

static void stress_store() {
  kt_store_t s = kt_store_new();
  std::atomic<bool> stop{false};

  std::vector<std::thread> threads;
  for (int w = 0; w < 4; ++w) {
    threads.emplace_back([&, w] {
      char trial[32];
      snprintf(trial, sizeof trial, "trial-%d", w);
      for (int i = 0; i < 2000; ++i)
        kt_store_report(s, trial, i % 2 ? "accuracy" : "loss", i * 0.5,
                        1000.0 + i, i);
    });
  }
  for (int r = 0; r < 2; ++r) {
    threads.emplace_back([&] {
      while (!stop.load()) {
        kt_query_t q = kt_store_get(s, "trial-1", "accuracy");
        int32_t n = kt_query_len(q);
        if (n > 0) {
          std::vector<double> vals(n);
          kt_query_values(q, vals.data());
        }
        kt_query_free(q);
        kt_query_t names = kt_store_trial_names(s);
        kt_query_names_blob(names);
        kt_query_free(names);
      }
    });
  }
  threads.emplace_back([&] {
    for (int i = 0; i < 200; ++i) kt_store_delete(s, "trial-3");
  });

  for (int w = 0; w < 4; ++w) threads[w].join();
  stop.store(true);
  for (size_t i = 4; i < threads.size(); ++i) threads[i].join();
  // sanity: every surviving write landed.  trial-3 raced the deleter (any
  // suffix of its writes may remain), but trials 0-2 must hold exactly
  // their 2000 entries — a lost update means a race even if TSan missed it.
  for (int w = 0; w < 3; ++w) {
    char trial[32];
    snprintf(trial, sizeof trial, "trial-%d", w);
    kt_query_t q = kt_store_get(s, trial, nullptr);
    int32_t got = kt_query_len(q);
    kt_query_free(q);
    if (got != 2000) {
      fprintf(stderr, "store stress: LOST UPDATES, %s has %d/2000\n", trial, got);
      exit(2);
    }
  }
  long long total = (long long)kt_store_total(s);
  if (total < 6000 || total > 8000) {
    fprintf(stderr, "store stress: impossible total=%lld\n", total);
    exit(2);
  }
  kt_store_free(s);
  printf("store stress: total=%lld\n", total);
}

static void stress_loader(const char* tmpdir) {
  const uint64_t record = 64, n = 1000, batch = 32;
  std::string path = std::string(tmpdir) + "/stress.bin";
  {
    FILE* f = fopen(path.c_str(), "wb");
    if (!f) { perror("fopen"); exit(2); }
    std::vector<uint8_t> buf(record * n);
    for (size_t i = 0; i < buf.size(); ++i) buf[i] = (uint8_t)(i * 31);
    fwrite(buf.data(), 1, buf.size(), f);
    fclose(f);
  }
  // normal consumption across several epoch turnovers
  void* h = ktl_open(path.c_str(), record, n, batch, 42, 4, 8);
  if (!h) { fprintf(stderr, "ktl_open failed\n"); exit(2); }
  uint64_t bpe = ktl_batches_per_epoch(h);
  std::vector<uint8_t> out(batch * record);
  for (uint64_t i = 0; i < bpe * 5; ++i)
    if (ktl_next(h, out.data()) != (int64_t)batch) { exit(2); }
  ktl_close(h);

  // shutdown mid-stream while workers are producing
  for (int round = 0; round < 5; ++round) {
    void* h2 = ktl_open(path.c_str(), record, n, batch, round, 4, 4);
    if (!h2) exit(2);
    for (int i = 0; i < round * 3; ++i) ktl_next(h2, out.data());
    ktl_close(h2);  // workers must wind down cleanly mid-epoch
  }
  printf("loader stress: ok (bpe=%llu)\n", (unsigned long long)bpe);
}

int main(int argc, char** argv) {
  const char* tmpdir = argc > 1 ? argv[1] : "/tmp";
  stress_store();
  stress_loader(tmpdir);
  printf("native stress: PASS\n");
  return 0;
}
