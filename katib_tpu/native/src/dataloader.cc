// Native batch loader: mmap'd fixed-size records, per-epoch deterministic
// shuffle, multi-threaded gather with in-order delivery through a bounded
// slot ring.  The TPU-native analog of the reference trial images' native
// input pipelines (torch DataLoader workers / tf.data) — host-side batch
// assembly overlaps with device compute so the step loop never waits on
// Python to gather a shuffled batch.
//
// C API (ctypes-friendly, see native/dataloader.py):
//   ktl_open(path, record_bytes, n_records, batch, seed, threads, queue_cap,
//            start_epoch)
//   ktl_next(h, out)  -> records copied (always == batch; -1 on error).
//                        The stream is epoch-continuous: consume exactly
//                        ktl_batches_per_epoch(h) batches per epoch.
//   ktl_epoch(h)      -> epoch index of the NEXT batch to be delivered
//   ktl_batches_per_epoch(h)
//   ktl_close(h)
//
// Determinism: epoch e uses a Fisher-Yates permutation seeded with
// splitmix64(seed, e); delivery order equals permutation order regardless
// of worker count, so tests can assert exact batch contents.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// splitmix64: tiny, well-mixed; good enough for shuffling
static inline uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Slot {
  std::vector<uint8_t> data;
  uint64_t seq = UINT64_MAX;  // which batch occupies the slot
  bool ready = false;
};

struct Loader {
  // immutable after open
  const uint8_t* base = nullptr;
  size_t map_len = 0;
  uint64_t record_bytes = 0, n_records = 0, batch = 0, seed = 0;
  uint64_t batches_per_epoch = 0;
  uint32_t queue_cap = 0;

  // permutation of the CURRENT producing epoch
  std::vector<uint64_t> perm;
  uint64_t perm_epoch = UINT64_MAX;

  std::mutex mu;
  std::condition_variable cv_workers, cv_consumer;
  std::vector<Slot> slots;
  uint64_t next_produce = 0;  // global batch sequence to claim next
  uint64_t next_consume = 0;  // global batch sequence the consumer wants
  bool stopping = false;
  std::vector<std::thread> workers;

  ~Loader() {
    {
      std::lock_guard<std::mutex> g(mu);
      stopping = true;
    }
    cv_workers.notify_all();
    cv_consumer.notify_all();
    for (auto& t : workers) t.join();
    if (base) munmap(const_cast<uint8_t*>(base), map_len);
  }

  void ensure_perm(uint64_t epoch) {  // caller holds mu
    if (perm_epoch == epoch) return;
    if (perm.size() != n_records) {
      perm.resize(n_records);
    }
    for (uint64_t i = 0; i < n_records; ++i) perm[i] = i;
    uint64_t s = mix64(seed ^ mix64(epoch));
    for (uint64_t i = n_records - 1; i > 0; --i) {
      s = mix64(s);
      uint64_t j = s % (i + 1);
      std::swap(perm[i], perm[j]);
    }
    perm_epoch = epoch;
  }

  void worker() {
    std::unique_lock<std::mutex> lk(mu);
    while (true) {
      // claim the next batch seq whose slot is free for writing
      while (!stopping && next_produce >= next_consume + queue_cap)
        cv_workers.wait(lk);
      if (stopping) return;
      uint64_t seq = next_produce++;
      uint64_t epoch = seq / batches_per_epoch;
      uint64_t b = seq % batches_per_epoch;
      ensure_perm(epoch);  // producers run ahead at most queue_cap batches,
                           // within one epoch boundary handled below
      // copy the indices we need while holding the lock (perm mutates at
      // epoch turnover); the record gather itself runs unlocked.  The slot
      // buffer is pre-sized at open and exclusively ours until `ready`
      // (the claim guard proves its previous occupant was consumed), so
      // gathering straight into it avoids per-batch allocation.
      std::vector<uint64_t> idx(perm.begin() + b * batch,
                                perm.begin() + (b + 1) * batch);
      Slot& slot = slots[seq % queue_cap];
      lk.unlock();

      for (uint64_t r = 0; r < batch; ++r)
        memcpy(slot.data.data() + r * record_bytes, base + idx[r] * record_bytes,
               record_bytes);

      lk.lock();
      slot.seq = seq;
      slot.ready = true;
      cv_consumer.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* ktl_open(const char* path, uint64_t record_bytes, uint64_t n_records,
               uint64_t batch, uint64_t seed, uint32_t n_threads,
               uint32_t queue_cap, uint64_t start_epoch) {
  if (record_bytes == 0 || n_records == 0 || batch == 0 || batch > n_records)
    return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 ||
      static_cast<uint64_t>(st.st_size) < record_bytes * n_records) {
    close(fd);
    return nullptr;
  }
  void* m = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (m == MAP_FAILED) return nullptr;

  auto* L = new Loader();
  L->base = static_cast<const uint8_t*>(m);
  L->map_len = st.st_size;
  L->record_bytes = record_bytes;
  L->n_records = n_records;
  L->batch = batch;
  L->seed = seed;
  L->batches_per_epoch = n_records / batch;  // drop-last semantics
  // Resume support: start the global batch sequence at `start_epoch` so a
  // restarted run consumes epoch k's permutation (seeded (seed, k)), not a
  // positional replay of epoch 0.  Set before workers spawn — no racing
  // producers exist yet, so no slot-reclaim protocol is needed.
  L->next_produce = L->next_consume = start_epoch * L->batches_per_epoch;
  if (n_threads == 0) n_threads = 2;
  if (queue_cap < n_threads) queue_cap = n_threads * 2;
  L->queue_cap = queue_cap;
  L->slots.resize(queue_cap);
  for (auto& s : L->slots) s.data.resize(batch * record_bytes);
  for (uint32_t i = 0; i < n_threads; ++i)
    L->workers.emplace_back(&Loader::worker, L);
  return L;
}

// Returns records copied into `out` (always == batch); the stream is
// epoch-continuous (epoch e+1 follows e with a fresh permutation) and the
// caller slices epochs by counting ktl_batches_per_epoch() deliveries.
int64_t ktl_next(void* h, uint8_t* out) {
  auto* L = static_cast<Loader*>(h);
  if (!L || !out) return -1;
  std::unique_lock<std::mutex> lk(L->mu);
  uint64_t seq = L->next_consume;
  Slot& slot = L->slots[seq % L->queue_cap];
  L->cv_consumer.wait(lk, [&] {
    return L->stopping || (slot.ready && slot.seq == seq);
  });
  if (L->stopping) return -1;
  memcpy(out, slot.data.data(), L->batch * L->record_bytes);
  slot.ready = false;
  slot.seq = UINT64_MAX;
  L->next_consume = seq + 1;
  L->cv_workers.notify_all();
  return static_cast<int64_t>(L->batch);
}

uint64_t ktl_epoch(void* h) {
  auto* L = static_cast<Loader*>(h);
  std::lock_guard<std::mutex> g(L->mu);
  return L->next_consume / L->batches_per_epoch;
}

uint64_t ktl_batches_per_epoch(void* h) {
  auto* L = static_cast<Loader*>(h);
  return L->batches_per_epoch;
}

void ktl_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
