"""ctypes wrapper for the native batch loader (``src/dataloader.cc``).

``NativeBatchLoader`` streams deterministically-shuffled (x, y) batches from
a memory-mapped record file with C++ worker threads doing the gather —
host-side batch assembly overlaps device compute, the TPU-native answer to
the reference trial images' torch-DataLoader/tf.data input pipelines.

The record file is built once per (dataset, cache_dir) by ``pack_dataset``:
each record is one sample's image bytes followed by its label bytes,
contiguous, so a batch gather is ``batch`` memcpys from the mapping.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from katib_tpu.native.build import ensure_built, load_lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.ktl_open.restype = ctypes.c_void_p
    lib.ktl_open.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64,
    ]
    lib.ktl_next.restype = ctypes.c_int64
    lib.ktl_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ktl_epoch.restype = ctypes.c_uint64
    lib.ktl_epoch.argtypes = [ctypes.c_void_p]
    lib.ktl_batches_per_epoch.restype = ctypes.c_uint64
    lib.ktl_batches_per_epoch.argtypes = [ctypes.c_void_p]
    lib.ktl_close.restype = None
    lib.ktl_close.argtypes = [ctypes.c_void_p]
    return lib


def pack_dataset(x: np.ndarray, y: np.ndarray, path: str) -> tuple[int, int]:
    """Write (x[i] || y[i]) records to ``path``; returns (record_bytes, n).

    An existing file of exactly the expected size is reused without
    rewriting (size-only heuristic — callers that pack DIFFERENT data of
    identical shape to the same path must remove the file first; the
    framework's own cache paths are per-run temp dirs, so reuse only ever
    sees the same arrays)."""
    x = np.ascontiguousarray(x)
    y = np.ascontiguousarray(y)
    n = len(x)
    assert len(y) == n and n > 0
    record_bytes = (x.nbytes + y.nbytes) // n
    try:
        if os.path.getsize(path) == record_bytes * n:
            return record_bytes, n
    except OSError:
        pass
    xb = x.reshape(n, -1).view(np.uint8).reshape(n, -1)
    yb = y.reshape(n, -1).view(np.uint8).reshape(n, -1)
    rec = np.concatenate([xb, yb], axis=1)
    tmp = path + ".tmp"
    rec.tofile(tmp)
    os.replace(tmp, path)
    return rec.shape[1], n


class NativeBatchLoader:
    """Iterate epochs of shuffled batches gathered by C++ worker threads.

    Deterministic: epoch ``e`` of a loader with seed ``s`` always yields the
    same batches in the same order, independent of thread count.
    """

    def __init__(
        self,
        x: np.ndarray,
        y: np.ndarray,
        *,
        batch: int,
        seed: int = 0,
        cache_path: str,
        n_threads: int = 2,
        queue_cap: int = 8,
        start_epoch: int = 0,
    ):
        if not ensure_built():
            raise RuntimeError("native runtime unavailable (no C++ toolchain)")
        self._lib = _bind(load_lib())
        self.x_shape = x.shape[1:]
        self.x_dtype = x.dtype
        self.y_shape = y.shape[1:]
        self.y_dtype = y.dtype
        self._x_bytes = int(np.prod(self.x_shape, dtype=np.int64)) * x.dtype.itemsize
        self._y_bytes = (
            int(np.prod(self.y_shape, dtype=np.int64) or 1) * y.dtype.itemsize
        )
        self.batch = batch
        record_bytes, n = pack_dataset(x, y, cache_path)
        assert record_bytes == self._x_bytes + self._y_bytes
        # start_epoch: a resumed run opens at its restored epoch so the
        # first .epoch() yields that epoch's (seed, epoch)-keyed shuffle,
        # not a positional replay of epoch 0
        self._h = self._lib.ktl_open(
            cache_path.encode(), record_bytes, n, batch, seed, n_threads,
            queue_cap, start_epoch,
        )
        if not self._h:
            raise RuntimeError(f"ktl_open failed for {cache_path}")
        self._record_bytes = record_bytes

    @property
    def batches_per_epoch(self) -> int:
        return int(self._lib.ktl_batches_per_epoch(self._h))

    @property
    def epoch_index(self) -> int:
        """Epoch of the next batch to be delivered."""
        return int(self._lib.ktl_epoch(self._h))

    def epoch(self):
        """Yield this epoch's (x, y) batches (drop-last semantics)."""
        for _ in range(self.batches_per_epoch):
            # C++ gathers straight into this batch's numpy allocation —
            # no intermediate staging buffer copy on the hot path
            raw = np.empty((self.batch, self._record_bytes), dtype=np.uint8)
            got = self._lib.ktl_next(
                self._h, raw.ctypes.data_as(ctypes.c_char_p)
            )
            if got != self.batch:
                raise RuntimeError(f"native loader returned {got}")
            xb = (
                raw[:, : self._x_bytes]
                .copy()
                .view(self.x_dtype)
                .reshape(self.batch, *self.x_shape)
            )
            yb = (
                raw[:, self._x_bytes:]
                .copy()
                .view(self.y_dtype)
                .reshape(self.batch, *self.y_shape)
                if self.y_shape
                else raw[:, self._x_bytes:].copy().view(self.y_dtype).reshape(self.batch)
            )
            yield xb, yb

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ktl_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
