"""Build/load machinery for the native runtime.

``ensure_built()`` compiles ``src/`` with the vendored Makefile into
``build/`` the first time it's needed (or when sources changed) and caches
the result; everything degrades gracefully — callers use
``native_available()`` and fall back to the pure-Python implementations when
no C++ toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
LIB_PATH = os.path.join(_BUILD, "libkatibnative.so")
DBMANAGER_PATH = os.path.join(_BUILD, "katib-db-manager")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _stale() -> bool:
    targets = (LIB_PATH, DBMANAGER_PATH)
    if not all(os.path.exists(t) for t in targets):
        return True
    try:
        newest_src = max(
            os.path.getmtime(os.path.join(_DIR, "src", f))
            for f in os.listdir(os.path.join(_DIR, "src"))
        )
    except (OSError, ValueError):
        # prebuilt artifacts shipped without src/: usable as-is
        return False
    return any(os.path.getmtime(t) < newest_src for t in targets)


def ensure_built() -> bool:
    """Compile if needed; returns True when the native artifacts exist."""
    global _build_error
    with _lock:
        if _build_error is not None:
            return False
        if not _stale():
            return True
        try:
            proc = subprocess.run(
                ["make", "-C", _DIR],
                capture_output=True,
                text=True,
                timeout=120,
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            _build_error = str(e)
            return False
        if proc.returncode != 0:
            _build_error = proc.stderr[-2000:]
            return False
        return True


def build_error() -> str | None:
    return _build_error


def native_available() -> bool:
    return ensure_built()


def load_lib() -> ctypes.CDLL:
    """Load (building if necessary) and declare the C ABI."""
    global _lib
    if _lib is not None:
        return _lib
    if not ensure_built():
        raise RuntimeError(f"native build failed: {_build_error}")
    lib = ctypes.CDLL(LIB_PATH)

    c = ctypes
    lib.kt_store_new.restype = c.c_void_p
    lib.kt_store_new.argtypes = []
    lib.kt_store_free.argtypes = [c.c_void_p]
    lib.kt_store_report.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char_p, c.c_double, c.c_double, c.c_int64,
    ]
    lib.kt_store_report_batch.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int32,
        c.POINTER(c.c_char_p), c.POINTER(c.c_double),
        c.POINTER(c.c_double), c.POINTER(c.c_int64),
    ]
    lib.kt_store_get.restype = c.c_void_p
    lib.kt_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p]
    lib.kt_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    lib.kt_store_total.restype = c.c_int64
    lib.kt_store_total.argtypes = [c.c_void_p]
    lib.kt_store_trial_names.restype = c.c_void_p
    lib.kt_store_trial_names.argtypes = [c.c_void_p]
    lib.kt_query_len.restype = c.c_int32
    lib.kt_query_len.argtypes = [c.c_void_p]
    lib.kt_query_names_blob.restype = c.c_char_p
    lib.kt_query_names_blob.argtypes = [c.c_void_p]
    lib.kt_query_values.argtypes = [c.c_void_p, c.POINTER(c.c_double)]
    lib.kt_query_timestamps.argtypes = [c.c_void_p, c.POINTER(c.c_double)]
    lib.kt_query_steps.argtypes = [c.c_void_p, c.POINTER(c.c_int64)]
    lib.kt_query_free.argtypes = [c.c_void_p]
    lib.kt_parse_text.restype = c.c_void_p
    lib.kt_parse_text.argtypes = [c.c_char_p, c.c_char_p]

    _lib = lib
    return lib
