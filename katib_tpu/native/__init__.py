"""Native (C++) runtime components.

- ``NativeObservationStore`` — in-RAM append-log metrics engine (ctypes).
- ``parse_text_lines_native`` — C++ TEXT metrics parser (default filter).
- ``spawn_db_manager`` / ``RemoteObservationStore`` — standalone metrics
  daemon + wire client, the cross-process parity of the reference's
  DB-manager gRPC service.
- ``NativeBatchLoader`` / ``pack_dataset`` — mmap'd prefetching batch
  loader (C++ worker threads gather shuffled batches; the torch-DataLoader
  analog for the white-box JAX trial loop).

Everything degrades gracefully: ``native_available()`` is False when no C++
toolchain is present and callers fall back to the pure-Python backends.
"""

from katib_tpu.native.build import build_error, ensure_built, native_available

__all__ = [
    "NativeBatchLoader",
    "NativeObservationStore",
    "RemoteObservationStore",
    "build_error",
    "ensure_built",
    "native_available",
    "pack_dataset",
    "parse_text_lines_native",
    "spawn_db_manager",
]


def __getattr__(name):  # lazy: importing the package must not trigger a build
    if name == "NativeObservationStore":
        from katib_tpu.native.store import NativeObservationStore

        return NativeObservationStore
    if name == "parse_text_lines_native":
        from katib_tpu.native.store import parse_text_lines_native

        return parse_text_lines_native
    if name in ("RemoteObservationStore", "spawn_db_manager"):
        from katib_tpu.native import dbmanager

        return getattr(dbmanager, name)
    if name in ("NativeBatchLoader", "pack_dataset"):
        from katib_tpu.native import dataloader

        return getattr(dataloader, name)
    raise AttributeError(name)
