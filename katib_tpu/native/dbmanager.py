"""Client + spawn helper for the native katib-db-manager daemon.

The daemon (``src/dbmanager.cc``) is the cross-process metrics front door —
parity with the reference's standalone DB-manager gRPC service
(``cmd/db-manager/v1beta1/main.go:51-70``).  Multi-host slice workers and
black-box trials in other processes report through ``RemoteObservationStore``;
in-process trials bypass it entirely.

Wire protocol: length-prefixed little-endian frames (documented in
``dbmanager.cc``).
"""

from __future__ import annotations

import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Iterable

from katib_tpu.core.types import MetricLog
from katib_tpu.native.build import DBMANAGER_PATH, ensure_built
from katib_tpu.store.base import ObservationStore

_OP_REPORT, _OP_GET, _OP_DELETE, _OP_PING = 1, 2, 3, 4


def _str16(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<H", len(b)) + b


class RemoteObservationStore(ObservationStore):
    """Observation store speaking the db-manager wire protocol over one
    persistent socket (reconnects on failure)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6789, timeout: float = 10.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    # -- wire ---------------------------------------------------------------

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port), timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _recv_exact(self, sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("db-manager closed connection")
            buf += chunk
        return buf

    def _call(self, payload: bytes) -> bytes:
        with self._lock:
            for attempt in (0, 1):  # one reconnect retry on a stale socket
                if self._sock is None:
                    self._sock = self._connect()
                sent = False
                try:
                    self._sock.sendall(struct.pack("<I", len(payload)) + payload)
                    sent = True
                    (rlen,) = struct.unpack("<I", self._recv_exact(self._sock, 4))
                    resp = self._recv_exact(self._sock, rlen)
                    break
                except (OSError, ConnectionError):
                    try:
                        self._sock.close()
                    finally:
                        self._sock = None
                    # Retrying is only safe when the frame never went out: a
                    # send failure means the daemon saw at most a partial
                    # frame (dropped, never processed).  After a successful
                    # send the daemon may have processed the request even
                    # though the reply was lost, and re-sending a REPORT
                    # would duplicate metric points — surface the error.
                    if attempt or sent:
                        raise
            if not resp or resp[0] != 0:
                raise RuntimeError("db-manager rejected request")
            return resp[1:]

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None

    # -- ObservationStore contract ------------------------------------------

    def report(self, trial_name: str, logs: Iterable[MetricLog]) -> None:
        logs = list(logs)
        if not logs:
            return
        parts = [struct.pack("<B", _OP_REPORT), _str16(trial_name),
                 struct.pack("<I", len(logs))]
        for l in logs:
            parts.append(_str16(l.metric_name))
            parts.append(struct.pack("<ddq", l.value, l.timestamp, l.step))
        self._call(b"".join(parts))

    def get(self, trial_name: str, metric_name: str | None = None) -> list[MetricLog]:
        payload = (
            struct.pack("<B", _OP_GET) + _str16(trial_name) + _str16(metric_name or "")
        )
        body = self._call(payload)
        (n,) = struct.unpack_from("<I", body, 0)
        off = 4
        out: list[MetricLog] = []
        for _ in range(n):
            (nlen,) = struct.unpack_from("<H", body, off)
            off += 2
            name = body[off : off + nlen].decode()
            off += nlen
            value, ts, step = struct.unpack_from("<ddq", body, off)
            off += 24
            out.append(MetricLog(metric_name=name, value=value, timestamp=ts, step=step))
        return out

    def delete(self, trial_name: str) -> None:
        self._call(struct.pack("<B", _OP_DELETE) + _str16(trial_name))

    def ping(self) -> int:
        """Liveness probe; returns the daemon's total stored point count."""
        body = self._call(struct.pack("<B", _OP_PING))
        (total,) = struct.unpack("<q", body)
        return total


class DbManagerHandle:
    def __init__(self, proc: subprocess.Popen, host: str, port: int):
        self.proc, self.host, self.port = proc, host, port

    def client(self) -> RemoteObservationStore:
        return RemoteObservationStore(self.host, self.port)

    def stop(self) -> None:
        self.proc.terminate()
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()


# resolved at import time: dlopen/symbol lookup must not run inside the
# post-fork preexec_fn (allocator locks held by other threads), and a
# platform without prctl (macOS, musl without libc.so.6) degrades to
# "no lifetime tie" instead of killing every spawn attempt
try:
    import ctypes
    import ctypes.util

    _libc = ctypes.CDLL(
        ctypes.util.find_library("c") or "libc.so.6", use_errno=True
    )
    _prctl = _libc.prctl if sys.platform.startswith("linux") else None
except OSError:  # pragma: no cover - non-glibc platforms
    _prctl = None


def _set_pdeathsig() -> None:
    """Child-side: die with SIGKILL when the parent exits (Linux prctl).
    Keeps a daemon spawned by a CLI wrapper from outliving it — even a
    SIGKILLed wrapper can't orphan a daemon holding the port + journal."""
    import signal

    PR_SET_PDEATHSIG = 1
    if _prctl is not None:
        _prctl(PR_SET_PDEATHSIG, signal.SIGKILL)


def spawn_db_manager(
    host: str = "127.0.0.1",
    port: int = 0,
    db_path: str | None = None,
    kill_on_parent_exit: bool = False,
) -> DbManagerHandle:
    """Launch the daemon (port 0 = ephemeral); blocks until it listens.

    ``db_path`` enables the append-only frame journal: acked mutations
    survive a daemon crash and are replayed on the next start (parity with
    the reference daemon's persisted SQL table, ``mysql/init.go:35``).
    ``kill_on_parent_exit`` ties the daemon's lifetime to the caller via
    ``PR_SET_PDEATHSIG`` (the CLI wrapper uses it).
    """
    if not ensure_built():
        from katib_tpu.native.build import build_error

        raise RuntimeError(f"native build failed: {build_error()}")
    cmd = [DBMANAGER_PATH, "--host", host, "--port", str(port)]
    if db_path is not None:
        cmd += ["--db", db_path]
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        text=True,
        preexec_fn=_set_pdeathsig if kill_on_parent_exit else None,
    )
    assert proc.stdout is not None
    deadline = time.monotonic() + 10.0
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("LISTENING "):
            return DbManagerHandle(proc, host, int(line.split()[1]))
        if proc.poll() is not None:
            break
    proc.kill()
    raise RuntimeError(f"db-manager failed to start (last output: {line!r})")
