from katib_tpu.ui.backend import UiServer, start_ui  # noqa: F401
