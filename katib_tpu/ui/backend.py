"""REST backend + embedded dashboard — parity with the reference UI.

The reference serves an Angular SPA from a Go REST backend that proxies CRD
CRUD, trial logs, DB-manager metric fetches, and a NAS graph view
(``pkg/ui/v1beta1/backend.go:86,138,181,463,514,566,617``, ``nas.go``).
TPU-native there is no API server to proxy: the orchestrator journals
status to ``<workdir>/<experiment>/status.json`` and metrics live in the
observation store, so the backend is a thin read-only HTTP layer over those
two sources plus a single-file HTML dashboard (no build step, no Node).

Endpoints (JSON unless noted):

- ``GET /api/experiments``                     summaries for every journaled experiment
- ``GET /api/experiment/<name>``               full status incl. trials
- ``GET /api/experiment/<name>/trials``        trials table rows
- ``GET /api/trial/<name>/metrics``            raw metric log from the store
- ``GET /api/experiment/<name>/nas``           NAS graph (nodes/edges) for the
                                               best (or named ``?trial=``) trial
- ``GET /api/flagship/progress``               per-epoch stream of long NAS runs
                                               (``artifacts/flagship/run_progress
                                               .jsonl``), grouped by config tag
- ``POST /api/experiments``                    create + run a black-box experiment
                                               (body: the YAML spec as JSON, or
                                               ``{"yaml": "<text>"}``) — parity with
                                               ``backend.go:86`` CreateExperiment
- ``POST /api/experiment/<name>/stop``         wind the running experiment down
- ``DELETE /api/experiment/<name>``            remove a finished experiment's journal
                                               (``backend.go:138`` DeleteExperiment)
- ``GET /``                                    dashboard (text/html): experiment
                                               table, create form, best-objective
                                               sparkline, and per-trial drill-down —
                                               click a trial row for its metric
                                               chart (fed by ``/metrics``) and
                                               rendered NAS cell/arc SVG (fed by
                                               ``/nas?trial=``), the single-file
                                               answer to the reference SPA's trial
                                               detail + browser NAS views

Write endpoints optionally require ``Authorization: Bearer <token>``
(``token=`` / ``KATIB_UI_TOKEN``); reads stay open like the reference UI.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from katib_tpu.core.types import ExperimentCondition
from katib_tpu.orchestrator.status import list_statuses, read_status
from katib_tpu.store.base import ObservationStore
from katib_tpu.utils.paths import artifacts_root


def _experiment_summary(status: dict) -> dict:
    return {
        "name": status.get("name"),
        "condition": status.get("condition"),
        "algorithm": status.get("algorithm"),
        "objective_metric": status.get("objective_metric"),
        "counts": status.get("counts", {}),
        "optimal": status.get("optimal"),
        "start_time": status.get("start_time"),
        "completion_time": status.get("completion_time"),
    }


def _trial_rows(status: dict) -> list[dict]:
    rows = []
    for name, t in (status.get("trials") or {}).items():
        obs = t.get("observation") or []
        rows.append(
            {
                "name": name,
                "condition": t.get("condition"),
                "assignments": t.get("assignments", {}),
                "labels": t.get("labels", {}),
                "metrics": {m["name"]: m["latest"] for m in obs},
                "start_time": t.get("start_time"),
                "completion_time": t.get("completion_time"),
            }
        )
    return rows


# -- NAS graph extraction ----------------------------------------------------


def _darts_graph(genotype: dict) -> dict:
    """Genotype → node/edge list, the shape the reference's UI renders
    (``nas.go``).  ``normal``/``reduce`` are per-node lists of kept
    ``[op, src_edge]`` pairs (nas/darts/model.py extract_genotype); source
    0/1 are the two cell inputs, source j+2 is intermediate node j."""
    nodes = [{"id": "c_{k-2}", "label": "input-2"}, {"id": "c_{k-1}", "label": "input-1"}]
    edges = []
    for cell in ("normal", "reduce"):
        per_node = genotype.get(cell) or []
        for i in range(len(per_node)):
            nodes.append({"id": f"{cell}-{i}", "label": f"{cell} node {i}"})
        for dst, pairs in enumerate(per_node):
            for op, src in pairs:
                src = int(src)
                src_id = ("c_{k-2}", "c_{k-1}")[src] if src < 2 else f"{cell}-{src - 2}"
                edges.append({"from": src_id, "to": f"{cell}-{dst}", "op": op})
    return {"type": "darts", "nodes": nodes, "edges": edges}


def _enas_graph(architecture: list) -> dict:
    """ENAS arc (per layer ``[op_id, skip...]``) → chain with skip edges."""
    nodes = [{"id": "input", "label": "input"}]
    edges = []
    for i, layer in enumerate(architecture):
        op = layer[0] if layer else 0
        nodes.append({"id": f"layer-{i}", "label": f"layer {i} (op {op})"})
        prev = "input" if i == 0 else f"layer-{i - 1}"
        edges.append({"from": prev, "to": f"layer-{i}", "op": "seq"})
        for j, bit in enumerate(layer[1:]):
            if int(bit):
                src = "input" if j == 0 else f"layer-{j - 1}"
                edges.append({"from": src, "to": f"layer-{i}", "op": "skip"})
    nodes.append({"id": "output", "label": "output"})
    if architecture:
        edges.append({"from": f"layer-{len(architecture) - 1}", "to": "output", "op": "seq"})
    return {"type": "enas", "nodes": nodes, "edges": edges}


def nas_graph_for_trial(trial: dict) -> dict | None:
    """Recover the architecture a trial trained: DARTS trials leave
    ``genotype.json`` in their checkpoint dir (nas/darts/search.py), ENAS
    trials carry it in the ``architecture`` assignment (enas/service.py)."""
    arch = (trial.get("assignments") or {}).get("architecture")
    if arch:
        try:
            return _enas_graph(json.loads(arch) if isinstance(arch, str) else arch)
        except (ValueError, TypeError):
            return None
    ckpt = trial.get("checkpoint_dir")
    if ckpt:
        path = os.path.join(ckpt, "genotype.json")
        try:
            with open(path) as f:
                return _darts_graph(json.load(f))
        except (OSError, ValueError):
            return None
    return None


# -- HTTP layer --------------------------------------------------------------


class UiServer:
    """Dashboard server over a workdir + observation store.  Reads come from
    the status journal; writes (create/stop/delete) own orchestrator runs in
    background threads — the collapse of the reference UI's CRD CRUD proxy
    (``backend.go:86-181``) now that there is no API server between UI and
    controller."""

    def __init__(
        self,
        workdir: str,
        store: ObservationStore | None = None,
        token: str | None = None,
        artifacts_dir: str | None = None,
    ):
        self.workdir = workdir
        self.store = store
        # flagship run-progress stream lives in the artifacts tree, not the
        # experiment workdir; the shared resolver keeps this reader and the
        # scripts/ writers on the same root under a redirect
        self.artifacts_dir = artifacts_dir or artifacts_root()
        # empty string (e.g. `KATIB_UI_TOKEN=` in a shell) means "no auth",
        # not "require the empty token"
        self.token = (token or os.environ.get("KATIB_UI_TOKEN")) or None
        self._runs: dict[str, object] = {}  # name -> Orchestrator
        self._threads: dict[str, threading.Thread] = {}
        self._run_lock = threading.Lock()

    # -- write path ----------------------------------------------------------

    def _parse_spec(self, payload: dict):
        from katib_tpu.sdk.yaml_spec import SpecError, experiment_spec_from_dict

        if "yaml" in payload:
            import yaml as _yaml

            try:
                payload = _yaml.safe_load(payload["yaml"])
            except _yaml.YAMLError as e:
                raise SpecError(f"bad YAML: {e}") from e
            if not isinstance(payload, dict):
                raise SpecError("YAML body must be a mapping")
        return experiment_spec_from_dict(payload)

    def create(self, payload: dict):
        from katib_tpu.core.validation import ValidationError, validate_experiment
        from katib_tpu.orchestrator import Orchestrator
        from katib_tpu.sdk.yaml_spec import SpecError

        try:
            spec = self._parse_spec(payload)
            # full admission check HERE so a bad spec (incl. a path-escaping
            # name) is a 400 at the API, not a silent background failure
            validate_experiment(spec)
        except (ValidationError, SpecError, KeyError, TypeError, ValueError) as e:
            return 400, {"error": str(e)}
        if spec.command is None:
            # a callable cannot arrive over HTTP; UI-created experiments are
            # black-box by construction (same restriction as the reference:
            # trials are container commands)
            return 400, {"error": "experiment must define trialTemplate.command"}
        with self._run_lock:
            running = self._threads.get(spec.name)
            if running is not None and running.is_alive():
                return 409, {"error": f"experiment {spec.name!r} is already running"}
            if read_status(self.workdir, spec.name) is not None:
                return 409, {"error": f"experiment {spec.name!r} already exists"}
            # journal the Created state BEFORE 201 so the resource exists the
            # moment the client learns its name — the background run's own
            # first publish lands after its durable-store + event-journal
            # setup, a window where GET /api/experiment/<name> would 404
            try:
                from katib_tpu.core.types import Experiment
                from katib_tpu.orchestrator.status import write_status

                write_status(Experiment(spec=spec), self.workdir)
            except OSError:
                pass  # the run thread's publish will catch up
            orch = Orchestrator(workdir=self.workdir, store=self.store)
            thread = threading.Thread(
                target=self._run_background,
                args=(orch, spec),
                name=f"ui-run-{spec.name}",
                daemon=True,
            )
            self._runs[spec.name] = orch
            self._threads[spec.name] = thread
            thread.start()
        return 201, {"ok": True, "name": spec.name}

    @staticmethod
    def _run_background(orch, spec) -> None:
        try:
            orch.run(spec)
        except Exception:
            pass  # terminal state + message are journaled by the orchestrator

    def stop(self, name: str):
        with self._run_lock:
            orch = self._runs.get(name)
            thread = self._threads.get(name)
        if orch is None or thread is None or not thread.is_alive():
            return 409, {"error": f"experiment {name!r} is not running here"}
        orch.stop()
        return 202, {"ok": True, "stopping": name}

    def delete(self, name: str, force: bool = False):
        status = read_status(self.workdir, name)
        if status is None:
            return 404, {"error": f"experiment {name!r} not found"}
        with self._run_lock:
            thread = self._threads.get(name)
            if thread is not None and thread.is_alive():
                return 409, {"error": f"experiment {name!r} is still running; stop it first"}
            # the journal may belong to an orchestrator in ANOTHER process
            # (`katib-tpu run` sharing this workdir) — deleting out from
            # under it loses its checkpoints mid-run.  A crashed run leaves
            # a stale non-terminal journal; ?force=1 overrides for that case.
            condition = str(status.get("condition", ""))
            try:
                terminal = ExperimentCondition(condition).is_terminal()
            except ValueError:
                terminal = False  # unrecognized journal → treat as live
            if not terminal and not force:
                return 409, {
                    "error": (
                        f"experiment {name!r} is {condition or 'non-terminal'} "
                        "(possibly running in another process); stop it first "
                        "or delete with ?force=1"
                    )
                }
            self._runs.pop(name, None)
            self._threads.pop(name, None)
        shutil.rmtree(os.path.join(self.workdir, name), ignore_errors=True)
        return 200, {"ok": True, "deleted": name}

    # route handlers return (status, payload) with payload JSON-serializable

    def experiments(self):
        return 200, [_experiment_summary(s) for s in list_statuses(self.workdir)]

    def status(self):
        """Live in-process metrics snapshot (counters, gauges, histogram
        aggregates) — the dashboard's counter strip reads this instead of
        scraping the Prometheus endpoint separately."""
        from katib_tpu.costmodel.profiler import list_profiles
        from katib_tpu.utils.observability import REGISTRY
        from katib_tpu.utils.meshhealth import last_report_dict

        return 200, {
            "workdir": self.workdir,
            "metrics": REGISTRY.snapshot(),
            # last device-preflight verdict of this process (None until a
            # doctor/preflight probe ran) — per-device health rows
            "device_health": last_report_dict(),
            # profiler captures taken by this process (enable_profiler
            # trials, ad-hoc `katib-tpu profile` runs): trace_dir + trial
            "profiles": list_profiles(),
        }

    def experiment(self, name: str):
        status = read_status(self.workdir, name)
        if status is None:
            return 404, {"error": f"experiment {name!r} not found"}
        return 200, status

    def trials(self, name: str):
        status = read_status(self.workdir, name)
        if status is None:
            return 404, {"error": f"experiment {name!r} not found"}
        return 200, _trial_rows(status)

    def trial_logs(self, trial_name: str):
        """Captured stdout of a black-box trial (reference UI fetches pod
        logs, ``backend.go:463``); resolution shared with the CLI via
        ``status.read_trial_log``."""
        from katib_tpu.orchestrator.status import read_trial_log

        log = read_trial_log(self.workdir, trial_name)
        if log is None:
            return 404, {
                "error": f"no captured log for trial {trial_name!r} "
                "(white-box trials report metrics in-process and have no stdout log)"
            }
        return 200, {"trial": trial_name, "log": log}

    def trial_metrics(self, trial_name: str):
        if self.store is None:
            return 503, {"error": "no observation store attached"}
        logs = self.store.get(trial_name)
        return 200, [
            {
                "metric_name": l.metric_name,
                "value": l.value,
                "timestamp": l.timestamp,
                "step": l.step,
            }
            for l in logs
        ]

    def nas(self, name: str, trial_name: str | None):
        status = read_status(self.workdir, name)
        if status is None:
            return 404, {"error": f"experiment {name!r} not found"}
        trials = status.get("trials") or {}
        if trial_name is None:
            optimal = status.get("optimal") or {}
            trial_name = optimal.get("trial_name")
        if not trial_name or trial_name not in trials:
            return 404, {"error": "no trial with a recoverable architecture"}
        graph = nas_graph_for_trial(trials[trial_name])
        if graph is None:
            return 404, {"error": f"trial {trial_name!r} has no architecture artifact"}
        graph["trial"] = trial_name
        return 200, graph

    def flagship_progress(self):
        """Per-epoch stream of long NAS runs (``run_progress.jsonl``),
        grouped by config tag — the dashboard's live view of a 50-epoch
        search, fed by the same file that survives a mid-run cutoff."""
        path = os.path.join(self.artifacts_dir, "flagship", "run_progress.jsonl")
        runs: dict[str, list[dict]] = {}
        try:
            # errors="replace": a crash mid-append (the exact cutoff this
            # stream exists to survive) can leave truncated bytes; serve
            # the parseable prefix instead of 500ing
            with open(path, errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if not isinstance(rec, dict):
                        continue  # valid JSON but not a record (null, [...])
                    runs.setdefault(rec.get("config") or "untagged", []).append(rec)
        except OSError:
            return 200, {"runs": {}}
        return 200, {"runs": runs}

    def route(self, path: str, query: dict):
        parts = [p for p in path.split("/") if p]
        if not parts:
            return "html", DASHBOARD_HTML
        if parts[0] != "api":
            return 404, {"error": "not found"}
        if parts[1:] == ["flagship", "progress"]:
            return self.flagship_progress()
        if parts[1:] == ["status"]:
            return self.status()
        if parts[1:] == ["experiments"]:
            return self.experiments()
        if len(parts) >= 3 and parts[1] == "experiment":
            name = parts[2]
            rest = parts[3:]
            if not rest:
                return self.experiment(name)
            if rest == ["trials"]:
                return self.trials(name)
            if rest == ["nas"]:
                return self.nas(name, (query.get("trial") or [None])[0])
        if len(parts) == 4 and parts[1] == "trial" and parts[3] == "metrics":
            return self.trial_metrics(parts[2])
        if len(parts) == 4 and parts[1] == "trial" and parts[3] == "logs":
            return self.trial_logs(parts[2])
        return 404, {"error": "not found"}

    def route_post(self, path: str, payload: dict):
        parts = [p for p in path.split("/") if p]
        if parts == ["api", "experiments"]:
            return self.create(payload)
        if len(parts) == 4 and parts[:2] == ["api", "experiment"] and parts[3] == "stop":
            return self.stop(parts[2])
        return 404, {"error": "not found"}

    def route_delete(self, path: str, query: dict | None = None):
        parts = [p for p in path.split("/") if p]
        if len(parts) == 3 and parts[:2] == ["api", "experiment"]:
            force = (query or {}).get("force", ["0"])[0] not in ("", "0", "false")
            return self.delete(parts[2], force=force)
        return 404, {"error": "not found"}

    # -- server lifecycle ----------------------------------------------------

    def serve(
        self, port: int = 0, host: str = "127.0.0.1", ssl_context=None
    ) -> "RunningUi":
        """``ssl_context`` (from ``utils.certgen.server_ssl_context``) serves
        the dashboard + API over TLS with the rotated self-signed bundle."""
        ui = self

        class Handler(BaseHTTPRequestHandler):
            # bounds a stalled peer (incl. a deferred TLS handshake that
            # never arrives) to this per-connection thread, not the server
            timeout = 60

            def _send(self, status, payload) -> None:
                if status == "html":
                    body = payload.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                else:
                    body = json.dumps(payload, default=str).encode()
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                parsed = urlparse(self.path)
                self._send(*ui.route(parsed.path, parse_qs(parsed.query)))

            def _write_guards(self) -> bool:
                """CSRF + DNS-rebinding guards for the write endpoints (the
                create endpoint runs trialTemplate commands).  JSON-only
                bodies can't ride a browser "simple" cross-origin request,
                and in token-less mode the Host header must name this
                machine so a rebound domain can't become same-origin."""
                from katib_tpu.utils.http import (
                    bearer_authorized,
                    json_content_type,
                    local_host_allowed,
                )

                if self.command == "POST" and not json_content_type(self.headers):
                    self._send(415, {"error": "Content-Type must be application/json"})
                    return False
                if ui.token is None and not local_host_allowed(self.headers):
                    self._send(403, {
                        "error": "Host not recognized (DNS-rebinding guard); "
                        "set a bearer token to accept writes on other hosts"
                    })
                    return False
                if not bearer_authorized(self.headers, ui.token):
                    self._send(401, {"error": "missing or bad bearer token"})
                    return False
                return True

            def do_POST(self):  # noqa: N802
                from katib_tpu.utils.http import read_json_body

                if not self._write_guards():
                    return
                try:
                    payload = read_json_body(self)
                except (ValueError, OSError) as e:
                    self._send(400, {"error": f"bad payload: {e}"})
                    return
                self._send(*ui.route_post(urlparse(self.path).path, payload))

            def do_DELETE(self):  # noqa: N802
                if not self._write_guards():
                    return
                parsed = urlparse(self.path)
                self._send(*ui.route_delete(parsed.path, parse_qs(parsed.query)))

            def log_message(self, *args):
                pass

        server = ThreadingHTTPServer((host, port), Handler)
        if ssl_context is not None:
            from katib_tpu.utils.certgen import wrap_server_socket

            server.socket = wrap_server_socket(ssl_context, server.socket)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return RunningUi(server, thread)


class RunningUi:
    def __init__(self, server: ThreadingHTTPServer, thread: threading.Thread):
        self._server = server
        self._thread = thread

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def start_ui(
    workdir: str, store: ObservationStore | None = None, port: int = 0,
    host: str = "127.0.0.1", token: str | None = None, ssl_context=None,
) -> RunningUi:
    return UiServer(workdir, store, token=token).serve(
        port=port, host=host, ssl_context=ssl_context
    )


# -- the dashboard (single file, no build step) ------------------------------

DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>katib-tpu</title>
<style>
body{font-family:system-ui,sans-serif;margin:2rem;background:#fafafa;color:#222}
h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
table{border-collapse:collapse;width:100%;background:#fff;box-shadow:0 1px 2px #0002}
th,td{padding:.45rem .7rem;border-bottom:1px solid #eee;text-align:left;font-size:.88rem}
th{background:#f0f0f3;font-weight:600}
tr.sel{background:#eef4ff} tbody tr{cursor:pointer}
.badge{padding:.1rem .45rem;border-radius:.6rem;font-size:.75rem;color:#fff}
.Succeeded,.MaxTrialsReached,.GoalReached{background:#2e7d32}.Failed{background:#c62828}
.Running{background:#1565c0}.EarlyStopped{background:#ef6c00}.MetricsUnavailable{background:#757575}
#detail{margin-top:1rem} pre{background:#272822;color:#f8f8f2;padding:1rem;overflow:auto;font-size:.8rem}
</style></head><body>
<h1>katib-tpu experiments</h1>
<div id="counters" style="margin:.2rem 0 .8rem;color:#555"></div>
<details id="create"><summary>create experiment</summary>
<fieldset style="border:1px solid #ddd;margin:.5rem 0;padding:.6rem">
<legend>wizard (fills the YAML below — edit freely before running)</legend>
<input id="w_name" placeholder="name" size="14">
<select id="w_algo"><option>random</option><option>grid</option><option>tpe</option>
<option>multivariate-tpe</option><option>bayesianoptimization</option><option>cmaes</option>
<option>sobol</option><option>hyperband</option><option>asha</option><option>pbt</option></select>
<select id="w_otype"><option>minimize</option><option>maximize</option></select>
<input id="w_metric" placeholder="objective metric" size="12" value="loss">
<input id="w_goal" placeholder="goal (opt)" size="8">
<input id="w_max" placeholder="max trials" size="6" value="12">
<input id="w_par" placeholder="parallel" size="5" value="3">
<table id="w_params" style="width:auto;margin:.4rem 0"><thead><tr><th>param</th><th>type</th>
<th>min</th><th>max</th><th>list (comma)</th></tr></thead><tbody></tbody></table>
<button id="w_addp" type="button">+ parameter</button>
<div><small>trial command, one argument per line (use ${trialParameters.&lt;name&gt;}):</small><br>
<textarea id="w_cmd" rows="3" style="width:100%;font-family:monospace">python
-c
print("loss=" + str((${trialParameters.lr}-0.03)**2))</textarea></div>
<button id="w_build" type="button">build YAML</button>
</fieldset>
<textarea id="yaml" rows="14" style="width:100%;font-family:monospace"></textarea><br>
<input id="token" placeholder="bearer token (if required)" style="width:18rem">
<button id="submit">run</button> <span id="createmsg"></span></details>
<table id="exps"><thead><tr><th>name</th><th>status</th><th>algorithm</th>
<th>objective</th><th>trials</th><th>best</th><th></th></tr></thead><tbody></tbody></table>
<div id="flagship"></div>
<div id="detail"></div>
<script>
const esc=s=>String(s??"").replace(/[&<>"]/g,c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
const badge=c=>`<span class="badge ${esc(c)}">${esc(c)}</span>`;
async function j(u){const r=await fetch(u);return r.json()}
function hdrs(){const t=document.getElementById('token').value;
  return t?{'Content-Type':'application/json','Authorization':'Bearer '+t}:{'Content-Type':'application/json'}}
async function act(u,method,body){const r=await fetch(u,{method,headers:hdrs(),body});
  const p=await r.json();document.getElementById('createmsg').textContent=p.error||'ok';refresh();return p}
let current=null;
async function flagshipRuns(){
  // per-epoch stream of long NAS searches (run_progress.jsonl) — one
  // accuracy-vs-epoch line per config tag
  const p=await j('/api/flagship/progress');const runs=p.runs||{};
  const keys=Object.keys(runs);const el=document.getElementById('flagship');
  if(!keys.length){el.innerHTML='';return}
  el.innerHTML='<h2>flagship NAS runs</h2>'+keys.map(k=>{
    const rows=runs[k],last=rows[rows.length-1],W=260,H=48,n=rows.length;
    const ys=rows.map(r=>r.accuracy),y0=Math.min(...ys),y1=Math.max(...ys);
    const px=i=>4+(W-8)*i/((n-1)||1),py=v=>H-4-(H-8)*(v-y0)/((y1-y0)||1);
    const pts=rows.map((r,i)=>px(i)+','+py(r.accuracy)).join(' ');
    return `<div style="margin:.4rem 0"><small>${esc(k)} — epoch ${esc(last.epoch)}, `+
      `val ${esc(last.accuracy)}, ${esc(last.epoch_secs)}s/epoch (${esc(last.platform)})</small><br>`+
      `<svg width="${W}" height="${H}"><polyline points="${pts}" fill="none" stroke="#15c" stroke-width="2"/></svg></div>`;
  }).join('');
}
async function counters(){
  // live registry snapshot from this server process (/api/status) — no
  // separate Prometheus scrape needed for the counter strip
  const s=await j('/api/status');const m=s.metrics||{};
  const tot=n=>m[n]?m[n].total:0;
  // per-tier slice of a labeled counter (artifact hit/miss strip)
  const tier=(n,t)=>m[n]?m[n].samples.filter(x=>(x.labels||{}).tier===t)
    .reduce((a,x)=>a+x.value,0):0;
  const dur=m['katib_trial_duration_seconds'];
  const mean=dur&&dur.total?(dur.samples.reduce((a,x)=>a+x.sum,0)/dur.total):null;
  // device-health strip: the per-device preflight gauge (1 healthy / 0
  // wedged-or-absent); absent until a doctor/preflight probe ran in-process
  const dh=m['katib_device_healthy'];
  const dhUp=dh?dh.samples.filter(x=>x.value>0).length:0;
  const dhAll=dh?dh.samples.length:0;
  // steps-per-dispatch: the dispatch-overhead diagnostic for the DARTS
  // step loop (window size under the scan loop, 1 under eager stepping)
  const spdM=m['katib_steps_per_dispatch'];
  const spd=spdM&&spdM.samples.length?spdM.samples[0].value:null;
  // async-orchestrator strip: mesh occupancy (busy slot fraction; sustained
  // < 0.5 means the mesh idles between cohorts), the suggest->schedule
  // queue depth, and mean suggester latency from the suggest loop
  const occM=m['katib_mesh_occupancy'];
  const occ=occM&&occM.samples.length?occM.samples[0].value:null;
  const pendM=m['katib_pending_proposals'];
  const pend=pendM&&pendM.samples.length?pendM.samples[0].value:null;
  // loop-supervision strip: any loop whose stalled gauge is up right now,
  // and the cumulative supervisor restart count across all loops
  const stallM=m['katib_loop_stalled'];
  const stalledLoops=stallM?stallM.samples.filter(x=>x.value>0)
    .map(x=>(x.labels||{}).loop||'?'):[];
  const sugM=m['katib_suggest_seconds'];
  const sug=sugM&&sugM.total?(sugM.samples.reduce((a,x)=>a+x.sum,0)/sugM.total):null;
  document.getElementById('counters').innerHTML=
    `<small>trials: ${tot('katib_trial_created_total')} created · `+
    `${tot('katib_trial_succeeded_total')} succeeded · `+
    `${tot('katib_trial_failed_total')} failed · `+
    `${tot('katib_trial_retried_total')} retried · `+
    `${tot('katib_trial_early_stopped_total')} early-stopped · `+
    `experiments running: ${tot('katib_experiments_current')}`+
    (dhAll?` · devices: ${dhUp}/${dhAll} healthy${dhUp<dhAll?' <b>POOL DEGRADED</b>':''}`:'')+
    (tot('katib_mesh_degraded_total')?` · mesh degradations: ${tot('katib_mesh_degraded_total')}`:'')+
    (tot('katib_compile_hangs_total')?` · compile hangs: ${tot('katib_compile_hangs_total')}`:'')+
    (tot('katib_trial_hangs_total')?` · hangs caught: ${tot('katib_trial_hangs_total')}`:'')+
    (tot('katib_checkpoint_fallback_total')?` · ckpt fallbacks: ${tot('katib_checkpoint_fallback_total')}`:'')+
    (tot('katib_drain_requested')?' · <b>DRAINING</b>':'')+
    (tot('katib_suggester_errors_total')?` · suggester errors: ${tot('katib_suggester_errors_total')}`:'')+
    (tot('katib_cohort_executed_total')?` · cohorts: ${tot('katib_cohort_executed_total')}`:'')+
    (tot('katib_pbt_generations_total')?
      ` · pbt: ${tot('katib_pbt_generations_total')} gens / ${tot('katib_pbt_exploits_total')} exploits${tot('katib_pbt_onchip')?' <b>ON-CHIP</b>':''}`:'')+
    ((tot('katib_compile_cache_hits_total')||tot('katib_compile_cache_misses_total'))?
      ` · compile cache: ${tot('katib_compile_cache_hits_total')} warm / ${tot('katib_compile_cache_misses_total')} cold`:'')+
    (tot('katib_prewarm_compiles_total')?` · prewarmed: ${tot('katib_prewarm_compiles_total')}`:'')+
    ((tot('katib_artifact_hits_total')||tot('katib_artifact_publishes_total'))?
      ` · artifacts: ${tier('katib_artifact_hits_total','local')} local / ${tier('katib_artifact_hits_total','shared')} shared fetched · ${tot('katib_artifact_publishes_total')} published`:'')+
    (tot('katib_artifact_quarantines_total')?` · <b>artifact quarantines: ${tot('katib_artifact_quarantines_total')}</b>`:'')+
    (tot('katib_journal_replayed_events_total')?` · journal replayed: ${tot('katib_journal_replayed_events_total')}`:'')+
    (tot('katib_settlement_duplicates_total')?` · settle dups dropped: ${tot('katib_settlement_duplicates_total')}`:'')+
    (tot('katib_suggester_fence_rebuilds_total')?` · fence rebuilds: ${tot('katib_suggester_fence_rebuilds_total')}`:'')+
    (tot('katib_fsck_repairs_total')?` · fsck repairs: ${tot('katib_fsck_repairs_total')}`:'')+
    (spd!==null?` · steps/dispatch: ${spd.toFixed(1)}${spd<=1?' <b>EAGER</b>':''}`:'')+
    (occ!==null?` · occupancy: ${occ.toFixed(2)}${occ<0.5?' <b>MESH IDLE</b>':''}`:'')+
    (pend!==null?` · pending proposals: ${pend.toFixed(0)}`:'')+
    (tot('katib_loop_restarts_total')?` · loop restarts: ${tot('katib_loop_restarts_total')}`:'')+
    (stalledLoops.length?` · <b>LOOP STALLED: ${stalledLoops.map(esc).join(', ')}</b>`:'')+
    (tot('katib_speculative_dispatch_total')?` · speculative: ${tot('katib_speculative_wins_total')}/${tot('katib_speculative_dispatch_total')} won`:'')+
    (sug!==null?` · suggest: ${sug.toFixed(3)}s`:'')+
    (mean!==null?` · mean trial ${mean.toFixed(1)}s`:'')+'</small>';
}
async function refresh(){
  flagshipRuns().catch(()=>{});
  counters().catch(()=>{});
  const exps=await j('/api/experiments');
  document.querySelector('#exps tbody').innerHTML=exps.map(e=>{
    const c=e.counts||{},o=e.optimal,n=encodeURIComponent(e.name);
    const running=e.condition==='Running'||e.condition==='Restarting';
    const btn=running?`<button onclick="event.stopPropagation();act('/api/experiment/${n}/stop','POST')">stop</button>`
      :`<button onclick="event.stopPropagation();act('/api/experiment/${n}','DELETE')">delete</button>`;
    return `<tr data-n="${esc(e.name)}" class="${e.name===current?'sel':''}">`+
      `<td>${esc(e.name)}</td><td>${badge(e.condition)}</td><td>${esc(e.algorithm)}</td>`+
      `<td>${esc(e.objective_metric)}</td><td>${c.succeeded??0}/${c.trials??0}</td>`+
      `<td>${o?esc(o.objective_value?.toFixed?.(5)??o.objective_value):"—"}</td><td>${btn}</td></tr>`;
  }).join('');
  document.querySelectorAll('#exps tbody tr').forEach(tr=>tr.onclick=()=>show(tr.dataset.n));
  if(current)show(current,false);
}
document.getElementById('submit').onclick=()=>
  act('/api/experiments','POST',JSON.stringify({yaml:document.getElementById('yaml').value}));
// -- creation wizard: assembles the Katib-style YAML client-side ----------
function addParamRow(name='',type='double',min='',max='',list=''){
  const tb=document.querySelector('#w_params tbody');
  const tr=document.createElement('tr');
  tr.innerHTML=`<td><input size="8" class="p_n" value="${esc(name)}"></td>`+
    `<td><select class="p_t"><option>double</option><option>int</option>`+
    `<option>discrete</option><option>categorical</option></select></td>`+
    `<td><input size="6" class="p_lo" value="${esc(min)}"></td>`+
    `<td><input size="6" class="p_hi" value="${esc(max)}"></td>`+
    `<td><input size="12" class="p_ls" value="${esc(list)}"></td>`;
  tr.querySelector('.p_t').value=type;
  tb.appendChild(tr);
}
document.getElementById('w_addp').onclick=()=>addParamRow();
addParamRow('lr','double','0.01','0.05');
document.getElementById('w_build').onclick=()=>{
  const v=id=>document.getElementById(id).value.trim();
  const q=JSON.stringify; // YAML-safe scalar quoting
  const msg=[];
  let y='apiVersion: kubeflow.org/v1beta1\nkind: Experiment\nmetadata:\n'+
    `  name: ${q(v('w_name')||'my-experiment')}\nspec:\n  objective:\n`+
    `    type: ${v('w_otype')}\n    objectiveMetricName: ${q(v('w_metric'))}\n`;
  // numeric fields are parsed client-side so stray text can't corrupt
  // the YAML (an unquoted ':' or '#' would truncate or break parsing)
  const goal=parseFloat(v('w_goal'));
  if(v('w_goal')&&!isNaN(goal))y+=`    goal: ${goal}\n`;
  else if(v('w_goal'))msg.push(`goal ${q(v('w_goal'))} is not a number — omitted`);
  y+=`  algorithm:\n    algorithmName: ${v('w_algo')}\n`+
    `  parallelTrialCount: ${parseInt(v('w_par'))||3}\n`+
    `  maxTrialCount: ${parseInt(v('w_max'))||12}\n`+
    '  parameters:\n';
  document.querySelectorAll('#w_params tbody tr').forEach(tr=>{
    const g=c=>tr.querySelector(c).value.trim();
    if(!g('.p_n'))return;
    if(!g('.p_ls')&&(!g('.p_lo')||!g('.p_hi'))){
      msg.push(`parameter ${q(g('.p_n'))} needs min+max or a list — skipped`);
      return;
    }
    y+=`    - name: ${q(g('.p_n'))}\n      parameterType: ${g('.p_t')}\n`;
    if(g('.p_ls'))
      y+=`      feasibleSpace: {list: [${g('.p_ls').split(',').map(s=>q(s.trim())).join(', ')}]}\n`;
    else
      y+=`      feasibleSpace: {min: ${q(g('.p_lo'))}, max: ${q(g('.p_hi'))}}\n`;
  });
  y+='  trialTemplate:\n    command:\n'+
    v('w_cmd').split('\n').filter(l=>l.length).map(l=>`      - ${q(l)}`).join('\n')+'\n';
  document.getElementById('yaml').value=y;
  document.getElementById('createmsg').textContent=msg.join('; ');
};
function sparkline(rows){
  if(!rows||!rows.length)return '';
  const xs=rows.map(r=>r.elapsed_s),ys=rows.map(r=>r.objective_value);
  const last=`best objective vs wallclock (${esc(ys[ys.length-1].toFixed?.(5)??ys[ys.length-1])} @ ${esc(xs[xs.length-1])}s)`;
  const W=260,H=48;
  if(rows.length<2)
    return `<div><small>${last}</small><br><svg width="${W}" height="${H}"><circle cx="8" cy="${H/2}" r="3" fill="#2a7"/></svg></div>`;
  const x0=Math.min(...xs),x1=Math.max(...xs)||1,y0=Math.min(...ys),y1=Math.max(...ys);
  const px=v=>4+(W-8)*(v-x0)/((x1-x0)||1),py=v=>H-4-(H-8)*(v-y0)/((y1-y0)||1);
  const pts=rows.map(r=>px(r.elapsed_s)+','+py(r.objective_value)).join(' ');
  return `<div><small>${last}</small><br>`+
    `<svg width="${W}" height="${H}"><polyline points="${pts}" fill="none" stroke="#2a7" stroke-width="2"/></svg></div>`;
}
const PALETTE=['#2a7','#15c','#e60','#a3c','#c22','#08a','#770'];
function metricChart(rows){
  // per-trial drill-down chart: one polyline per metric series from
  // /api/trial/<name>/metrics (x = step, falling back to report order)
  if(!rows||!rows.length)return '<small>no metric points</small>';
  const series={};
  rows.forEach(r=>{(series[r.metric_name]??=[]).push(r)});
  const W=560,H=180,names=Object.keys(series);
  const ally=rows.map(r=>r.value);
  const y0=Math.min(...ally),y1=Math.max(...ally);
  const py=v=>H-16-(H-32)*(v-y0)/((y1-y0)||1);
  const lines=names.map((nm,i)=>{
    const s=series[nm],useStep=s.every(r=>r.step>=0);
    const xs=s.map((r,k)=>useStep?r.step:k);
    const x0=Math.min(...xs),x1=Math.max(...xs);
    const px=v=>40+(W-56)*(v-x0)/((x1-x0)||1);
    const pts=s.map((r,k)=>px(xs[k])+','+py(r.value)).join(' ');
    return s.length>1
      ?`<polyline points="${pts}" fill="none" stroke="${PALETTE[i%PALETTE.length]}" stroke-width="1.6"/>`
      :`<circle cx="${px(xs[0])}" cy="${py(s[0].value)}" r="3" fill="${PALETTE[i%PALETTE.length]}"/>`;
  }).join('');
  const legend=names.map((nm,i)=>
    `<tspan x="46" dy="14" fill="${PALETTE[i%PALETTE.length]}">● ${esc(nm)}</tspan>`).join('');
  return `<svg id="metricchart" width="${W}" height="${H}" style="background:#fff;box-shadow:0 1px 2px #0002">`+
    `<text x="4" y="14" font-size="10">${esc(y1.toFixed?.(4)??y1)}</text>`+
    `<text x="4" y="${H-6}" font-size="10">${esc(y0.toFixed?.(4)??y0)}</text>`+
    lines+`<text font-size="11">${legend}</text></svg>`;
}
function nasGraph(g){
  // rendered NAS cell/arc graph (the reference UI renders nas.go's graph
  // in the browser); layered left→right by topological depth
  if(!g||!g.nodes||!g.nodes.length)return '';
  const depth={},incoming={};
  g.nodes.forEach(n=>{incoming[n.id]=[]});
  g.edges.forEach(e=>{(incoming[e.to]??=[]).push(e.from)});
  const d=id=>{
    if(depth[id]!=null)return depth[id];
    depth[id]=0; // breaks accidental cycles
    const ins=(incoming[id]||[]).map(d);
    return depth[id]=ins.length?Math.max(...ins)+1:0;
  };
  g.nodes.forEach(n=>d(n.id));
  const cols={};
  g.nodes.forEach(n=>{(cols[depth[n.id]]??=[]).push(n.id)});
  const pos={},CW=150,RH=52;
  const H=40+RH*Math.max(...Object.values(cols).map(c=>c.length));
  Object.entries(cols).forEach(([dep,ids])=>ids.forEach((id,k)=>{
    pos[id]=[30+dep*CW,24+k*RH+((H-48-RH*(ids.length-1))/2)];
  }));
  const W=60+CW*Math.max(...Object.keys(cols).map(Number))+80;
  const edges=g.edges.map(e=>{
    const [x1,y1]=pos[e.from],[x2,y2]=pos[e.to];
    const mx=(x1+x2)/2,my=(y1+y2)/2;
    return `<line x1="${x1+46}" y1="${y1}" x2="${x2-46}" y2="${y2}" stroke="#888" marker-end="url(#arr)"/>`+
      (e.op&&e.op!=='seq'?`<text x="${mx}" y="${my-4}" font-size="9" text-anchor="middle" fill="#555">${esc(e.op)}</text>`:'');
  }).join('');
  const nodes=g.nodes.map(n=>{
    const [x,y]=pos[n.id];
    return `<rect x="${x-46}" y="${y-13}" width="92" height="26" rx="6" fill="#eef4ff" stroke="#15c"/>`+
      `<text x="${x}" y="${y+4}" font-size="10" text-anchor="middle">${esc(n.label||n.id)}</text>`;
  }).join('');
  return `<h2>architecture — ${esc(g.trial||'')} (${esc(g.type)})</h2>`+
    `<svg id="nasgraph" width="${W}" height="${H}" style="background:#fff;box-shadow:0 1px 2px #0002">`+
    `<defs><marker id="arr" markerWidth="7" markerHeight="7" refX="6" refY="3" orient="auto">`+
    `<path d="M0,0 L7,3 L0,6 z" fill="#888"/></marker></defs>`+edges+nodes+`</svg>`;
}
let trialOf=null; // which experiment the drill-down panel belongs to
async function showTrial(exp,trial){
  trialOf=exp;
  const t=encodeURIComponent(trial);
  const [m,nas,logs]=await Promise.all([
    j('/api/trial/'+t+'/metrics'),
    j('/api/experiment/'+encodeURIComponent(exp)+'/nas?trial='+t),
    j('/api/trial/'+t+'/logs')]);
  document.getElementById('trialdetail').innerHTML=
    `<h2>${esc(trial)} — metrics</h2>`+metricChart(Array.isArray(m)?m:[])+
    (nas&&nas.nodes?nasGraph(nas):'')+
    (logs&&logs.log?`<details><summary>captured log (${esc(trial)})</summary>`+
      `<pre>${esc(logs.log.slice(-20000))}</pre></details>`:'');
}
async function show(name,re=true){
  current=name;
  const [st,t]=await Promise.all([
    j('/api/experiment/'+encodeURIComponent(name)),
    j('/api/experiment/'+encodeURIComponent(name)+'/trials')]);
  const cols=[...new Set(t.flatMap(r=>Object.keys(r.metrics||{})))];
  const pcols=[...new Set(t.flatMap(r=>Object.keys(r.assignments||{})))];
  // keep the drill-down across the 3s redraw, but not across a switch to
  // a different experiment (stale charts would masquerade as the new one's)
  const keep=trialOf===name?(document.getElementById('trialdetail')?.innerHTML||''):'';
  document.getElementById('detail').innerHTML=
    sparkline(st.optimal_history)+
    `<h2>${esc(name)} — trials</h2><table><thead><tr><th>trial</th><th>status</th>`+
    pcols.map(p=>`<th>${esc(p)}</th>`).join('')+cols.map(c=>`<th>${esc(c)}</th>`).join('')+
    `</tr></thead><tbody>`+t.map(r=>`<tr data-t="${esc(r.name)}"><td>${esc(r.name)}</td><td>${badge(r.condition)}</td>`+
      pcols.map(p=>`<td>${esc(r.assignments?.[p])}</td>`).join('')+
      cols.map(c=>{const v=r.metrics?.[c];return `<td>${v==null?"—":esc(v.toFixed?.(5)??v)}</td>`}).join('')+
    `</tr>`).join('')+`</tbody></table><div id="trialdetail"></div>`;
  document.getElementById('trialdetail').innerHTML=keep; // survive the 3s redraw
  document.querySelectorAll('#detail tbody tr').forEach(tr=>
    tr.onclick=()=>showTrial(name,tr.dataset.t));
  if(re)refresh();
}
refresh();setInterval(refresh,3000);
</script></body></html>
"""
