"""REST backend + embedded dashboard — parity with the reference UI.

The reference serves an Angular SPA from a Go REST backend that proxies CRD
CRUD, trial logs, DB-manager metric fetches, and a NAS graph view
(``pkg/ui/v1beta1/backend.go:86,138,181,463,514,566,617``, ``nas.go``).
TPU-native there is no API server to proxy: the orchestrator journals
status to ``<workdir>/<experiment>/status.json`` and metrics live in the
observation store, so the backend is a thin read-only HTTP layer over those
two sources plus a single-file HTML dashboard (no build step, no Node).

Endpoints (JSON unless noted):

- ``GET /api/experiments``                     summaries for every journaled experiment
- ``GET /api/experiment/<name>``               full status incl. trials
- ``GET /api/experiment/<name>/trials``        trials table rows
- ``GET /api/trial/<name>/metrics``            raw metric log from the store
- ``GET /api/experiment/<name>/nas``           NAS graph (nodes/edges) for the
                                               best (or named ``?trial=``) trial
- ``GET /``                                    dashboard (text/html)
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from katib_tpu.orchestrator.status import list_statuses, read_status
from katib_tpu.store.base import ObservationStore


def _experiment_summary(status: dict) -> dict:
    return {
        "name": status.get("name"),
        "condition": status.get("condition"),
        "algorithm": status.get("algorithm"),
        "objective_metric": status.get("objective_metric"),
        "counts": status.get("counts", {}),
        "optimal": status.get("optimal"),
        "start_time": status.get("start_time"),
        "completion_time": status.get("completion_time"),
    }


def _trial_rows(status: dict) -> list[dict]:
    rows = []
    for name, t in (status.get("trials") or {}).items():
        obs = t.get("observation") or []
        rows.append(
            {
                "name": name,
                "condition": t.get("condition"),
                "assignments": t.get("assignments", {}),
                "labels": t.get("labels", {}),
                "metrics": {m["name"]: m["latest"] for m in obs},
                "start_time": t.get("start_time"),
                "completion_time": t.get("completion_time"),
            }
        )
    return rows


# -- NAS graph extraction ----------------------------------------------------


def _darts_graph(genotype: dict) -> dict:
    """Genotype → node/edge list, the shape the reference's UI renders
    (``nas.go``).  ``normal``/``reduce`` are per-node lists of kept
    ``[op, src_edge]`` pairs (nas/darts/model.py extract_genotype); source
    0/1 are the two cell inputs, source j+2 is intermediate node j."""
    nodes = [{"id": "c_{k-2}", "label": "input-2"}, {"id": "c_{k-1}", "label": "input-1"}]
    edges = []
    for cell in ("normal", "reduce"):
        per_node = genotype.get(cell) or []
        for i in range(len(per_node)):
            nodes.append({"id": f"{cell}-{i}", "label": f"{cell} node {i}"})
        for dst, pairs in enumerate(per_node):
            for op, src in pairs:
                src = int(src)
                src_id = ("c_{k-2}", "c_{k-1}")[src] if src < 2 else f"{cell}-{src - 2}"
                edges.append({"from": src_id, "to": f"{cell}-{dst}", "op": op})
    return {"type": "darts", "nodes": nodes, "edges": edges}


def _enas_graph(architecture: list) -> dict:
    """ENAS arc (per layer ``[op_id, skip...]``) → chain with skip edges."""
    nodes = [{"id": "input", "label": "input"}]
    edges = []
    for i, layer in enumerate(architecture):
        op = layer[0] if layer else 0
        nodes.append({"id": f"layer-{i}", "label": f"layer {i} (op {op})"})
        prev = "input" if i == 0 else f"layer-{i - 1}"
        edges.append({"from": prev, "to": f"layer-{i}", "op": "seq"})
        for j, bit in enumerate(layer[1:]):
            if int(bit):
                src = "input" if j == 0 else f"layer-{j - 1}"
                edges.append({"from": src, "to": f"layer-{i}", "op": "skip"})
    nodes.append({"id": "output", "label": "output"})
    if architecture:
        edges.append({"from": f"layer-{len(architecture) - 1}", "to": "output", "op": "seq"})
    return {"type": "enas", "nodes": nodes, "edges": edges}


def nas_graph_for_trial(trial: dict) -> dict | None:
    """Recover the architecture a trial trained: DARTS trials leave
    ``genotype.json`` in their checkpoint dir (nas/darts/search.py), ENAS
    trials carry it in the ``architecture`` assignment (enas/service.py)."""
    arch = (trial.get("assignments") or {}).get("architecture")
    if arch:
        try:
            return _enas_graph(json.loads(arch) if isinstance(arch, str) else arch)
        except (ValueError, TypeError):
            return None
    ckpt = trial.get("checkpoint_dir")
    if ckpt:
        path = os.path.join(ckpt, "genotype.json")
        try:
            with open(path) as f:
                return _darts_graph(json.load(f))
        except (OSError, ValueError):
            return None
    return None


# -- HTTP layer --------------------------------------------------------------


class UiServer:
    """Read-only dashboard server over a workdir + observation store."""

    def __init__(self, workdir: str, store: ObservationStore | None = None):
        self.workdir = workdir
        self.store = store

    # route handlers return (status, payload) with payload JSON-serializable

    def experiments(self):
        return 200, [_experiment_summary(s) for s in list_statuses(self.workdir)]

    def experiment(self, name: str):
        status = read_status(self.workdir, name)
        if status is None:
            return 404, {"error": f"experiment {name!r} not found"}
        return 200, status

    def trials(self, name: str):
        status = read_status(self.workdir, name)
        if status is None:
            return 404, {"error": f"experiment {name!r} not found"}
        return 200, _trial_rows(status)

    def trial_metrics(self, trial_name: str):
        if self.store is None:
            return 503, {"error": "no observation store attached"}
        logs = self.store.get(trial_name)
        return 200, [
            {
                "metric_name": l.metric_name,
                "value": l.value,
                "timestamp": l.timestamp,
                "step": l.step,
            }
            for l in logs
        ]

    def nas(self, name: str, trial_name: str | None):
        status = read_status(self.workdir, name)
        if status is None:
            return 404, {"error": f"experiment {name!r} not found"}
        trials = status.get("trials") or {}
        if trial_name is None:
            optimal = status.get("optimal") or {}
            trial_name = optimal.get("trial_name")
        if not trial_name or trial_name not in trials:
            return 404, {"error": "no trial with a recoverable architecture"}
        graph = nas_graph_for_trial(trials[trial_name])
        if graph is None:
            return 404, {"error": f"trial {trial_name!r} has no architecture artifact"}
        graph["trial"] = trial_name
        return 200, graph

    def route(self, path: str, query: dict):
        parts = [p for p in path.split("/") if p]
        if not parts:
            return "html", DASHBOARD_HTML
        if parts[0] != "api":
            return 404, {"error": "not found"}
        if parts[1:] == ["experiments"]:
            return self.experiments()
        if len(parts) >= 3 and parts[1] == "experiment":
            name = parts[2]
            rest = parts[3:]
            if not rest:
                return self.experiment(name)
            if rest == ["trials"]:
                return self.trials(name)
            if rest == ["nas"]:
                return self.nas(name, (query.get("trial") or [None])[0])
        if len(parts) == 4 and parts[1] == "trial" and parts[3] == "metrics":
            return self.trial_metrics(parts[2])
        return 404, {"error": "not found"}

    # -- server lifecycle ----------------------------------------------------

    def serve(self, port: int = 0, host: str = "127.0.0.1") -> "RunningUi":
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                parsed = urlparse(self.path)
                status, payload = ui.route(parsed.path, parse_qs(parsed.query))
                if status == "html":
                    body = payload.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                else:
                    body = json.dumps(payload, default=str).encode()
                    self.send_response(status)
                    self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        server = ThreadingHTTPServer((host, port), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return RunningUi(server, thread)


class RunningUi:
    def __init__(self, server: ThreadingHTTPServer, thread: threading.Thread):
        self._server = server
        self._thread = thread

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def start_ui(
    workdir: str, store: ObservationStore | None = None, port: int = 0,
    host: str = "127.0.0.1",
) -> RunningUi:
    return UiServer(workdir, store).serve(port=port, host=host)


# -- the dashboard (single file, no build step) ------------------------------

DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>katib-tpu</title>
<style>
body{font-family:system-ui,sans-serif;margin:2rem;background:#fafafa;color:#222}
h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
table{border-collapse:collapse;width:100%;background:#fff;box-shadow:0 1px 2px #0002}
th,td{padding:.45rem .7rem;border-bottom:1px solid #eee;text-align:left;font-size:.88rem}
th{background:#f0f0f3;font-weight:600}
tr.sel{background:#eef4ff} tbody tr{cursor:pointer}
.badge{padding:.1rem .45rem;border-radius:.6rem;font-size:.75rem;color:#fff}
.Succeeded,.MaxTrialsReached,.GoalReached{background:#2e7d32}.Failed{background:#c62828}
.Running{background:#1565c0}.EarlyStopped{background:#ef6c00}.MetricsUnavailable{background:#757575}
#detail{margin-top:1rem} pre{background:#272822;color:#f8f8f2;padding:1rem;overflow:auto;font-size:.8rem}
</style></head><body>
<h1>katib-tpu experiments</h1>
<table id="exps"><thead><tr><th>name</th><th>status</th><th>algorithm</th>
<th>objective</th><th>trials</th><th>best</th></tr></thead><tbody></tbody></table>
<div id="detail"></div>
<script>
const esc=s=>String(s??"").replace(/[&<>"]/g,c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
const badge=c=>`<span class="badge ${esc(c)}">${esc(c)}</span>`;
async function j(u){const r=await fetch(u);return r.json()}
let current=null;
async function refresh(){
  const exps=await j('/api/experiments');
  document.querySelector('#exps tbody').innerHTML=exps.map(e=>{
    const c=e.counts||{},o=e.optimal;
    return `<tr data-n="${esc(e.name)}" class="${e.name===current?'sel':''}">`+
      `<td>${esc(e.name)}</td><td>${badge(e.condition)}</td><td>${esc(e.algorithm)}</td>`+
      `<td>${esc(e.objective_metric)}</td><td>${c.succeeded??0}/${c.trials??0}</td>`+
      `<td>${o?esc(o.objective_value?.toFixed?.(5)??o.objective_value):"—"}</td></tr>`;
  }).join('');
  document.querySelectorAll('#exps tbody tr').forEach(tr=>tr.onclick=()=>show(tr.dataset.n));
  if(current)show(current,false);
}
async function show(name,re=true){
  current=name;
  const t=await j('/api/experiment/'+encodeURIComponent(name)+'/trials');
  const cols=[...new Set(t.flatMap(r=>Object.keys(r.metrics||{})))];
  const pcols=[...new Set(t.flatMap(r=>Object.keys(r.assignments||{})))];
  document.getElementById('detail').innerHTML=
    `<h2>${esc(name)} — trials</h2><table><thead><tr><th>trial</th><th>status</th>`+
    pcols.map(p=>`<th>${esc(p)}</th>`).join('')+cols.map(c=>`<th>${esc(c)}</th>`).join('')+
    `</tr></thead><tbody>`+t.map(r=>`<tr><td>${esc(r.name)}</td><td>${badge(r.condition)}</td>`+
      pcols.map(p=>`<td>${esc(r.assignments?.[p])}</td>`).join('')+
      cols.map(c=>{const v=r.metrics?.[c];return `<td>${v==null?"—":esc(v.toFixed?.(5)??v)}</td>`}).join('')+
    `</tr>`).join('')+`</tbody></table>`;
  if(re)refresh();
}
refresh();setInterval(refresh,3000);
</script></body></html>
"""
