"""Shared filesystem-root resolution.

One definition of the artifact tree root, used by BOTH writers (the
``scripts/`` harnesses via ``scripts/_common.write_artifact``) and readers
(the dashboard's flagship-progress endpoint) — a ``KATIB_ARTIFACTS_DIR``
redirect must move every producer and consumer together or evidence
silently splits across trees.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _default_root() -> str:
    """Package-relative ``artifacts/`` — but if the package was imported
    from an installed copy (site-packages) that default points at a tree
    the scripts never write, so fall back to searching upward from the
    working directory for a checkout that actually has one."""
    pkg_rel = os.path.join(_REPO_ROOT, "artifacts")
    if os.path.isdir(pkg_rel):
        return pkg_rel
    d = os.getcwd()
    while True:
        cand = os.path.join(d, "artifacts")
        # require this repo's marker, not just any directory that happens
        # to be named artifacts/ — an unrelated project's tree must not
        # silently capture every write_artifact
        if os.path.isfile(os.path.join(cand, "README.md")) and os.path.isdir(
            os.path.join(d, "katib_tpu")
        ):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return pkg_rel  # nothing found: keep the package-relative path
        d = parent


def artifacts_root() -> str:
    """The artifact tree root; ``KATIB_ARTIFACTS_DIR`` redirects it
    (integration tests run the real scripts without clobbering the
    committed ``artifacts/``)."""
    return os.environ.get("KATIB_ARTIFACTS_DIR") or _default_root()
