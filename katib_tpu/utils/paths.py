"""Shared filesystem-root resolution.

One definition of the artifact tree root, used by BOTH writers (the
``scripts/`` harnesses via ``scripts/_common.write_artifact``) and readers
(the dashboard's flagship-progress endpoint) — a ``KATIB_ARTIFACTS_DIR``
redirect must move every producer and consumer together or evidence
silently splits across trees.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def artifacts_root() -> str:
    """The artifact tree root; ``KATIB_ARTIFACTS_DIR`` redirects it
    (integration tests run the real scripts without clobbering the
    committed ``artifacts/``)."""
    return os.environ.get("KATIB_ARTIFACTS_DIR") or os.path.join(
        _REPO_ROOT, "artifacts"
    )
