"""Failure classification, retry backoff, circuit breaking, and deterministic
fault injection — the fault-tolerance vocabulary shared by the orchestrator
and the trial runner.

The reference treats trial failure as a controller-level concern: the trial
controller requeues metrics-less trials (``trial_controller.go:182-185``) and
the experiment controller counts failures against ``maxFailedTrialCount``
(``experiment_controller.go:274-330``), but a pod OOM-kill and a shape bug
both land in the same ``Failed`` bucket.  On TPUs that conflation is
expensive: preemptions and ``RESOURCE_EXHAUSTED`` are *normal* events on
long sweeps (Podracer-style architectures treat worker preemption as
routine), while a ``ValueError`` from a bad hyperparameter will fail
identically on every re-run.  This module draws that line once:

- :class:`FailureKind` + the ``classify_*`` helpers decide TRANSIENT
  (retry-worthy: preemption, RESOURCE_EXHAUSTED, OSError family, a
  signal-killed subprocess) vs PERMANENT (deterministic: ValueError /
  assertion / shape errors, ordinary nonzero exits);
- :class:`Backoff` is the one exponential-backoff-with-jitter helper
  (capped, stop-event responsive) used for trial retries, metrics re-runs,
  and suggester cooldowns;
- :class:`CircuitBreaker` isolates a flaky suggester: closed → cooling →
  half-open probe per failure, tripped open (terminal) after ``threshold``
  consecutive failures;
- :class:`FaultInjector` is the seeded, spec-driven chaos harness threaded
  through the orchestrator/runner seams ("fail trial k's attempt j as
  transient", "raise in suggester call n", "corrupt checkpoint step s",
  "delay metrics by d") so every recovery path is exercised
  deterministically in tests and via ``katib-tpu chaos``.

Everything here is stdlib-only (jax-free) so classification is importable
from metadata-only paths (status serialization, the CLI).
"""

from __future__ import annotations

import enum
import os
import random
import threading
import time

from katib_tpu.utils.clock import get_clock


class FailureKind(str, enum.Enum):
    """Why a trial attempt failed — the retry decision in one bit.

    Values are the journal/metric-label strings (``status.json``
    ``failure_kind``, ``katib_trial_retried_total{kind=...}``).
    """

    TRANSIENT = "Transient"
    PERMANENT = "Permanent"
    # no-progress stall past progressDeadlineSeconds, classified by the hang
    # watchdog (utils/watchdog.py).  Retryable like TRANSIENT: a wedged
    # compile or deadlocked collective usually clears on a re-run from the
    # last checkpoint, unlike a deterministic shape bug.
    HANG = "Hang"
    # device/mesh-layer fault: a wedged or vanished accelerator under a
    # running trial/cohort (utils/meshhealth.py classifies the pool, the
    # cohort engine degrades onto survivors).  Retryable: the re-run lands
    # on a rebuilt mesh or the serial fallback, not the dead chip.
    DEVICE = "Device"
    # jit compile / first dispatch exceeded compileDeadlineSeconds (the
    # compile watchdog in runner/trial_runner.py).  Retryable: a warm
    # compile cache or a recovered pool usually clears it.
    COMPILE_HANG = "CompileHang"

    @property
    def retryable(self) -> bool:
        """Whether the orchestrator's bounded retry loop should re-run the
        attempt (same trial name + checkpoint dir)."""
        return self in (
            FailureKind.TRANSIENT,
            FailureKind.HANG,
            FailureKind.DEVICE,
            FailureKind.COMPILE_HANG,
        )


# Infrastructure-failure markers inside exception text / tracebacks.  TPU
# preemptions and allocator exhaustion surface as XlaRuntimeError (a
# RuntimeError) whose *message* carries the gRPC-style status — there is no
# stable exception type to catch across jaxlib versions, so match the text.
_TRANSIENT_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "unavailable",
    "deadline_exceeded",
    "preempt",  # "preempted", "preemption notice received"
    "connection reset",
    "broken pipe",
    "temporarily",  # EAGAIN-style "resource temporarily unavailable"
    "device or resource busy",
    "injected transient",  # FaultInjector tracebacks classify like the real thing
)

# Device/mesh-layer markers: a chip dying or vanishing under a running
# program.  Checked before the transient markers — a dead device needs the
# mesh-rebuild path (elastic cohort degradation), not a blind same-mesh
# re-run.  libtpu/PJRT surface these as XlaRuntimeError text, like the
# transient family.
_DEVICE_MARKERS = (
    "device is in an invalid state",
    "device not found",
    "device disappeared",
    "chip has been disabled",
    "slice health",
    "injected device",  # FaultInjector device wedges classify like the real thing
)

# Exception families with an unambiguous kind.  Checked before the text
# markers: a ValueError whose message happens to say "unavailable" is still
# a deterministic bug.
_TRANSIENT_TYPES = (
    MemoryError,
    ConnectionError,
    TimeoutError,
    InterruptedError,
    OSError,  # the taxonomy's catch-all for host/IO flakiness
)
_PERMANENT_TYPES = (
    ValueError,  # shape errors, bad hyperparameters, failed casts
    TypeError,
    AssertionError,
    KeyError,
    IndexError,
    AttributeError,
    ZeroDivisionError,
    NotImplementedError,
)

# Exit codes worth a re-run: a signal-killed subprocess (Popen reports
# negative returncodes; shells report 128+signum) usually means the host OOM
# killer or a preemption SIGTERM, and EX_TEMPFAIL (75) is the sysexits
# convention for "try again".  SIGABRT (134) is included because libtpu
# aborts the process on slice/device health events.
RETRYABLE_EXIT_CODES = frozenset({75, 128 + 6, 128 + 9, 128 + 15})


def _classify_text(text: str) -> FailureKind:
    low = text.lower()
    if any(marker in low for marker in _DEVICE_MARKERS):
        return FailureKind.DEVICE
    if any(marker in low for marker in _TRANSIENT_MARKERS):
        return FailureKind.TRANSIENT
    return FailureKind.PERMANENT


def classify_exception(exc: BaseException) -> FailureKind:
    """Classify a caught exception.  Unknown types default to PERMANENT —
    retrying a bug wastes the retry budget, while a missed transient only
    costs one trial slot."""
    if isinstance(exc, InjectedFault):
        return exc.kind
    if isinstance(exc, _TRANSIENT_TYPES):
        return FailureKind.TRANSIENT
    if isinstance(exc, _PERMANENT_TYPES):
        return FailureKind.PERMANENT
    return _classify_text(f"{type(exc).__name__}: {exc}")


def classify_traceback(text: str) -> FailureKind:
    """Classify from traceback *text* — the whitebox path journals only the
    formatted traceback, and resumed trials have no live exception object."""
    low = text.lower()
    if any(marker in low for marker in _DEVICE_MARKERS):
        return FailureKind.DEVICE
    if any(marker in low for marker in _TRANSIENT_MARKERS):
        return FailureKind.TRANSIENT
    for name in (
        "oserror",
        "connectionerror",
        "connectionreseterror",
        "brokenpipeerror",
        "timeouterror",
        "memoryerror",
        "interruptederror",
        "filenotfounderror",
        "permissionerror",
    ):
        # the raising line is "SomeError: message"; a colon keeps substring
        # matches from firing on prose that merely mentions the type
        if f"{name}:" in low or low.rstrip().endswith(name):
            return FailureKind.TRANSIENT
    return FailureKind.PERMANENT


def classify_exit_code(rc: int) -> FailureKind:
    """Classify a black-box subprocess exit.  Negative = killed by signal
    (OOM killer, preemption SIGTERM) → transient; the ``RETRYABLE_EXIT_CODES``
    set covers the shell-style 128+signum encodings and EX_TEMPFAIL; any
    other nonzero exit is the trial's own deterministic failure."""
    if rc < 0 or rc in RETRYABLE_EXIT_CODES:
        return FailureKind.TRANSIENT
    return FailureKind.PERMANENT


# ---------------------------------------------------------------------------
# Backoff
# ---------------------------------------------------------------------------


class Backoff:
    """Exponential backoff with deterministic jitter, capped at ``cap``.

    ``delay(attempt)`` for 1-based attempts is ``base * factor**(attempt-1)``
    clamped to ``cap``, then scaled by a ±``jitter`` fraction drawn from a
    seeded RNG (same seed → same schedule, so chaos runs reproduce).
    With ``full_jitter=True`` the delay is instead drawn uniformly from
    ``[0, min(base * factor**(attempt-1), cap)]`` (AWS "full jitter") —
    preferred when many actors may back off in lockstep (loop restarts,
    suggester-timeout retries) because it decorrelates their wakeups.
    ``wait`` sleeps through ``stop_event.wait`` so a requested experiment
    stop is never delayed by a pending retry.

    Both time and randomness are injectable: ``clock`` (a ``utils.clock``
    Clock; None = the ambient one, which the simulator swaps for virtual
    time) and ``rng`` (a ``random.Random``; overrides ``seed`` so the chaos
    soak and the simulator can hand every actor a stream off one root seed).
    """

    def __init__(
        self,
        base: float = 1.0,
        factor: float = 2.0,
        cap: float = 30.0,
        jitter: float = 0.25,
        seed=None,
        full_jitter: bool = False,
        clock=None,
        rng: random.Random | None = None,
    ):
        self.base = max(0.0, float(base))
        self.factor = float(factor)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self.full_jitter = bool(full_jitter)
        self._clock = clock
        self._rng = rng if rng is not None else random.Random(seed)

    def delay(self, attempt: int) -> float:
        d = min(self.base * self.factor ** max(0, attempt - 1), self.cap)
        if self.full_jitter:
            return self._rng.uniform(0.0, d)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, min(d, self.cap))

    def wait(self, attempt: int, stop_event: threading.Event | None = None) -> bool:
        """Sleep out the attempt's delay.  Returns False when interrupted by
        ``stop_event`` (the caller should abandon the retry)."""
        d = self.delay(attempt)
        clock = self._clock if self._clock is not None else get_clock()
        if stop_event is None:
            clock.sleep(d)
            return True
        return not clock.wait(stop_event, d)


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Consecutive-failure breaker for the suggester seam.

    States (``state`` property):

    - ``closed``  — healthy; calls allowed.
    - ``cooling`` — a failure was recorded; ``allow()`` is False until the
      exponential cooldown elapses (bounded retry-with-backoff).
    - ``half-open`` — cooldown elapsed; exactly the next call is the probe.
      Success closes the breaker, failure re-enters cooling.
    - ``open``    — ``threshold`` consecutive failures (``tripped``); the
      caller fails the experiment with ``last_failure``.

    Not thread-safe by design: it lives on the orchestrator's single event
    loop.  ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        threshold: int = 5,
        base_cooldown: float = 0.05,
        cap: float = 30.0,
        clock=None,
    ):
        self.threshold = max(1, int(threshold))
        self.base_cooldown = float(base_cooldown)
        self.cap = float(cap)
        # bare monotonic callable; None = the ambient injectable clock
        self._clock = clock if clock is not None else (
            lambda: get_clock().monotonic()
        )
        self.failures = 0
        self.last_failure = ""
        self._retry_at = 0.0

    @property
    def tripped(self) -> bool:
        return self.failures >= self.threshold

    @property
    def state(self) -> str:
        if self.tripped:
            return "open"
        if self.failures == 0:
            return "closed"
        return "half-open" if self._clock() >= self._retry_at else "cooling"

    def allow(self) -> bool:
        """May the caller attempt a call right now?"""
        return not self.tripped and self._clock() >= self._retry_at

    def record_failure(self, detail: str = "") -> bool:
        """Count one failure; returns True when this one trips the breaker."""
        self.failures += 1
        self.last_failure = detail
        self._retry_at = self._clock() + min(
            self.base_cooldown * 2.0 ** (self.failures - 1), self.cap
        )
        return self.tripped

    def record_success(self) -> None:
        self.failures = 0
        self.last_failure = ""
        self._retry_at = 0.0


# ---------------------------------------------------------------------------
# Deterministic fault injection
# ---------------------------------------------------------------------------


class InjectedFault(RuntimeError):
    """A failure planted by :class:`FaultInjector`; carries its kind so
    ``classify_exception`` routes it exactly like the real thing."""

    def __init__(self, message: str, kind: FailureKind = FailureKind.TRANSIENT):
        super().__init__(message)
        self.kind = kind


class FaultInjector:
    """Seeded, spec-driven chaos harness.

    Spec builders (chainable) address trials by *creation index* (0-based,
    deterministic under ``parallel_trial_count=1``) or by name; attempts are
    1-based and count every execution of the trial body (transient retries
    and metrics re-runs alike):

    - ``fail_trial(k, j, kind)`` — raise at the start of trial k's attempt j;
    - ``fail_suggester(n)``      — raise inside the n-th (1-based)
      ``get_suggestions`` call;
    - ``corrupt_checkpoint(k, step)`` — overwrite the files of checkpoint
      ``step`` before trial k's next attempt (fires once);
    - ``delay_metrics(k, d)``    — stall trial k's metric production by d
      seconds each attempt (stop-event responsive);
    - ``hang_trial(k, j)``       — wedge trial k's attempt j inside the
      white-box step (sleeps until interrupted — the hang watchdog's
      ``progressDeadlineSeconds`` path must catch it);
    - ``preempt_at(k)``          — deliver SIGTERM to this process when
      trial k starts (fires once — exercises the orchestrator drain path);
    - ``compile_hang(k, j)``     — wedge trial k's attempt j in its compile
      phase (only the ``compileDeadlineSeconds`` watchdog can settle it);
    - ``wedge_device(n)``        — mark device id n wedged: the mesh-health
      prober reports it WEDGED, and cohorts whose mesh contains it raise a
      DEVICE fault (exercises elastic degradation);
    - ``flake(rate, kind)``      — seeded random per-attempt failures.

    The seams (``on_trial_attempt`` / ``on_suggester_call`` /
    ``apply_metrics_delay``) are called by the runner/orchestrator inside
    their normal classification paths, so an injected fault takes exactly
    the code path a real one would.  ``log`` records every injection that
    fired, for assertions and the ``katib-tpu chaos`` report.
    """

    def __init__(
        self,
        seed: int = 0,
        rng: random.Random | None = None,
        clock=None,
    ):
        self.seed = seed
        self._rng = rng if rng is not None else random.Random(seed)
        self._clock = clock  # None = ambient (utils.clock.get_clock())
        self._lock = threading.Lock()
        self._trial_faults: dict[tuple[object, int], FailureKind] = {}
        self._suggester_calls: set[int] = set()
        self._corruptions: dict[object, list[int]] = {}
        self._metric_delays: dict[object, float] = {}
        self._hangs: set[tuple[object, int]] = set()
        self._compile_hangs: set[tuple[object, int]] = set()
        self._wedged_devices: set[int] = set()
        self._preempts: set[object] = set()
        self._loop_kills: dict[str, list[int]] = {}
        self._loop_iters: dict[str, int] = {}
        self._suggester_stalls: dict[int, float] = {}
        self._flake_rate = 0.0
        self._flake_kind = FailureKind.TRANSIENT
        self._order: dict[str, int] = {}  # trial name -> creation index
        self._attempts: dict[str, int] = {}  # trial name -> attempts so far
        self._suggester_count = 0
        self.log: list[dict] = []

    # -- spec builders ------------------------------------------------------

    def fail_trial(self, trial, attempt: int, kind=FailureKind.TRANSIENT):
        self._trial_faults[(trial, int(attempt))] = FailureKind(kind)
        return self

    def fail_suggester(self, call: int):
        self._suggester_calls.add(int(call))
        return self

    def corrupt_checkpoint(self, trial, step: int):
        self._corruptions.setdefault(trial, []).append(int(step))
        return self

    def delay_metrics(self, trial, seconds: float):
        self._metric_delays[trial] = float(seconds)
        return self

    def hang_trial(self, trial, attempt: int = 1):
        """Wedge trial ``trial``'s attempt ``attempt`` inside the white-box
        step: the runner's ``maybe_hang`` seam sleeps until an interruption
        event (hang watchdog / stop / drain) is set."""
        self._hangs.add((trial, int(attempt)))
        return self

    def compile_hang(self, trial, attempt: int = 1):
        """Wedge trial ``trial``'s attempt ``attempt`` in its *compile/first
        dispatch* phase: the runner's ``maybe_compile_hang`` seam sleeps
        until interrupted, so only the compile watchdog
        (``compileDeadlineSeconds``) can settle it as COMPILE_HANG."""
        self._compile_hangs.add((trial, int(attempt)))
        return self

    def wedge_device(self, device_id: int):
        """Mark device ``device_id`` wedged: ``is_device_wedged`` reports it
        to the mesh-health prober (doctor / preflight classify it WEDGED
        without burning wall-clock), and ``on_cohort_execute`` raises a
        DEVICE fault for any cohort whose mesh still contains it — the
        deterministic stand-in for a chip dying under a sharded cohort."""
        self._wedged_devices.add(int(device_id))
        return self

    def unwedge_device(self, device_id: int):
        """Clear a wedge (models a pool releasing a stale grant)."""
        self._wedged_devices.discard(int(device_id))
        return self

    def preempt_at(self, trial):
        """SIGTERM this process when trial ``trial`` (creation index or
        name) starts — the deterministic stand-in for a TPU preemption
        notice; ``katib-tpu run``'s drain handler takes it from there."""
        self._preempts.add(trial)
        return self

    def flake(self, rate: float, kind=FailureKind.TRANSIENT):
        self._flake_rate = float(rate)
        self._flake_kind = FailureKind(kind)
        return self

    def kill_loop(self, loop: str, at_iteration: int = 1):
        """Raise out of async loop ``loop`` ('suggest' | 'schedule' |
        'harvest') at the top of its ``at_iteration``-th (1-based) iteration
        — the thread dies exactly the way an unhandled bug would, and only
        the supervisor can notice.  Fires once per arm."""
        self._loop_kills.setdefault(str(loop), []).append(int(at_iteration))
        return self

    def stall_suggester(self, seconds: float, call: int = 1):
        """Wedge the ``call``-th (1-based) ``get_suggestions`` call for
        ``seconds`` (stop-event responsive): exercises the suggester-timeout
        path — the call must trip the CircuitBreaker via its deadline
        instead of blocking the suggest loop forever."""
        self._suggester_stalls[int(call)] = float(seconds)
        return self

    def kill_loop_now(self, loop: str):
        """Time-indexed arming (the simulator's fault schedule): kill loop
        ``loop`` at whatever its NEXT iteration happens to be, instead of a
        pre-counted iteration number."""
        with self._lock:
            n = self._loop_iters.get(str(loop), 0) + 1
            self._loop_kills.setdefault(str(loop), []).append(n)
        return self

    def stall_suggester_now(self, seconds: float):
        """Time-indexed arming: stall whichever ``get_suggestions`` call
        comes next for ``seconds``."""
        with self._lock:
            self._suggester_stalls[self._suggester_count + 1] = float(seconds)
        return self

    # -- seams --------------------------------------------------------------

    def attempts_of(self, trial_name: str) -> int:
        with self._lock:
            return self._attempts.get(trial_name, 0)

    def _keys(self, name: str, idx: int):
        return (name, idx)

    def on_trial_attempt(self, trial) -> None:
        """Runner seam, called at the start of every attempt inside the
        classification try-block.  May corrupt checkpoints or raise."""
        name = trial.name
        with self._lock:
            idx = self._order.setdefault(name, len(self._order))
            attempt = self._attempts[name] = self._attempts.get(name, 0) + 1
            corrupt_steps = []
            for key in self._keys(name, idx):
                corrupt_steps += self._corruptions.pop(key, [])
            preempt = False
            for key in self._keys(name, idx):
                if key in self._preempts:
                    self._preempts.discard(key)
                    preempt = True
                    break
            kind = None
            for key in self._keys(name, idx):
                if (key, attempt) in self._trial_faults:
                    kind = self._trial_faults[(key, attempt)]
                    break
            if kind is None and self._flake_rate and self._rng.random() < self._flake_rate:
                kind = self._flake_kind
        for step in corrupt_steps:
            self._corrupt_step(trial.checkpoint_dir, step, name)
        if preempt:
            # the signal is asynchronous: this attempt keeps running and the
            # orchestrator's drain handler asks it to checkpoint-and-exit
            self.log.append({"seam": "preempt", "trial": name, "attempt": attempt})
            import signal as _signal

            os.kill(os.getpid(), _signal.SIGTERM)
        if kind is not None:
            self.log.append(
                {"seam": "trial", "trial": name, "attempt": attempt, "kind": kind.value}
            )
            raise InjectedFault(
                f"injected {kind.value.lower()} fault: trial={name} attempt={attempt}",
                kind,
            )

    def on_suggester_call(self, events: tuple = (), poll: float = 0.02) -> None:
        """Orchestrator seam, called inside the fault-isolated
        ``get_suggestions`` wrapper.  May stall (``stall_suggester``) or
        raise (``fail_suggester``)."""
        with self._lock:
            self._suggester_count += 1
            n = self._suggester_count
            stall = self._suggester_stalls.pop(n, 0.0)
        if stall > 0.0:
            self.log.append({"seam": "suggester-stall", "call": n, "seconds": stall})
            clock = self._clock if self._clock is not None else get_clock()
            deadline = clock.monotonic() + stall
            while clock.monotonic() < deadline:
                if any(ev.is_set() for ev in events):
                    break
                clock.sleep(poll)
        if n in self._suggester_calls:
            self.log.append({"seam": "suggester", "call": n})
            raise InjectedFault(f"injected suggester fault: call={n}")

    def on_loop_iteration(self, loop: str) -> None:
        """Async-loop seam, called at the top of every suggest/schedule/
        harvest loop iteration *outside all locks*.  Raises to kill the
        thread when a ``kill_loop`` arm matches this iteration."""
        with self._lock:
            n = self._loop_iters[loop] = self._loop_iters.get(loop, 0) + 1
            arms = self._loop_kills.get(loop)
            fire = bool(arms) and n in arms
            if fire:
                arms.remove(n)
        if fire:
            self.log.append({"seam": "kill-loop", "loop": loop, "iteration": n})
            raise InjectedFault(f"injected loop kill: loop={loop} iteration={n}")

    def apply_metrics_delay(self, trial, stop_event: threading.Event | None = None) -> None:
        """Runner seam: stall the trial's metric production (exercises
        deadline / metrics-retry interplay)."""
        with self._lock:
            idx = self._order.get(trial.name)
        delay = 0.0
        for key in (trial.name, idx):
            if key is not None and key in self._metric_delays:
                delay = self._metric_delays[key]
                break
        if delay <= 0.0:
            return
        self.log.append({"seam": "metrics", "trial": trial.name, "delay": delay})
        clock = self._clock if self._clock is not None else get_clock()
        if stop_event is not None:
            clock.wait(stop_event, delay)
        else:
            clock.sleep(delay)

    def maybe_hang(self, trial, events: tuple = (), poll: float = 0.02) -> None:
        """Runner seam, called inside the white-box trial body: when a
        ``hang_trial`` spec matches the current attempt, wedge here —
        sleeping until any of ``events`` (hang-watchdog flag, stop, drain)
        is set — exactly like a stuck compile or deadlocked collective.
        Fires once per (trial, attempt)."""
        name = trial.name
        with self._lock:
            idx = self._order.get(name)
            attempt = self._attempts.get(name, 1)
            key = None
            for k in self._keys(name, idx):
                if (k, attempt) in self._hangs:
                    key = (k, attempt)
                    break
            if key is None:
                return
            self._hangs.discard(key)
        self.log.append({"seam": "hang", "trial": name, "attempt": attempt})
        clock = self._clock if self._clock is not None else get_clock()
        live = [e for e in events if e is not None]
        while not any(e.is_set() for e in live):
            clock.sleep(poll)

    def maybe_compile_hang(self, trial, events: tuple = (), poll: float = 0.02) -> None:
        """Runner seam, called where jit compile / first dispatch would run:
        when a ``compile_hang`` spec matches the current attempt, wedge here
        until any of ``events`` (compile-watchdog flag, stop, drain) is set
        — exactly like an XLA compile that never returns.  Fires once per
        (trial, attempt)."""
        name = trial.name
        with self._lock:
            idx = self._order.get(name)
            attempt = self._attempts.get(name, 1)
            key = None
            for k in self._keys(name, idx):
                if (k, attempt) in self._compile_hangs:
                    key = (k, attempt)
                    break
            if key is None:
                return
            self._compile_hangs.discard(key)
        self.log.append({"seam": "compile-hang", "trial": name, "attempt": attempt})
        clock = self._clock if self._clock is not None else get_clock()
        live = [e for e in events if e is not None]
        while not any(e.is_set() for e in live):
            clock.sleep(poll)

    def is_device_wedged(self, device_id: int) -> bool:
        """Prober seam (``utils.meshhealth``): True when ``wedge_device``
        marked this device id — the probe classifies it WEDGED immediately
        instead of sleeping out the real deadline."""
        with self._lock:
            wedged = int(device_id) in self._wedged_devices
        if wedged:
            self.log.append({"seam": "device-probe", "device": int(device_id)})
        return wedged

    def on_cohort_execute(self, trials, device_ids) -> None:
        """Cohort seam (``runner/cohort.py``), called just before the
        vectorized program executes with the mesh's device ids: a mesh that
        still contains a wedged device raises a DEVICE fault — the elastic
        degradation path must rebuild the mesh from survivors and re-run."""
        with self._lock:
            hit = sorted(self._wedged_devices.intersection(int(d) for d in device_ids))
        if not hit:
            return
        names = [t.name for t in trials]
        self.log.append({"seam": "cohort-device", "devices": hit, "trials": names})
        raise InjectedFault(
            f"injected device fault: wedged device(s) {hit} in cohort mesh "
            f"(members: {', '.join(names)})",
            FailureKind.DEVICE,
        )

    def _corrupt_step(self, checkpoint_dir: str | None, step: int, name: str) -> None:
        if not checkpoint_dir:
            return
        # TrialCheckpointer lays steps out as step_%08d; accept a bare
        # str(step) dir too for non-Orbax custom layouts
        step_dir = os.path.join(checkpoint_dir, f"step_{int(step):08d}")
        if not os.path.isdir(step_dir):
            step_dir = os.path.join(checkpoint_dir, str(step))
        if not os.path.isdir(step_dir):
            return
        self.log.append({"seam": "checkpoint", "trial": name, "step": step})
        for root, _, files in os.walk(step_dir):
            for fname in files:
                try:
                    with open(os.path.join(root, fname), "wb") as f:
                        f.write(b"\x00CORRUPTED-BY-FAULT-INJECTOR")
                except OSError:
                    pass


# ---------------------------------------------------------------------------
# Deterministic crash points (the hard-kill sibling of FaultInjector)
# ---------------------------------------------------------------------------
#
# FaultInjector raises exceptions INSIDE a live process — it exercises the
# retry/classify paths but can never prove crash consistency, because the
# process survives to run its cleanup handlers.  A CrashPoint is the real
# thing: `crash_point("journal.append")` dies instantly (`os._exit` or
# SIGKILL, no atexit, no finally, no flush) when armed, so the bytes on disk
# at that instant are exactly what a power loss there would leave.  Each
# persistence site in the tree calls `crash_point(<site>)` in its
# vulnerable window; `katib-tpu chaos --crash-at/--kill-at <site>[:<n>]`
# and the sweep test in tests/test_journal_crash.py arm them via the
# environment (inherited by subprocesses, which is the point: the parent
# arms, the child dies, the parent resumes and asserts invariants).

#: env var arming one site: "site" or "site:n" (die on the n-th hit, 1-based)
CRASH_AT_ENV = "KATIB_CRASH_AT"
#: env var selecting how to die: "exit" (os._exit 137, default) or "kill"
#: (SIGKILL to self — indistinguishable from the OOM killer)
CRASH_MODE_ENV = "KATIB_CRASH_MODE"

#: every registered persistence site, in journal order.  Static so the
#: sweep test and the chaos CLI can enumerate sites without importing (and
#: therefore executing) every module that hosts one.
CRASH_POINTS = (
    "journal.append",      # journal record written, not yet fsync'd
    "journal.snapshot",    # snapshot temp file written, not yet renamed
    "suggester.pickle",    # suggester state temp file written, not renamed
    "status.write",        # status.json temp file written, not renamed
    "checkpoint.manifest", # checkpoint manifest temp written, not renamed
    "retry.budget",        # retry_count bumped in memory, not yet journaled
    "store.report",        # observation rows inserted, not yet committed
)

_crash_hits: dict[str, int] = {}
_crash_lock = threading.Lock()


def registered_crash_points() -> tuple[str, ...]:
    return CRASH_POINTS


def crash_point(site: str) -> None:
    """Die instantly iff ``KATIB_CRASH_AT`` arms ``site`` and this is the
    armed hit.  Unarmed (the normal case) this is one env read — cheap
    enough to leave in production code paths."""
    spec = os.environ.get(CRASH_AT_ENV)
    if not spec:
        return
    armed, _, nth = spec.partition(":")
    if armed != site:
        return
    try:
        want = max(1, int(nth)) if nth else 1
    except ValueError:
        want = 1
    with _crash_lock:
        _crash_hits[site] = _crash_hits.get(site, 0) + 1
        hit = _crash_hits[site]
    if hit < want:
        return
    if os.environ.get(CRASH_MODE_ENV) == "kill":
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
        # SIGKILL delivery can race the return; never fall through alive
        time.sleep(60)
    os._exit(137)
