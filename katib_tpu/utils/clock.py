"""Injectable clock seam for the orchestrator path.

Every time-dependent operation in the orchestrator / supervisor / watchdog /
runner stack (reading the monotonic clock, sleeping, waiting on events,
joining threads, waiting on futures, spawning worker threads, submitting
pool work) routes through one ambient :class:`Clock` so the discrete-event
simulator (``katib_tpu/sim``) can substitute a virtual clock and run a
50k-trial sweep in seconds of wall time — with the *real* scheduler code in
the loop.

The module is stdlib-only and imports nothing from ``katib_tpu`` so every
layer (``core.types`` included) can depend on it without cycles.

Production behavior is unchanged: the default :class:`SystemClock` is a
thin passthrough to ``time`` / ``threading`` / ``concurrent.futures``.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
import time
from typing import Any, Callable, Iterable, Protocol, runtime_checkable


@runtime_checkable
class Clock(Protocol):
    """The full seam surface.  See :class:`SystemClock` for semantics."""

    def monotonic(self) -> float: ...

    def perf_counter(self) -> float: ...

    def time(self) -> float: ...

    def sleep(self, seconds: float) -> None: ...

    def wait(self, event: threading.Event, timeout: float | None = None) -> bool: ...

    def join_thread(
        self, thread: threading.Thread, timeout: float | None = None
    ) -> bool: ...

    def wait_futures(
        self, futures: Iterable[cf.Future], timeout: float | None = None
    ) -> Any: ...

    def spawn(
        self,
        target: Callable[[], Any],
        *,
        name: str | None = None,
        daemon: bool = True,
    ) -> threading.Thread: ...

    def submit(
        self, pool: cf.Executor, fn: Callable[..., Any], /, *args: Any, **kwargs: Any
    ) -> cf.Future: ...


class SystemClock:
    """Real time.  The production default: trivial passthroughs."""

    def monotonic(self) -> float:
        return time.monotonic()

    def perf_counter(self) -> float:
        return time.perf_counter()

    def time(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(max(0.0, seconds))

    def wait(self, event: threading.Event, timeout: float | None = None) -> bool:
        return event.wait(timeout)

    def join_thread(
        self, thread: threading.Thread, timeout: float | None = None
    ) -> bool:
        thread.join(timeout)
        return not thread.is_alive()

    def wait_futures(
        self, futures: Iterable[cf.Future], timeout: float | None = None
    ) -> Any:
        return cf.wait(list(futures), timeout=timeout)

    def spawn(
        self,
        target: Callable[[], Any],
        *,
        name: str | None = None,
        daemon: bool = True,
    ) -> threading.Thread:
        t = threading.Thread(target=target, name=name, daemon=daemon)
        t.start()
        return t

    def submit(
        self, pool: cf.Executor, fn: Callable[..., Any], /, *args: Any, **kwargs: Any
    ) -> cf.Future:
        return pool.submit(fn, *args, **kwargs)


_DEFAULT = SystemClock()
_ambient: Clock = _DEFAULT
_ambient_lock = threading.Lock()


def get_clock() -> Clock:
    """The process-ambient clock (SystemClock unless a simulator swapped it)."""
    return _ambient


def set_clock(clock: Clock | None) -> Clock:
    """Install ``clock`` as the ambient clock; returns the previous one.

    Pass ``None`` to restore the real :class:`SystemClock`.  Callers must
    restore the previous clock when done (the simulator and tests use
    try/finally); the swap is process-global by design — the orchestrator
    stack reaches the clock ambiently rather than threading a parameter
    through every constructor.
    """
    global _ambient
    with _ambient_lock:
        prev = _ambient
        _ambient = clock if clock is not None else _DEFAULT
        return prev
