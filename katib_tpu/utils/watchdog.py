"""Hang watchdog: heartbeat registry + monitor thread for stuck trials.

The cooperative deadline in ``TrialContext`` is only polled at reporting
points (``runner/context.py``), so a white-box trial wedged *between*
``report()`` calls — a stuck XLA compile, a deadlocked collective, an infeed
stall — pins its orchestrator slot forever.  The reference has no analog
(a hung pod is eventually reaped by Kubernetes liveness machinery); on a
single-process TPU orchestrator the watchdog is that machinery:

- trials ``register()`` a heartbeat with their ``progress_deadline_seconds``;
- progress signals ``beat()`` it: white-box trials via ``TrialContext.report``,
  cohorts at step boundaries via ``CohortContext.report``, black-box trials
  from the runner's poll loop on metric-line/metric-file-mtime activity;
- a single monitor daemon thread scans all registered heartbeats; one that
  goes silent past its deadline fires its ``on_hang`` callback exactly once
  and bumps ``katib_trial_hangs_total``.

``on_hang`` is the interruption seam: the white-box runner passes an event
setter the trial observes cooperatively through ``ctx.should_stop()``, the
black-box runner triggers its existing SIGTERM→SIGKILL escalation.  The
resulting failure classifies as :class:`~katib_tpu.utils.faults.FailureKind`
``HANG`` — retryable, so the orchestrator's PR-2 retry machinery re-runs the
trial from its last checkpoint.

The same registry also arms the *compile* watchdog: the white-box runner and
``run_cohort`` register a second, one-shot heartbeat named ``compile:<name>``
with ``compile_deadline_seconds`` that is closed on the first ``beat()``
(first dispatch completed).  If it fires instead, the trial settles as the
retryable ``FailureKind.COMPILE_HANG`` — a stuck XLA compile is otherwise
indistinguishable from a wedged device.

Stdlib-only (no jax) and clock-injectable for deterministic tests.
"""

from __future__ import annotations

import threading
from typing import Callable

from katib_tpu.analysis import guarded_by, make_lock
from katib_tpu.utils.clock import get_clock


class Heartbeat:
    """One registered trial's progress pulse.  ``beat()`` is the only method
    trial code touches; it is safe from any thread and allocation-free."""

    __slots__ = (
        "name", "deadline", "on_hang", "count_metric", "_last", "_fired",
        "_silenced", "_wd",
    )

    def __init__(
        self, wd: "Watchdog", name: str, deadline: float, on_hang,
        count_metric: bool = True,
    ):
        self._wd = wd
        self.name = name
        self.deadline = float(deadline)
        self.on_hang = on_hang
        self.count_metric = count_metric
        self._last = wd._clock()
        self._fired = False
        self._silenced = False

    def beat(self) -> None:
        """Record progress (resets the stall clock)."""
        self._last = self._wd._clock()

    @property
    def fired(self) -> bool:
        """True once the watchdog classified this trial as hung."""
        return self._fired

    @property
    def last(self) -> float:
        """Clock value of the most recent ``beat()`` (the raw watermark)."""
        return self._last

    def silence(self) -> None:
        """Stop scanning this heartbeat without unregistering it — used by
        the loop supervisor while a loop is legitimately idle (STARVED):
        no-work silence must not count toward its stall deadline."""
        self._silenced = True

    def reset(self) -> None:
        """Re-arm after ``silence()`` or after a fire — the stall clock
        restarts from now (a restarted loop begins with a clean deadline)."""
        self._silenced = False
        self._fired = False
        self._last = self._wd._clock()

    def close(self) -> None:
        self._wd.unregister(self)


class Watchdog:
    """Heartbeat registry with one shared monitor thread.

    The thread starts lazily on the first ``register()`` and exits on
    ``stop()`` (or with the process — it is a daemon).  Scanning is O(live
    trials) every ``interval`` seconds, so detection latency is bounded by
    ``deadline + interval``.
    """

    # Heartbeat's own fields (_last/_fired/_silenced) are deliberately
    # lock-free: beat() must be allocation-free and safe from any thread,
    # and a stale read only delays hang detection by one scan interval.
    _GUARDS = guarded_by(_lock=("_beats", "_thread", "hang_count"))

    def __init__(self, interval: float = 0.25, clock=None, start: bool = True):
        self.interval = float(interval)
        # None = the ambient injectable clock (utils.clock); tests and the
        # supervisor may still inject a bare callable.
        self._clock = clock if clock is not None else (lambda: get_clock().monotonic())
        self._autostart = bool(start)
        self._lock = make_lock("watchdog.beats")
        self._beats: list[Heartbeat] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.hang_count = 0

    def register(
        self,
        name: str,
        deadline: float,
        on_hang: Callable[[str], None] | None = None,
        count_metric: bool = True,
    ) -> Heartbeat:
        """Start watching a trial; returns its :class:`Heartbeat` handle.
        ``on_hang(name)`` fires at most once, from the monitor thread.
        ``count_metric=False`` keeps a fire out of ``katib_trial_hangs_total``
        (supervisor loop heartbeats are not trial hangs)."""
        hb = Heartbeat(self, name, deadline, on_hang, count_metric=count_metric)
        with self._lock:
            self._beats.append(hb)
            if self._thread is None and self._autostart:
                self._stop.clear()
                self._thread = get_clock().spawn(
                    self._monitor, name="katib-watchdog", daemon=True
                )
        return hb

    def unregister(self, hb: Heartbeat) -> None:
        with self._lock:
            try:
                self._beats.remove(hb)
            except ValueError:
                pass

    def stop(self) -> None:
        """Stop the monitor thread (idempotent); registered heartbeats stay
        valid but are no longer scanned."""
        self._stop.set()
        # LCK001 fix: take+clear the thread handle under the lock (a
        # concurrent register() reads _thread to decide whether to spawn);
        # join OUTSIDE it — the monitor's _scan takes the same lock
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None:
            get_clock().join_thread(thread, timeout=2.0)

    def check_now(self) -> list[str]:
        """Run one scan synchronously (deterministic tests with a fake
        clock); returns the names newly classified as hung."""
        return self._scan()

    # -- internals ----------------------------------------------------------

    def _monitor(self) -> None:
        while not get_clock().wait(self._stop, self.interval):
            self._scan()

    def _scan(self) -> list[str]:
        now = self._clock()
        with self._lock:
            stalled = [
                hb
                for hb in self._beats
                if not hb._fired
                and not hb._silenced
                and now - hb._last > hb.deadline
            ]
            for hb in stalled:
                hb._fired = True
            self.hang_count += len(stalled)
        if stalled:
            from katib_tpu.utils import observability as obs

            for hb in stalled:
                if hb.count_metric:
                    obs.trial_hangs.inc()
                if hb.on_hang is not None:
                    try:
                        hb.on_hang(hb.name)
                    except Exception:
                        pass  # the monitor must outlive a bad callback
        return [hb.name for hb in stalled]
