"""Self-signed certificate generation + rotation for the framework's network
surfaces (UI backend, suggestion service).

The reference runs a cert-controller rotator that maintains a self-signed CA
("katib-ca", org "katib") and a webhook serving cert for the service DNS name,
regenerating before expiry (``pkg/certgenerator/v1beta1/generator.go:37-58``).
Here the same contract is a library: ``ensure_certs`` is the rotator (generate
if absent, regenerate inside the expiry grace window), and the PEM bundle on
disk is the Secret analog.  Servers wrap their listening socket with
``server_ssl_context``; clients verify against the CA with
``client_ssl_context`` — no system trust store involvement, exactly like the
reference injecting its CA bundle into the webhook clientConfig.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from dataclasses import dataclass

CA_NAME = "katib-ca"
ORGANIZATION = "katib"
# cert-controller defaults: 10y CA, 1y leaf, rotate when <90d remain
CA_VALIDITY_DAYS = 3650
LEAF_VALIDITY_DAYS = 365
ROTATE_BEFORE_DAYS = 90


@dataclass(frozen=True)
class CertBundle:
    """Paths of the PEM material one server needs (the Secret analog)."""

    ca_cert: str
    cert: str
    key: str


def _paths(cert_dir: str) -> CertBundle:
    return CertBundle(
        ca_cert=os.path.join(cert_dir, "ca.crt"),
        cert=os.path.join(cert_dir, "tls.crt"),
        key=os.path.join(cert_dir, "tls.key"),
    )


def _write_private(path: str, data: bytes) -> None:
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(data)


def generate_certs(
    cert_dir: str,
    dns_names: tuple[str, ...] = ("localhost",),
    ip_addresses: tuple[str, ...] = ("127.0.0.1",),
) -> CertBundle:
    """Create a fresh CA + server leaf under ``cert_dir`` (overwrites).

    Mirrors the rotator's shape: CA CN ``katib-ca`` / org ``katib``; the leaf
    carries the server's DNS/IP SANs the way the reference leaf carries
    ``<service>.<namespace>.svc``.  Keys are written 0600; certs 0644.
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    os.makedirs(cert_dir, exist_ok=True)
    paths = _paths(cert_dir)
    now = datetime.datetime.now(datetime.timezone.utc)

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_subject = x509.Name(
        [
            x509.NameAttribute(NameOID.COMMON_NAME, CA_NAME),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, ORGANIZATION),
        ]
    )
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_subject)
        .issuer_name(ca_subject)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=CA_VALIDITY_DAYS))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True,
                key_cert_sign=True,
                crl_sign=True,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                encipher_only=False,
                decipher_only=False,
            ),
            critical=True,
        )
        .sign(ca_key, hashes.SHA256())
    )

    leaf_key = ec.generate_private_key(ec.SECP256R1())
    sans: list[x509.GeneralName] = [x509.DNSName(d) for d in dns_names]
    sans += [x509.IPAddress(ipaddress.ip_address(i)) for i in ip_addresses]
    leaf_cert = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name(
                [
                    x509.NameAttribute(NameOID.COMMON_NAME, dns_names[0]),
                    x509.NameAttribute(NameOID.ORGANIZATION_NAME, ORGANIZATION),
                ]
            )
        )
        .issuer_name(ca_subject)
        .public_key(leaf_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=LEAF_VALIDITY_DAYS))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
        .add_extension(
            x509.ExtendedKeyUsage([ExtendedKeyUsageOID.SERVER_AUTH]), critical=False
        )
        .sign(ca_key, hashes.SHA256())
    )

    with open(paths.ca_cert, "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    with open(paths.cert, "wb") as f:
        f.write(leaf_cert.public_bytes(serialization.Encoding.PEM))
        # servers load cert+chain from one file; append the CA so clients
        # that did not pin ca.crt can still build the chain
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    _write_private(
        paths.key,
        leaf_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
    )
    # the CA key is intentionally NOT persisted: nothing needs to issue a
    # second leaf from the same CA, and a missing key cannot leak (the
    # rotator regenerates the whole bundle instead of re-issuing)
    return paths


def _load_leaf(cert_path: str):
    from cryptography import x509

    try:
        with open(cert_path, "rb") as f:
            return x509.load_pem_x509_certificate(f.read())
    except (OSError, ValueError):
        return None


def _leaf_covers(leaf, dns_names, ip_addresses) -> bool:
    """True iff every requested SAN is already on the leaf — a bundle
    generated for a different --host must be rotated even if unexpired."""
    from cryptography import x509

    try:
        san = leaf.extensions.get_extension_for_class(x509.SubjectAlternativeName).value
    except x509.ExtensionNotFound:
        return False
    have_dns = set(san.get_values_for_type(x509.DNSName))
    have_ips = {str(i) for i in san.get_values_for_type(x509.IPAddress)}
    return set(dns_names) <= have_dns and set(ip_addresses) <= have_ips


def ensure_certs(
    cert_dir: str,
    dns_names: tuple[str, ...] = ("localhost",),
    ip_addresses: tuple[str, ...] = ("127.0.0.1",),
    rotate_before_days: float = ROTATE_BEFORE_DAYS,
) -> CertBundle:
    """The rotator: return the existing bundle if every file is present, the
    leaf is outside the rotation window, AND its SANs cover the requested
    names (a bundle minted for another host must not be silently reused —
    pinned clients would fail verification for a year)."""
    paths = _paths(cert_dir)
    complete = all(os.path.exists(p) for p in (paths.ca_cert, paths.cert, paths.key))
    if complete:
        leaf = _load_leaf(paths.cert)
        if leaf is not None and _leaf_covers(leaf, dns_names, ip_addresses):
            remaining = leaf.not_valid_after_utc - datetime.datetime.now(
                datetime.timezone.utc
            )
            if remaining > datetime.timedelta(days=rotate_before_days):
                return paths
    return generate_certs(cert_dir, dns_names, ip_addresses)


def server_ssl_context(bundle: CertBundle):
    """TLS-server context for wrapping an ``http.server`` socket."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(bundle.cert, bundle.key)
    return ctx


def wrap_server_socket(ssl_context, sock):
    """Wrap a listening socket for a threading HTTP server WITHOUT doing the
    handshake in ``accept()``: with ``do_handshake_on_connect=True`` a client
    that connects and never sends a ClientHello would block the single accept
    loop and wedge every other client.  Deferred, the handshake happens on
    first read inside the per-connection handler thread (which must set a
    socket timeout to bound a stalled peer)."""
    return ssl_context.wrap_socket(
        sock, server_side=True, do_handshake_on_connect=False
    )


def client_ssl_context(ca_cert_path: str):
    """Client context that trusts ONLY the generated CA (full hostname
    verification stays on) — the CABundle-injection analog."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_verify_locations(cafile=ca_cert_path)
    return ctx
