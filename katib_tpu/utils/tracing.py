"""Span tracing — wall-clock attribution for every pipeline stage.

The counters in ``utils.observability`` say *what happened*; this module says
*where the time went*.  Podracer-style TPU systems attribute every wall-clock
second to a pipeline stage before optimizing it — suggestion latency, trial
queueing, XLA compile, per-step training — so the orchestrator opens one
:class:`Tracer` per experiment and every layer (orchestrator, suggesters,
trial runner, NAS loops) records spans into it:

- ``Tracer.span(name, **attrs)`` — context manager measuring ``perf_counter``
  intervals; each finished span is one JSONL line in
  ``<workdir>/<experiment>/trace.jsonl`` (the trace journal).
- the journal is append-only and restart-safe: a resumed experiment
  continues from the previous max elapsed offset (the same monotonic-base
  pattern ``darts/search.py`` uses for ``elapsed_s``), so a single export
  covers the experiment's whole life across process restarts.
- spans carry experiment/trial IDs in ``args`` so one export reconstructs
  the full lifecycle of e.g. a 32-trial Hyperband sweep.

Layers below the orchestrator don't hold a Tracer reference; they use the
ambient per-thread tracer (``activate``/``use_tracer`` set it, the
module-level :func:`span` / :func:`record_span` pick it up and no-op when
none is active — instrumented code stays runnable standalone).

Export: ``to_chrome_trace`` converts journal records to Chrome-trace JSON
(the ``traceEvents`` array Perfetto and ``chrome://tracing`` load directly);
``summarize`` aggregates latency distributions per span name.  CLI verbs
``katib-tpu trace export`` / ``trace summary`` wrap both.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator

TRACE_FILE = "trace.jsonl"

TRACE_ENV = "KATIB_TRACE"


def enabled() -> bool:
    """Span-tracing kill switch: ``KATIB_TRACE=0`` (or ``false``/``off``)
    suppresses the per-experiment trace journal.  Tracing is best-effort by
    contract, and at sweep scale (tens of thousands of short trials — e.g.
    the virtual-time simulator) the per-span write+flush is pure overhead."""
    return os.environ.get(TRACE_ENV, "1").strip().lower() not in (
        "0",
        "false",
        "off",
    )


def trace_path(workdir: str, experiment_name: str) -> str:
    return os.path.join(workdir, experiment_name, TRACE_FILE)


class Span:
    """Handle yielded by ``span(...)``: collects attributes to attach when
    the span closes (``sp.set(condition="Succeeded")``)."""

    __slots__ = ("name", "attrs")

    def __init__(self, name: str, attrs: dict[str, Any]):
        self.name = name
        self.attrs = attrs

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)


class _NullSpan(Span):
    """Returned when no tracer is active; absorbs ``set`` calls."""

    def __init__(self) -> None:
        super().__init__("", {})

    def set(self, **attrs: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


def _journal_elapsed_base(path: str) -> float:
    """Max ``ts + dur`` over an existing journal — the monotonic elapsed
    base a resumed experiment continues from (0.0 for a fresh journal)."""
    base = 0.0
    try:
        with open(path, errors="replace") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail write from a crash mid-append
                if isinstance(rec, dict):
                    try:
                        end = float(rec.get("ts", 0.0)) + float(rec.get("dur", 0.0))
                    except (TypeError, ValueError):
                        continue
                    base = max(base, end)
    except OSError:
        return 0.0
    return base


class Tracer:
    """Thread-safe span recorder appending to one experiment's trace journal.

    Every write is one line + flush so the journal survives a crash with at
    most the in-flight span lost; recording is best-effort (a full disk must
    never fail the experiment)."""

    def __init__(self, path: str, experiment: str | None = None):
        self.path = path
        self.experiment = experiment
        self._lock = threading.Lock()
        base = _journal_elapsed_base(path)
        # elapsed base continues across restarts so ts stays monotonic over
        # the experiment's whole life (darts/search.py elapsed_s pattern)
        self._t0 = time.perf_counter() - base
        # wall-clock anchor for ts→epoch conversion in exported traces
        self._wall_anchor = time.time() - base
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "a")
        self._closed = False

    def elapsed(self) -> float:
        """Seconds since experiment start (monotonic across restarts)."""
        return time.perf_counter() - self._t0

    def record(self, name: str, start_s: float, dur_s: float, **attrs: Any) -> None:
        """Append one finished span (``start_s`` in journal-elapsed seconds)."""
        rec: dict[str, Any] = {
            "name": name,
            "ts": round(start_s, 6),
            "dur": round(max(dur_s, 0.0), 6),
            "wall": round(self._wall_anchor + start_s, 3),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if self.experiment is not None:
            attrs.setdefault("experiment", self.experiment)
        if attrs:
            rec["args"] = attrs
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):
            return
        with self._lock:
            if self._closed:
                return
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except (OSError, ValueError):
                pass  # tracing is best-effort; never fail the experiment

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        sp = Span(name, attrs)
        start = self.elapsed()
        try:
            yield sp
        except BaseException as e:
            sp.attrs.setdefault("error", type(e).__name__)
            raise
        finally:
            self.record(name, start, self.elapsed() - start, **sp.attrs)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            try:
                self._fh.close()
            except OSError:
                pass


# -- ambient per-thread tracer ------------------------------------------------

_active = threading.local()


def current_tracer() -> Tracer | None:
    return getattr(_active, "tracer", None)


def activate(tracer: Tracer | None) -> Tracer | None:
    """Set the calling thread's ambient tracer; returns the previous one
    (pass it back to :func:`deactivate` to restore)."""
    prev = current_tracer()
    _active.tracer = tracer
    return prev


def deactivate(prev: Tracer | None) -> None:
    _active.tracer = prev


@contextmanager
def use_tracer(tracer: Tracer | None) -> Iterator[Tracer | None]:
    prev = activate(tracer)
    try:
        yield tracer
    finally:
        deactivate(prev)


@contextmanager
def span(name: str, **attrs: Any) -> Iterator[Span]:
    """Span on the ambient tracer; no-op (null span) when none is active."""
    tracer = current_tracer()
    if tracer is None:
        yield _NULL_SPAN
        return
    with tracer.span(name, **attrs) as sp:
        yield sp


def record_span(name: str, dur_s: float, **attrs: Any) -> None:
    """Record a span that ended *now* with the given duration — for code
    that measures intervals itself (e.g. time between epoch callbacks)."""
    tracer = current_tracer()
    if tracer is not None:
        end = tracer.elapsed()
        tracer.record(name, end - dur_s, dur_s, **attrs)


# -- journal readers / exporters ---------------------------------------------


def read_journal(path: str) -> list[dict]:
    """Parse a trace journal, skipping torn/corrupt lines (crash mid-append)."""
    records: list[dict] = []
    try:
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "name" in rec and "ts" in rec:
                    records.append(rec)
    except OSError:
        return []
    return records


def to_chrome_trace(records: list[dict]) -> dict:
    """Journal records → Chrome-trace JSON object format (complete events),
    loadable by Perfetto / ``chrome://tracing`` as-is.  Timestamps are µs of
    journal-elapsed time, so restarts stay on one monotonic axis."""

    def _num(rec: dict, key: str) -> float:
        try:
            return float(rec.get(key, 0.0))
        except (TypeError, ValueError):
            return 0.0

    events: list[dict] = []
    pids: set = set()
    for rec in records:
        pid = rec.get("pid", 0)
        pids.add(pid)
        events.append(
            {
                "name": str(rec.get("name", "?")),
                "cat": "katib",
                "ph": "X",
                "ts": round(_num(rec, "ts") * 1e6, 3),
                "dur": round(_num(rec, "dur") * 1e6, 3),
                "pid": pid,
                "tid": rec.get("tid", 0),
                "args": rec.get("args", {}),
            }
        )
    # process metadata rows label each restart's process in the viewer
    for pid in sorted(pids, key=str):
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"katib-tpu pid {pid}"},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def summarize(records: list[dict]) -> list[dict]:
    """Latency distribution per span name: count, total/mean/p50/p95/max
    seconds — ordered by total descending (where the wall-clock went)."""
    by_name: dict[str, list[float]] = {}
    for rec in records:
        try:
            dur = float(rec.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        by_name.setdefault(str(rec.get("name", "?")), []).append(dur)
    out = []
    for name, durs in by_name.items():
        durs.sort()
        total = sum(durs)
        out.append(
            {
                "name": name,
                "count": len(durs),
                "total_s": round(total, 6),
                "mean_s": round(total / len(durs), 6),
                "p50_s": round(_percentile(durs, 0.50), 6),
                "p95_s": round(_percentile(durs, 0.95), 6),
                "max_s": round(durs[-1], 6),
            }
        )
    out.sort(key=lambda r: r["total_s"], reverse=True)
    return out


def export_chrome_trace(journal_path: str, out_path: str) -> int:
    """Read a journal, write Chrome-trace JSON to ``out_path``; returns the
    number of span events exported (0 when the journal is missing/empty)."""
    records = read_journal(journal_path)
    if not records:
        return 0
    doc = to_chrome_trace(records)
    parent = os.path.dirname(out_path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return len(records)
