"""Durable filesystem writes — the one place that knows how to make a
file survive a hard kill (SIGKILL/OOM/power loss, not just SIGTERM).

``os.replace`` alone gives atomicity (readers see old or new, never a
mix) but NOT durability: on many filesystems the rename can hit disk
before the data blocks, so a crash right after replace surfaces an
empty or partial file.  The full recipe is fsync(tempfile) →
``os.replace`` → fsync(directory), and every persistence site in the
tree (status snapshot, suggester pickle, journal snapshot, checkpoint
manifest) routes through here so none of them can drift on the recipe.

Stdlib-only, jax-free.
"""

from __future__ import annotations

import os
import tempfile


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry is durable.  Best-effort:
    some platforms/filesystems refuse O_RDONLY on directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_replace(
    path: str,
    data: bytes,
    *,
    prefix: str = ".tmp-",
    crash_site: str | None = None,
) -> None:
    """Durably replace ``path`` with ``data``: write a sibling temp file,
    flush + fsync it, rename over ``path``, fsync the directory.

    ``crash_site`` names the :func:`katib_tpu.utils.faults.crash_point`
    fired between the temp-file write and the rename — the window the
    deterministic crash harness kills in to prove readers only ever see
    the old complete file or the new complete file.
    """
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=prefix)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if crash_site is not None:
            from katib_tpu.utils.faults import crash_point

            crash_point(crash_site)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    fsync_dir(d)
