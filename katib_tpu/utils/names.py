"""Single-sourced safety predicate for names that become workdir path
components (experiment names from YAML, URLs, or the SDK).

The reference gets this for free from K8s DNS-1123 object-name rules; here
one shared helper keeps the admission webhook (``core/validation.py``) and
the journal reader (``orchestrator/status.py``) from drifting apart on what
counts as path-safe."""

from __future__ import annotations

import os


def is_safe_path_component(name: str) -> bool:
    """True iff ``name`` can be joined under a workdir without escaping it:
    non-empty, not a dot-dir, and free of separators and NUL bytes."""
    if not name or name in (".", ".."):
        return False
    if "/" in name or "\x00" in name:
        return False
    if os.sep in name or (os.altsep and os.altsep in name):
        return False
    return True
