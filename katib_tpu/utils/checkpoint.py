"""Trial checkpointing: Orbax pytree snapshots with step retention.

Replaces the reference's three ad-hoc checkpoint mechanisms (SURVEY.md §5):
PBT's ``shutil.copytree`` of opaque trial dirs on a RWX PVC
(``pbt/service.py:259-268``), the ENAS controller's TF1 Saver
(``enas/service.py:278``), and the simple-pbt example's pickle files
(``pbt_test.py:49-66``).  Here every checkpoint is a JAX pytree written
through Orbax — the same format on one chip or a v5e-64 mesh (Orbax handles
sharded arrays natively), so PBT exploit copies, experiment resume, and
preemption recovery all move the same artifacts.

Layout under a trial's checkpoint directory::

    <dir>/step_00000010/   # one Orbax PyTree checkpoint per retained step

PBT lineage needs no special casing: the suggester copies the parent's
whole directory tree before the child trial starts, and the child's
``restore()`` picks up the parent's latest step.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any

_STEP_DIR = re.compile(r"^step_(\d{8})$")


def _step_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:08d}")


class TrialCheckpointer:
    """Save/restore pytrees under one trial's checkpoint directory.

    Orbax is imported lazily so trials that never checkpoint pay nothing.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        if not directory:
            raise ValueError("checkpoint directory is required")
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self._ckptr = None

    def _checkpointer(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            self._ckptr = ocp.PyTreeCheckpointer()
        return self._ckptr

    # -- queries -------------------------------------------------------------

    def all_steps(self) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_DIR.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save / restore ------------------------------------------------------

    def save(self, pytree: Any, step: int, *, force: bool = True) -> str:
        """Write ``pytree`` as the checkpoint for ``step``; prunes old steps
        beyond ``max_to_keep``.  Returns the checkpoint path."""
        os.makedirs(self.directory, exist_ok=True)
        path = _step_path(self.directory, step)
        if os.path.exists(path):
            if not force:
                raise FileExistsError(path)
            shutil.rmtree(path)
        self._checkpointer().save(path, pytree)
        if self.max_to_keep is not None and self.max_to_keep > 0:
            for old in self.all_steps()[: -self.max_to_keep]:
                shutil.rmtree(_step_path(self.directory, old), ignore_errors=True)
        return path

    def restore(self, template: Any = None, step: int | None = None):
        """Restore ``(pytree, step)``; ``None`` when no checkpoint exists.

        ``template`` (a pytree of arrays or ShapeDtypeStructs) pins the
        restored structure/sharding; without it Orbax returns its default
        representation (nested dicts of numpy arrays).
        """
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        path = _step_path(self.directory, step)
        if not os.path.isdir(path):
            return None
        if template is not None:
            import orbax.checkpoint as ocp

            restored = self._checkpointer().restore(
                path, args=ocp.args.PyTreeRestore(template)
            )
        else:
            restored = self._checkpointer().restore(path)
        return restored, step


def copy_checkpoint_tree(src_dir: str, dst_dir: str) -> bool:
    """PBT exploit: clone a parent trial's full checkpoint lineage directory.
    Returns False when the parent has nothing yet (the child cold-starts)."""
    if not os.path.isdir(src_dir):
        return False
    if os.path.isdir(dst_dir):
        shutil.rmtree(dst_dir)
    shutil.copytree(src_dir, dst_dir)
    return True
