"""Trial checkpointing: Orbax pytree snapshots with step retention.

Replaces the reference's three ad-hoc checkpoint mechanisms (SURVEY.md §5):
PBT's ``shutil.copytree`` of opaque trial dirs on a RWX PVC
(``pbt/service.py:259-268``), the ENAS controller's TF1 Saver
(``enas/service.py:278``), and the simple-pbt example's pickle files
(``pbt_test.py:49-66``).  Here every checkpoint is a JAX pytree written
through Orbax — the same format on one chip or a v5e-64 mesh (Orbax handles
sharded arrays natively), so PBT exploit copies, experiment resume, and
preemption recovery all move the same artifacts.

Layout under a trial's checkpoint directory::

    <dir>/step_00000010/               # one Orbax PyTree checkpoint per step
    <dir>/step_00000010.manifest.json  # sidecar: file sizes + tree digest
    <dir>/quarantine-step_00000012/    # a step restore() refused (corrupt)

The sidecar manifest (written after the Orbax commit succeeds) is what makes
``restore()`` preemption-proof: a step whose files are missing, truncated,
or whose pytree-structure digest changed is *quarantined* (renamed aside for
post-mortem) and restore falls back to the newest step that still verifies,
instead of making the latest write a single point of failure for the whole
resume story.  Steps without a manifest (pre-manifest layouts, hand-copied
dirs) are attempted best-effort and quarantined only if Orbax itself rejects
them.

PBT lineage needs no special casing: the suggester copies the parent's
whole directory tree before the child trial starts, and the child's
``restore()`` picks up the parent's latest step.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Any

_STEP_DIR = re.compile(r"^step_(\d{8})$")
_MANIFEST_SUFFIX = ".manifest.json"
_QUARANTINE_PREFIX = "quarantine-"


def _step_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:08d}")


def _manifest_path(directory: str, step: int) -> str:
    return _step_path(directory, step) + _MANIFEST_SUFFIX


def _tree_digest(pytree: Any) -> str:
    """Structure digest of a pytree: treedef + per-leaf shape/dtype, hashed.
    Catches a manifest paired with a *different* trial's step (PBT copy gone
    wrong) without reading any array data."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(pytree)
    parts = [str(treedef)]
    for leaf in leaves:
        parts.append(f"{getattr(leaf, 'shape', ())}:{getattr(leaf, 'dtype', type(leaf).__name__)}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _walk_sizes(step_dir: str) -> dict[str, int]:
    sizes: dict[str, int] = {}
    for root, _, files in os.walk(step_dir):
        for fname in files:
            full = os.path.join(root, fname)
            rel = os.path.relpath(full, step_dir)
            try:
                sizes[rel] = os.path.getsize(full)
            except OSError:
                sizes[rel] = -1
    return sizes


class TrialCheckpointer:
    """Save/restore pytrees under one trial's checkpoint directory.

    Orbax is imported lazily so trials that never checkpoint pay nothing.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        if not directory:
            raise ValueError("checkpoint directory is required")
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self._ckptr = None

    def _checkpointer(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            self._ckptr = ocp.PyTreeCheckpointer()
        return self._ckptr

    # -- queries -------------------------------------------------------------

    def all_steps(self) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_DIR.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def verify_step(self, step: int) -> bool | None:
        """Check a step against its sidecar manifest: True = verified,
        False = provably damaged (missing/resized files, step mismatch),
        None = no manifest to check against (legacy/hand-copied step)."""
        step_dir = _step_path(self.directory, step)
        if not os.path.isdir(step_dir):
            return False
        try:
            with open(_manifest_path(self.directory, step)) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if manifest.get("step") != step:
            return False
        for rel, size in (manifest.get("files") or {}).items():
            full = os.path.join(step_dir, rel)
            try:
                if os.path.getsize(full) != size:
                    return False
            except OSError:
                return False
        return True

    def quarantine_step(self, step: int, reason: str = "") -> None:
        """Move a damaged step (and its manifest) aside for post-mortem;
        ``all_steps()`` no longer sees it.  Best-effort: an unmovable dir is
        deleted instead so restore cannot pick it again."""
        step_dir = _step_path(self.directory, step)
        target = os.path.join(
            self.directory, f"{_QUARANTINE_PREFIX}step_{step:08d}"
        )
        suffix = 0
        while os.path.exists(target):
            suffix += 1
            target = os.path.join(
                self.directory, f"{_QUARANTINE_PREFIX}step_{step:08d}.{suffix}"
            )
        try:
            os.rename(step_dir, target)
            if reason:
                with open(os.path.join(target, "QUARANTINE_REASON"), "w") as f:
                    f.write(reason + "\n")
        except OSError:
            shutil.rmtree(step_dir, ignore_errors=True)
        manifest = _manifest_path(self.directory, step)
        try:
            os.replace(manifest, target + _MANIFEST_SUFFIX)
        except OSError:
            pass

    # -- save / restore ------------------------------------------------------

    def save(self, pytree: Any, step: int, *, force: bool = True) -> str:
        """Write ``pytree`` as the checkpoint for ``step``; prunes old steps
        beyond ``max_to_keep``.  Returns the checkpoint path.

        After the Orbax commit succeeds a sidecar manifest (per-file sizes +
        pytree structure digest) is written beside the step dir — the
        verification record ``restore()`` uses to refuse half-written steps
        after a preemption."""
        os.makedirs(self.directory, exist_ok=True)
        path = _step_path(self.directory, step)
        if os.path.exists(path):
            if not force:
                raise FileExistsError(path)
            shutil.rmtree(path)
        self._checkpointer().save(path, pytree)
        self._write_manifest(pytree, step, path)
        if self.max_to_keep is not None and self.max_to_keep > 0:
            for old in self.all_steps()[: -self.max_to_keep]:
                shutil.rmtree(_step_path(self.directory, old), ignore_errors=True)
                try:
                    os.unlink(_manifest_path(self.directory, old))
                except OSError:
                    pass
        return path

    def _write_manifest(self, pytree: Any, step: int, step_dir: str) -> None:
        # best-effort (a manifest-less step still restores, just unverified);
        # written atomically AND durably (fsync file + dir, utils/fsio.py) so
        # neither a preemption mid-write nor a hard kill right after the
        # rename can leave a manifest that condemns a perfectly good step
        try:
            from katib_tpu.utils.fsio import atomic_replace

            doc = {
                "step": step,
                "tree_digest": _tree_digest(pytree),
                "files": _walk_sizes(step_dir),
            }
            atomic_replace(
                _manifest_path(self.directory, step),
                json.dumps(doc).encode(),
                prefix=".manifest-",
                crash_site="checkpoint.manifest",
            )
        except Exception:
            pass

    def restore(self, template: Any = None, step: int | None = None):
        """Restore ``(pytree, step)``; ``None`` when no restorable checkpoint
        exists (cold start).

        Last-good recovery: without an explicit ``step``, candidates are
        tried newest-first.  A step that fails manifest verification or whose
        Orbax restore raises is quarantined (``quarantine-step_XXXXXXXX``),
        ``katib_checkpoint_fallback_total`` is bumped, and the next-older
        step is tried — a torn latest write costs one step of progress, not
        the whole trial.

        ``template`` (a pytree of arrays or ShapeDtypeStructs) pins the
        restored structure/sharding; without it Orbax returns its default
        representation (nested dicts of numpy arrays).
        """
        if step is not None:
            candidates = [step]
        else:
            candidates = list(reversed(self.all_steps()))
        for i, cand in enumerate(candidates):
            path = _step_path(self.directory, cand)
            if not os.path.isdir(path):
                if step is not None:
                    return None
                continue
            verdict = self.verify_step(cand)
            if verdict is False:
                self._fallback(cand, "manifest verification failed")
                continue
            try:
                restored = self._restore_step(path, template)
            except Exception as e:
                self._fallback(cand, f"restore raised {type(e).__name__}: {e}")
                continue
            return restored, cand
        return None

    def _restore_step(self, path: str, template: Any):
        if template is not None:
            import orbax.checkpoint as ocp

            return self._checkpointer().restore(
                path, args=ocp.args.PyTreeRestore(template)
            )
        return self._checkpointer().restore(path)

    def _fallback(self, step: int, reason: str) -> None:
        self.quarantine_step(step, reason)
        from katib_tpu.utils import observability as obs

        obs.checkpoint_fallbacks.inc()


def copy_checkpoint_tree(src_dir: str, dst_dir: str) -> bool:
    """PBT exploit: clone a parent trial's full checkpoint lineage directory.
    Returns False when the parent has nothing yet (the child cold-starts).

    Crash-safe: the copy lands in a ``.tmp`` sibling first and is renamed
    into place only when complete, so a process killed mid-copy leaves either
    the previous ``dst_dir`` or none — never a half-copied lineage whose
    latest step restores garbage into the child."""
    if not os.path.isdir(src_dir):
        return False
    tmp_dir = dst_dir.rstrip("/\\") + ".tmp"
    if os.path.isdir(tmp_dir):
        shutil.rmtree(tmp_dir)  # leftover from an interrupted earlier copy
    shutil.copytree(src_dir, tmp_dir)
    if os.path.isdir(dst_dir):
        shutil.rmtree(dst_dir)
    os.rename(tmp_dir, dst_dir)
    return True
