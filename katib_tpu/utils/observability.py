"""Prometheus-style metrics + profiling hooks.

Parity with the reference controller's Prometheus instrumentation
(``pkg/controller.v1beta1/experiment/util/prometheus_metrics.go:40-60`` and
``trial/util/prometheus_metrics.go:40-60``: ``katib_experiment_*_total``,
``katib_experiments_current``, ``katib_trial_*_total`` incl.
``katib_trial_metrics_unavailable_total``) without the client_golang
dependency: a tiny thread-safe registry with text exposition and an optional
``/metrics`` HTTP endpoint.  The orchestrator increments these; anything
that scrapes Prometheus text format can consume them.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable


class _Metric:
    def __init__(self, name: str, help_text: str, kind: str):
        self.name = name
        self.help = help_text
        self.kind = kind
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
        return tuple(sorted(labels.items()))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def samples(self) -> Iterable[tuple[dict[str, str], float]]:
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> _Metric:
        return self._register(name, help_text, "counter")

    def gauge(self, name: str, help_text: str = "") -> _Metric:
        return self._register(name, help_text, "gauge")

    def _register(self, name: str, help_text: str, kind: str) -> _Metric:
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = _Metric(name, help_text, kind)
                self._metrics[name] = metric
            return metric

    def render(self) -> str:
        """Prometheus text exposition format."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            samples = m.samples()
            if not samples:
                lines.append(f"{m.name} 0")
                continue
            for labels, value in samples:
                if labels:
                    label_str = ",".join(
                        f'{k}="{v}"' for k, v in sorted(labels.items())
                    )
                    lines.append(f"{m.name}{{{label_str}}} {value:g}")
                else:
                    lines.append(f"{m.name} {value:g}")
        return "\n".join(lines) + "\n"

    def serve(self, port: int = 0, host: str = "127.0.0.1") -> "MetricsServer":
        """Expose ``/metrics`` on a daemon thread; returns a stoppable handle
        (reference serves on ``:8080``, ``config defaults.go:14``)."""
        registry = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr noise
                pass

        server = ThreadingHTTPServer((host, port), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return MetricsServer(server, thread)


class MetricsServer:
    def __init__(self, server: ThreadingHTTPServer, thread: threading.Thread):
        self._server = server
        self._thread = thread

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# -- default registry + the reference metric set -----------------------------

REGISTRY = MetricsRegistry()

experiments_created = REGISTRY.counter(
    "katib_experiment_created_total", "Experiments started"
)
experiments_succeeded = REGISTRY.counter(
    "katib_experiment_succeeded_total", "Experiments reaching a success condition"
)
experiments_failed = REGISTRY.counter(
    "katib_experiment_failed_total", "Experiments reaching Failed"
)
experiments_current = REGISTRY.gauge(
    "katib_experiments_current", "Experiments currently running"
)
trials_created = REGISTRY.counter("katib_trial_created_total", "Trials launched")
trials_succeeded = REGISTRY.counter(
    "katib_trial_succeeded_total", "Trials completing successfully"
)
trials_failed = REGISTRY.counter("katib_trial_failed_total", "Trials failing")
trials_early_stopped = REGISTRY.counter(
    "katib_trial_early_stopped_total", "Trials stopped by early-stopping rules"
)
trials_killed = REGISTRY.counter(
    "katib_trial_killed_total", "Trials killed by experiment shutdown"
)
trials_metrics_unavailable = REGISTRY.counter(
    "katib_trial_metrics_unavailable_total",
    "Trials finishing without reporting the objective metric",
)
