"""Prometheus-style metrics + profiling hooks.

Parity with the reference controller's Prometheus instrumentation
(``pkg/controller.v1beta1/experiment/util/prometheus_metrics.go:40-60`` and
``trial/util/prometheus_metrics.go:40-60``: ``katib_experiment_*_total``,
``katib_experiments_current``, ``katib_trial_*_total`` incl.
``katib_trial_metrics_unavailable_total``) without the client_golang
dependency: a tiny thread-safe registry with text exposition and an optional
``/metrics`` HTTP endpoint.  The orchestrator increments these; anything
that scrapes Prometheus text format can consume them.

Beyond the reference set this registry also carries latency histograms
(``_bucket``/``_sum``/``_count`` exposition) and device telemetry gauges —
the aggregate view that pairs with the per-span journal in
``utils.tracing``.
"""

from __future__ import annotations

import bisect
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable

from katib_tpu.analysis import guarded_by, make_lock


def _escape_label_value(v: str) -> str:
    """Text exposition format: backslash, double-quote, and newline must be
    escaped inside label values or they corrupt the scrape output."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_labels(labels: dict[str, str]) -> str:
    return ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in sorted(labels.items())
    )


def _format_value(value: float) -> str:
    return f"{value:g}"


class _Metric:
    _GUARDS = guarded_by(_lock=("_values",))

    def __init__(self, name: str, help_text: str, kind: str):
        self.name = name
        self.help = help_text
        self.kind = kind
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        self._lock = make_lock("metrics.metric")

    def _key(self, labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
        return tuple(sorted(labels.items()))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def samples(self) -> Iterable[tuple[dict[str, str], float]]:
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]

    def render_samples(self) -> list[str]:
        samples = self.samples()
        if not samples:
            return [f"{self.name} 0"]
        lines = []
        for labels, value in samples:
            if labels:
                lines.append(
                    f"{self.name}{{{_format_labels(labels)}}} {_format_value(value)}"
                )
            else:
                lines.append(f"{self.name} {_format_value(value)}")
        return lines

    def snapshot(self) -> dict:
        samples = [
            {"labels": labels, "value": value} for labels, value in self.samples()
        ]
        return {
            "kind": self.kind,
            "help": self.help,
            "total": sum(s["value"] for s in samples),
            "samples": samples,
        }


# Default bucket boundaries span sub-millisecond suggestion calls through
# multi-minute trials (seconds).
DEFAULT_BUCKETS = (
    0.001,
    0.005,
    0.01,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
    30.0,
    60.0,
    120.0,
    300.0,
    600.0,
)


class _Histogram(_Metric):
    """Prometheus histogram: per-series bucket counts + sum + count, rendered
    as cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count`` series."""

    _GUARDS = guarded_by(_lock=("_series",))

    def __init__(
        self,
        name: str,
        help_text: str,
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_text, "histogram")
        self.buckets = tuple(sorted(buckets))
        # per label-key: [bucket counts (len+1, last = +Inf overflow), sum, count]
        self._series: dict[tuple[tuple[str, str], ...], list] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = series
            series[0][idx] += 1
            series[1] += value
            series[2] += 1

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        raise TypeError(f"histogram {self.name} supports observe(), not inc()")

    set = inc  # type: ignore[assignment]

    def get_count(self, **labels: str) -> int:
        with self._lock:
            series = self._series.get(self._key(labels))
            return series[2] if series else 0

    def get_sum(self, **labels: str) -> float:
        with self._lock:
            series = self._series.get(self._key(labels))
            return series[1] if series else 0.0

    def samples(self) -> Iterable[tuple[dict[str, str], float]]:
        # "samples" for a histogram = per-series observation counts; the
        # full bucket detail lives in render_samples()/snapshot().
        with self._lock:
            return [(dict(k), float(s[2])) for k, s in self._series.items()]

    def _snapshot_series(self) -> list[tuple[dict[str, str], list[int], float, int]]:
        with self._lock:
            return [
                (dict(k), list(s[0]), s[1], s[2]) for k, s in self._series.items()
            ]

    def render_samples(self) -> list[str]:
        series = self._snapshot_series()
        if not series:
            # expose empty bucket/sum/count series so scrapers see the metric
            series = [({}, [0] * (len(self.buckets) + 1), 0.0, 0)]
        lines = []
        for labels, counts, total, count in series:
            cumulative = 0
            for bound, c in zip(self.buckets, counts):
                cumulative += c
                le_labels = dict(labels)
                le_labels["le"] = _format_value(bound)
                lines.append(
                    f"{self.name}_bucket{{{_format_labels(le_labels)}}} {cumulative}"
                )
            cumulative += counts[-1]
            inf_labels = dict(labels)
            inf_labels["le"] = "+Inf"
            lines.append(
                f"{self.name}_bucket{{{_format_labels(inf_labels)}}} {cumulative}"
            )
            suffix = f"{{{_format_labels(labels)}}}" if labels else ""
            lines.append(f"{self.name}_sum{suffix} {_format_value(total)}")
            lines.append(f"{self.name}_count{suffix} {count}")
        return lines

    def snapshot(self) -> dict:
        series = self._snapshot_series()
        return {
            "kind": self.kind,
            "help": self.help,
            "total": sum(count for _, _, _, count in series),
            "samples": [
                {
                    "labels": labels,
                    "count": count,
                    "sum": total,
                    "mean": (total / count) if count else 0.0,
                }
                for labels, _, total, count in series
            ],
        }


class MetricsRegistry:
    _GUARDS = guarded_by(_lock=("_metrics",))

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = make_lock("metrics.registry")

    def counter(self, name: str, help_text: str = "") -> _Metric:
        return self._register(name, help_text, "counter")

    def gauge(self, name: str, help_text: str = "") -> _Metric:
        return self._register(name, help_text, "gauge")

    def histogram(
        self,
        name: str,
        help_text: str = "",
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> _Histogram:
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = _Histogram(name, help_text, buckets)
                self._metrics[name] = metric
            if not isinstance(metric, _Histogram):
                raise TypeError(f"metric {name} already registered as {metric.kind}")
            return metric

    def _register(self, name: str, help_text: str, kind: str) -> _Metric:
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = _Metric(name, help_text, kind)
                self._metrics[name] = metric
            return metric

    def render(self) -> str:
        """Prometheus text exposition format."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.render_samples())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict[str, dict]:
        """JSON-friendly view of every metric — served by the UI backend so
        the dashboard shows counters without a separate Prometheus scrape."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.snapshot() for m in metrics}

    def serve(self, port: int = 0, host: str = "127.0.0.1") -> "MetricsServer":
        """Expose ``/metrics`` on a daemon thread; returns a stoppable handle
        (reference serves on ``:8080``, ``config defaults.go:14``)."""
        registry = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, include_body: bool) -> None:
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if include_body:
                    self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                self._respond(include_body=True)

            def do_HEAD(self):  # noqa: N802 — probes HEAD before scraping
                self._respond(include_body=False)

            def _method_not_allowed(self):
                self.send_response(405)
                self.send_header("Allow", "GET, HEAD")
                self.send_header("Content-Length", "0")
                self.end_headers()

            do_POST = _method_not_allowed  # noqa: N815 (http.server API)
            do_PUT = _method_not_allowed  # noqa: N815
            do_DELETE = _method_not_allowed  # noqa: N815
            do_PATCH = _method_not_allowed  # noqa: N815
            do_OPTIONS = _method_not_allowed  # noqa: N815

            def log_message(self, *args):  # silence per-request stderr noise
                pass

        server = ThreadingHTTPServer((host, port), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return MetricsServer(server, thread)


class MetricsServer:
    def __init__(self, server: ThreadingHTTPServer, thread: threading.Thread):
        self._server = server
        self._thread = thread

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


# -- default registry + the reference metric set -----------------------------

REGISTRY = MetricsRegistry()

experiments_created = REGISTRY.counter(
    "katib_experiment_created_total", "Experiments started"
)
experiments_succeeded = REGISTRY.counter(
    "katib_experiment_succeeded_total", "Experiments reaching a success condition"
)
experiments_failed = REGISTRY.counter(
    "katib_experiment_failed_total", "Experiments reaching Failed"
)
experiments_current = REGISTRY.gauge(
    "katib_experiments_current", "Experiments currently running"
)
trials_created = REGISTRY.counter("katib_trial_created_total", "Trials launched")
trials_succeeded = REGISTRY.counter(
    "katib_trial_succeeded_total", "Trials completing successfully"
)
trials_failed = REGISTRY.counter("katib_trial_failed_total", "Trials failing")
trials_early_stopped = REGISTRY.counter(
    "katib_trial_early_stopped_total", "Trials stopped by early-stopping rules"
)
trials_killed = REGISTRY.counter(
    "katib_trial_killed_total", "Trials killed by experiment shutdown"
)
trials_metrics_unavailable = REGISTRY.counter(
    "katib_trial_metrics_unavailable_total",
    "Trials finishing without reporting the objective metric",
)
trials_retried = REGISTRY.counter(
    "katib_trial_retried_total",
    "Trial attempts re-run after a classified failure (kind label)",
)
suggester_errors = REGISTRY.counter(
    "katib_suggester_errors_total",
    "get_suggestions exceptions absorbed by the circuit breaker (algorithm label)",
)

# -- latency distributions + device telemetry ---------------------------------

experiment_duration = REGISTRY.histogram(
    "katib_experiment_duration_seconds",
    "Wall-clock duration of completed experiments",
)
trial_duration = REGISTRY.histogram(
    "katib_trial_duration_seconds",
    "Wall-clock duration of completed trials",
)
suggestion_latency = REGISTRY.histogram(
    "katib_suggestion_latency_seconds",
    "Latency of suggester get_suggestions calls",
)
trial_attempts = REGISTRY.histogram(
    "katib_trial_attempts",
    "Executions per terminal trial (1 = no retry; includes transient retries "
    "and metrics re-runs)",
    buckets=(1.0, 2.0, 3.0, 4.0, 5.0, 8.0, 13.0),
)
trial_step_seconds = REGISTRY.histogram(
    "katib_trial_step_seconds",
    "Per-step (or per-epoch-averaged) training step time",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
)
trial_first_step_seconds = REGISTRY.gauge(
    "katib_trial_first_step_seconds",
    "First-step latency split into compile vs execute (phase label)",
)
trial_images_per_second = REGISTRY.gauge(
    "katib_trial_images_per_second",
    "Training throughput of the most recent epoch",
)
device_hbm_bytes = REGISTRY.gauge(
    "katib_device_hbm_bytes_in_use",
    "Per-device bytes in use (jax device memory_stats, where available)",
)
step_loop_window = REGISTRY.gauge(
    "katib_step_loop_window",
    "Configured scan-window size of the device-resident DARTS step loop "
    "(steps folded into one dispatch; 0 when the step loop is not engaged)",
)
steps_per_dispatch = REGISTRY.gauge(
    "katib_steps_per_dispatch",
    "Training steps executed per host dispatch in the most recent epoch "
    "(window size under the device-resident step loop, 1 under eager "
    "stepping — the first thing to check when MFU is low)",
)

# -- roofline telemetry (katib_tpu/costmodel/) --------------------------------

dispatch_mfu = REGISTRY.gauge(
    "katib_dispatch_mfu",
    "Model-flops utilization of the live dispatch path: XLA-counted flops "
    "per measured step second over the device kind's peak "
    "(costmodel.peaks; KATIB_PEAK_FLOPS overrides the denominator)",
)
arithmetic_intensity = REGISTRY.gauge(
    "katib_arithmetic_intensity",
    "Flops per byte accessed of the live program (XLA pre-fusion bytes); "
    "below the device's ridge intensity the program is memory-bound and "
    "no dispatch tuning reaches peak flops",
)
roofline_headroom = REGISTRY.gauge(
    "katib_roofline_headroom",
    "Measured step time over the program's binding roofline floor "
    "(1.0 = running at the roofline; 10 = 10x slack — look at "
    "katib_steps_per_dispatch and the trace journal before the kernel)",
)

# -- async orchestration (orchestrator/async_loops.py) ------------------------

suggest_seconds = REGISTRY.histogram(
    "katib_suggest_seconds",
    "Wall-clock latency of each suggest-loop suggester call (async "
    "orchestrator; hidden behind training when lookahead is healthy)",
)
pending_proposals = REGISTRY.gauge(
    "katib_pending_proposals",
    "Proposed-but-undispatched trials held in the suggest->schedule queue "
    "(0 sustained means the suggester cannot keep up with the mesh)",
)
mesh_occupancy = REGISTRY.gauge(
    "katib_mesh_occupancy",
    "Fraction of executor slots busy with dispatched trials "
    "(sustained < 0.5 means the mesh idles between cohorts)",
)
loop_restarts = REGISTRY.counter(
    "katib_loop_restarts_total",
    "Async loop threads restarted by the supervisor, by loop= label "
    "(suggest/schedule/harvest); a climbing count is a restart storm — "
    "check the journal's supervisor events for the crash tracebacks",
)
loop_stalled = REGISTRY.gauge(
    "katib_loop_stalled",
    "1 while the supervisor classifies the loop= labeled async loop as "
    "STALLED (alive but its progress watermark is frozen past "
    "loopStallDeadlineSeconds with upstream work available), else 0",
)
speculative_dispatches = REGISTRY.counter(
    "katib_speculative_dispatch_total",
    "Straggler trials speculatively re-dispatched as singletons "
    "(stragglerFactor x median settle time exceeded)",
)
speculative_wins = REGISTRY.counter(
    "katib_speculative_wins_total",
    "Speculative re-dispatches that settled before their original attempt "
    "(a low win/dispatch ratio means stragglerFactor is too aggressive)",
)

# -- vectorized trial cohorts (runner/cohort.py) ------------------------------

cohorts_executed = REGISTRY.counter(
    "katib_cohort_executed_total",
    "Vectorized trial cohorts executed (vmap-batched multi-trial programs)",
)
cohort_size = REGISTRY.histogram(
    "katib_cohort_size",
    "Member trials per vectorized cohort",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
)
cohort_trials_per_sec = REGISTRY.gauge(
    "katib_cohort_trials_per_sec",
    "Member-trial throughput of the most recent cohort execution",
)
cohort_fallbacks = REGISTRY.counter(
    "katib_cohort_fallback_total",
    "Cohorts whose vectorized path failed and re-ran members serially",
)
cohort_devices = REGISTRY.gauge(
    "katib_cohort_devices",
    "Devices the most recent cohort's trial axis spanned "
    "(1 = single-device vmap, D = SPMD-sharded member dimension)",
)

# -- on-device Population Based Training (parallel/pbt.py) --------------------

pbt_generations = REGISTRY.counter(
    "katib_pbt_generations_total",
    "PBT generations executed (train + select + clone + perturb rounds)",
)
pbt_exploits = REGISTRY.counter(
    "katib_pbt_exploits_total",
    "PBT exploit events: members overwritten by a top-quantile winner's "
    "state + hyperparameters",
)
pbt_onchip = REGISTRY.gauge(
    "katib_pbt_onchip",
    "1 while a PBT population is evolving on device (fused generation "
    "dispatches, zero host transfers inside a generation); 0 when the "
    "host checkpoint-exchange path is active",
)
compile_cache_enabled = REGISTRY.gauge(
    "katib_compile_cache_enabled",
    "1 when the persistent XLA compilation cache is wired "
    "(KATIB_COMPILE_CACHE / ExperimentSpec.compile_cache)",
)

# -- compile amortization (katib_tpu/compile/) --------------------------------

compile_cache_hits = REGISTRY.counter(
    "katib_compile_cache_hits_total",
    "First steps whose compile signature was already registered "
    "(warm: in-process jit cache or persistent-cache deserialize; "
    "program label)",
)
compile_cache_misses = REGISTRY.counter(
    "katib_compile_cache_misses_total",
    "First steps whose compile signature was never seen before "
    "(cold: full XLA compile on the critical path; program label)",
)
prewarm_compiles = REGISTRY.counter(
    "katib_prewarm_compiles_total",
    "Programs compiled ahead of execution by the background prewarm "
    "worker / CLI prewarm verb (program label)",
)
first_step_compile_seconds = REGISTRY.histogram(
    "katib_first_step_compile_seconds",
    "Time from trial start to the first step boundary, split warm vs cold "
    "(cache label) — a cache regression shows as the cold series growing",
)
artifact_hits = REGISTRY.counter(
    "katib_artifact_hits_total",
    "Serialized-executable artifacts fetched and loaded, per tier "
    "(tier=local|shared) — a shared hit is a compile another host paid",
)
artifact_misses = REGISTRY.counter(
    "katib_artifact_misses_total",
    "Artifact lookups that found nothing in a tier (tier label); a miss "
    "in every tier degrades to a cold compile",
)
artifact_publishes = REGISTRY.counter(
    "katib_artifact_publishes_total",
    "Serialized executables published to an artifact tier (tier label; "
    "deduped on content address, so fleets publish each program once)",
)
artifact_quarantines = REGISTRY.counter(
    "katib_artifact_quarantines_total",
    "Corrupt or mismatched artifact envelopes moved aside "
    "(tier=local|shared|fsck) instead of crashing the fetch path",
)

# -- preemption / hang robustness (utils/watchdog.py, orchestrator drain) -----

trial_hangs = REGISTRY.counter(
    "katib_trial_hangs_total",
    "Trials interrupted by the hang watchdog "
    "(no progress past progressDeadlineSeconds)",
)
drain_requested = REGISTRY.gauge(
    "katib_drain_requested",
    "1 while the orchestrator is draining after SIGTERM/SIGINT "
    "(checkpoint-and-exit requested; run is resumable)",
)
checkpoint_fallbacks = REGISTRY.counter(
    "katib_checkpoint_fallback_total",
    "Corrupt/unverifiable checkpoint steps skipped by restore() "
    "(quarantined; an older verifiable step was used instead)",
)

# -- device-layer fault tolerance (utils/meshhealth.py, elastic cohorts) ------

device_healthy = REGISTRY.gauge(
    "katib_device_healthy",
    "Per-device preflight verdict: 1 healthy, 0 wedged/absent "
    "(device/platform labels; set by katib-tpu doctor and the run/bench "
    "preflight)",
)
mesh_degraded = REGISTRY.counter(
    "katib_mesh_degraded_total",
    "Elastic cohort degradations after a device fault "
    "(sharded -> narrower mesh -> single-device vmap -> serial)",
)
compile_hangs = REGISTRY.counter(
    "katib_compile_hangs_total",
    "Trials whose jit compile / first dispatch overran "
    "compileDeadlineSeconds (classified retryable CompileHang)",
)
journal_replayed_events = REGISTRY.counter(
    "katib_journal_replayed_events_total",
    "Experiment-journal records applied during a resume replay "
    "(orchestrator/journal.py)",
)
settlement_duplicates = REGISTRY.counter(
    "katib_settlement_duplicates_total",
    "Duplicate/out-of-order settled records dropped by exactly-once "
    "replay (keyed by trial name + attempt epoch)",
)
suggester_fence_rebuilds = REGISTRY.counter(
    "katib_suggester_fence_rebuilds_total",
    "Stale suggester_state.pkl discarded on resume (fence older than the "
    "journal's last settled seq); suggester rebuilt from trial history",
)
fsck_repairs = REGISTRY.counter(
    "katib_fsck_repairs_total",
    "Repairs applied by katib-tpu fsck (torn journal tails truncated, "
    "unverifiable snapshots quarantined)",
)


def record_device_memory(registry_gauge: _Metric | None = None) -> None:
    """Best-effort per-device memory gauges via ``Device.memory_stats()``
    (TPU/GPU backends expose ``bytes_in_use``; CPU usually returns None)."""
    gauge = registry_gauge or device_hbm_bytes
    try:
        import jax

        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                gauge.set(float(in_use), device=str(d.id), kind=d.platform)
    except Exception:
        pass  # telemetry only — never break a training loop
