"""One boolean parser for user-supplied flag strings.

Settings, trial params, and env knobs all carry booleans as strings; ad-hoc
``not in ("", "0")`` checks treat explicit opt-outs like ``"false"`` or
``"no"`` as TRUE.  Every surface that accepts a boolean-ish string goes
through this one function so the accepted spellings can't drift.
"""

from __future__ import annotations

_FALSY = ("", "0", "false", "no", "none", "off")


def parse_bool(raw: object, default: bool = False) -> bool:
    """``"false"/"no"/"none"/"off"/"0"/"" -> False``; other strings True;
    ``None`` -> ``default``; real bools pass through."""
    if raw is None:
        return default
    if isinstance(raw, bool):
        return raw
    return str(raw).strip().lower() not in _FALSY
