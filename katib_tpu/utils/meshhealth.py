"""Bounded-time device/mesh health probing — the preflight behind
``katib-tpu doctor`` and the ``run``/``bench`` gates.

The failure mode this exists for: a wedged accelerator pool (the axon relay
holding a stale grant) makes ``jax.devices()`` — or the first program
dispatched to one chip — block *forever*.  Four bench rounds (BENCH_r01-r04)
produced no artifact for exactly that reason.  Trial-level robustness
(retries, hang watchdog, drain) never fires because nothing ever starts.

So every step here is deadline-bounded and runs on abandonable daemon
threads: device *enumeration* gets its own bounded wait (it can hang before
any device object exists), then every visible device is probed concurrently
with a tiny jitted program.  A probe that does not complete inside the
deadline classifies the device WEDGED; probes that raise record the error;
devices the caller expected but enumeration did not return classify ABSENT.
The result is a machine-readable :class:`HealthReport` that the CLI prints,
``bench.py`` embeds in its artifact, the orchestrator journals, and the
elastic cohort degradation path (``runner/cohort.py``) uses to pick
survivors after a mid-cohort device fault.

``FaultInjector.wedge_device`` plugs in through the ``injector`` seam:
injector-wedged devices classify WEDGED immediately (no wall-clock burn),
so chaos tests and ``katib-tpu doctor --simulate-wedge`` are deterministic
and fast.

Everything here degrades to stdlib when jax is absent/unimportable; jax is
imported lazily inside the probe functions only.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

#: default overall preflight deadline, seconds (env-overridable)
DEADLINE_ENV = "KATIB_PREFLIGHT_DEADLINE"
DEFAULT_DEADLINE = 60.0

HEALTHY = "healthy"
WEDGED = "wedged"
ABSENT = "absent"


def default_deadline() -> float:
    try:
        return float(os.environ.get(DEADLINE_ENV, ""))
    except ValueError:
        pass
    return DEFAULT_DEADLINE


@dataclasses.dataclass
class DeviceHealth:
    """One device's preflight verdict."""

    device: str  # "<platform>:<id>", stable across report consumers
    platform: str
    status: str  # HEALTHY | WEDGED | ABSENT
    probe_seconds: float = 0.0
    error: str = ""

    def to_dict(self) -> dict:
        d = {
            "device": self.device,
            "platform": self.platform,
            "status": self.status,
            "probe_seconds": round(self.probe_seconds, 3),
        }
        if self.error:
            d["error"] = self.error
        return d


@dataclasses.dataclass
class HealthReport:
    """Machine-readable pool verdict: the doctor's output, the bench
    artifact's ``health`` block, and ``status.json``'s ``device_health``."""

    status: str  # HEALTHY | WEDGED | ABSENT
    deadline_seconds: float
    elapsed_seconds: float
    devices: list[DeviceHealth] = dataclasses.field(default_factory=list)
    generated_at: float = 0.0
    error: str = ""  # enumeration-level failure (no per-device detail)

    def ok(self) -> bool:
        return self.status == HEALTHY and bool(self.devices)

    @property
    def healthy_count(self) -> int:
        return sum(1 for d in self.devices if d.status == HEALTHY)

    @property
    def wedged_count(self) -> int:
        return sum(1 for d in self.devices if d.status == WEDGED)

    @property
    def absent_count(self) -> int:
        return sum(1 for d in self.devices if d.status == ABSENT)

    def to_dict(self) -> dict:
        d = {
            "status": self.status,
            "deadline_seconds": self.deadline_seconds,
            "elapsed_seconds": round(self.elapsed_seconds, 3),
            "healthy": self.healthy_count,
            "wedged": self.wedged_count,
            "absent": self.absent_count,
            "generated_at": self.generated_at,
            "devices": [dev.to_dict() for dev in self.devices],
        }
        if self.error:
            d["error"] = self.error
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def summary(self) -> str:
        """One line for log messages and experiment failure text."""
        if self.error and not self.devices:
            return f"pool {self.status}: {self.error}"
        parts = [f"{self.healthy_count} healthy"]
        if self.wedged_count:
            parts.append(f"{self.wedged_count} wedged")
        if self.absent_count:
            parts.append(f"{self.absent_count} absent")
        return (
            f"pool {self.status}: {', '.join(parts)} "
            f"({self.elapsed_seconds:.1f}s/{self.deadline_seconds:.0f}s probe)"
        )


# last preflight of this process, embedded into status.json by
# orchestrator/status.py (None until a preflight ran)
_LAST_REPORT: HealthReport | None = None
_LAST_LOCK = threading.Lock()


def last_report() -> HealthReport | None:
    with _LAST_LOCK:
        return _LAST_REPORT


def last_report_dict() -> dict | None:
    r = last_report()
    return r.to_dict() if r is not None else None


def _record(report: HealthReport) -> None:
    global _LAST_REPORT
    with _LAST_LOCK:
        _LAST_REPORT = report


def _default_prober(device) -> None:
    """The tiny end-to-end proof a device is alive: host->device transfer,
    a jitted reduction, and a host fetch.  Anything short of all three can
    succeed against a wedged pool (enumeration and even placement are
    client-side; only a round-tripped execution exercises the chip)."""
    import jax
    import numpy as np

    x = jax.device_put(np.arange(8, dtype=np.float32), device)
    y = jax.jit(lambda v: (v * 2.0).sum())(x)
    y.block_until_ready()
    float(y)


def _device_key(device) -> str:
    return f"{getattr(device, 'platform', '?')}:{getattr(device, 'id', '?')}"


def probe_devices(
    devices,
    deadline: float | None = None,
    clock=time.monotonic,
    prober=None,
    injector=None,
    expect_ids=None,
) -> HealthReport:
    """Probe every device in ``devices`` concurrently under ONE overall
    ``deadline``.  Each probe runs on a daemon thread so a genuinely wedged
    device is abandoned, not waited out.  ``expect_ids`` (optional iterable
    of device ids) adds ABSENT rows for ids enumeration did not return —
    how a 4-chip mesh notices it came back with 3.

    ``injector`` (``faults.FaultInjector``) short-circuits devices marked
    by ``wedge_device`` to WEDGED without consuming wall-clock, keeping
    chaos runs deterministic.  ``prober``/``clock`` are injectable for
    tests (a slow prober + a small real deadline exercises the timeout
    path in milliseconds)."""
    if deadline is None:
        deadline = default_deadline()
    probe = prober or _default_prober
    devices = list(devices)
    t0 = clock()
    entries: dict[int, DeviceHealth] = {}
    threads: list[tuple[int, threading.Thread]] = []
    done: dict[int, tuple[float, str]] = {}  # slot -> (probe_seconds, error)
    done_lock = threading.Lock()

    for slot, dev in enumerate(devices):
        key = _device_key(dev)
        platform = getattr(dev, "platform", "?")
        if injector is not None and injector.is_device_wedged(getattr(dev, "id", -1)):
            entries[slot] = DeviceHealth(
                key, platform, WEDGED, 0.0, "injected device wedge"
            )
            continue
        entries[slot] = DeviceHealth(key, platform, WEDGED)  # until proven alive

        def _probe(slot=slot, dev=dev):
            t = clock()
            err = ""
            try:
                probe(dev)
            except Exception as e:  # a raising probe is a diagnosis
                err = f"{type(e).__name__}: {e}"
            with done_lock:
                done[slot] = (clock() - t, err)

        th = threading.Thread(target=_probe, daemon=True, name=f"probe-{key}")
        th.start()
        threads.append((slot, th))

    for slot, th in threads:
        remaining = deadline - (clock() - t0)
        if remaining > 0:
            th.join(remaining)
        with done_lock:
            outcome = done.get(slot)
        e = entries[slot]
        if outcome is None:
            e.probe_seconds = clock() - t0
            e.error = f"probe did not complete within {deadline:.0f}s"
        else:
            e.probe_seconds, e.error = outcome
            if not e.error:
                e.status = HEALTHY

    report_devices = [entries[i] for i in range(len(devices)) if i in entries]
    if expect_ids is not None:
        seen = {getattr(d, "id", None) for d in devices}
        for missing in sorted(set(int(i) for i in expect_ids) - seen):
            report_devices.append(
                DeviceHealth(
                    f"?:{missing}", "?", ABSENT, 0.0, "device not enumerated"
                )
            )

    if any(d.status == WEDGED for d in report_devices):
        status = WEDGED
    elif any(d.status == ABSENT for d in report_devices) or not report_devices:
        status = ABSENT
    else:
        status = HEALTHY
    return HealthReport(
        status=status,
        deadline_seconds=float(deadline),
        elapsed_seconds=clock() - t0,
        devices=report_devices,
        generated_at=time.time(),
    )


def healthy_devices(devices, report: HealthReport):
    """Filter ``devices`` down to the ones ``report`` called HEALTHY —
    the survivor set the elastic cohort degradation rebuilds its mesh from."""
    ok = {d.device for d in report.devices if d.status == HEALTHY}
    return [d for d in devices if _device_key(d) in ok]


def _enumerate_devices(deadline: float, clock=time.monotonic):
    """``jax.devices()`` on a bounded daemon thread: on a wedged pool the
    PJRT client's *constructor* can block forever, before any device object
    exists to probe.  Returns (devices|None, error)."""
    box: dict = {}

    def _enum():
        try:
            import jax

            box["devices"] = jax.devices()
        except Exception as e:
            box["error"] = f"{type(e).__name__}: {e}"

    th = threading.Thread(target=_enum, daemon=True, name="device-enumeration")
    t0 = clock()
    th.start()
    th.join(deadline)
    if "devices" in box:
        return box["devices"], ""
    if "error" in box:
        return None, box["error"]
    return None, (
        f"device enumeration did not complete within {deadline:.0f}s "
        f"(accelerator runtime wedged?); waited {clock() - t0:.1f}s"
    )


def preflight(
    deadline: float | None = None,
    injector=None,
    record: bool = True,
    expect_ids=None,
    prober=None,
    clock=time.monotonic,
) -> HealthReport:
    """The full bounded preflight: enumerate devices (bounded), probe each
    (bounded, concurrent), publish ``katib_device_healthy`` gauges, record a
    ``preflight`` span in the ambient trace journal, and stash the report
    for ``status.json``.  Never raises and never blocks past ~deadline."""
    from katib_tpu.utils import observability as obs
    from katib_tpu.utils import tracing

    if deadline is None:
        deadline = default_deadline()
    t0 = clock()
    devices, enum_error = _enumerate_devices(deadline, clock=clock)
    if devices is None:
        report = HealthReport(
            status=WEDGED,
            deadline_seconds=float(deadline),
            elapsed_seconds=clock() - t0,
            devices=[],
            generated_at=time.time(),
            error=enum_error,
        )
    else:
        remaining = max(0.5, deadline - (clock() - t0))
        report = probe_devices(
            devices,
            deadline=remaining,
            clock=clock,
            prober=prober,
            injector=injector,
            expect_ids=expect_ids,
        )
        report.elapsed_seconds = clock() - t0
        report.deadline_seconds = float(deadline)
    for d in report.devices:
        obs.device_healthy.set(
            1.0 if d.status == HEALTHY else 0.0,
            device=d.device,
            platform=d.platform,
        )
    tracing.record_span(
        "preflight",
        report.elapsed_seconds,
        status=report.status,
        healthy=report.healthy_count,
        wedged=report.wedged_count,
        absent=report.absent_count,
    )
    if record:
        _record(report)
    return report


# -- subprocess isolation (doctor / bench) ------------------------------------
#
# In-process preflight threads bound the wait but cannot reclaim a thread
# stuck inside a wedged PJRT call.  Process-owning callers (the doctor CLI,
# bench.py) therefore run the preflight in a killable CHILD and parse the
# JSON line below; the parent enforces deadline+grace with SIGKILL.

_REPORT_TAG = "@@KATIB_HEALTH@@"
_SIMULATE_ENV = "KATIB_DOCTOR_SIMULATE_WEDGE"


def _doctor_child() -> None:
    """Child entrypoint: run the preflight, print the tagged report JSON,
    exit 0 healthy / 1 otherwise.  Honors JAX_PLATFORMS explicitly (the
    axon PJRT plugin registers from sitecustomize and ignores the env
    var) and ``KATIB_DOCTOR_SIMULATE_WEDGE`` (comma-separated device ids)
    for deterministic wedged-pool simulation."""
    import sys

    try:
        import jax

        want = os.environ.get("JAX_PLATFORMS")
        if want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass
    injector = None
    simulate = os.environ.get(_SIMULATE_ENV, "").strip()
    if simulate:
        from katib_tpu.utils.faults import FaultInjector

        injector = FaultInjector(seed=0)
        for part in simulate.split(","):
            part = part.strip()
            if part:
                injector.wedge_device(int(part))
    report = preflight(injector=injector)
    print(_REPORT_TAG + json.dumps(report.to_dict()))
    sys.exit(0 if report.ok() else 1)


def doctor_report(
    deadline: float | None = None,
    simulate_wedge=None,
    env: dict | None = None,
) -> HealthReport:
    """Parent side: run :func:`_doctor_child` in a killable subprocess and
    parse its report.  A child that outlives deadline + grace is SIGKILLed
    (safe: a client blocked in device init holds no grant) and synthesized
    into a WEDGED report — the doctor itself can never hang."""
    import subprocess
    import sys

    if deadline is None:
        deadline = default_deadline()
    child_env = dict(os.environ if env is None else env)
    child_env[DEADLINE_ENV] = str(deadline)
    # the child must import katib_tpu the same way the parent did, even
    # when the package was path-inserted rather than installed (the child
    # inherits cwd, not the parent's sys.path)
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    existing = child_env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        child_env["PYTHONPATH"] = (
            pkg_root + (os.pathsep + existing if existing else "")
        )
    if simulate_wedge:
        child_env[_SIMULATE_ENV] = ",".join(str(int(i)) for i in simulate_wedge)
    else:
        child_env.pop(_SIMULATE_ENV, None)
    t0 = time.monotonic()
    proc = subprocess.Popen(
        [
            sys.executable,
            "-c",
            "from katib_tpu.utils.meshhealth import _doctor_child; _doctor_child()",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=child_env,
    )
    grace = 30.0  # interpreter start + jax import on top of the probe deadline
    try:
        out, err = proc.communicate(timeout=deadline + grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return HealthReport(
            status=WEDGED,
            deadline_seconds=float(deadline),
            elapsed_seconds=time.monotonic() - t0,
            devices=[],
            generated_at=time.time(),
            error=(
                "device runtime did not respond within "
                f"{deadline + grace:.0f}s (probe child killed)"
            ),
        )
    for line in (out or "").splitlines():
        if line.startswith(_REPORT_TAG):
            try:
                d = json.loads(line[len(_REPORT_TAG):])
            except ValueError:
                continue
            report = HealthReport(
                status=d.get("status", WEDGED),
                deadline_seconds=float(d.get("deadline_seconds", deadline)),
                elapsed_seconds=float(d.get("elapsed_seconds", 0.0)),
                devices=[
                    DeviceHealth(
                        device=e.get("device", "?"),
                        platform=e.get("platform", "?"),
                        status=e.get("status", WEDGED),
                        probe_seconds=float(e.get("probe_seconds", 0.0)),
                        error=e.get("error", ""),
                    )
                    for e in d.get("devices", [])
                ],
                generated_at=float(d.get("generated_at", 0.0)),
                error=d.get("error", ""),
            )
            _record(report)
            return report
    tail = (err or "").strip().splitlines()
    return HealthReport(
        status=WEDGED,
        deadline_seconds=float(deadline),
        elapsed_seconds=time.monotonic() - t0,
        devices=[],
        generated_at=time.time(),
        error=(
            f"probe child exited rc={proc.returncode} without a report"
            + (f" ({tail[-1]})" if tail else "")
        ),
    )
