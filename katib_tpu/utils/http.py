"""Shared helpers for the framework's ``http.server``-based endpoints (UI
backend, suggestion service) — one implementation of bearer-token auth and
JSON body reading so the two servers cannot drift."""

from __future__ import annotations

import functools as _functools
import hmac
import json


def bearer_authorized(headers, token: str | None) -> bool:
    """Constant-time check of ``Authorization: Bearer <token>``; a ``None``
    token means the endpoint is open.  Any undecodable/malformed header is
    an auth failure, never an exception (a 500 would leak whether a token is
    configured)."""
    if token is None:
        return True
    try:
        got = headers.get("Authorization", "") or ""
        return hmac.compare_digest(got.encode("utf-8"), f"Bearer {token}".encode("utf-8"))
    except (UnicodeError, TypeError):
        return False


def local_host_allowed(headers) -> bool:
    """DNS-rebinding guard for token-less servers: the Content-Type check
    stops cross-origin *requests*, but a malicious domain can rebind its DNS
    to 127.0.0.1 and become same-origin — so when no bearer token protects
    the writes, the ``Host`` header must name this machine (localhost, its
    hostname, or one of its addresses; extend via ``KATIB_ALLOWED_HOSTS``,
    comma-separated).  Token-protected deployments skip this check — the
    token already gates the write, and their legit DNS names are unknowable
    here."""
    import os
    from urllib.parse import urlsplit

    try:
        name = (urlsplit("//" + (headers.get("Host") or "")).hostname or "").lower()
    except ValueError:
        return False
    if not name:
        return False
    allowed = set(_machine_hosts())
    extra = os.environ.get("KATIB_ALLOWED_HOSTS", "")
    allowed.update(h.strip().lower() for h in extra.split(",") if h.strip())
    return name in allowed


_HOSTS_TTL_S = 60.0
_hosts_cache: tuple[float, frozenset[str]] | None = None


def _machine_hosts() -> frozenset[str]:
    """This machine's names/addresses.  ``gethostbyname_ex`` can mean a real
    (slow) DNS query, so don't resolve per request — but don't cache forever
    either: a resolver that was down at first request, or an address that
    changed (DHCP), must converge within the TTL instead of pinning a
    degraded set for the process lifetime."""
    global _hosts_cache
    import time

    now = time.monotonic()
    if _hosts_cache is not None and now - _hosts_cache[0] < _HOSTS_TTL_S:
        return _hosts_cache[1]
    import socket

    allowed = {"localhost", "127.0.0.1", "::1"}
    try:
        hostname = socket.gethostname().lower()
        allowed.add(hostname)
        allowed.update(socket.gethostbyname_ex(hostname)[2])
    except OSError:
        pass
    _hosts_cache = (now, frozenset(allowed))
    return _hosts_cache[1]


def json_content_type(headers) -> bool:
    """True iff the request declares ``Content-Type: application/json``.
    Enforcing this on state-changing endpoints is the CSRF guard: a JSON
    content type can't ride a browser's "simple" cross-origin request, so
    the attempt dies in a CORS preflight this server never answers."""
    ctype = (headers.get("Content-Type") or "").split(";")[0].strip().lower()
    return ctype == "application/json"


def read_json_body(handler) -> dict:
    """Read and parse the request body of a ``BaseHTTPRequestHandler`` as a
    JSON object.  Raises ``ValueError`` on anything malformed."""
    n = int(handler.headers.get("Content-Length", 0))
    payload = json.loads(handler.rfile.read(n) or b"{}")
    if not isinstance(payload, dict):
        raise ValueError("body must be a JSON object")
    return payload
