"""Shared helpers for the framework's ``http.server``-based endpoints (UI
backend, suggestion service) — one implementation of bearer-token auth and
JSON body reading so the two servers cannot drift."""

from __future__ import annotations

import hmac
import json


def bearer_authorized(headers, token: str | None) -> bool:
    """Constant-time check of ``Authorization: Bearer <token>``; a ``None``
    token means the endpoint is open.  Any undecodable/malformed header is
    an auth failure, never an exception (a 500 would leak whether a token is
    configured)."""
    if token is None:
        return True
    try:
        got = headers.get("Authorization", "") or ""
        return hmac.compare_digest(got.encode("utf-8"), f"Bearer {token}".encode("utf-8"))
    except (UnicodeError, TypeError):
        return False


def read_json_body(handler) -> dict:
    """Read and parse the request body of a ``BaseHTTPRequestHandler`` as a
    JSON object.  Raises ``ValueError`` on anything malformed."""
    n = int(handler.headers.get("Content-Length", 0))
    payload = json.loads(handler.rfile.read(n) or b"{}")
    if not isinstance(payload, dict):
        raise ValueError("body must be a JSON object")
    return payload
