"""Observation-log storage contract.

The reference fronts MySQL/Postgres with a gRPC DB-manager daemon whose whole
schema is one table ``observation_logs(trial_name, id, time, metric_name,
value)`` (``pkg/db/v1beta1/common/kdb.go:23``, ``mysql/init.go:35``).  The
TPU-native design keeps the same three-operation contract —
report / get / delete per trial — but runs it in-process: trials are white-box
functions, so the metrics path is a function call, not
sidecar → gRPC → SQL → gRPC → controller.

Backends:
- ``MemoryObservationStore``   — dict of lists; fastest, default for local runs.
- ``SqliteObservationStore``   — durable single-file store (``store/sqlite.py``).
- ``NativeObservationStore``   — C++ append-log engine via ctypes (``native/``).

All backends are thread-safe: trial runners report from worker threads while
the orchestrator reads.
"""

from __future__ import annotations

import abc
import threading
import time
from typing import Callable, Iterable

from katib_tpu.core.types import (
    Metric,
    MetricLog,
    MetricStrategyType,
    Observation,
    ObjectiveSpec,
)


class ObservationStore(abc.ABC):
    """Report/Get/Delete observation-log contract (reference ``kdb.go:23-29``)."""

    @abc.abstractmethod
    def report(self, trial_name: str, logs: Iterable[MetricLog]) -> None:
        """Append metric points for a trial (reference ``ReportObservationLog``)."""

    @abc.abstractmethod
    def get(self, trial_name: str, metric_name: str | None = None) -> list[MetricLog]:
        """Fetch a trial's log, optionally filtered to one metric, in report order
        (reference ``GetObservationLog``; the reference also filters by start/end
        time, which callers here do with a list comprehension)."""

    @abc.abstractmethod
    def delete(self, trial_name: str) -> None:
        """Drop a trial's log (reference ``DeleteObservationLog``)."""

    # -- conveniences shared by all backends -------------------------------

    def report_point(
        self, trial_name: str, metric_name: str, value: float, step: int = -1
    ) -> None:
        self.report(
            trial_name,
            [MetricLog(metric_name=metric_name, value=value, timestamp=time.time(), step=step)],
        )

    def reduce(
        self, trial_name: str, metric_name: str, strategy: MetricStrategyType
    ) -> float | None:
        values = [l.value for l in self.get(trial_name, metric_name)]
        return strategy.reduce(values) if values else None

    def observation_for(
        self, trial_name: str, objective: ObjectiveSpec
    ) -> Observation | None:
        """Build a reduced Observation by applying metric strategies — the
        controller-side logic of ``UpdateTrialStatusObservation``
        (reference ``trial_controller_util.go``).  Returns None when the
        objective metric was never reported (→ MetricsUnavailable)."""
        metrics: list[Metric] = []
        for name in objective.all_metric_names():
            values = [l.value for l in self.get(trial_name, name)]
            if not values:
                continue
            metrics.append(
                Metric(
                    name=name,
                    value=objective.strategy_for(name).reduce(values),
                    min=min(values),
                    max=max(values),
                    latest=values[-1],
                )
            )
        if not any(m.name == objective.objective_metric_name for m in metrics):
            return None
        return Observation(metrics=metrics)


class MemoryObservationStore(ObservationStore):
    """In-memory backend with optional live subscribers (the "metrics bus").

    Subscribers receive every reported point; the early-stopping evaluator
    hooks in here instead of tailing files the way the reference sidecar does
    (``file-metricscollector/main.go:143``).
    """

    def __init__(self) -> None:
        self._logs: dict[str, list[MetricLog]] = {}
        self._lock = threading.RLock()
        self._subscribers: list[Callable[[str, MetricLog], None]] = []

    def subscribe(self, fn: Callable[[str, MetricLog], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def report(self, trial_name: str, logs: Iterable[MetricLog]) -> None:
        logs = list(logs)
        with self._lock:
            self._logs.setdefault(trial_name, []).extend(logs)
            subs = list(self._subscribers)
        for fn in subs:
            for log in logs:
                fn(trial_name, log)

    def get(self, trial_name: str, metric_name: str | None = None) -> list[MetricLog]:
        with self._lock:
            logs = list(self._logs.get(trial_name, ()))
        if metric_name is None:
            return logs
        return [l for l in logs if l.metric_name == metric_name]

    def delete(self, trial_name: str) -> None:
        with self._lock:
            self._logs.pop(trial_name, None)

    def trial_names(self) -> list[str]:
        with self._lock:
            return list(self._logs)
