"""External-SQL observation-log backend over any DB-API 2.0 connection.

The reference fronts MySQL (``pkg/db/v1beta1/mysql/init.go:35``) and
Postgres (``postgres/init.go:35``) behind its DB-manager daemon with one
table::

    observation_logs(trial_name VARCHAR(255) NOT NULL,
                     id        <auto-increment primary key>,
                     time      DATETIME(6) / TIMESTAMP(6),
                     metric_name VARCHAR(255) NOT NULL,
                     value     TEXT NOT NULL)

This adapter speaks that exact schema through a caller-supplied DB-API
connection (PyMySQL, mysqlclient, psycopg2, or sqlite3 for tests), so a
deployment can point the orchestrator at an existing Katib database and
read/write the same rows the reference's DB-manager would
(``mysql/mysql.go:66-135`` RegisterObservationLog / GetObservationLog /
DeleteObservationLog semantics: time stored as a UTC ``DATETIME(6)``
string, value stored as TEXT, reads ordered by time).

Differences from the in-process backends (``store/sqlite.py``):
- ``step`` is NOT persisted — the reference schema has no step column,
  and schema parity (interoperating with an existing Katib DB) wins;
  round-tripped logs come back with ``step=-1``.
- values are stored as text and parsed on read; rows whose value is not
  numeric (the reference stores e.g. ``Best-Genotype=...`` strings) are
  skipped by ``get`` but preserved in the table, matching how the
  reference's metric math treats unparseable values.

No new dependency: the driver module is the caller's choice (none is
imported here), and the sqlite3 stdlib driver exercises the full code
path in tests (``tests/test_dbapi_store.py``).
"""

from __future__ import annotations

import datetime as _dt
import threading
from typing import Callable, Iterable

from katib_tpu.core.types import MetricLog
from katib_tpu.store.base import ObservationStore

# Reference DDL per engine (mysql/init.go:35, postgres/init.go:35); the
# sqlite variant exists so tests can prove schema compatibility with the
# stdlib driver.
_DDL = {
    "mysql": (
        "CREATE TABLE IF NOT EXISTS observation_logs"
        " (trial_name VARCHAR(255) NOT NULL,"
        " id INT AUTO_INCREMENT PRIMARY KEY,"
        " time DATETIME(6),"
        " metric_name VARCHAR(255) NOT NULL,"
        " value TEXT NOT NULL)"
    ),
    "postgres": (
        "CREATE TABLE IF NOT EXISTS observation_logs"
        " (trial_name VARCHAR(255) NOT NULL,"
        " id serial PRIMARY KEY,"
        " time TIMESTAMP(6),"
        " metric_name VARCHAR(255) NOT NULL,"
        " value TEXT NOT NULL)"
    ),
    "sqlite": (
        "CREATE TABLE IF NOT EXISTS observation_logs"
        " (trial_name VARCHAR(255) NOT NULL,"
        " id INTEGER PRIMARY KEY AUTOINCREMENT,"
        " time DATETIME(6),"
        " metric_name VARCHAR(255) NOT NULL,"
        " value TEXT NOT NULL)"
    ),
}

# the reference's mysqlTimeFmt: microsecond DATETIME as a UTC string
_TIME_FMT = "%Y-%m-%d %H:%M:%S.%f"


def _fmt_time(ts: float) -> str:
    return _dt.datetime.fromtimestamp(ts, tz=_dt.timezone.utc).strftime(_TIME_FMT)


def _parse_time(raw: object) -> float:
    if isinstance(raw, _dt.datetime):
        dt = raw if raw.tzinfo else raw.replace(tzinfo=_dt.timezone.utc)
        return dt.timestamp()
    try:
        return (
            _dt.datetime.strptime(str(raw), _TIME_FMT)
            .replace(tzinfo=_dt.timezone.utc)
            .timestamp()
        )
    except ValueError:
        return 0.0


class DbapiObservationStore(ObservationStore):
    """Reference-schema store over a DB-API connection.

    ``conn``: an open DB-API 2.0 connection, or a zero-arg factory that
    returns one (the factory is called once, lazily).  ``paramstyle``:
    the driver's placeholder style — ``"qmark"`` (sqlite3) or
    ``"format"`` (PyMySQL/mysqlclient/psycopg2); defaults from the
    dialect.  ``init_schema=False`` mirrors the reference's
    ``DB_SKIP_DB_INITIALIZATION`` flag: validate the table exists
    instead of creating it (``mysql/init.go:44-49``).
    """

    def __init__(
        self,
        conn: object | Callable[[], object],
        *,
        dialect: str = "mysql",
        paramstyle: str | None = None,
        init_schema: bool = True,
    ) -> None:
        if dialect not in _DDL:
            raise ValueError(f"unknown dialect {dialect!r}; known: {sorted(_DDL)}")
        # a DB-API connection has .cursor(); anything else callable is
        # treated as a factory (sqlite3 connections are themselves
        # callable, so callable() alone cannot discriminate)
        self._conn = conn if hasattr(conn, "cursor") else conn()
        self._lock = threading.RLock()
        self._ph = {
            "qmark": "?",
            "format": "%s",
        }[paramstyle or ("qmark" if dialect == "sqlite" else "format")]
        with self._lock:
            cur = self._conn.cursor()
            try:
                if init_schema:
                    cur.execute(_DDL[dialect])
                else:
                    cur.execute(
                        "SELECT trial_name, id, time, metric_name, value"
                        " FROM observation_logs LIMIT 1"
                    )
                    cur.fetchall()
                self._conn.commit()
            finally:
                cur.close()

    def _sql(self, q: str) -> str:
        return q.replace("?", self._ph)

    def report(self, trial_name: str, logs: Iterable[MetricLog]) -> None:
        rows = [
            (trial_name, _fmt_time(l.timestamp), l.metric_name, str(l.value))
            for l in logs
        ]
        if not rows:
            return
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.executemany(
                    self._sql(
                        "INSERT INTO observation_logs"
                        " (trial_name, time, metric_name, value)"
                        " VALUES (?, ?, ?, ?)"
                    ),
                    rows,
                )
                self._conn.commit()
            finally:
                cur.close()

    def get(
        self,
        trial_name: str,
        metric_name: str | None = None,
        start_time: float | None = None,
        end_time: float | None = None,
    ) -> list[MetricLog]:
        q = (
            "SELECT time, metric_name, value FROM observation_logs"
            " WHERE trial_name = ?"
        )
        args: list = [trial_name]
        if metric_name is not None:
            q += " AND metric_name = ?"
            args.append(metric_name)
        # the reference's optional start/end window (mysql.go:115-132)
        if start_time is not None:
            q += " AND time >= ?"
            args.append(_fmt_time(start_time))
        if end_time is not None:
            q += " AND time <= ?"
            args.append(_fmt_time(end_time))
        q += " ORDER BY time"
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(self._sql(q), args)
                rows = cur.fetchall()
            finally:
                cur.close()
        out: list[MetricLog] = []
        for t, m, v in rows:
            try:
                value = float(v)
            except (TypeError, ValueError):
                continue  # non-numeric value rows (see module doc)
            out.append(MetricLog(metric_name=m, value=value, timestamp=_parse_time(t)))
        return out

    def delete(self, trial_name: str) -> None:
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(
                    self._sql("DELETE FROM observation_logs WHERE trial_name = ?"),
                    (trial_name,),
                )
                self._conn.commit()
            finally:
                cur.close()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
