from katib_tpu.store.base import MemoryObservationStore, ObservationStore  # noqa: F401
from katib_tpu.store.dbapi import DbapiObservationStore  # noqa: F401
from katib_tpu.store.sqlite import SqliteObservationStore  # noqa: F401
