"""SQLite observation-log backend.

Durable equivalent of the reference DB-manager's MySQL/Postgres table
``observation_logs(trial_name, id, time, metric_name, value)``
(``pkg/db/v1beta1/mysql/init.go:35``) without the standalone daemon: the
orchestrator embeds the store, so the sidecar→gRPC→SQL hop disappears.
Schema keeps an extra ``step`` column because white-box trials report
structured (step, value) points rather than parsed log lines.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterable

from katib_tpu.core.types import MetricLog
from katib_tpu.store.base import ObservationStore

_SCHEMA = """
CREATE TABLE IF NOT EXISTS observation_logs (
    id          INTEGER PRIMARY KEY AUTOINCREMENT,
    trial_name  TEXT    NOT NULL,
    time        REAL    NOT NULL,
    metric_name TEXT    NOT NULL,
    value       REAL    NOT NULL,
    step        INTEGER NOT NULL DEFAULT -1
);
CREATE INDEX IF NOT EXISTS idx_obs_trial ON observation_logs (trial_name, metric_name, id);
"""


class SqliteObservationStore(ObservationStore):
    def __init__(self, path: str = ":memory:") -> None:
        # one shared connection guarded by a lock: sqlite serializes writers
        # anyway, and this keeps ':memory:' stores coherent across threads.
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            if path != ":memory:":
                # WAL survives crashes without blocking readers on writers —
                # the durability mode the resume path depends on
                self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    def report(self, trial_name: str, logs: Iterable[MetricLog]) -> None:
        rows = [(trial_name, l.timestamp, l.metric_name, l.value, l.step) for l in logs]
        if not rows:
            return
        with self._lock:
            self._conn.executemany(
                "INSERT INTO observation_logs (trial_name, time, metric_name, value, step)"
                " VALUES (?, ?, ?, ?, ?)",
                rows,
            )
            self._conn.commit()

    def get(self, trial_name: str, metric_name: str | None = None) -> list[MetricLog]:
        q = (
            "SELECT metric_name, value, time, step FROM observation_logs"
            " WHERE trial_name = ?"
        )
        args: list = [trial_name]
        if metric_name is not None:
            q += " AND metric_name = ?"
            args.append(metric_name)
        q += " ORDER BY id"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [
            MetricLog(metric_name=m, value=v, timestamp=t, step=s) for (m, v, t, s) in rows
        ]

    def delete(self, trial_name: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM observation_logs WHERE trial_name = ?", (trial_name,)
            )
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
