"""SQLite observation-log backend.

Durable equivalent of the reference DB-manager's MySQL/Postgres table
``observation_logs(trial_name, id, time, metric_name, value)``
(``pkg/db/v1beta1/mysql/init.go:35``) without the standalone daemon: the
orchestrator embeds the store, so the sidecar→gRPC→SQL hop disappears.
Schema keeps an extra ``step`` column because white-box trials report
structured (step, value) points rather than parsed log lines.

Crash-safety contract (the orchestrator's journal makes this store the
default, so it must survive a hard kill mid-report):

- WAL journal mode + ``synchronous=NORMAL``: committed transactions
  survive process death (WAL is fsync'd at commit); readers never block
  on writers;
- ``busy_timeout``: a second process (fsck, the UI backend) polling the
  file does not surface spurious ``database is locked`` errors;
- a ``schema_info`` version row so future migrations can detect what
  they are upgrading;
- exactly-once step rows: ``(trial_name, metric_name, step)`` is unique
  for ``step >= 0`` (white-box structured reports) with last-writer-wins
  upsert — a trial re-run after a crash or retry re-reports the same
  steps idempotently instead of duplicating the series.  Unstepped rows
  (``step = -1``, parsed log lines) keep append semantics, matching the
  reference's raw observation log.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterable

from katib_tpu.core.types import MetricLog
from katib_tpu.store.base import ObservationStore

SCHEMA_VERSION = 2

_SCHEMA = """
CREATE TABLE IF NOT EXISTS observation_logs (
    id          INTEGER PRIMARY KEY AUTOINCREMENT,
    trial_name  TEXT    NOT NULL,
    time        REAL    NOT NULL,
    metric_name TEXT    NOT NULL,
    value       REAL    NOT NULL,
    step        INTEGER NOT NULL DEFAULT -1
);
CREATE INDEX IF NOT EXISTS idx_obs_trial ON observation_logs (trial_name, metric_name, id);
CREATE TABLE IF NOT EXISTS schema_info (
    key   TEXT PRIMARY KEY,
    value TEXT NOT NULL
);
"""

#: partial unique index backing the step-row upsert; created after a
#: dedup pass so pre-v2 databases with duplicate step rows still open
_STEP_INDEX = (
    "CREATE UNIQUE INDEX IF NOT EXISTS idx_obs_step ON observation_logs"
    " (trial_name, metric_name, step) WHERE step >= 0"
)


class SqliteObservationStore(ObservationStore):
    def __init__(self, path: str = ":memory:") -> None:
        # one shared connection guarded by a lock: sqlite serializes writers
        # anyway, and this keeps ':memory:' stores coherent across threads.
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            if path != ":memory:":
                # WAL survives crashes without blocking readers on writers —
                # the durability mode the resume path depends on.  NORMAL
                # syncs the WAL at commit (durable against process death;
                # at most the last commit can be lost to POWER loss, which
                # replay tolerates — the journal is the source of truth
                # for settlement, the store for series points).
                self._conn.execute("PRAGMA journal_mode=WAL")
                self._conn.execute("PRAGMA synchronous=NORMAL")
            # concurrent readers (fsck, UI backend) wait out a writer's
            # commit instead of raising "database is locked"
            self._conn.execute("PRAGMA busy_timeout=5000")
            self._conn.executescript(_SCHEMA)
            self._migrate()
            self._conn.commit()

    def _migrate(self) -> None:
        """Bring a pre-existing database up to SCHEMA_VERSION.  v1 → v2:
        dedup historic (trial, metric, step>=0) rows (newest id wins) then
        add the unique step index that makes re-reports idempotent."""
        row = self._conn.execute(
            "SELECT value FROM schema_info WHERE key='schema_version'"
        ).fetchone()
        version = int(row[0]) if row else 1
        if version < 2:
            self._conn.execute(
                "DELETE FROM observation_logs WHERE step >= 0 AND id NOT IN ("
                " SELECT MAX(id) FROM observation_logs WHERE step >= 0"
                " GROUP BY trial_name, metric_name, step)"
            )
        self._conn.execute(_STEP_INDEX)
        self._conn.execute(
            "INSERT INTO schema_info (key, value) VALUES ('schema_version', ?)"
            " ON CONFLICT(key) DO UPDATE SET value=excluded.value",
            (str(SCHEMA_VERSION),),
        )

    def report(self, trial_name: str, logs: Iterable[MetricLog]) -> None:
        from katib_tpu.utils.faults import crash_point

        rows = [(trial_name, l.timestamp, l.metric_name, l.value, l.step) for l in logs]
        if not rows:
            return
        with self._lock:
            self._conn.executemany(
                "INSERT INTO observation_logs (trial_name, time, metric_name, value, step)"
                " VALUES (?, ?, ?, ?, ?)"
                " ON CONFLICT(trial_name, metric_name, step) WHERE step >= 0"
                " DO UPDATE SET value=excluded.value, time=excluded.time",
                rows,
            )
            # kill window: rows inserted, transaction not yet committed — a
            # crash here must roll back cleanly (WAL), never corrupt the db
            crash_point("store.report")
            self._conn.commit()

    def get(self, trial_name: str, metric_name: str | None = None) -> list[MetricLog]:
        q = (
            "SELECT metric_name, value, time, step FROM observation_logs"
            " WHERE trial_name = ?"
        )
        args: list = [trial_name]
        if metric_name is not None:
            q += " AND metric_name = ?"
            args.append(metric_name)
        q += " ORDER BY id"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [
            MetricLog(metric_name=m, value=v, timestamp=t, step=s) for (m, v, t, s) in rows
        ]

    def delete(self, trial_name: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM observation_logs WHERE trial_name = ?", (trial_name,)
            )
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
