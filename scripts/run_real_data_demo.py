"""Real-data accuracy evidence: TPE-tuned classifier on the bundled UCI
handwritten digits (scikit-learn's ``load_digits`` — the one genuinely
non-synthetic dataset reachable with zero egress).

Every other workload in this image runs on structured synthetic fallbacks
(``models/data.py``), so their accuracies prove orchestration, not
learning.  This demo pins a real number: a TPE sweep over lr/batch/width
on 1400 real train digits, best test accuracy recorded in
``artifacts/real_data/digits_tuning.json``.  Typical outcome ≥0.95 top-1
on the 397-sample held-out split — real-world evidence the training stack
learns, within what this image's data allows (CIFAR-10 parity still needs
a ``KATIB_DATA_DIR`` npz).

Run: python scripts/run_real_data_demo.py   (CPU)
     DEMO_TPU=1 python scripts/run_real_data_demo.py   (on-chip: fixed
     architecture, lr+momentum sweep — compile-once, so trial 1 carries
     the only XLA compile and trials 2+ run at chip speed; per-trial
     wall-clocks land in the artifact as the evidence)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax, write_artifact  # noqa: E402


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from katib_tpu.utils.booleans import parse_bool

    tpu_mode = parse_bool(os.environ.get("DEMO_TPU"))
    jax = setup_jax(
        force_platform=None if tpu_mode else os.environ.get("DEMO_PLATFORM", "cpu"),
        virtual_devices=0 if tpu_mode else 8,
        compile_cache=tpu_mode,
    )

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.models.data import load_digits_real
    from katib_tpu.models.mnist import MLP, train_classifier
    from katib_tpu.orchestrator import Orchestrator

    dataset = load_digits_real()
    trials = int(os.environ.get("DEMO_TRIALS", "12"))

    def train(ctx):
        def report(epoch, accuracy, loss):
            return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

        # on-chip mode fixes the architecture and batch so every trial
        # shares ONE compiled step (hyperparameters are runtime state —
        # models/mnist.py _family_optimizer); the CPU demo keeps the wider
        # arch-bearing space
        train_classifier(
            MLP(units=64 if tpu_mode else int(float(ctx.params["width"]))),
            dataset,
            lr=float(ctx.params["lr"]),
            momentum=float(ctx.params["momentum"]) if tpu_mode else 0.9,
            epochs=20,
            batch_size=64 if tpu_mode else int(float(ctx.params["batch"])),
            mesh=ctx.mesh,
            report=report,
            eval_batch=len(dataset.x_test),
        )

    if tpu_mode:
        parameters = [
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.005, max=0.5)),
            ParameterSpec("momentum", ParameterType.DOUBLE, FeasibleSpace(min=0.5, max=0.99)),
        ]
    else:
        parameters = [
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.005, max=0.5)),
            ParameterSpec(
                "batch", ParameterType.CATEGORICAL, FeasibleSpace(list=("32", "64", "128"))
            ),
            ParameterSpec("width", ParameterType.INT, FeasibleSpace(min=32, max=256)),
        ]
    spec = ExperimentSpec(
        name="digits-real-tpu" if tpu_mode else "digits-real",
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        algorithm=AlgorithmSpec(
            name="tpe", settings={"n_startup_trials": "5", "random_state": "7"}
        ),
        parameters=parameters,
        max_trial_count=trials,
        # one chip = one trial stream on TPU (clean per-trial wall-clocks);
        # the CPU demo exercises concurrency
        parallel_trial_count=1 if tpu_mode else 4,
        train_fn=train,
    )
    started = time.time()
    exp = Orchestrator(workdir=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "katib_runs"
    )).run(spec)
    wall = time.time() - started

    summary = {
        "dataset": "sklearn load_digits (UCI handwritten digits, REAL data)",
        "train_samples": len(dataset.x_train),
        "test_samples": len(dataset.x_test),
        "platform": jax.devices()[0].platform,
        "algorithm": "tpe",
        "trials": len(exp.trials),
        "trials_succeeded": exp.succeeded_count,
        "wallclock_s": round(wall, 1),
        "best_test_accuracy": exp.optimal.objective_value if exp.optimal else None,
        "best_assignments": (
            {a.name: a.value for a in exp.optimal.assignments} if exp.optimal else None
        ),
        "best_objective_vs_wallclock": list(exp.optimal_history),
    }
    if tpu_mode:
        # compile-once evidence: trial 1 carries the only XLA compile;
        # trials 2+ reuse the executable and run at chip speed
        summary["trial_durations_s"] = [
            round(t.completion_time - t.start_time, 2)
            for t in sorted(exp.trials.values(), key=lambda t: t.start_time)
            if t.completion_time
        ]
        summary["fixed"] = {"width": 64, "batch": 64, "optimizer": "momentum"}
    write_artifact(
        "real_data",
        "digits_tuning_tpu.json" if tpu_mode else "digits_tuning.json",
        summary,
    )
    print(json.dumps({k: summary[k] for k in (
        "dataset", "trials", "best_test_accuracy", "wallclock_s",
    )}), flush=True)
    ok = (
        exp.succeeded_count == trials
        and exp.optimal is not None
        and exp.optimal.objective_value >= 0.9
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
