"""Real-data accuracy evidence: TPE-tuned classifier on the bundled UCI
handwritten digits (scikit-learn's ``load_digits`` — the one genuinely
non-synthetic dataset reachable with zero egress).

Every other workload in this image runs on structured synthetic fallbacks
(``models/data.py``), so their accuracies prove orchestration, not
learning.  This demo pins a real number: a TPE sweep over lr/batch/width
on 1400 real train digits, best test accuracy recorded in
``artifacts/real_data/digits_tuning.json``.  Typical outcome ≥0.95 top-1
on the 397-sample held-out split — real-world evidence the training stack
learns, within what this image's data allows (CIFAR-10 parity still needs
a ``KATIB_DATA_DIR`` npz).

Run: python scripts/run_real_data_demo.py   (CPU)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax, write_artifact  # noqa: E402


def main() -> int:
    jax = setup_jax(
        force_platform=os.environ.get("DEMO_PLATFORM", "cpu"), virtual_devices=8
    )

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.models.data import load_digits_real
    from katib_tpu.models.mnist import MLP, train_classifier
    from katib_tpu.orchestrator import Orchestrator

    dataset = load_digits_real()
    trials = int(os.environ.get("DEMO_TRIALS", "12"))

    def train(ctx):
        def report(epoch, accuracy, loss):
            return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

        train_classifier(
            MLP(units=int(float(ctx.params["width"]))),
            dataset,
            lr=float(ctx.params["lr"]),
            epochs=20,
            batch_size=int(float(ctx.params["batch"])),
            mesh=ctx.mesh,
            report=report,
            eval_batch=len(dataset.x_test),
        )

    spec = ExperimentSpec(
        name="digits-real",
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        algorithm=AlgorithmSpec(
            name="tpe", settings={"n_startup_trials": "5", "random_state": "7"}
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.005, max=0.5)),
            ParameterSpec(
                "batch", ParameterType.CATEGORICAL, FeasibleSpace(list=("32", "64", "128"))
            ),
            ParameterSpec("width", ParameterType.INT, FeasibleSpace(min=32, max=256)),
        ],
        max_trial_count=trials,
        parallel_trial_count=4,
        train_fn=train,
    )
    started = time.time()
    exp = Orchestrator(workdir=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "katib_runs"
    )).run(spec)
    wall = time.time() - started

    summary = {
        "dataset": "sklearn load_digits (UCI handwritten digits, REAL data)",
        "train_samples": len(dataset.x_train),
        "test_samples": len(dataset.x_test),
        "platform": jax.devices()[0].platform,
        "algorithm": "tpe",
        "trials": len(exp.trials),
        "trials_succeeded": exp.succeeded_count,
        "wallclock_s": round(wall, 1),
        "best_test_accuracy": exp.optimal.objective_value if exp.optimal else None,
        "best_assignments": (
            {a.name: a.value for a in exp.optimal.assignments} if exp.optimal else None
        ),
        "best_objective_vs_wallclock": list(exp.optimal_history),
    }
    write_artifact("real_data", "digits_tuning.json", summary)
    print(json.dumps({k: summary[k] for k in (
        "dataset", "trials", "best_test_accuracy", "wallclock_s",
    )}), flush=True)
    ok = (
        exp.succeeded_count == trials
        and exp.optimal is not None
        and exp.optimal.objective_value >= 0.9
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
