"""Orchestrator-overhead microbenchmark: how fast can the control plane
push trials when trials are free?

The reference's per-trial cost is dominated by Kubernetes machinery (CR
writes, webhook admission, pod scheduling, sidecar PID scans — multiple
seconds per trial even in CI).  Here a trial is a function call plus
journal/store writes, so the control-plane overhead should be
milliseconds.  The committed artifact pins that claim with numbers (amortized across 16-way parallelism):
200 no-op white-box trials and 60 subprocess black-box trials, recording
trials/hour and mean per-trial overhead.

Run: python scripts/benchmark_orchestrator.py   (CPU)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax, write_artifact  # noqa: E402


def main() -> int:
    setup_jax(force_platform=os.environ.get("ORCH_PLATFORM", "cpu"))

    import tempfile

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        MetricsCollectorKind,
        MetricsCollectorSpec,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.orchestrator import Orchestrator

    results = {}

    # -- white-box: trial = function call -------------------------------
    n_white = int(os.environ.get("ORCH_WHITE_TRIALS", "200"))

    def train(ctx):
        ctx.report(step=0, loss=abs(float(ctx.params["x"]) - 0.5))

    spec = ExperimentSpec(
        name="orch-bench-white",
        objective=ObjectiveSpec(type=ObjectiveType.MINIMIZE, objective_metric_name="loss"),
        algorithm=AlgorithmSpec(name="random", settings={"random_state": "1"}),
        parameters=[
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0))
        ],
        max_trial_count=n_white,
        parallel_trial_count=16,
        train_fn=train,
    )
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as wd:
        exp = Orchestrator(workdir=wd).run(spec)
        dt = time.perf_counter() - t0
    assert exp.succeeded_count == n_white, exp.succeeded_count
    results["whitebox"] = {
        "trials": n_white,
        "parallel": 16,
        "wallclock_s": round(dt, 2),
        "trials_per_hour": round(n_white / dt * 3600.0, 0),
        # amortized: wall-clock / trials under 16-way parallelism (a single
        # trial's in-plane latency is up to 16x this)
        "amortized_ms_per_trial": round(dt / n_white * 1000.0, 2),
    }

    # -- black-box: trial = subprocess + stdout collector ----------------
    n_black = int(os.environ.get("ORCH_BLACK_TRIALS", "60"))
    spec_b = ExperimentSpec(
        name="orch-bench-black",
        objective=ObjectiveSpec(type=ObjectiveType.MINIMIZE, objective_metric_name="loss"),
        algorithm=AlgorithmSpec(name="random", settings={"random_state": "1"}),
        parameters=[
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0))
        ],
        max_trial_count=n_black,
        parallel_trial_count=16,
        command=[
            sys.executable, "-c",
            "print('loss=' + str(abs(${trialParameters.x} - 0.5)))",
        ],
        metrics_collector=MetricsCollectorSpec(kind=MetricsCollectorKind.STDOUT),
    )
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as wd:
        exp_b = Orchestrator(workdir=wd).run(spec_b)
        dt_b = time.perf_counter() - t0
    assert exp_b.succeeded_count == n_black, exp_b.succeeded_count
    results["blackbox"] = {
        "trials": n_black,
        "parallel": 16,
        "wallclock_s": round(dt_b, 2),
        "trials_per_hour": round(n_black / dt_b * 3600.0, 0),
        "amortized_ms_per_trial": round(dt_b / n_black * 1000.0, 2),
    }
    # context: the reference's CI bound is <=40 MINUTES per e2e experiment
    # of ~12 trials (run-e2e-experiment.py:11) — minutes/trial, not ms
    results["reference_context"] = (
        "reference e2e bound: <=40min per ~12-trial experiment on CI "
        "(seconds-to-minutes per trial through the K8s control plane)"
    )
    write_artifact("orchestrator", "throughput.json", results)
    print(json.dumps(results, indent=1), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
