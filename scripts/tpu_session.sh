#!/bin/bash
# Unattended TPU measurement session. The axon pool grants the chip to one
# client at a time and a crashed session can leave a stale grant (claim
# TTL, server-side) — so: probe until device init succeeds, then run the
# measurement sequence with local AOT compile (see bench.py module doc).
# Usage: bash scripts/tpu_session.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG="${1:-.tpu_session.log}"
: > "$LOG"
say() { echo "[tpu_session $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

probe() {
  PALLAS_AXON_REMOTE_COMPILE=0 timeout 330 python - <<'EOF' >>"$LOG" 2>&1
import time, jax, jax.numpy as jnp
t0 = time.time(); d = jax.devices()
print("probe: init", round(time.time() - t0, 1), "s", d[0].platform, flush=True)
t0 = time.time()
y = (jnp.ones((512, 512)) @ jnp.ones((512, 512))).block_until_ready()
print("probe: matmul", round(time.time() - t0, 2), "s sum", float(y.sum()), flush=True)
EOF
}

say "waiting for TPU pool grant (probe every 150s, up to 3h)"
ok=0
for i in $(seq 1 72); do
  if probe; then ok=1; say "pool grant acquired (attempt $i)"; break; fi
  say "probe $i failed; pool still wedged — sleeping 150s"
  sleep 150
done
if [ "$ok" != 1 ]; then say "pool never recovered; giving up"; exit 3; fi

say "=== warm bench (full-size compile, local AOT) ==="
BENCH_WARM_ONLY=1 BENCH_INIT_TIMEOUT=300 BENCH_RETRIES=2 BENCH_RETRY_BACKOFF=120 \
  BENCH_NO_FALLBACK=1 python bench.py >>"$LOG" 2>&1
say "warm bench rc=$?"

say "=== timed bench ==="
BENCH_INIT_TIMEOUT=300 BENCH_RETRIES=2 BENCH_RETRY_BACKOFF=120 BENCH_NO_FALLBACK=1 \
  python bench.py > .bench_preview.json 2>>"$LOG"
rc=$?
say "timed bench rc=$rc: $(cat .bench_preview.json 2>/dev/null | head -c 400)"

say "=== flagship DARTS search ==="
python scripts/run_flagship_tpu.py >>"$LOG" 2>&1
say "flagship rc=$?"

say "=== long-context attention bench ==="
python scripts/run_longcontext_tpu.py >>"$LOG" 2>&1
say "longcontext rc=$?"
say "session complete"
