"""Scan-unroll A/B for the flagship epoch loop, on-chip.

VERDICT r4 item 3: the op microbench exposed a fixed ~1.35-1.5 ms
per-scan-iteration floor that dwarfs the ~4.6 ms marginal cost of a whole
cell.  If that floor is XLA While-loop machinery, inlining several bilevel
steps per loop iteration (``lax.scan(..., unroll=k)``) amortizes it; if it
is per-op cost inside the body, unrolling buys nothing and the artifact
honestly refutes the lever — either way the measurement is kept, like the
fused-plan A/B (``artifacts/flagship/bench_tpu_b64_fused.json``).

Measures a K-step scan over the FULL-SIZE second-order bilevel step (the
exact program ``run_darts_search(device_data=True)`` dispatches per epoch,
``nas/darts/search.py``) at each requested unroll factor.  Timing
discipline per docs/performance.md: one dispatch per measurement, clock
stopped on a host-fetched scalar.

Artifact: ``artifacts/flagship/scan_unroll_ab.json``.
Env: UNROLL_FACTORS (default ``1,2``), UNROLL_STEPS (scan length, default
8), UNROLL_SMALL=1 (CPU smoke shapes), BENCH_BATCH etc. pass through to
the shared model builder.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402

sys.path.insert(0, REPO)  # for bench.py's shared model builder


def main() -> int:
    from katib_tpu.utils.booleans import parse_bool

    small = parse_bool(os.environ.get("UNROLL_SMALL"))
    if small:
        os.environ.setdefault("BENCH_SMALL", "1")
    jax = setup_jax(compile_cache=True)
    import jax.numpy as jnp

    from bench import _build_flagship

    factors = [
        int(f)
        for f in os.environ.get("UNROLL_FACTORS", "1,2").split(",")
        if f.strip()
    ]
    k_steps = int(os.environ.get("UNROLL_STEPS", "2" if small else "8"))
    platform = jax.devices()[0].platform

    step, state, batch, net, remat = _build_flagship(jax, jnp)
    x, y = batch
    # K distinct batches so no iteration's work can be CSE'd away
    keyb = jax.random.PRNGKey(7)
    xs = x[None] + 1e-3 * jax.random.normal(
        keyb, (k_steps, *x.shape), x.dtype
    )
    ys = jnp.tile(y[None], (k_steps, 1))

    def make_epoch(u):
        def epoch(s, xs, ys):
            def body(c, b):
                xb, yb = b
                c, m = step(c, (xb, yb), (xb, yb))
                return c, m["train_loss"]

            return jax.lax.scan(body, s, (xs, ys), unroll=u)

        return jax.jit(epoch)

    @jax.jit
    def redsum(s):
        return sum(
            jnp.sum(a.astype(jnp.float32)) for a in jax.tree_util.tree_leaves(s)
        )

    points = []
    for u in factors:
        epoch = make_epoch(u)
        print(f"unroll_ab: compiling unroll={u} (K={k_steps}) ...", flush=True)
        t0 = time.perf_counter()
        s1, _ = epoch(state, xs, ys)
        float(redsum(s1))  # compile + first run, fetch-forced
        compile_secs = time.perf_counter() - t0
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            s1, losses = epoch(state, xs, ys)
            float(redsum(losses))
            times.append(time.perf_counter() - t0)
        dt = min(times)
        step_secs = dt / k_steps
        img_per_sec = x.shape[0] * k_steps / dt
        points.append(
            {
                "unroll": u,
                "scan_steps": k_steps,
                "step_secs": round(step_secs, 4),
                "images_per_sec": round(img_per_sec, 2),
                "compile_secs": round(compile_secs, 1),
            }
        )
        print(
            f"unroll_ab: unroll={u}: {step_secs*1e3:.1f} ms/step "
            f"({img_per_sec:.1f} img/s, compile {compile_secs:.0f}s)",
            flush=True,
        )

    # name the actual baseline: a rerun of only the higher factors must
    # not mislabel its ratios as "vs unroll 1"
    base = next((p for p in points if p["unroll"] == 1), points[0])
    out = {
        "baseline_unroll": base["unroll"],
        "what": (
            "K-step scan over the full-size second-order bilevel step at "
            "each unroll factor; one dispatch per measurement, clock ends "
            "on a host-fetched scalar (docs/performance.md)"
        ),
        "platform": platform,
        "config": {
            "batch": int(x.shape[0]),
            "small_shapes": small,
            "remat": remat,
        },
        "points": points,
        f"speedup_vs_unroll{base['unroll']}": {
            str(p["unroll"]): round(base["step_secs"] / p["step_secs"], 3)
            for p in points
        },
    }
    write_artifact("flagship", "scan_unroll_ab.json", out)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
