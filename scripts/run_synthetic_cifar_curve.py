"""Accuracy-vs-wallclock DARTS search curve at the north-star INPUT scale.

Real CIFAR-10 cannot be downloaded in this zero-egress image
(``fetch_cifar10.py`` is the one-command upgrade path when an archive
lands), so convergence evidence at the reference's 32x32x3 input shape
comes from the structured synthetic CIFAR stand-in (``models/data.py``
``synthetic_classification``: smoothed class prototypes + Gaussian noise).
The artifact documents the stand-in's measured ceiling — the accuracy of
the Bayes-like nearest-class-mean classifier — so the curve cannot be
over-read as real-data capability.

Writes ``artifacts/flagship/synthetic_cifar_curve.json``.

Env knobs (defaults size the run for a ~30-45 min single-core budget;
on a TPU grant the same script runs the full flagship shape):
  CURVE_EPOCHS       search epochs (default 4)
  CURVE_LAYERS       supernet layers (default 4)
  CURVE_CHANNELS     init channels (default 8)
  CURVE_BATCH        batch size (default 32)
  CURVE_TRAIN        train samples (default 4096)
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402

# CPU by default: the ambient env always exports JAX_PLATFORMS=axon on
# this box, so "honor ambient" would aim every curve run at a possibly
# wedged pool (and collide with the probe loop's single grant).
# CURVE_TPU=1 opts into the chip.
from katib_tpu.utils.booleans import parse_bool  # noqa: E402

on_tpu = parse_bool(os.environ.get("CURVE_TPU"))
jax = setup_jax(force_platform=None if on_tpu else "cpu", compile_cache=True)


def nearest_class_mean_ceiling(ds) -> float:
    """Accuracy of classifying test points by nearest class mean of the
    train set — for the prototype+noise generator this approximates the
    Bayes classifier, i.e. the stand-in's accuracy ceiling."""
    means = np.stack([
        ds.x_train[ds.y_train == c].mean(axis=0) for c in range(ds.num_classes)
    ]).reshape(ds.num_classes, -1)
    xt = ds.x_test.reshape(len(ds.x_test), -1)
    d2 = ((xt[:, None, :] - means[None, :, :]) ** 2).sum(-1)
    return float((d2.argmin(1) == ds.y_test).mean())


def main() -> None:
    from katib_tpu.models.data import load_cifar10, using_real_data
    from katib_tpu.nas.darts import DartsHyper, run_darts_search
    from katib_tpu.nas.darts.ops import DEFAULT_PRIMITIVES

    epochs = int(os.environ.get("CURVE_EPOCHS", "4"))
    layers = int(os.environ.get("CURVE_LAYERS", "4"))
    channels = int(os.environ.get("CURVE_CHANNELS", "8"))
    batch = int(os.environ.get("CURVE_BATCH", "32"))
    n_train = int(os.environ.get("CURVE_TRAIN", "4096"))

    ds = load_cifar10(n_train=n_train, n_test=1024)
    real = using_real_data("cifar10")
    assert ds.x_train.shape[1:] == (32, 32, 3), ds.x_train.shape
    ceiling = nearest_class_mean_ceiling(ds)
    print(f"dataset: {'REAL cifar10 npz' if real else 'synthetic stand-in'}, "
          f"{len(ds.x_train)} train; nearest-class-mean ceiling {ceiling:.4f}",
          flush=True)

    t0 = time.perf_counter()
    result = run_darts_search(
        ds,
        num_epochs=epochs,
        primitives=DEFAULT_PRIMITIVES,
        num_layers=layers,
        init_channels=channels,
        n_nodes=4,
        batch_size=batch,
        hyper=DartsHyper(unrolled=True),
        seed=0,
    )
    wall = time.perf_counter() - t0

    payload = {
        "what": (
            "DARTS second-order search convergence curve at the north-star "
            "32x32x3 input shape; dataset is the structured synthetic CIFAR "
            "stand-in unless real_data is true — accuracy here measures "
            "search/optimization plumbing against the documented synthetic "
            "ceiling, NOT real CIFAR-10 capability"
        ),
        "real_data": real,
        "platform": jax.devices()[0].platform,
        "input_shape": [32, 32, 3],
        "config": {
            "epochs": epochs, "layers": layers, "init_channels": channels,
            "n_nodes": 4, "batch": batch, "n_train": len(ds.x_train),
            "unrolled": True,
        },
        "ceiling_nearest_class_mean": round(ceiling, 4),
        "best_accuracy": round(result["best_accuracy"], 4),
        "fraction_of_ceiling": round(result["best_accuracy"] / max(ceiling, 1e-9), 4),
        "history": result["history"],
        "genotype": result.get("genotype"),
        "wallclock_s": round(wall, 1),
    }
    path = write_artifact("flagship", "synthetic_cifar_curve.json", payload)
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
