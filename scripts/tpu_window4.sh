#!/usr/bin/env bash
# Round-4 recovery queue: the pool wedged mid-flagship (epoch 15/50
# checkpointed, resume-ready). Wait for the pool to recover, then run
# everything the round still needs, highest value first:
#   1. the 50-epoch flagship resume (picks up at the last Orbax snapshot)
#   2. batch scaling (b64 / b128-dots) with the compile-locality fix
#   3. op microbench with the two-point dispatch/marginal fit
#   4. 32-trial Hyperband sweep serialized on the chip (redirected)
#   5-7. real-data digits NAS / ENAS / PBT on-chip (redirected)
# Probes the pool again between steps; a re-wedge skips to the probe
# rather than burning each step's full timeout.
# Usage: bash scripts/tpu_window4.sh   (detached)
# Logs:  /tmp/tpu_window4/<step>.log
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_window4
ART=/tmp/tpu_window4/artifacts
mkdir -p "$LOG"

probe() {
    env POOL_WATCH_PROBE_TIMEOUT=180 POOL_WATCH_INTERVAL=120 \
        POOL_WATCH_MAX_HOURS=9 python scripts/pool_watch.py \
        >>"$LOG/pool_watch.log" 2>&1
}

run() {
    local t=$1 name=$2; shift 2
    echo "=== $name start $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    setsid "$@" >"$LOG/$name.log" 2>&1 &
    local pid=$!
    ( sleep "$t" && kill -- -"$pid" 2>/dev/null && sleep 20 \
        && kill -9 -- -"$pid" 2>/dev/null ) &
    local watcher=$!
    local rc=0
    wait "$pid" || rc=$?
    kill "$watcher" 2>/dev/null; wait "$watcher" 2>/dev/null
    kill -9 -- -"$pid" 2>/dev/null
    echo "=== $name rc=$rc end $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
}

probe || exit 1

# 1. flagship resume (epoch 16 onward; ~35.8 s/epoch measured + one
#    terminal-side recompile if the wedge dropped the server cache)
run 9000 flagship_resume env FLAGSHIP_EPOCHS=50 FLAGSHIP_BATCH=64 \
    FLAGSHIP_REMAT=0 FLAGSHIP_FUSED=0 python scripts/run_flagship_tpu.py

probe || exit 1

# 2. batch scaling at the proven configs
run 5400 batch_scaling python scripts/run_batch_scaling.py

probe || exit 1

# 3. op microbench, two-point fit
run 2700 op_microbench python scripts/run_op_microbench.py

probe || exit 1

# 4. Hyperband sweep serialized on the chip (redirected, copied in)
run 5400 hyperband_tpu env SWEEP_PLATFORM=axon KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_hyperband_sweep.py
[ -f "$ART/hyperband/sweep_summary.json" ] && \
    cp "$ART/hyperband/sweep_summary.json" artifacts/hyperband/sweep_summary_tpu.json

probe || exit 1

# 5. real-data digits NAS on-chip
run 3600 nas_digits env DEMO_PLATFORM=axon KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_nas_real_data.py
[ -f "$ART/real_data/digits_nas.json" ] && \
    cp "$ART/real_data/digits_nas.json" artifacts/real_data/digits_nas_tpu.json

probe || exit 1

# 6. ENAS on-chip
run 3600 enas_digits env ENAS_PLATFORM=axon ENAS_DATASET=digits \
    KATIB_ARTIFACTS_DIR="$ART" python scripts/run_enas_demo.py
[ -f "$ART/enas/digits_summary.json" ] && \
    cp "$ART/enas/digits_summary.json" artifacts/enas/digits_summary_tpu.json

probe || exit 1

# 7. PBT on-chip
run 3600 pbt_digits env PBT_PLATFORM=axon PBT_DATASET=digits \
    PBT_GENERATIONS=6 KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_pbt_demo.py
[ -f "$ART/pbt/digits_summary.json" ] && \
    cp "$ART/pbt/digits_summary.json" artifacts/pbt/digits_summary_tpu.json

echo "=== window4 complete $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
