"""Augment phase measured on the chip: the discovered genotype trained as
a discrete network (DARTS paper eval protocol, the stage the reference's
README cites — ``pkg/suggestion/v1beta1/nas/darts/README.md:3-7``).

Two measurements in one run:

1. **Honest step timing** (the ``docs/performance.md`` recipe: chained
   jitted steps, fresh warmup, clock ended on a host-fetched scalar) of
   the augment train step at the paper shape (36 channels by default) —
   img/s + MFU from XLA's own per-step flop count.  The discrete network
   is structurally MXU-friendlier than the supernet (2 kept ops per node,
   no mixed-op softmax over 8 primitives), so this pins the round-3
   hand-waving ("expected much higher than 0.56%") to a number.
2. **A bounded accuracy run**: AUGMENT_EPOCHS of real training with
   per-epoch held-out accuracy, so the artifact carries learning
   evidence, not just throughput.

The artifact folds the measured rate into the north-star accounting:
search hours (measured bilevel step x 50 epochs) + augment hours
(measured augment step x AUGMENT_ACCOUNT_EPOCHS) vs the <=4 h target.

Chip safety: before anything touches the relay the script AOT-compiles
the train step against a deviceless v5e topology and refuses configs
that do not fit HBM (the batch-512 terminal crash rule from
``run_batch_scaling.py``).  ``AUGMENT_AOT_ONLY=1`` stops after writing
the fit-proof (no device grant needed — run it while the pool is
wedged).

Env knobs: AUGMENT_CHANNELS (36), AUGMENT_LAYERS (8), AUGMENT_BATCH (96),
AUGMENT_EPOCHS (2), AUGMENT_ACCOUNT_EPOCHS (20), AUGMENT_STEPS (20,
timed steps), AUGMENT_SMALL=1 (CPU smoke), KATIB_DATASET (cifar10).
Artifacts: ``artifacts/flagship/augment_tpu.json`` (+ ``augment_aot.json``
fit-proof).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, artifacts_root, setup_jax, write_artifact  # noqa: E402

V5E_HBM_BYTES = 16 * 1024**3
PEAK_FLOPS_BF16 = 197e12


def _load_genotype():
    from katib_tpu.nas.darts.model import Genotype

    # the redirected tree wins when it holds a genotype (a flagship run
    # under the same redirect produced it); otherwise fall back to the
    # committed artifact — a redirect must not break an input-only read
    path = os.path.join(artifacts_root(), "flagship", "genotype.json")
    if not os.path.exists(path):
        committed = os.path.join(REPO, "artifacts", "flagship", "genotype.json")
        if os.path.exists(committed):
            path = committed
    with open(path) as f:
        raw = json.load(f)
    to_gene = lambda g: tuple(  # noqa: E731
        tuple((str(op), int(src)) for op, src in node) for node in g
    )
    return Genotype(normal=to_gene(raw["normal"]), reduce=to_gene(raw["reduce"]))


def _build(jax, genotype, channels, layers, batch, num_classes, input_shape):
    import jax.numpy as jnp
    import optax

    from katib_tpu.nas.darts.augment import GenotypeNetwork
    from katib_tpu.parallel.train import (
        TrainState,
        cross_entropy_loss,
        make_train_step,
    )

    net = GenotypeNetwork(
        genotype=genotype,
        init_channels=channels,
        num_layers=layers,
        num_classes=num_classes,
    )

    def loss_fn(params, batch_xy):
        x, y = batch_xy
        return cross_entropy_loss(net.apply(params, x), y)

    tx = optax.sgd(0.025, momentum=0.9)
    step = make_train_step(loss_fn, tx, mesh=None)  # already jitted
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (batch, *input_shape), jnp.float32)
    y = jax.random.randint(jax.random.fold_in(key, 1), (batch,), 0, num_classes)
    params = net.init(key, x[:1])
    opt_state = tx.init(params)
    state = TrainState(jnp.zeros((), jnp.int32), params, opt_state)
    return net, step, state, (x, y)


def _aot_fit_proof() -> dict:
    """Deviceless v5e AOT compile of the augment train step: flops, HBM
    footprint, fit verdict.  Runs in a scrubbed child so the axon plugin
    never loads (same isolation as bench.py's AOT block)."""
    import subprocess

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
    env.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    env["AUGMENT_AOT_CHILD"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        capture_output=True,
        text=True,
        env=env,
        timeout=float(os.environ.get("AUGMENT_AOT_TIMEOUT", "2700")),
    )
    for line in (proc.stdout or "").splitlines():
        if line.startswith("@@AOT@@"):
            return json.loads(line[len("@@AOT@@"):])
    raise RuntimeError(
        f"augment AOT child failed rc={proc.returncode}:\n"
        + (proc.stderr or "")[-1500:]
    )


def _aot_child() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass
    from jax.experimental import topologies
    from jax.sharding import SingleDeviceSharding

    channels = int(os.environ.get("AUGMENT_CHANNELS", "36"))
    layers = int(os.environ.get("AUGMENT_LAYERS", "8"))
    batch = int(os.environ.get("AUGMENT_BATCH", "96"))
    genotype = _load_genotype()
    topo = topologies.get_topology_desc(
        platform="tpu",
        topology_name="v5e:1x1x1",
        chips_per_host_bounds=(1, 1, 1),
        num_slices=1,
    )
    dev = topo.devices[0]
    net, step, state, batch_xy = _build(
        jax, genotype, channels, layers, batch, 10, (32, 32, 3)
    )
    place = lambda a: jax.ShapeDtypeStruct(  # noqa: E731
        a.shape, a.dtype, sharding=SingleDeviceSharding(dev)
    )
    state_s, batch_s = jax.tree.map(place, (state, batch_xy))
    t0 = time.perf_counter()
    compiled = jax.jit(step).lower(state_s, batch_s).compile()
    compile_secs = time.perf_counter() - t0
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    mem = compiled.memory_analysis()
    hbm = int(
        mem.argument_size_in_bytes
        + mem.output_size_in_bytes
        + mem.temp_size_in_bytes
        + mem.generated_code_size_in_bytes
    )
    print(
        "@@AOT@@"
        + json.dumps(
            {
                "target": "v5e:1x1x1 (deviceless AOT, local libtpu)",
                "flops_per_step": float(cost.get("flops", 0.0)),
                "hbm_bytes": hbm,
                "hbm_gib": round(hbm / 1024**3, 3),
                "hbm_fits_v5e": hbm < V5E_HBM_BYTES,
                "compile_secs": round(compile_secs, 1),
                "config": {
                    "channels": channels,
                    "layers": layers,
                    "batch": batch,
                },
            }
        )
    )


def main() -> int:
    if os.environ.get("AUGMENT_AOT_CHILD"):
        _aot_child()
        return 0

    from katib_tpu.utils.booleans import parse_bool

    small = parse_bool(os.environ.get("AUGMENT_SMALL"))
    channels = int(os.environ.get("AUGMENT_CHANNELS", "8" if small else "36"))
    layers = int(os.environ.get("AUGMENT_LAYERS", "2" if small else "8"))
    batch = int(os.environ.get("AUGMENT_BATCH", "16" if small else "96"))
    epochs = int(os.environ.get("AUGMENT_EPOCHS", "1" if small else "2"))
    timed_steps = int(os.environ.get("AUGMENT_STEPS", "3" if small else "20"))
    account_epochs = int(os.environ.get("AUGMENT_ACCOUNT_EPOCHS", "20"))

    # deviceless fit-proof BEFORE any relay contact (memoized on disk; the
    # committed proof also lets a later run skip straight to the chip).
    # Read through the same root write_artifact writes, so a
    # KATIB_ARTIFACTS_DIR redirect cannot split the memo's read/write paths
    proof_path = os.path.join(artifacts_root(), "flagship", "augment_aot.json")
    proof = None
    if not small:
        # memo keyed on config AND jax version (the bench.py _run_aot
        # rule): HBM footprint is compiler-version dependent, so a proof
        # from an older jax/libtpu must not gate a newer one
        from importlib.metadata import version as _pkg_version

        jax_version = _pkg_version("jax")
        try:
            with open(proof_path) as f:
                cached = json.load(f)
            if cached.get("config") == {
                "channels": channels,
                "layers": layers,
                "batch": batch,
            } and cached.get("jax_version") == jax_version:
                proof = cached
        except (OSError, ValueError):
            pass
        if proof is None:
            print("augment: AOT fit-proof (deviceless, no grant) ...", flush=True)
            proof = _aot_fit_proof()
            proof["jax_version"] = jax_version
            write_artifact("flagship", "augment_aot.json", proof)
        if not proof["hbm_fits_v5e"]:
            print(
                f"augment: config does not fit v5e HBM ({proof['hbm_gib']} GiB) "
                "— refusing to submit to the chip",
                file=sys.stderr,
            )
            return 3
        print(
            f"augment: fit-proof ok — {proof['hbm_gib']} GiB, "
            f"{proof['flops_per_step'] / 1e9:.1f} GFLOP/step",
            flush=True,
        )
        if parse_bool(os.environ.get("AUGMENT_AOT_ONLY")):
            return 0

    jax = setup_jax(compile_cache=True)
    import jax.numpy as jnp

    from katib_tpu.models.data import (
        dataset_from_env,
        is_real_data,
        load_named_dataset,
    )
    from katib_tpu.nas.darts.augment import train_genotype

    platform = jax.devices()[0].platform
    ds_name = dataset_from_env("cifar10")
    dataset = load_named_dataset(
        ds_name, 256 if small else None, 128 if small else None
    )
    genotype = _load_genotype()
    print(
        f"augment: platform={platform} channels={channels} layers={layers} "
        f"batch={batch} dataset={ds_name} real_data={is_real_data(ds_name)}",
        flush=True,
    )

    # ---- 1. honest step timing on synthetic tensors (pure compute rate)
    net, step, state, batch_xy = _build(
        jax,
        genotype,
        channels,
        layers,
        batch,
        dataset.num_classes,
        dataset.input_shape,
    )
    runner = step  # make_train_step returns the jitted dispatch path
    flops = 0.0
    try:
        compiled = runner.lower(state, batch_xy).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
    except Exception as e:
        print(f"augment: cost analysis unavailable ({e})", file=sys.stderr)

    @jax.jit
    def _redsum(m):
        return sum(
            jnp.sum(a.astype(jnp.float32)) for a in jax.tree_util.tree_leaves(m)
        )

    for _ in range(2):
        state, metrics = runner(state, batch_xy)
    float(_redsum(metrics))
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        state, metrics = runner(state, batch_xy)
    float(_redsum(metrics))  # host fetch ends the clock (integrity recipe)
    dt = time.perf_counter() - t0
    step_secs = dt / timed_steps
    img_per_sec = batch / step_secs
    mfu = (flops / step_secs) / PEAK_FLOPS_BF16 if flops else None
    print(
        f"augment: {step_secs * 1e3:.1f} ms/step, {img_per_sec:.1f} img/s"
        + (f", MFU {mfu:.2%}" if mfu else ""),
        flush=True,
    )

    # ---- 2. bounded accuracy run on the actual dataset
    history: list[dict] = []
    t_train0 = time.perf_counter()

    def report(epoch, accuracy, loss):
        history.append(
            {
                "epoch": epoch,
                "accuracy": round(float(accuracy), 4),
                "loss": round(float(loss), 4),
                "elapsed_s": round(time.perf_counter() - t_train0, 1),
            }
        )
        print(f"augment: epoch={epoch} acc={accuracy:.4f}", flush=True)
        return True

    # AUGMENT_DATA_AUG=1: the reference's crop/flip/cutout pipeline as
    # device-side transforms (models/augmentation.py) — opt-in so the
    # throughput series stays comparable with earlier rounds
    data_augment = parse_bool(os.environ.get("AUGMENT_DATA_AUG"))
    final_acc = train_genotype(
        genotype,
        dataset,
        init_channels=channels,
        num_layers=layers,
        epochs=epochs,
        batch_size=batch,
        report=report,
        data_augment=data_augment,
    )

    # ---- north-star accounting with MEASURED rates
    steps_per_epoch = len(dataset.x_train) // batch
    augment_hours = account_epochs * steps_per_epoch * step_secs / 3600.0
    search_hours = None
    try:
        with open(os.path.join(artifacts_root(), "flagship", "bench_tpu.json")) as f:
            bench = json.load(f)
        if bench.get("platform") == "tpu":
            # 50-epoch search at the measured bilevel rate, 25k images/epoch
            # split in half for w/alpha (run_trial.py:98-111)
            search_steps = 50 * (25000 // 2 // bench["config"]["batch"])
            search_hours = search_steps * bench["step_secs"] / 3600.0
    except (OSError, ValueError, KeyError):
        pass

    payload = {
        "what": (
            "DARTS augment phase (discrete genotype network) measured on "
            "this platform: honest chained-step timing + a bounded real "
            "training run"
        ),
        "platform": platform,
        "dataset": ds_name,
        "real_data": is_real_data(ds_name),
        "config": {
            "channels": channels,
            "layers": layers,
            "batch": batch,
            "epochs_run": epochs,
            "data_augment": data_augment,
        },
        "step_secs": round(step_secs, 5),
        "images_per_sec": round(img_per_sec, 1),
        "mfu": round(mfu, 5) if mfu is not None else None,
        "flops_per_step": flops,
        "final_accuracy": final_acc,
        "accuracy_history": history,
        "north_star_accounting": {
            "search_hours_50ep_measured": (
                round(search_hours, 2) if search_hours is not None else None
            ),
            "augment_epochs_assumed": account_epochs,
            "augment_hours_measured_rate": round(augment_hours, 2),
            "total_hours": (
                round(search_hours + augment_hours, 2)
                if search_hours is not None
                else None
            ),
            "target_hours": 4.0,
        },
        "aot_fit_proof": proof,
    }
    write_artifact("flagship", "augment_tpu.json", payload)
    print(
        json.dumps(
            {
                k: payload[k]
                for k in (
                    "platform",
                    "images_per_sec",
                    "mfu",
                    "final_accuracy",
                )
            }
            | {"north_star": payload["north_star_accounting"]}
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
