"""Batch-scaling study for the flagship bilevel step on the TPU.

The honest batch-64 measurement (artifacts/flagship/bench_tpu.json,
~535 ms/step, 0.56% MFU) is small-op/tile-padding-bound, so throughput
should scale sub-linearly-in-time with batch — this harness measures how
far.  Each configuration runs through ``bench.py`` itself (same child
isolation, same fetch-forced timing), so a scaling point is produced by
exactly the code the driver benches with.

Safety: a batch-512 terminal-side compile crashed the pool terminal and
wedged the grant (docs/performance.md), so every configuration must carry
a committed deviceless-AOT block proving ``hbm_fits_v5e`` before this
script will submit it to the chip.  Missing AOT memo => the config is
SKIPPED with a note, never attempted.

Artifacts: ``artifacts/flagship/batch_scaling.json``.
Env knobs: SCALING_CONFIGS (comma list like ``64:none,128:dots``; extra
``:``-separated variant fields select program variants — ``ph`` adds the
paired-Hessian step (fit-proof looked up under the matching ``_pairhess``
tag), ``w<N>`` runs the bench child's fused step loop with an N-step scan
window (``BENCH_STEP_LOOP_WINDOW``), e.g. ``128:dots:ph:w8``),
BENCH_STEPS per point (default 5).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import (  # noqa: E402
    REPO,
    _local_compile_probe,
    artifacts_root,
    write_artifact,
)

RESULT_PREFIX = '{"metric"'


def parse_configs(raw: str) -> list[tuple[int, str | None, bool, int | None]]:
    out: list[tuple[int, str | None, bool, int | None]] = []
    for part in raw.split(","):
        fields = [f.strip() for f in part.strip().split(":")]
        batch = int(fields[0])
        policy = fields[1] if len(fields) > 1 and fields[1] not in ("", "none") else None
        pairhess = False
        window: int | None = None
        # fail fast on anything unrecognized: a typo'd variant that silently
        # parsed as the non-variant would burn a fit-proof-gated chip point
        # on the wrong program and only surface after the window ends
        for f in fields[2:]:
            if f == "ph":
                pairhess = True
            elif len(f) > 1 and f[0] == "w" and f[1:].isdigit() and int(f[1:]) >= 1:
                window = int(f[1:])
            else:
                raise ValueError(
                    f"unknown variant field {f!r} in {part!r} "
                    "(only 'ph' and 'w<N>')"
                )
        out.append((batch, policy, pairhess, window))
    return out


def aot_block_for(batch: int, policy: str | None, pairhess: bool = False) -> dict | None:
    """The committed deviceless-AOT evidence for this config, or None."""
    if policy is None and batch == 64 and not pairhess:
        name = "aot_v5e.json"
    else:
        tag = f"b{batch}" + ("_remat" if policy is not None else "")
        if policy:
            tag += f"_{policy}"
        if pairhess:
            tag += "_pairhess"
        name = f"aot_v5e_{tag}.json"
    try:
        with open(os.path.join(artifacts_root(), "flagship", name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _flush(points: list[dict]) -> dict:
    """Rewrite batch_scaling.json with the points measured SO FAR.  Called
    after every point: the outer window driver (scripts/tpu_window5c.sh)
    hard-kills this script's process group at its step timeout, and an
    end-only write would lose every already-measured chip point with it."""
    result = {
        "what": (
            "flagship second-order bilevel step throughput vs batch size; "
            "each point measured by bench.py's fetch-forced child on the "
            "chip, submitted only with committed AOT HBM-fit proof"
        ),
        "points": points,
    }
    write_artifact("flagship", "batch_scaling.json", result)
    return result


def main() -> int:
    configs = parse_configs(os.environ.get("SCALING_CONFIGS", "64:none,128:dots"))
    steps = os.environ.get("BENCH_STEPS", "5")
    # probe once, outside the loop: the verdict cannot change between the
    # points of one invocation, and an inconclusive (None) probe on a
    # wedged pool would otherwise charge every point its full timeout
    # before the bench child even starts
    remote_compile = _local_compile_probe() is False
    points: list[dict] = []
    for batch, policy, pairhess, window in configs:
        # the scan window chunks dispatches of the SAME per-step program —
        # donated carry, no extra live activations — so the fit-proof is
        # keyed on (batch, policy, pairhess) only
        aot = aot_block_for(batch, policy, pairhess)
        if aot is None or not aot.get("hbm_fits_v5e"):
            points.append(
                {
                    "batch": batch,
                    "remat_policy": policy,
                    "paired_hessian": pairhess,
                    "skipped": True,
                    "reason": (
                        "no committed AOT fit-proof — oversized terminal "
                        "compiles crash the pool (docs/performance.md); "
                        "run the deviceless AOT first"
                        if aot is None
                        else f"AOT says {aot['hbm_gib']} GiB > v5e HBM"
                    ),
                }
            )
            _flush(points)
            continue
        env = dict(os.environ)
        env.update(
            BENCH_BATCH=str(batch),
            BENCH_SKIP_AOT="1",
            BENCH_NO_FALLBACK="1",
            # 2, not 1: bench's libtpu-mismatch auto-flip to terminal-side
            # compile happens on the attempt AFTER the mismatch is seen —
            # a single attempt fails before the flip can ever fire (this
            # exact footgun burned the first on-chip scaling run)
            BENCH_RETRIES="2",
            BENCH_STEPS=steps,
        )
        # consult the cached compile-locality verdict up front so attempt 1
        # already compiles on the correct side instead of burning an
        # attempt rediscovering the mismatch per point
        if remote_compile:
            env["KATIB_REMOTE_COMPILE"] = "1"
        if policy is not None:
            env.update(BENCH_REMAT="1", BENCH_REMAT_POLICY=policy)
        else:
            env.pop("BENCH_REMAT", None)
            env.pop("BENCH_REMAT_POLICY", None)
        if pairhess:
            env["BENCH_PAIRED_HESSIAN"] = "1"
        else:
            env.pop("BENCH_PAIRED_HESSIAN", None)
        if window is not None:
            env["BENCH_STEP_LOOP_WINDOW"] = str(window)
        else:
            env.pop("BENCH_STEP_LOOP_WINDOW", None)
        print(
            f"scaling: batch={batch} policy={policy} pairhess={pairhess}"
            f" window={window} ...",
            flush=True,
        )
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")],
                capture_output=True,
                text=True,
                env=env,
                timeout=float(os.environ.get("SCALING_POINT_TIMEOUT", "3000")),
            )
        except subprocess.TimeoutExpired:
            # one wedged point must not lose the points already measured
            points.append(
                {
                    "batch": batch,
                    "remat_policy": policy,
                    "paired_hessian": pairhess,
                    "failed": True,
                    "timeout": True,
                }
            )
            _flush(points)
            continue
        rec: dict | None = None
        for line in (proc.stdout or "").splitlines():
            if line.startswith(RESULT_PREFIX):
                rec = json.loads(line)
        if rec is None:
            points.append(
                {
                    "batch": batch,
                    "remat_policy": policy,
                    "paired_hessian": pairhess,
                    "failed": True,
                    "stderr_tail": (proc.stderr or "")[-500:],
                }
            )
            _flush(points)
            continue
        point = {
            "batch": batch,
            "remat_policy": policy,
            "paired_hessian": pairhess,
            "images_per_sec": rec["value"],
            "step_secs": rec["step_secs"],
            "mfu": rec["mfu"],
            "platform": rec["platform"],
            "aot_hbm_gib": aot["hbm_gib"],
            "steps_per_dispatch": rec.get("steps_per_dispatch", 1),
        }
        fused = rec.get("fused_loop")
        if fused is not None:
            point["fused_loop"] = {
                "images_per_sec": fused["value"],
                "step_secs": fused["step_secs"],
                "steps_per_dispatch": fused["steps_per_dispatch"],
                "mfu": fused["mfu"],
            }
        points.append(point)
        _flush(points)
        print(f"scaling:   -> {rec['value']} img/s ({rec['step_secs']}s/step)", flush=True)
        if fused is not None:
            print(
                f"scaling:   -> fused x{fused['steps_per_dispatch']}: "
                f"{fused['value']} img/s ({fused['step_secs']}s/step)",
                flush=True,
            )

    result = _flush(points)
    print(json.dumps(result["points"]), flush=True)
    ok = any("images_per_sec" in p for p in points)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
