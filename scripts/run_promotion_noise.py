"""Promotion-noise characterization at short-trial scale (VERDICT r4 item 7).

The 32-trial Hyperband sweep and the ASHA comparison promote on ~7 s
trainings (``artifacts/hyperband/sweep_summary.json per_trial_secs``);
this harness quantifies how noisy those promotion decisions are, two ways:

**A. Fixed-config replicates (rank stability).**  Sample one set of
configurations, then train each under ``NOISE_SEEDS`` different training
seeds (init + shuffle — the actual noise source at this scale), recording
the rung-0 proxy metric (accuracy after 1 epoch) and the full-resource
metric (accuracy after ``NOISE_FULL_EPOCHS``).  Reported:

- per-seed Spearman rank correlation between proxy and full-resource
  accuracy — how much signal a rung-0 decision actually has;
- across seeds, mean pairwise Jaccard overlap of the survivor set
  (top 1/eta by proxy) — how much the PROMOTED SET changes when only the
  seed changes;
- the probability that a config in the TRUE top-1/eta (by mean
  full-resource accuracy) is dropped at rung 0, per seed.

**B. Repeated end-to-end sweeps (best-objective variance).**  The real
orchestrator + Hyperband suggester end-to-end, ``NOISE_SWEEPS`` times
with different ``random_state``; reports best-objective mean/stdev/range
— the variance column the sweep artifacts were missing.

This extends the reference e2e's semantic invariants
(``test/e2e/v1beta1/scripts/gh-actions/run-e2e-experiment.py:52-60``,
which assert one run's outcome) with replication, the piece a 7-second
trial regime needs.

Artifact: ``artifacts/hyperband/promotion_noise.json``.
Env: NOISE_SEEDS (5), NOISE_CONFIGS (12), NOISE_FULL_EPOCHS (8),
NOISE_ETA (4), NOISE_SWEEPS (5), NOISE_SWEEP_RL (16),
NOISE_SWEEP_TRIALS (32), NOISE_SMALL=1 (CI smoke: tiny everything).
"""

from __future__ import annotations

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402

jax = setup_jax(force_platform="cpu", virtual_devices=8)

sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def _avg_ranks(x: list[float]) -> np.ndarray:
    """Average ranks for ties (scipy.stats.rankdata semantics) — digits
    accuracies quantize to multiples of 1/n_test, so ties are routine and
    arbitrary distinct ranks would bias the correlation."""
    arr = np.asarray(x, dtype=float)
    order = np.argsort(arr, kind="stable")
    ranks = np.empty(len(arr), dtype=float)
    i = 0
    while i < len(arr):
        j = i
        while j + 1 < len(arr) and arr[order[j + 1]] == arr[order[i]]:
            j += 1
        ranks[order[i : j + 1]] = (i + j) / 2.0
        i = j + 1
    return ranks


def spearman(a: list[float], b: list[float]) -> float:
    ra, rb = _avg_ranks(a), _avg_ranks(b)
    if np.std(ra) == 0 or np.std(rb) == 0:
        return 0.0
    return float(np.corrcoef(ra, rb)[0, 1])


def jaccard(x: set, y: set) -> float:
    return len(x & y) / len(x | y) if (x | y) else 1.0


def part_a(small: bool) -> dict:
    from katib_tpu.models.data import load_named_dataset
    from katib_tpu.models.mnist import SmallCNN, train_classifier

    n_seeds = int(os.environ.get("NOISE_SEEDS", "2" if small else "5"))
    n_configs = int(os.environ.get("NOISE_CONFIGS", "4" if small else "12"))
    full_epochs = int(os.environ.get("NOISE_FULL_EPOCHS", "2" if small else "8"))
    eta = int(os.environ.get("NOISE_ETA", "2" if small else "4"))
    dataset = load_named_dataset("digits")

    # one fixed config set: log-uniform lr (the knob that matters for the
    # digits CNN), sampled once so every seed ranks the SAME candidates
    rng = np.random.default_rng(12345)
    lrs = sorted(10 ** rng.uniform(-3.0, -0.3, size=n_configs))

    proxy: list[list[float]] = []  # [seed][config] acc after 1 epoch
    final: list[list[float]] = []  # [seed][config] acc after full_epochs
    for seed in range(n_seeds):
        p_row, f_row = [], []
        for lr in lrs:
            accs = {}

            def report(epoch, accuracy, loss):
                accs[epoch] = float(accuracy)
                return True

            train_classifier(
                SmallCNN(),
                dataset,
                lr=float(lr),
                epochs=full_epochs,
                batch_size=64,
                seed=seed,
                report=report,
                eval_batch=256,
            )
            p_row.append(accs[0])
            f_row.append(accs[max(accs)])
        proxy.append(p_row)
        final.append(f_row)
        print(
            f"noise A: seed={seed} spearman(proxy,final)="
            f"{spearman(p_row, f_row):.3f}",
            flush=True,
        )

    k = max(1, n_configs // eta)  # survivor count at eta
    survivors = [
        set(np.argsort(row)[-k:].tolist()) for row in proxy
    ]
    pairs = [
        jaccard(survivors[i], survivors[j])
        for i in range(n_seeds)
        for j in range(i + 1, n_seeds)
    ]
    mean_final = np.mean(final, axis=0)
    true_top = set(np.argsort(mean_final)[-k:].tolist())
    drop_rates = [
        1.0 - len(true_top & s) / len(true_top) for s in survivors
    ]
    return {
        "n_seeds": n_seeds,
        "n_configs": n_configs,
        "eta": eta,
        "proxy_epochs": 1,
        "full_epochs": full_epochs,
        "lrs": [round(float(x), 5) for x in lrs],
        "spearman_proxy_vs_final_per_seed": [
            round(spearman(proxy[s], final[s]), 3) for s in range(n_seeds)
        ],
        "survivor_jaccard_mean_pairwise": (
            round(statistics.mean(pairs), 3) if pairs else 1.0
        ),
        "true_top_dropped_at_rung0_rate": {
            "per_seed": [round(d, 3) for d in drop_rates],
            "mean": round(statistics.mean(drop_rates), 3),
        },
        "per_seed_proxy_acc": [[round(v, 4) for v in r] for r in proxy],
        "per_seed_final_acc": [[round(v, 4) for v in r] for r in final],
    }


def part_b(small: bool) -> dict:
    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.models.data import load_named_dataset
    from katib_tpu.models.mnist import SmallCNN, train_classifier
    from katib_tpu.orchestrator import Orchestrator
    from katib_tpu.parallel.distributed import SliceAllocator

    n_sweeps = int(os.environ.get("NOISE_SWEEPS", "2" if small else "5"))
    r_l = int(os.environ.get("NOISE_SWEEP_RL", "4" if small else "16"))
    max_trials = int(os.environ.get("NOISE_SWEEP_TRIALS", "6" if small else "32"))
    dataset = load_named_dataset("digits")
    import tempfile

    bests, walls = [], []
    for seed in range(n_sweeps):
        def train(ctx):
            def report(epoch, accuracy, loss):
                return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

            train_classifier(
                SmallCNN(),
                dataset,
                lr=float(ctx.params["lr"]),
                epochs=int(float(ctx.params["epochs"])),
                batch_size=64,
                seed=seed,
                mesh=ctx.mesh,
                report=report,
                eval_batch=256,
            )

        spec = ExperimentSpec(
            name=f"noise-sweep-{seed}",
            algorithm=AlgorithmSpec(
                name="hyperband",
                settings={
                    "r_l": str(r_l),
                    "eta": "4",
                    "resource_name": "epochs",
                    "random_state": str(seed),
                },
            ),
            objective=ObjectiveSpec(
                type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
            ),
            parameters=[
                ParameterSpec(
                    "lr", ParameterType.DOUBLE, FeasibleSpace(min=0.001, max=0.5)
                ),
                ParameterSpec(
                    "epochs", ParameterType.INT, FeasibleSpace(min=1, max=r_l)
                ),
            ],
            # hyperband's rung-0 bracket width is r_l wide at eta=4; the
            # suggester refuses parallelism below it (run_hyperband_sweep
            # uses 16 for the same reason)
            max_trial_count=max_trials,
            parallel_trial_count=max(16, r_l),
            train_fn=train,
        )
        alloc = SliceAllocator(slice_size=1, devices=jax.devices())
        t0 = time.perf_counter()
        with tempfile.TemporaryDirectory() as wd:
            exp = Orchestrator(workdir=wd, slice_allocator=alloc).run(spec)
        walls.append(round(time.perf_counter() - t0, 1))
        bests.append(
            round(exp.optimal.objective_value, 5) if exp.optimal else None
        )
        print(f"noise B: sweep seed={seed} best={bests[-1]}", flush=True)

    vals = [b for b in bests if b is not None]
    return {
        "n_sweeps": n_sweeps,
        "r_l": r_l,
        "max_trials": max_trials,
        "best_objective_per_seed": bests,
        "best_objective_mean": round(statistics.mean(vals), 5) if vals else None,
        "best_objective_stdev": (
            round(statistics.stdev(vals), 5) if len(vals) > 1 else None
        ),
        "best_objective_range": (
            [min(vals), max(vals)] if vals else None
        ),
        "wallclock_s_per_sweep": walls,
    }


def main() -> int:
    from katib_tpu.utils.booleans import parse_bool

    small = parse_bool(os.environ.get("NOISE_SMALL"))
    a = part_a(small)
    b = part_b(small)
    payload = {
        "what": (
            "promotion-decision noise at the ~7s-trial scale the sweep/ASHA "
            "artifacts operate at: fixed-config seed replicates (rank "
            "stability of rung-0 survivors) + repeated end-to-end sweeps "
            "(best-objective variance)"
        ),
        "platform": jax.devices()[0].platform,
        "dataset": "digits",
        "fixed_config_replicates": a,
        "repeated_sweeps": b,
        "reading": (
            "spearman near 1 and jaccard near 1 => promotions at this trial "
            "length are signal-driven; low values => rung-0 decisions are "
            "seed lottery and r_l / proxy epochs should rise before "
            "trusting the sweep's best_objective"
        ),
    }
    path = write_artifact("hyperband", "promotion_noise.json", payload)
    print("wrote", path, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
