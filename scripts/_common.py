"""Shared bootstrap for the repo's run scripts — one copy of the
environment/bootstrap logic so the four harnesses can't drift.

Import `REPO` and call `setup_jax(...)` BEFORE importing jax-heavy modules.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


_COMPILE_MODE_CACHE = os.path.join(REPO, ".compile_mode.json")
_COMPILE_MODE_TTL_S = 4 * 3600.0


def _local_compile_probe() -> bool | None:
    """Does a locally-compiled trivial op actually execute on the pool?

    The pool terminal refuses executables from a client whose ``libtpu``
    build differs from its own ("libtpu version mismatch", FAILED_
    PRECONDITION — seen live when the pool rolled to an older build than
    the pip wheel).  Local compile is all-or-nothing under that skew, so
    one 1-element add answers for every program.  Returns ``True`` (local
    ok), ``False`` (mismatch — use terminal-side compile), or ``None``
    (inconclusive: pool wedged / probe timeout — keep the default).
    The verdict is cached in ``.compile_mode.json`` for 4h because the
    probe costs a device claim (~1 min through the relay).
    """
    import subprocess
    import time

    try:
        with open(_COMPILE_MODE_CACHE) as f:
            cached = json.load(f)
        if time.time() - cached["ts"] < _COMPILE_MODE_TTL_S:
            return cached["local_ok"]
    except (OSError, ValueError, KeyError):
        pass
    env = dict(os.environ)
    env["PALLAS_AXON_REMOTE_COMPILE"] = "0"
    env.pop("KATIB_REMOTE_COMPILE", None)
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax, jax.numpy as jnp;"
                "print('PROBE_OK', jnp.add(1, 1))",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=float(os.environ.get("KATIB_COMPILE_PROBE_TIMEOUT", "240")),
        )
    except subprocess.TimeoutExpired:
        return None
    if "PROBE_OK" in (proc.stdout or ""):
        verdict: bool | None = True
    elif "libtpu version mismatch" in (proc.stderr or ""):
        verdict = False
    else:
        return None
    try:
        with open(_COMPILE_MODE_CACHE, "w") as f:
            json.dump({"local_ok": verdict, "ts": time.time()}, f)
    except OSError:
        pass
    return verdict


def ensure_local_compile() -> None:
    """Re-exec with ``PALLAS_AXON_REMOTE_COMPILE=0`` if the ambient env asks
    for terminal-side compile — unless the pool's libtpu build rejects
    locally-compiled executables, in which case stay on terminal-side.

    The axon sitecustomize registers the PJRT plugin at interpreter boot
    with whatever the env said THEN, so flipping the variable here is too
    late for this process — re-exec so the fresh interpreter registers the
    local-AOT-compile path (XLA compiles against the pip-installed
    ``libtpu.so`` client-side; only execution crosses the relay).  The
    remote path was measured at minutes per trivial op through the tunnel
    and wedged the session on the full-size bilevel program — see
    ``bench.py``'s module doc.  ``KATIB_REMOTE_COMPILE=1`` opts back in
    explicitly; otherwise :func:`_local_compile_probe` decides (a version
    skew between the pip ``libtpu`` and the pool terminal makes local
    compile hard-fail at first execution, so probing beats crashing an
    hour into a run).
    """
    if remote_compile_requested():
        return
    if os.environ.get("PALLAS_AXON_REMOTE_COMPILE") == "1":
        if _local_compile_probe() is False:
            # record the decision for child processes (the bench children
            # and subprocess trials consult KATIB_REMOTE_COMPILE)
            os.environ["KATIB_REMOTE_COMPILE"] = "1"
            return  # interpreter already registered terminal-side compile
        os.environ["PALLAS_AXON_REMOTE_COMPILE"] = "0"
        # orig_argv preserves interpreter options (-u, -m, -X ...) that
        # sys.argv has already stripped
        os.execve(sys.executable, list(sys.orig_argv), os.environ)


def remote_compile_requested() -> bool:
    """One opt-back-in knob for terminal-side compile, shared by bench.py
    and the run scripts so the two surfaces can't drift."""
    return os.environ.get("KATIB_REMOTE_COMPILE", "") not in ("", "0")


def setup_jax(
    *,
    force_platform: str | None = None,
    virtual_devices: int = 0,
    compile_cache: bool = False,
):
    """Configure JAX and return the imported module.

    ``force_platform``: hard-select a platform (CPU demos pass "cpu" — the
    ambient env on this box exports JAX_PLATFORMS=axon, i.e. the TPU, and a
    setdefault would silently send a CPU demo to a possibly-wedged pool).
    ``None`` honors the ambient JAX_PLATFORMS (TPU benches).
    ``virtual_devices``: forced-host-platform CPU device count for mesh demos.
    ``compile_cache``: persist XLA executables under .jax_cache (TPU benches).
    """
    if virtual_devices and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={virtual_devices}"
        ).strip()
    if force_platform is not None:
        os.environ["JAX_PLATFORMS"] = force_platform
    elif os.environ.get("JAX_PLATFORMS") == "axon":
        ensure_local_compile()  # may re-exec; no-op once the env is right

    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        # the axon PJRT plugin ignores the env var; set the config explicitly
        jax.config.update("jax_platforms", want)
    if compile_cache:
        try:
            jax.config.update(
                "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
        except Exception:
            pass  # cache flags are version-dependent
    return jax


def artifacts_root() -> str:
    """The artifact tree root.  KATIB_ARTIFACTS_DIR redirects it —
    integration tests run the real scripts without clobbering the
    committed artifacts/ — and every writer AND reader of artifact paths
    must resolve through here so a redirect can't split them.  One
    definition, shared with in-package readers (the dashboard's
    flagship-progress endpoint)."""
    from katib_tpu.utils.paths import artifacts_root as _shared

    return _shared()


def write_artifact(subdir: str, name: str, payload: dict) -> str:
    out_dir = os.path.join(artifacts_root(), subdir)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
    return path
