"""Shared bootstrap for the repo's run scripts — one copy of the
environment/bootstrap logic so the four harnesses can't drift.

Import `REPO` and call `setup_jax(...)` BEFORE importing jax-heavy modules.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def ensure_local_compile() -> None:
    """Re-exec with ``PALLAS_AXON_REMOTE_COMPILE=0`` if the ambient env asks
    for terminal-side compile.

    The axon sitecustomize registers the PJRT plugin at interpreter boot
    with whatever the env said THEN, so flipping the variable here is too
    late for this process — re-exec so the fresh interpreter registers the
    local-AOT-compile path (XLA compiles against the pip-installed
    ``libtpu.so`` client-side; only execution crosses the relay).  The
    remote path was measured at minutes per trivial op through the tunnel
    and wedged the session on the full-size bilevel program — see
    ``bench.py``'s module doc.  ``KATIB_REMOTE_COMPILE=1`` opts back in.
    """
    if remote_compile_requested():
        return
    if os.environ.get("PALLAS_AXON_REMOTE_COMPILE") == "1":
        os.environ["PALLAS_AXON_REMOTE_COMPILE"] = "0"
        # orig_argv preserves interpreter options (-u, -m, -X ...) that
        # sys.argv has already stripped
        os.execve(sys.executable, list(sys.orig_argv), os.environ)


def remote_compile_requested() -> bool:
    """One opt-back-in knob for terminal-side compile, shared by bench.py
    and the run scripts so the two surfaces can't drift."""
    return os.environ.get("KATIB_REMOTE_COMPILE", "") not in ("", "0")


def setup_jax(
    *,
    force_platform: str | None = None,
    virtual_devices: int = 0,
    compile_cache: bool = False,
):
    """Configure JAX and return the imported module.

    ``force_platform``: hard-select a platform (CPU demos pass "cpu" — the
    ambient env on this box exports JAX_PLATFORMS=axon, i.e. the TPU, and a
    setdefault would silently send a CPU demo to a possibly-wedged pool).
    ``None`` honors the ambient JAX_PLATFORMS (TPU benches).
    ``virtual_devices``: forced-host-platform CPU device count for mesh demos.
    ``compile_cache``: persist XLA executables under .jax_cache (TPU benches).
    """
    if virtual_devices and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={virtual_devices}"
        ).strip()
    if force_platform is not None:
        os.environ["JAX_PLATFORMS"] = force_platform
    elif os.environ.get("JAX_PLATFORMS") == "axon":
        ensure_local_compile()  # may re-exec; no-op once the env is right

    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        # the axon PJRT plugin ignores the env var; set the config explicitly
        jax.config.update("jax_platforms", want)
    if compile_cache:
        try:
            jax.config.update(
                "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
        except Exception:
            pass  # cache flags are version-dependent
    return jax


def write_artifact(subdir: str, name: str, payload: dict) -> str:
    out_dir = os.path.join(REPO, "artifacts", subdir)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
    return path
