"""Shared bootstrap for the repo's run scripts — one copy of the
environment/bootstrap logic so the four harnesses can't drift.

Import `REPO` and call `setup_jax(...)` BEFORE importing jax-heavy modules.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def setup_jax(
    *,
    force_platform: str | None = None,
    virtual_devices: int = 0,
    compile_cache: bool = False,
):
    """Configure JAX and return the imported module.

    ``force_platform``: hard-select a platform (CPU demos pass "cpu" — the
    ambient env on this box exports JAX_PLATFORMS=axon, i.e. the TPU, and a
    setdefault would silently send a CPU demo to a possibly-wedged pool).
    ``None`` honors the ambient JAX_PLATFORMS (TPU benches).
    ``virtual_devices``: forced-host-platform CPU device count for mesh demos.
    ``compile_cache``: persist XLA executables under .jax_cache (TPU benches).
    """
    if virtual_devices and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={virtual_devices}"
        ).strip()
    if force_platform is not None:
        os.environ["JAX_PLATFORMS"] = force_platform

    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        # the axon PJRT plugin ignores the env var; set the config explicitly
        jax.config.update("jax_platforms", want)
    if compile_cache:
        try:
            jax.config.update(
                "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
        except Exception:
            pass  # cache flags are version-dependent
    return jax


def write_artifact(subdir: str, name: str, payload: dict) -> str:
    out_dir = os.path.join(REPO, "artifacts", subdir)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
    return path
