#!/usr/bin/env bash
# Follow-up window queue: waits for tpu_window5.sh's completion marker,
# then runs the late-breaking A/Bs that landed after window5 started:
#   1. paired-Hessian bilevel step (BENCH_PAIRED_HESSIAN=1) vs the
#      committed unfused baseline — 4 network passes per step instead of
#      5 (architect.py DartsHyper.paired_hessian); gated on the
#      committed deviceless fit-proof so no unproven compile touches the
#      terminal
# Usage: setsid bash scripts/tpu_window5b.sh &   Logs: /tmp/tpu_window5b/
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_window5b
mkdir -p "$LOG"

echo "window5b: waiting for window5 completion marker" >"$LOG/driver.log"
until grep -q "window5 complete" /tmp/tpu_window5/driver.log 2>/dev/null; do
    sleep 60
done

probe() {
    env POOL_WATCH_PROBE_TIMEOUT=180 POOL_WATCH_INTERVAL=120 \
        POOL_WATCH_MAX_HOURS=6 python scripts/pool_watch.py \
        >>"$LOG/pool_watch.log" 2>&1
}

run() {
    local t=$1 name=$2; shift 2
    echo "=== $name start $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    setsid "$@" >"$LOG/$name.log" 2>&1 &
    local pid=$!
    ( sleep "$t" && kill -- -"$pid" 2>/dev/null && sleep 20 \
        && kill -9 -- -"$pid" 2>/dev/null ) &
    local watcher=$!
    local rc=0
    wait "$pid" || rc=$?
    kill "$watcher" 2>/dev/null; wait "$watcher" 2>/dev/null
    kill -9 -- -"$pid" 2>/dev/null
    echo "=== $name rc=$rc end $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
}

probe || exit 1

# paired-Hessian A/B: only with the committed fit-proof (terminal-crash
# rule from run_batch_scaling.py)
if python - <<'EOF'
import json, sys
try:
    d = json.load(open("artifacts/flagship/aot_v5e_b64_pairhess.json"))
    sys.exit(0 if d.get("hbm_fits_v5e") else 1)
except Exception:
    sys.exit(1)
EOF
then
    run 7200 bench_pairhess env BENCH_PAIRED_HESSIAN=1 BENCH_NO_FALLBACK=1 \
        BENCH_RETRIES=2 python bench.py
else
    echo "window5b: no pairhess fit-proof — skipping" | tee -a "$LOG/driver.log"
fi

echo "=== window5b complete $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
