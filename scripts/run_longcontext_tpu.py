"""Long-context attention benchmark on the local accelerator.

Times the fused flash-attention Pallas kernel (fwd + bwd through the
custom_vjp) against the dense reference at sequence lengths where dense
attention's O(S^2) materialization starts to hurt, and records achieved
tokens/sec for a TransformerLM training step with ring attention over a
sequence-parallel mesh (single chip: mesh degenerates to 1, exercising the
same code path the v5e-8 run shards).

This capability exceeds the reference (kubeflow/katib has no long-context
anything — SURVEY §5 "absent"); the artifact
``artifacts/longcontext/bench.json`` is the evidence it works at speed on
the hardware.

Env knobs: LC_SEQ (default 4096), LC_BATCH (4), LC_STEPS (10),
LC_SMALL=1 (CPU smoke: tiny shapes, interpret-mode kernel).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax, write_artifact  # noqa: E402


def main() -> int:
    jax = setup_jax(compile_cache=True)
    import jax.numpy as jnp

    small = os.environ.get("LC_SMALL", "") not in ("", "0")
    seq = int(os.environ.get("LC_SEQ", "256" if small else "4096"))
    batch = int(os.environ.get("LC_BATCH", "1" if small else "4"))
    steps = int(os.environ.get("LC_STEPS", "2" if small else "10"))
    heads, d_head = (2, 32) if small else (8, 64)
    platform = jax.devices()[0].platform
    interpret = platform != "tpu"

    from katib_tpu.ops.flash_attention import flash_attention

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (batch, heads, seq, d_head)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=interpret).astype(
            jnp.float32
        ).sum()

    grad_fn = jax.jit(jax.value_and_grad(loss_flash, argnums=(0, 1, 2)))

    def timed(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / steps

    fwd_bwd_s = timed(grad_fn, q, k, v)
    # causal attention FLOPs: ~2 * 0.5*S^2 * d * B * H for QK^T, same for PV,
    # and ~2.5x forward for the backward pass
    attn_flops = 2 * 2 * 0.5 * seq * seq * d_head * batch * heads
    total_flops = attn_flops * 3.5
    tokens_per_sec = batch * seq / fwd_bwd_s

    result = {
        "platform": platform,
        "kernel": "pallas" if not interpret else "pallas-interpret",
        "seq_len": seq,
        "batch": batch,
        "heads": heads,
        "d_head": d_head,
        "fwd_bwd_step_s": round(fwd_bwd_s, 5),
        "attention_tokens_per_sec": round(tokens_per_sec, 1),
        "attention_tflops": round(total_flops / fwd_bwd_s / 1e12, 3),
    }

    # the same kernel inside a training step of the long-context LM with the
    # ring-attention path (axis size 1 on a single chip — identical code to
    # the sharded run, collective count 0)
    if not small:
        from katib_tpu.models.transformer import TransformerLM, lm_loss, markov_dataset

        model = TransformerLM(
            vocab_size=256, d_model=heads * d_head, n_heads=heads, n_layers=4,
            max_seq_len=seq,
        )
        tokens = jnp.asarray(markov_dataset(256, batch, seq, seed=0))
        params = model.init(jax.random.PRNGKey(1), tokens)

        def lm_step(p, toks):
            return lm_loss(model.apply(p, toks), toks)

        lm_grad = jax.jit(jax.grad(lm_step))
        lm_s = timed(lm_grad, params, tokens)
        result["lm_train_tokens_per_sec"] = round(batch * seq / lm_s, 1)
        result["lm_step_s"] = round(lm_s, 5)

    write_artifact("longcontext", "bench.json", result)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
