"""Long-context attention benchmark on the local accelerator.

Times the fused flash-attention Pallas kernel (fwd + bwd through the
custom_vjp) against the dense reference at sequence lengths where dense
attention's O(S^2) materialization starts to hurt, and records achieved
tokens/sec for a TransformerLM training step with ring attention over a
sequence-parallel mesh (single chip: mesh degenerates to 1, exercising the
same code path the v5e-8 run shards).

This capability exceeds the reference (kubeflow/katib has no long-context
anything — SURVEY §5 "absent"); the artifact
``artifacts/longcontext/bench.json`` is the evidence it works at speed on
the hardware.

Env knobs: LC_SEQ (default 4096), LC_BATCH (4), LC_STEPS (10),
LC_SMALL=1 (CPU smoke: tiny shapes, interpret-mode kernel).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax, write_artifact  # noqa: E402


def main() -> int:
    jax = setup_jax(compile_cache=True)
    import jax.numpy as jnp

    small = os.environ.get("LC_SMALL", "") not in ("", "0")
    seq = int(os.environ.get("LC_SEQ", "256" if small else "4096"))
    batch = int(os.environ.get("LC_BATCH", "1" if small else "4"))
    steps = int(os.environ.get("LC_STEPS", "2" if small else "10"))
    heads, d_head = (2, 32) if small else (8, 64)
    platform = jax.devices()[0].platform
    interpret = platform != "tpu"

    from katib_tpu.ops.flash_attention import flash_attention, reference_attention

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (batch, heads, seq, d_head)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)

    def timed_chain(update_fn, init_carry):
        """Avg seconds per update, measured as ONE jitted lax.scan dispatch.

        Two measured failure modes of naive timing through the axon relay,
        both producing physically impossible numbers (first cuts of this
        script recorded 7.5 and 27.9 PFLOP/s on a 197 TFLOP/s chip):

        - independent back-to-back dispatches of the same executable don't
          serialize — fixed by the scan chain (each step consumes the
          previous step's output, so the work cannot be elided or
          overlapped);
        - re-invoking an executable on the SAME input buffers can resolve
          from the previous result's already-ready buffers without a fresh
          execution, so even ``block_until_ready`` returns in microseconds
          — fixed by bumping the carry through a jitted identity-valued op
          with a fresh scalar operand (new device buffers, same values)
          before the timed rep, and by fetching a reduced scalar to the
          host, which forces real bytes computed on the chip.
        """

        @jax.jit
        def many(carry):
            return jax.lax.scan(
                lambda c, _: (update_fn(c), None), carry, None, length=steps
            )[0]

        @jax.jit
        def bump(carry, i):
            z = jnp.float32(i) * 0.0
            return jax.tree.map(lambda a: a + z.astype(a.dtype), carry)

        @jax.jit
        def redsum(carry):
            return sum(
                jnp.sum(a.astype(jnp.float32)) for a in jax.tree.leaves(carry)
            )

        float(redsum(many(bump(init_carry, 1))))  # compile + warm everything
        fresh = bump(init_carry, 2)
        jax.block_until_ready(fresh)
        t0 = time.perf_counter()
        out = many(fresh)
        float(redsum(out))  # real bytes off the chip end the clock
        return (time.perf_counter() - t0) / steps

    def eps_sgd(grad_fn, eps=1e-3):
        """Chainable update: epsilon-SGD keeps values bounded while forcing
        true data dependence between scan iterations (eps=0 would let XLA
        drop the whole gradient computation as dead code)."""

        def update(carry):
            _, grads = grad_fn(*carry)
            return tuple(
                a - jnp.asarray(eps, a.dtype) * g for a, g in zip(carry, grads)
            )

        return update

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=interpret).astype(
            jnp.float32
        ).sum()

    fwd_bwd_s = timed_chain(
        eps_sgd(jax.value_and_grad(loss_flash, argnums=(0, 1, 2))), (q, k, v)
    )

    def loss_dense(q, k, v):
        return reference_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    dense_s = timed_chain(
        eps_sgd(jax.value_and_grad(loss_dense, argnums=(0, 1, 2))), (q, k, v)
    )

    # causal attention FLOPs: ~2 * 0.5*S^2 * d * B * H for QK^T, same for PV,
    # and ~2.5x forward for the backward pass
    attn_flops = 2 * 2 * 0.5 * seq * seq * d_head * batch * heads
    total_flops = attn_flops * 3.5
    tokens_per_sec = batch * seq / fwd_bwd_s
    tflops = total_flops / fwd_bwd_s / 1e12
    # physical upper bound per chip generation (bf16 dense peak) — any
    # number above it means the harness, not the kernel, is being measured
    peaks = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak_tflops = float(
        os.environ.get("LC_PEAK_TFLOPS", peaks.get(gen, peaks["v5e"]))
    )
    sane = tflops < peak_tflops * 1.05 or platform != "tpu"

    result = {
        "platform": platform,
        "kernel": "pallas" if not interpret else "pallas-interpret",
        "seq_len": seq,
        "batch": batch,
        "heads": heads,
        "d_head": d_head,
        "fwd_bwd_step_s": round(fwd_bwd_s, 6),
        "dense_fwd_bwd_step_s": round(dense_s, 6),
        "flash_speedup_vs_dense": round(dense_s / fwd_bwd_s, 3),
        "attention_tokens_per_sec": round(tokens_per_sec, 1),
        "attention_tflops": round(tflops, 3),
        "sanity": {"peak_tflops_bf16": peak_tflops, "below_peak": sane},
    }
    if not sane:
        print(
            f"longcontext: MEASUREMENT INSANE ({tflops:.0f} TFLOP/s > chip "
            f"peak {peak_tflops}); refusing to write the artifact",
            file=sys.stderr,
        )
        print(json.dumps(result), flush=True)
        return 1

    # the same kernel inside a training step of the long-context LM with the
    # ring-attention path (axis size 1 on a single chip — identical code to
    # the sharded run, collective count 0)
    if not small:
        from katib_tpu.models.transformer import TransformerLM, lm_loss, markov_dataset

        model = TransformerLM(
            vocab_size=256, d_model=heads * d_head, n_heads=heads, n_layers=4,
            max_seq_len=seq,
        )
        tokens = jnp.asarray(markov_dataset(256, batch, seq, seed=0))
        params = model.init(jax.random.PRNGKey(1), tokens)

        def lm_step(p, toks):
            return lm_loss(model.apply(p, toks), toks)

        lm_grad = jax.grad(lm_step)

        def lm_update(p):
            # same eps-SGD chaining trick as eps_sgd(), over a pytree carry
            g = lm_grad(p, tokens)
            return jax.tree.map(lambda w, gw: w - 1e-4 * gw, p, g)

        lm_s = timed_chain(lm_update, params)
        result["lm_train_tokens_per_sec"] = round(batch * seq / lm_s, 1)
        result["lm_step_s"] = round(lm_s, 6)

    write_artifact("longcontext", "bench.json", result)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
