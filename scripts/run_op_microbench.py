"""Per-op microbenchmark of the DARTS supernet's building blocks on-chip.

The honest flagship step time (~535 ms at batch 64) is far above both the
MFU=1 floor (3.2 ms) and any sane bandwidth estimate, so SOMETHING about
op granularity dominates — this harness measures the supernet's atoms
individually so optimization targets the measured cost, not a guess:

- depthwise 3x3 / 5x5 (the shift-MAC-free native grouped form)
- pointwise (1x1-as-einsum) at 16 and 64 channels
- stateless batch_norm
- max/avg pool
- one full MixedOp edge and one full Cell, forward and fwd+bwd

Timing discipline per docs/performance.md (measurement integrity): each
atom runs CHAINED inside one lax.scan dispatch, inputs are bumped into
fresh buffers, and the clock stops on a host-fetched scalar.  Atom
programs are tiny, so terminal-side compiles are seconds, not the
wedge-hazard class.

Artifact: ``artifacts/flagship/op_microbench.json``.
Env: OPBENCH_BATCH (64), OPBENCH_STEPS (50), OPBENCH_SMALL=1 (CPU smoke).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax, write_artifact  # noqa: E402


def main() -> int:
    jax = setup_jax(compile_cache=True)
    import jax.numpy as jnp

    small = os.environ.get("OPBENCH_SMALL", "") not in ("", "0")
    batch = int(os.environ.get("OPBENCH_BATCH", "8" if small else "64"))
    steps = int(os.environ.get("OPBENCH_STEPS", "3" if small else "50"))
    hw = 8 if small else 32
    platform = jax.devices()[0].platform

    from katib_tpu.nas.darts.ops import MixedOp, DEFAULT_PRIMITIVES, batch_norm
    from katib_tpu.nas.darts.model import Cell
    from katib_tpu.ops.depthwise import DepthwiseConv, PointwiseConv

    key = jax.random.PRNGKey(0)

    def timed(apply_fn, x, label, unroll=1):
        """(seconds per application, seconds fixed overhead per dispatch).

        The first on-chip run timed one N-step scan and divided by N —
        and every small atom landed at ~1.35 ms, suspiciously equal:
        ~67 ms/50 steps, i.e. the RELAY's per-dispatch round-trip split
        across iterations, not on-chip op cost.  So time the scan at two
        lengths and fit: per_iter = (T(4N) - T(N)) / 3N isolates the true
        marginal iteration cost; overhead = T(N) - N*per_iter is the
        dispatch+fetch cost the relay charges once per jit call.

        ``unroll``: inline that many body applications per XLA While
        iteration — the A/B for whether the per-iteration floor is loop
        machinery (amortizes with unroll) or per-op cost (does not).
        """

        def make_many(n):
            @jax.jit
            def many(x0):
                def body(c, _):
                    out = apply_fn(c)
                    if out.shape == c.shape:
                        # renormalized feedback: bounded, full dependence
                        nxt = out / (
                            jnp.float32(1.0) + jnp.abs(out).max()
                        ).astype(out.dtype)
                        return nxt.astype(c.dtype), None
                    # shape-changing op (e.g. a Cell concat): feed a
                    # reduced scalar back so iterations still chain
                    dep = jnp.mean(out.astype(jnp.float32)) * jnp.float32(1e-6)
                    return c + dep.astype(c.dtype), None
                return jax.lax.scan(body, x0, None, length=n, unroll=unroll)[0]

            return many

        @jax.jit
        def bump(x0, i):
            return x0 + (jnp.float32(i) * 0.0).astype(x0.dtype)

        @jax.jit
        def redsum(x0):
            return jnp.sum(x0.astype(jnp.float32))

        def run_once(many, seed):
            fresh = bump(x, seed)
            jax.block_until_ready(fresh)
            t0 = time.perf_counter()
            float(redsum(many(fresh)))
            return time.perf_counter() - t0

        n_lo, n_hi = steps, 4 * steps
        many_lo, many_hi = make_many(n_lo), make_many(n_hi)
        run_once(many_lo, 1)  # compile
        run_once(many_hi, 2)  # compile
        t_lo = min(run_once(many_lo, 3), run_once(many_lo, 5))
        t_hi = min(run_once(many_hi, 4), run_once(many_hi, 6))
        per_iter = max((t_hi - t_lo) / (n_hi - n_lo), 0.0)
        overhead = max(t_lo - n_lo * per_iter, 0.0)
        print(
            f"opbench: {label}: {per_iter*1e3:.3f} ms/iter "
            f"+ {overhead*1e3:.1f} ms/dispatch",
            flush=True,
        )
        return per_iter, overhead

    results: dict[str, tuple[float, float]] = {}

    # measured floor: a near-no-op body through the same chained scan —
    # whatever per-iteration cost the harness itself (feedback
    # renormalization + scan plumbing) charges, so atom entries read as
    # floor + marginal
    x_floor = jax.random.normal(key, (batch, hw, hw, 16), jnp.bfloat16)
    results["scan_floor_identity"] = timed(
        lambda a: a * jnp.float32(1.0).astype(a.dtype), x_floor, "scan_floor_identity"
    )

    def bench_module(mod, c, label, shape=None, unroll=1):
        x = jax.random.normal(key, shape or (batch, hw, hw, c), jnp.bfloat16)
        params = mod.init(jax.random.PRNGKey(1), x)
        results[label] = timed(
            lambda a: mod.apply(params, a), x, label, unroll=unroll
        )

    for c in (16, 64):
        bench_module(DepthwiseConv(kernel=3, dtype=jnp.bfloat16), c, f"dw3_c{c}")
        bench_module(DepthwiseConv(kernel=5, dtype=jnp.bfloat16), c, f"dw5_c{c}")
        bench_module(PointwiseConv(c, dtype=jnp.bfloat16), c, f"pw_c{c}")

    x16 = jax.random.normal(key, (batch, hw, hw, 16), jnp.bfloat16)
    results["batch_norm_c16"] = timed(lambda a: batch_norm(a).astype(a.dtype), x16, "batch_norm_c16")
    results["max_pool_c16"] = timed(
        lambda a: jax.lax.reduce_window(
            a, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 1, 1, 1), "SAME"
        ),
        x16,
        "max_pool_c16",
    )

    # one full mixed-op edge (all 8 primitives + weighted sum), fwd only
    mo = MixedOp(DEFAULT_PRIMITIVES, 16, 1, dtype=jnp.bfloat16)
    w = jax.nn.softmax(jnp.zeros((len(DEFAULT_PRIMITIVES),)))
    mo_params = mo.init(jax.random.PRNGKey(2), x16, w)
    results["mixed_op_edge_c16_fwd"] = timed(
        lambda a: mo.apply(mo_params, a, w), x16, "mixed_op_edge_c16_fwd"
    )

    # one full cell fwd and fwd+bwd (the remat/vmap unit of the supernet)
    cell = Cell(primitives=DEFAULT_PRIMITIVES, channels=16, n_nodes=4,
                dtype=jnp.bfloat16)
    from katib_tpu.nas.darts.model import n_edges

    cw = jax.nn.softmax(
        jnp.zeros((n_edges(4), len(DEFAULT_PRIMITIVES))), axis=-1
    )
    cparams = cell.init(jax.random.PRNGKey(3), x16, x16, cw)
    results["cell_c16_fwd"] = timed(
        lambda a: cell.apply(cparams, a, a, cw), x16, "cell_c16_fwd"
    )

    def cell_loss(a):
        return jnp.sum(cell.apply(cparams, a, a, cw).astype(jnp.float32))

    results["cell_c16_fwd_bwd"] = timed(
        lambda a: jax.grad(lambda q: cell_loss(q))(a), x16, "cell_c16_fwd_bwd"
    )

    # scan-unroll A/B (VERDICT r4 item 3): if the per-iteration floor is
    # While-loop machinery it amortizes ~1/unroll; if it is per-op cost
    # inside the body, unrolled entries match their unroll=1 twins
    results["scan_floor_identity_u8"] = timed(
        lambda a: a * jnp.float32(1.0).astype(a.dtype),
        x_floor,
        "scan_floor_identity_u8",
        unroll=8,
    )
    bench_module(
        DepthwiseConv(kernel=3, dtype=jnp.bfloat16), 16, "dw3_c16_u8", unroll=8
    )
    results["cell_c16_fwd_u4"] = timed(
        lambda a: cell.apply(cparams, a, a, cw), x16, "cell_c16_fwd_u4", unroll=4
    )
    results["cell_c16_fwd_bwd_u4"] = timed(
        lambda a: jax.grad(lambda q: cell_loss(q))(a),
        x16,
        "cell_c16_fwd_bwd_u4",
        unroll=4,
    )

    out = {
        "platform": platform,
        "batch": batch,
        "spatial": hw,
        "steps": steps,
        "note": (
            "two-point scan fit: ms_per_op is the true marginal cost per "
            "application (T(4N)-T(N))/3N with the per-dispatch relay "
            "round-trip separated out into ms_dispatch_overhead; "
            "scan_floor_identity is the harness's own per-iteration "
            "plumbing cost (subtract it for the op's net cost)"
        ),
        "ms_per_op": {k: round(v[0] * 1e3, 4) for k, v in results.items()},
        "ms_dispatch_overhead": {
            k: round(v[1] * 1e3, 2) for k, v in results.items()
        },
    }
    write_artifact("flagship", "op_microbench.json", out)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
