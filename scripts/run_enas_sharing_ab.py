"""Multi-seed A/B: ENAS weight sharing vs cold starts at equal budget.

A single-seed comparison of the sharing feature is dominated by
controller-sampling luck, so this driver runs BOTH arms (cold and
shared-pool children, identical per-child epoch budget on real digits)
across several seeds — seeds vary via the experiment name, which every
derived stream hashes — and commits the per-seed table plus means to
``artifacts/enas/sharing_ab.json``.

The default budget is deliberately LEAN (2 epochs/child): at 4+ epochs
the digits children learn enough that the cold arm's rewards crowd the
ceiling and the sharing delta has no gradient to show (round-3 finding);
at 2 epochs a cold child is still far from converged, which is exactly
the regime the ENAS paper's sharing targets.

Run: python scripts/run_enas_sharing_ab.py   (CPU, ~35 min at 5 seeds)
Env: AB_SEEDS (default 5), AB_EPOCHS (default 2)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, write_artifact  # noqa: E402


def run_arm(share: bool, suffix: str, epochs: int) -> dict:
    import shutil

    # a leftover experiment dir from a previous invocation carries a mature
    # weight-sharing pool — round 0 would warm-start from it and the A/B
    # would compare against contaminated state
    name = ("enas-digits-shared" if share else "enas-digits") + suffix
    shutil.rmtree(os.path.join(REPO, "katib_runs", name), ignore_errors=True)
    env = dict(os.environ)
    env.update(
        ENAS_DATASET="digits",
        ENAS_EPOCHS=str(epochs),
        ENAS_SHARE="1" if share else "0",
        ENAS_NAME_SUFFIX=suffix,
        # seed-PAIRED arms: the controller stream comes from ENAS_SEED, not
        # the (arm-specific) experiment name, so round 0 is identical
        # across arms and every delta is the pool's doing
        ENAS_SEED=suffix.lstrip("-ab") or "0",
        # pin the budget the scenario string documents
        ENAS_ROUNDS="3",
        ENAS_PER_ROUND="4",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_enas_demo.py")],
        capture_output=True,
        text=True,
        env=env,
    )
    lines = [l for l in (out.stdout or "").splitlines() if l.startswith("{")]
    if out.returncode != 0 or not lines:
        raise RuntimeError(
            f"arm share={share} suffix={suffix} rc={out.returncode}:\n"
            + (out.stderr or "")[-1500:]
        )
    return json.loads(lines[-1])


def main() -> int:
    n_seeds = int(os.environ.get("AB_SEEDS", "5"))
    epochs = int(os.environ.get("AB_EPOCHS", "2"))
    rows = []
    for i in range(n_seeds):
        suffix = f"-ab{i}"
        cold = run_arm(False, suffix, epochs)
        shared = run_arm(True, suffix, epochs)
        rows.append(
            {
                "seed": i,
                "cold_trials": cold["trials_total"],
                "shared_trials": shared["trials_total"],
                "cold_best": cold["best_objective"],
                "shared_best": shared["best_objective"],
                "cold_mean_rewards": [
                    r["mean_reward"] for r in cold["reward_curve"]
                ],
                "shared_mean_rewards": [
                    r["mean_reward"] for r in shared["reward_curve"]
                ],
            }
        )
        print(json.dumps(rows[-1]), flush=True)

    def mean(xs):
        return round(sum(xs) / len(xs), 4)

    payload = {
        "scenario": (
            f"ENAS on REAL digits, 12 trials x {epochs} epochs/child per "
            f"arm, {n_seeds} seeds; identical budgets — the only difference "
            "is the weight_sharing pool; the lean per-child budget keeps "
            "the cold arm OFF the reward ceiling so the delta has gradient"
        ),
        "epochs_per_child": epochs,
        "n_seeds": n_seeds,
        "per_seed": rows,
        "mean_best": {
            "cold": mean([r["cold_best"] for r in rows]),
            "shared": mean([r["shared_best"] for r in rows]),
        },
        "mean_round0_reward": {
            "cold": mean([r["cold_mean_rewards"][0] for r in rows]),
            "shared": mean([r["shared_mean_rewards"][0] for r in rows]),
        },
        "mean_overall_reward": {
            "cold": mean([v for r in rows for v in r["cold_mean_rewards"]]),
            "shared": mean(
                [v for r in rows for v in r["shared_mean_rewards"]]
            ),
        },
        # rounds >= 1: the pool has matured; this is the number the docs
        # cite, kept in the payload so prose can't drift from the artifact
        "mean_mature_reward": {
            "cold": mean(
                [v for r in rows for v in r["cold_mean_rewards"][1:]]
            ),
            "shared": mean(
                [v for r in rows for v in r["shared_mean_rewards"][1:]]
            ),
        },
    }
    write_artifact("enas", "sharing_ab.json", payload)
    print(json.dumps({k: payload[k] for k in (
        "mean_best", "mean_round0_reward", "mean_overall_reward",
        "mean_mature_reward")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
