"""Execute the FULL-SIZE flagship bilevel step to completion on CPU.

The bench's full configuration (batch 64 / 8 layers / 16 channels / 4
nodes — the reference's CIFAR-10 search shape,
``darts-cnn-cifar10/run_trial.py:29-47``) had, through round 2, never
executed to completion on any backend: TPU attempts died in the wedged
pool and the CPU fallback ran reduced shapes.  This harness runs the exact
full-shape second-order program on CPU XLA — slow is fine, it is run once
and bounded — to retire the shape/memory/overflow unknowns and record a
loss trajectory.

Writes ``artifacts/flagship/full_shape_cpu.json``.

Env knobs:
  FULLSHAPE_STEPS   steps to run (default 4; ≥3 proves the step loop)
  FULLSHAPE_BUDGET  wall-clock budget in seconds (default 5400); the loop
                    stops cleanly after the current step when exceeded
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402

jax = setup_jax(force_platform="cpu")
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, REPO)
import bench  # noqa: E402  (full shapes: BENCH_SMALL unset)


def main() -> None:
    assert not bench._SMALL, "run without BENCH_SMALL: this harness exists to execute FULL shapes"
    steps_wanted = int(os.environ.get("FULLSHAPE_STEPS", "4"))
    budget = float(os.environ.get("FULLSHAPE_BUDGET", "5400"))

    t_build0 = time.perf_counter()
    step, state, batch, net, remat = bench._build_flagship(jax, jnp)
    build_secs = time.perf_counter() - t_build0

    t_c0 = time.perf_counter()
    compiled = jax.jit(step).lower(state, batch, batch).compile()
    compile_secs = time.perf_counter() - t_c0
    print(f"full-shape compile: {compile_secs:.1f}s (build {build_secs:.1f}s)", flush=True)

    t_run0 = time.perf_counter()
    losses: list[float] = []
    step_secs: list[float] = []
    for i in range(steps_wanted):
        t0 = time.perf_counter()
        state, metrics = compiled(state, batch, batch)
        # host sync per step is deliberate here: we want honest per-step
        # wall-clock and the float loss for the trajectory record
        loss = float(metrics["train_loss"])
        dt = time.perf_counter() - t0
        losses.append(loss)
        step_secs.append(round(dt, 2))
        print(f"step {i}: loss {loss:.5f}  ({dt:.1f}s)", flush=True)
        if not (loss == loss) or loss in (float("inf"), float("-inf")):
            raise SystemExit(f"non-finite loss at step {i}: {loss}")
        if time.perf_counter() - t_run0 > budget and i + 1 < steps_wanted:
            print(f"budget {budget:.0f}s exceeded after step {i}; stopping", flush=True)
            break

    payload = {
        "what": (
            "full-shape (batch 64 / 8 layers / 16 ch / 4 nodes) second-order "
            "DARTS bilevel step executed to completion on CPU XLA — the "
            "program the TPU bench times, at the reference's search shape"
        ),
        "platform": "cpu",
        "config": {
            "batch": bench.BATCH,
            "num_layers": bench.NUM_LAYERS,
            "init_channels": bench.INIT_CHANNELS,
            "n_nodes": bench.N_NODES,
            "remat": remat,
            "dtype": "bf16" if net.dtype == jnp.bfloat16 else "f32",
        },
        "steps_completed": len(losses),
        "losses": [round(x, 5) for x in losses],
        "loss_decreased": len(losses) >= 2 and losses[-1] < losses[0],
        "step_secs": step_secs,
        "compile_secs": round(compile_secs, 1),
        "total_secs": round(time.perf_counter() - t_run0, 1),
    }
    path = write_artifact("flagship", "full_shape_cpu.json", payload)
    print("wrote", path, flush=True)
    if len(losses) < 3:
        raise SystemExit("fewer than 3 steps completed — evidence bar not met")


if __name__ == "__main__":
    main()
