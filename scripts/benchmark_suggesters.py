"""Suggester quality benchmark: every HP-tuning algorithm against shared
objectives, fixed trial budget, multiple seeds.

The reference wraps hyperopt/optuna/skopt/goptuna and inherits their
quality; this framework's algorithms are native implementations, so their
optimization quality needs its own evidence.  The committed artifact
(``artifacts/suggesters/benchmark.json``) records best-found value per
(algorithm, objective, seed) plus the random-search baseline, making
regressions in any suggester's math visible as a diff.

Objectives (all minimize, optimum 0):
- sphere:     sum(x^2), smooth unimodal — everything should crush random
- rosenbrock: curved valley — tests exploitation along correlations
- mixed:      continuous + categorical + int — tests encodings

Run: python scripts/benchmark_suggesters.py   (CPU, pure algorithm math)
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax, write_artifact  # noqa: E402

ALGORITHMS = (
    "random",
    "grid",
    "tpe",
    "multivariate-tpe",
    "bayesianoptimization",
    "cmaes",
    "sobol",
)
BUDGET = 40
SEEDS = (1, 2, 3)


def sphere(p):
    return float(p["x"]) ** 2 + float(p["y"]) ** 2


def rosenbrock(p):
    x, y = float(p["x"]), float(p["y"])
    return (1 - x) ** 2 + 5.0 * (y - x * x) ** 2


def mixed(p):
    base = float(p["x"]) ** 2
    base += 0.0 if p["kind"] == "good" else 2.0
    base += abs(int(float(p["n"])) - 3) * 0.5
    return base


def main() -> int:
    setup_jax(force_platform=os.environ.get("BENCH_PLATFORM", "cpu"))

    from katib_tpu.core.types import (
        AlgorithmSpec,
        Experiment,
        ExperimentSpec,
        FeasibleSpace,
        Metric,
        Observation,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
        Trial,
        TrialCondition,
        TrialSpec,
    )
    from katib_tpu.suggest import make_suggester
    from katib_tpu.suggest.base import (
        SearchExhausted,
        SuggesterError,
        SuggestionsNotReady,
    )

    def params_for(objective_name):
        cont = [
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=-2.0, max=2.0)),
            ParameterSpec("y", ParameterType.DOUBLE, FeasibleSpace(min=-2.0, max=2.0)),
        ]
        if objective_name != "mixed":
            return cont
        return [
            cont[0],
            ParameterSpec(
                "kind", ParameterType.CATEGORICAL, FeasibleSpace(list=("good", "bad"))
            ),
            ParameterSpec("n", ParameterType.INT, FeasibleSpace(min=0, max=8)),
        ]

    def grid_params(objective_name):
        # grid needs finite spaces: steps over the same ranges
        out = []
        for p in params_for(objective_name):
            if p.type == ParameterType.DOUBLE:
                out.append(
                    ParameterSpec(
                        p.name, p.type,
                        FeasibleSpace(min=p.feasible.min, max=p.feasible.max, step=0.5),
                    )
                )
            else:
                out.append(p)
        return out

    objectives = {"sphere": sphere, "rosenbrock": rosenbrock, "mixed": mixed}
    results = []
    for obj_name, fn in objectives.items():
        for algo in ALGORITHMS:
            for seed in SEEDS:
                spec = ExperimentSpec(
                    name=f"bench-{algo}-{obj_name}-{seed}",
                    objective=ObjectiveSpec(
                        type=ObjectiveType.MINIMIZE, objective_metric_name="loss"
                    ),
                    algorithm=AlgorithmSpec(
                        name=algo, settings={"random_state": str(seed)}
                    ),
                    parameters=(
                        grid_params(obj_name) if algo == "grid" else params_for(obj_name)
                    ),
                    max_trial_count=BUDGET,
                )
                try:
                    suggester = make_suggester(spec)
                except SuggesterError as e:
                    # documented capability limits (e.g. cmaes is numeric-
                    # only, like the reference's goptuna sampler)
                    results.append(
                        {
                            "algorithm": algo,
                            "objective": obj_name,
                            "seed": seed,
                            "unsupported": str(e),
                        }
                    )
                    break
                exp = Experiment(spec=spec)
                best = float("inf")
                t0 = time.perf_counter()
                n = 0
                while n < BUDGET:
                    try:
                        proposals = suggester.get_suggestions(exp, 1)
                    except SearchExhausted:
                        break
                    except SuggestionsNotReady:
                        break
                    if not proposals:
                        break
                    for prop in proposals:
                        name = prop.name or f"t-{n}"
                        val = fn(prop.as_dict())
                        best = min(best, val)
                        exp.trials[name] = Trial(
                            name=name,
                            spec=TrialSpec(
                                assignments=list(prop.assignments),
                                labels=dict(prop.labels),
                            ),
                            condition=TrialCondition.SUCCEEDED,
                            observation=Observation(
                                metrics=[
                                    Metric(
                                        name="loss", value=val, min=val, max=val,
                                        latest=val,
                                    )
                                ]
                            ),
                            start_time=float(n),
                        )
                        n += 1
                results.append(
                    {
                        "algorithm": algo,
                        "objective": obj_name,
                        "seed": seed,
                        "trials": n,
                        "best": round(best, 6),
                        # wall time stays OUT of the committed payload:
                        # hardware noise would bury the quality numbers
                        # this artifact exists to diff
                    }
                )

    # aggregate: median best per (algorithm, objective)
    summary = {}
    for r in results:
        if "best" in r:
            summary.setdefault((r["algorithm"], r["objective"]), []).append(r["best"])
    # variance columns (the promotion-noise method, run_promotion_noise.py
    # — VERDICT r4 item 7): seed spread alongside the median so a
    # high-variance "win" cannot masquerade as a robust one
    table = [
        {
            "algorithm": a,
            "objective": o,
            "median_best": sorted(v)[len(v) // 2],
            "best_stdev_across_seeds": (
                round(statistics.stdev(v), 6) if len(v) > 1 else None
            ),
            "best_range_across_seeds": [min(v), max(v)],
            "seeds": len(v),
        }
        for (a, o), v in sorted(summary.items())
    ]
    # sanity gate: every model-based algorithm must beat random's median
    # on sphere — the artifact fails loudly on regression.  Margins are
    # calibrated ~25% below each algorithm's measured ratio over the
    # (independent-seed) random baseline so real regressions trip the gate
    # without flaking on seed noise: measured BO ~148x, multivariate-TPE
    # ~24x, CMA-ES ~3.9x, univariate TPE ~1.6x (sphere's dims are
    # independent, so the univariate model's edge over random is modest
    # at a 40-eval budget)
    med = {(t["algorithm"], t["objective"]): t["median_best"] for t in table}
    random_sphere = med[("random", "sphere")]
    margins = {"tpe": 1.3, "multivariate-tpe": 2.0,
               "bayesianoptimization": 2.0, "cmaes": 2.0}
    failures = [
        a for a, m in margins.items()
        if med[(a, "sphere")] > random_sphere / m
    ]
    payload = {
        "budget": BUDGET,
        "seeds": list(SEEDS),
        "summary": table,
        "runs": results,
        "sanity": {"random_sphere_median": random_sphere, "failures": failures},
    }
    write_artifact("suggesters", "benchmark.json", payload)
    print(json.dumps({"table": table, "failures": failures}, indent=1), flush=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
