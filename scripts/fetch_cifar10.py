"""Fetch + verify CIFAR-10 into ``KATIB_DATA_DIR/cifar10.npz``.

The reference trains on real CIFAR-10 downloaded at container start
(``darts-cnn-cifar10/run_trial.py:100-111`` torchvision download,
``enas-cnn-cifar10/RunTrial.py:40-50``).  This image has zero egress, so
the download leg cannot run here — but the moment a
``cifar-10-python.tar.gz`` lands (mounted, copied, or fetched on a
networked box), one command turns it into the npz every loader in the
framework picks up automatically (``models/data.py`` ``_load_or_synthesize``),
instantly upgrading every accuracy artifact from the synthetic stand-in to
real data.

Integrity is sha256-pinned: a wrong/corrupt archive fails loudly before
anything is written.  Usage:

    python scripts/fetch_cifar10.py                # download (needs egress)
    python scripts/fetch_cifar10.py --tar /path/to/cifar-10-python.tar.gz
    KATIB_DATA_DIR=~/data python scripts/fetch_cifar10.py --tar ...
"""

from __future__ import annotations

import argparse
import hashlib
import io
import os
import pickle
import sys
import tarfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from katib_tpu.models.data import DATA_DIR_ENV  # noqa: E402

URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
# canonical digests of cifar-10-python.tar.gz (the md5 is the one torchvision
# pins; the sha256 is of the same archive)
SHA256 = "6d958be074577803d12ecdefd02955f39262c83c16fe9348329d7fe0b5c001ce"
MD5 = "c58f30108f718f92721af3b95e74349a"


def _digest(path: str, algo: str) -> str:
    h = hashlib.new(algo)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def verify(tar_path: str) -> None:
    sha = _digest(tar_path, "sha256")
    if sha != SHA256:
        md5 = _digest(tar_path, "md5")
        detail = f"sha256 {sha} != {SHA256}"
        if md5 != MD5:
            detail += f"; md5 {md5} != {MD5}"
        raise SystemExit(f"integrity check FAILED for {tar_path}: {detail}")
    print(f"sha256 ok: {sha}")


def unpack(tar_path: str, expect_full: bool = True) -> dict[str, np.ndarray]:
    """CIFAR python-version batches → the npz keys ``models/data.py`` loads.

    Images stay uint8 HWC (the loader normalizes and keeps NHWC); labels
    int32.  ``expect_full=False`` drops the 50k/10k size gate so tests can
    exercise the pipeline on a miniature archive."""

    def to_nhwc(raw: np.ndarray) -> np.ndarray:
        return raw.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)

    xs, ys = [], []
    x_test = y_test = None
    with tarfile.open(tar_path, "r:gz") as tf:
        for member in tf.getmembers():
            base = os.path.basename(member.name)
            if not (base.startswith("data_batch_") or base == "test_batch"):
                continue
            fobj = tf.extractfile(member)
            assert fobj is not None
            batch = pickle.load(io.BytesIO(fobj.read()), encoding="bytes")
            data = np.asarray(batch[b"data"], dtype=np.uint8)
            labels = np.asarray(batch[b"labels"], dtype=np.int32)
            if base == "test_batch":
                x_test, y_test = to_nhwc(data), labels
            else:
                xs.append((base, data, labels))
    if len(xs) != 5 or x_test is None:
        raise SystemExit(
            f"archive incomplete: {len(xs)} train batches, test={x_test is not None}"
        )
    xs.sort()  # data_batch_1..5 in order, independent of tar member order
    x_train = to_nhwc(np.concatenate([d for _, d, _ in xs]))
    y_train = np.concatenate([l for _, _, l in xs])
    if expect_full:
        assert x_train.shape == (50000, 32, 32, 3) and x_test.shape == (10000, 32, 32, 3)
    return {
        "x_train": x_train,
        "y_train": y_train,
        "x_test": x_test,
        "y_test": y_test,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tar", help="existing cifar-10-python.tar.gz (skips download)")
    ap.add_argument(
        "--out-dir",
        default=os.environ.get(DATA_DIR_ENV, os.path.expanduser("~/.katib_tpu/data")),
        help=f"target dir (default: ${DATA_DIR_ENV} or ~/.katib_tpu/data)",
    )
    args = ap.parse_args()

    tar_path = args.tar
    if tar_path is None:
        import urllib.request

        tar_path = os.path.join(args.out_dir, "cifar-10-python.tar.gz")
        os.makedirs(args.out_dir, exist_ok=True)
        if not os.path.exists(tar_path):
            print(f"downloading {URL} ...")
            urllib.request.urlretrieve(URL, tar_path)  # noqa: S310 (pinned URL)

    verify(tar_path)
    arrays = unpack(tar_path)
    os.makedirs(args.out_dir, exist_ok=True)
    out = os.path.join(args.out_dir, "cifar10.npz")
    np.savez_compressed(out, **arrays)
    print(f"wrote {out} ({os.path.getsize(out) / 1e6:.1f} MB)")
    print(
        f"set {DATA_DIR_ENV}={args.out_dir} and every cifar10 loader/demo "
        "uses the real data automatically"
    )


if __name__ == "__main__":
    main()
