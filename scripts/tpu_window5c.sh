#!/usr/bin/env bash
# Takeover queue for round 5, replacing tpu_window5.sh from step 2 on.
# Context: window5's step-2 flagship (epoch-scan program) sat >2h in a
# terminal-side compile that never returned, while the single-step program
# compiled in ~8 min in the same window — so the flagship here runs in
# KATIB_STEP_LOOP=1 mode (device-resident splits, per-step dispatch of the
# single-step program; search.py), whose compile cost is known-bounded.
# Also folds window5b's paired-Hessian A/B into the batch-scaling step via
# the new `batch:policy:ph` config syntax.
# Usage: setsid bash scripts/tpu_window5c.sh &   Logs: /tmp/tpu_window5c/
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_window5c
ART=/tmp/tpu_window5c/artifacts
mkdir -p "$LOG"

probe() {
    env POOL_WATCH_PROBE_TIMEOUT=180 POOL_WATCH_INTERVAL=120 \
        POOL_WATCH_MAX_HOURS=8 python scripts/pool_watch.py \
        >>"$LOG/pool_watch.log" 2>&1
}

run() {
    local t=$1 name=$2; shift 2
    echo "=== $name start $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    setsid "$@" >"$LOG/$name.log" 2>&1 &
    local pid=$!
    ( sleep "$t" && kill -- -"$pid" 2>/dev/null && sleep 20 \
        && kill -9 -- -"$pid" 2>/dev/null ) &
    local watcher=$!
    local rc=0
    wait "$pid" || rc=$?
    kill "$watcher" 2>/dev/null; wait "$watcher" 2>/dev/null
    kill -9 -- -"$pid" 2>/dev/null
    echo "=== $name rc=$rc end $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    return $rc
}

probe || exit 1

# 1. flagship at 50 epochs in step-loop mode.  Per-epoch Orbax snapshots +
#    watchdog exit-75 keep mid-run stalls resume-safe; loop attempts.
#    flagship_ok records whether ANY attempt completed: the augment step
#    below consumes the genotype this search writes, and augmenting a
#    stale/absent genotype silently reports the wrong round's architecture.
flagship_ok=0
for attempt in 1 2 3; do
    run 9000 flagship_steploop_$attempt env KATIB_STEP_LOOP=1 \
        FLAGSHIP_EPOCHS=50 FLAGSHIP_BATCH=64 FLAGSHIP_REMAT=0 \
        FLAGSHIP_FUSED=0 python scripts/run_flagship_tpu.py
    rc=$?
    if [ "$rc" -eq 0 ]; then flagship_ok=1; break; fi
    echo "=== flagship attempt $attempt rc=$rc — reprobing" >>"$LOG/driver.log"
    probe || exit 1
done

probe || exit 1

# 2. augment the discovered genotype: accuracy-vs-epoch + honest timing.
#    Genotype-dependent: skipped (and marked in the driver log) when no
#    flagship attempt succeeded this round — there is no fresh genotype.
if [ "$flagship_ok" -eq 1 ]; then
    run 5400 augment_genotype env AUGMENT_EPOCHS=20 python scripts/run_augment_tpu.py
else
    echo "=== augment_genotype SKIPPED: no flagship attempt succeeded this round" \
        | tee -a "$LOG/driver.log"
fi

probe || exit 1

# 3. batch scaling incl. the paired-Hessian combos (every config carries a
#    committed fit-proof; b96:dots:ph auto-skips until its proof lands)
run 12000 batch_scaling env \
    SCALING_CONFIGS="64:none,96:dots,128:dots,64:none:ph,128:dots:ph,96:dots:ph" \
    python scripts/run_batch_scaling.py

probe || exit 1

# 4. Hyperband sweep serialized on the chip (redirected, copied back)
run 5400 hyperband_tpu env SWEEP_PLATFORM=axon KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_hyperband_sweep.py
[ -f "$ART/hyperband/sweep_summary.json" ] && \
    cp "$ART/hyperband/sweep_summary.json" artifacts/hyperband/sweep_summary_tpu.json

probe || exit 1

# 5. op microbench: two-point dispatch/marginal fit + unroll atoms
run 3600 op_microbench python scripts/run_op_microbench.py

probe || exit 1

# 6. full-step scan-unroll A/B (two fresh terminal compiles; keep last)
run 7200 scan_unroll_ab env UNROLL_FACTORS=1,2 BENCH_RETRIES=2 \
    python scripts/run_scan_unroll_ab.py

probe || exit 1

# 7. paper-protocol augment: one step timed at 20 cells, 600-epoch
#    accounting — redirected + copied back
run 5400 augment_20cell env AUGMENT_LAYERS=20 AUGMENT_CHANNELS=36 \
    AUGMENT_EPOCHS=1 AUGMENT_ACCOUNT_EPOCHS=600 \
    KATIB_ARTIFACTS_DIR="$ART" python scripts/run_augment_tpu.py
for f in augment_tpu augment_aot; do
    [ -f "$ART/flagship/$f.json" ] && \
        cp "$ART/flagship/$f.json" "artifacts/flagship/${f}_20cell.json"
done

# 7b. the 20-cell step at batch 384 (fit-proof-gated; augment is the paper
#     protocol's long pole and overhead-bound at b96)
if [ -f artifacts/flagship/augment_aot_20cell_b384.json ]; then
    probe || exit 1
    cp artifacts/flagship/augment_aot_20cell_b384.json "$ART/flagship/augment_aot.json"
    rm -f "$ART/flagship/augment_tpu.json"
    run 5400 augment_20cell_b384 env AUGMENT_LAYERS=20 AUGMENT_CHANNELS=36 \
        AUGMENT_BATCH=384 AUGMENT_EPOCHS=1 AUGMENT_ACCOUNT_EPOCHS=600 \
        KATIB_ARTIFACTS_DIR="$ART" python scripts/run_augment_tpu.py
    [ -f "$ART/flagship/augment_tpu.json" ] && \
        cp "$ART/flagship/augment_tpu.json" artifacts/flagship/augment_tpu_20cell_b384.json
fi

probe || exit 1

# 8. real-data on-chip runs carried from window4
run 3600 nas_digits env DEMO_PLATFORM=axon KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_nas_real_data.py
[ -f "$ART/real_data/digits_nas.json" ] && \
    cp "$ART/real_data/digits_nas.json" artifacts/real_data/digits_nas_tpu.json

probe || exit 1

run 3600 enas_digits env ENAS_PLATFORM=axon ENAS_DATASET=digits \
    KATIB_ARTIFACTS_DIR="$ART" python scripts/run_enas_demo.py
[ -f "$ART/enas/digits_summary.json" ] && \
    cp "$ART/enas/digits_summary.json" artifacts/enas/digits_summary_tpu.json

probe || exit 1

run 3600 pbt_digits env PBT_PLATFORM=axon PBT_DATASET=digits \
    PBT_GENERATIONS=6 KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_pbt_demo.py
[ -f "$ART/pbt/digits_summary.json" ] && \
    cp "$ART/pbt/digits_summary.json" artifacts/pbt/digits_summary_tpu.json

probe || exit 1

# 9. closing live bench: fresh on-chip memo + warm terminal cache so the
#    driver's end-of-round run completes live
run 5400 bench_final env BENCH_RETRIES=2 python bench.py

echo "=== window5c complete $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
