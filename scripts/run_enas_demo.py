"""ENAS demo: REINFORCE controller searching child CNN architectures.

Runs a full ENAS experiment through the orchestrator — the JAX LSTM
controller samples an architecture per trial, child CNNs actually train on
the (synthetic-fallback) CIFAR-10 loader, and after each round the
controller takes REINFORCE steps on the mean child validation accuracy
(reference flow: ``enas/service.py:238`` sampling + ``:400`` reward
aggregation + ``Controller.py:198`` trainer).

The committed artifact records the per-round mean reward so the
controller's learning signal is inspectable, plus trials/hour and the best
sampled architecture: ``artifacts/enas/demo_summary.json`` for the default
(synthetic-fallback CIFAR-10) children, ``artifacts/enas/digits_summary.json``
when ``ENAS_DATASET=digits`` trains them on the bundled REAL UCI digits.

Run: python scripts/run_enas_demo.py   (forces the CPU mesh; ENAS search is
controller-on-CPU + child-on-mesh, same split as the reference)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402


def main() -> int:
    # ambient JAX_PLATFORMS=axon would send this CPU demo to the TPU
    jax = setup_jax(
        force_platform=os.environ.get("ENAS_PLATFORM", "cpu"), virtual_devices=8
    )

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        GraphConfig,
        NasConfig,
        NasOperation,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.nas.enas.trial import enas_trial
    from katib_tpu.orchestrator import Orchestrator

    rounds = int(os.environ.get("ENAS_ROUNDS", "3"))
    per_round = int(os.environ.get("ENAS_PER_ROUND", "4"))
    from katib_tpu.models.data import NAMED_DATASETS, dataset_from_env

    # ENAS_DATASET=digits runs the children on the bundled REAL dataset
    # (UCI handwritten digits) instead of the synthetic CIFAR-10 fallback;
    # the cross-script KATIB_DATASET flag (models/data.py DATASET_ENV) is
    # honored when ENAS_DATASET is not set, so one env var flips the
    # flagship + hyperband + ENAS artifacts to a dropped-in real dataset
    try:
        dataset = os.environ.get("ENAS_DATASET") or dataset_from_env("cifar10")
    except ValueError as e:  # bad KATIB_DATASET
        print(f"ENAS dataset: {e}", file=sys.stderr)
        return 2
    if dataset not in NAMED_DATASETS:
        # fail now, not after a multi-minute sweep recorded a dataset name
        # that was never actually loaded
        print(
            f"ENAS dataset must be one of {NAMED_DATASETS}, got {dataset!r}",
            file=sys.stderr,
        )
        return 2

    # ENAS_SHARE=1 turns on weight sharing (the ENAS paper's efficiency
    # core, absent in the reference): children inherit the experiment's
    # shared parameter pool, so a much smaller per-child epoch budget
    # reaches comparable rewards
    from katib_tpu.utils.booleans import parse_bool

    share = parse_bool(os.environ.get("ENAS_SHARE"))

    def train(ctx):
        # small child budget so the demo finishes in minutes on CPU; the
        # digits children get more epochs — the dataset is tiny (1400
        # samples) so the extra budget is cheap and makes the reward signal
        # reflect real learning instead of initialization noise
        if share:
            ctx.params.setdefault("weight_sharing", "true")
        ctx.params.setdefault("dataset", dataset)
        ctx.params.setdefault(
            "n_train",
            os.environ.get(
                "ENAS_NTRAIN", "1400" if dataset == "digits" else "1024"
            ),
        )
        ctx.params.setdefault(
            "n_test",
            os.environ.get("ENAS_NTEST", "397" if dataset == "digits" else "256"),
        )
        # shared-pool children warm-start, so a third of the epoch budget
        # suffices for comparable rewards
        if dataset == "digits":
            default_epochs = "4" if share else "12"
        else:
            default_epochs = "2"
        ctx.params.setdefault(
            "num_epochs", os.environ.get("ENAS_EPOCHS", default_epochs)
        )
        ctx.params.setdefault("channels", "16" if dataset == "digits" else "8")
        ctx.params.setdefault("batch_size", "64")
        enas_trial(ctx)

    # ENAS_NAME_SUFFIX varies the experiment name and therefore every
    # derived seed stream — the knob multi-seed A/B studies use
    suffix = os.environ.get("ENAS_NAME_SUFFIX", "")
    base_name = ("enas-digits-shared" if share else "enas-digits") \
        if dataset == "digits" else "enas-demo"
    spec = ExperimentSpec(
        name=base_name + suffix,
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        algorithm=AlgorithmSpec(
            name="enas",
            settings={
                "controller_hidden_size": "32",
                "controller_train_steps": "10",
                # ENAS_SEED pins the controller's stream independently of
                # the experiment name, so A/B arms can be seed-PAIRED
                **({"random_state": os.environ["ENAS_SEED"]}
                   if os.environ.get("ENAS_SEED") else {}),
            },
        ),
        nas_config=NasConfig(
            graph_config=GraphConfig(num_layers=4),
            # filter_size params expand to op names the child op library
            # builds (separable_convolution_3x3, ...) — reference search
            # space shape, `enas-cnn-cifar10` op_library
            operations=(
                NasOperation(
                    "separable_convolution",
                    parameters=(
                        ParameterSpec(
                            "filter_size",
                            ParameterType.CATEGORICAL,
                            FeasibleSpace(list=("3", "5")),
                        ),
                    ),
                ),
                NasOperation(
                    "convolution",
                    parameters=(
                        ParameterSpec(
                            "filter_size",
                            ParameterType.CATEGORICAL,
                            FeasibleSpace(list=("3",)),
                        ),
                    ),
                ),
                NasOperation("max_pooling"),
                NasOperation("avg_pooling"),
            ),
        ),
        max_trial_count=rounds * per_round,
        parallel_trial_count=per_round,
        train_fn=train,
    )
    started = time.time()
    exp = Orchestrator(workdir=os.path.join(REPO, "katib_runs")).run(spec)
    wall = time.time() - started

    # per-round mean reward = the controller's REINFORCE signal
    by_round: dict[str, list[float]] = {}
    for t in exp.trials.values():
        if t.observation is None:
            continue
        rnd = t.labels.get("enas-round", "?")
        for m in t.observation.metrics:
            if m.name == "accuracy":
                by_round.setdefault(rnd, []).append(m.max)
    # numeric rounds in order; anything unlabeled sorts last rather than
    # crashing the summary after a multi-minute run
    def round_key(kv):
        try:
            return (0, int(kv[0]))
        except ValueError:
            return (1, 0)

    reward_curve = [
        {"round": r, "trials": len(v), "mean_reward": round(sum(v) / len(v), 4)}
        for r, v in sorted(by_round.items(), key=round_key)
    ]

    best_arch = None
    if exp.optimal is not None:
        assigns = {a.name: a.value for a in exp.optimal.assignments}
        best_arch = json.loads(assigns.get("architecture", "null"))

    from katib_tpu.models.data import is_real_data

    summary = {
        "experiment": exp.spec.name,
        "condition": exp.condition.value,
        "dataset": dataset,
        "real_data": is_real_data(dataset),
        "platform": jax.devices()[0].platform,
        "trials_total": len(exp.trials),
        "trials_succeeded": exp.succeeded_count,
        "wallclock_s": round(wall, 1),
        "trials_per_hour": round(len(exp.trials) / wall * 3600.0, 1),
        "best_objective": exp.optimal.objective_value if exp.optimal else None,
        "best_architecture": best_arch,
        "controller_reward_per_round": reward_curve,
    }
    summary["weight_sharing"] = share
    if not suffix:  # A/B sweep runs must not clobber the canonical artifacts
        name = "demo_summary.json"
        if dataset == "digits":
            name = "digits_shared_summary.json" if share else "digits_summary.json"
        write_artifact("enas", name, summary)
    print(json.dumps({k: summary[k] for k in (
        "condition", "trials_total", "wallclock_s", "best_objective",
    )} | {"reward_curve": reward_curve}), flush=True)
    return 0 if exp.succeeded_count == spec.max_trial_count else 1


if __name__ == "__main__":
    sys.exit(main())
