"""Poll the axon TPU pool until a device grant goes through, then exit 0.

Each probe runs in a child process (a client blocked in device init holds
no grant, so killing it is safe — bench.py's wedge-hardening rationale).
The watcher exists so a long CPU-side work session can start on-chip
harnesses the moment the pool recovers instead of discovering a healthy
window hours late.

Env knobs:
  POOL_WATCH_PROBE_TIMEOUT  per-probe device-init deadline, s (default 240)
  POOL_WATCH_INTERVAL       sleep between probes, s (default 300)
  POOL_WATCH_MAX_HOURS      give up after this long (default 11)
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_PROBE = """
import jax
d = jax.devices()
import jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
y = (x @ x).block_until_ready()
print("POOL_OK", d[0].platform, d[0].device_kind, float(jnp.sum(y)))
"""


def probe(timeout: float) -> bool:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print(f"probe timed out at {timeout:.0f}s (device init blocked)", flush=True)
        return False
    ok = proc.returncode == 0 and "POOL_OK" in (proc.stdout or "")
    if ok:
        print(proc.stdout.strip(), flush=True)
    else:
        tail = (proc.stderr or "")[-300:].replace("\n", " | ")
        print(f"probe failed rc={proc.returncode}: {tail}", flush=True)
    return ok


def main() -> int:
    timeout = float(os.environ.get("POOL_WATCH_PROBE_TIMEOUT", "240"))
    interval = float(os.environ.get("POOL_WATCH_INTERVAL", "300"))
    max_secs = float(os.environ.get("POOL_WATCH_MAX_HOURS", "11")) * 3600
    t0 = time.time()
    n = 0
    while time.time() - t0 < max_secs:
        n += 1
        print(f"pool_watch: probe {n} at +{time.time() - t0:.0f}s", flush=True)
        if probe(timeout):
            print(f"pool_watch: POOL HEALTHY after {time.time() - t0:.0f}s", flush=True)
            return 0
        time.sleep(interval)
    print("pool_watch: gave up", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
