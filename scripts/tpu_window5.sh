#!/usr/bin/env bash
# Round-5 window queue — fires the moment pool_watch sees a healthy pool,
# highest value first (VERDICT r4 "Next round" order):
#   1. live driver bench (item 8 — a live capture, not the memo)
#   2. 50-epoch flagship resume, retried across mid-run stalls (item 1)
#   3. augment of the discovered genotype to 20 epochs (item 1, phase 2)
#   4. batch scaling b64/b96-dots/b128-dots (item 2)
#   5. 32-trial Hyperband sweep on-chip (item 5)
#   6. op microbench two-point fit + unroll atoms (item 3)
#   7. full-step scan-unroll A/B (item 3)
#   8. 20-cell paper-protocol augment step timing (item 4)
#   9. real-data digits NAS / ENAS / PBT on-chip (carried from window4)
#  10. closing live bench (fresh memo + warm cache for the driver)
# Probes between steps; a re-wedge waits for recovery instead of burning
# each step's timeout.
# Usage: setsid bash scripts/tpu_window5.sh &   Logs: /tmp/tpu_window5/
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_window5
ART=/tmp/tpu_window5/artifacts
mkdir -p "$LOG"

probe() {
    env POOL_WATCH_PROBE_TIMEOUT=180 POOL_WATCH_INTERVAL=120 \
        POOL_WATCH_MAX_HOURS=10 python scripts/pool_watch.py \
        >>"$LOG/pool_watch.log" 2>&1
}

run() {
    # own process group + group kill on deadline (tpu_window.sh rationale)
    local t=$1 name=$2; shift 2
    echo "=== $name start $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    setsid "$@" >"$LOG/$name.log" 2>&1 &
    local pid=$!
    ( sleep "$t" && kill -- -"$pid" 2>/dev/null && sleep 20 \
        && kill -9 -- -"$pid" 2>/dev/null ) &
    local watcher=$!
    local rc=0
    wait "$pid" || rc=$?
    kill "$watcher" 2>/dev/null; wait "$watcher" 2>/dev/null
    kill -9 -- -"$pid" 2>/dev/null
    echo "=== $name rc=$rc end $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    return $rc
}

probe || exit 1

# 1. live driver bench (program cached terminal-side from r4 → minutes)
run 5400 bench env BENCH_RETRIES=2 python bench.py

probe || exit 1

# 2. flagship resume → epoch 50.  Watchdog exits 75 on a mid-run stall
#    (resume-safe); loop probe+relaunch up to 4 attempts so one wedge
#    doesn't end the search at epoch N<50 again.
for attempt in 1 2 3 4; do
    run 9000 flagship_resume_$attempt env FLAGSHIP_EPOCHS=50 \
        FLAGSHIP_BATCH=64 FLAGSHIP_REMAT=0 FLAGSHIP_FUSED=0 \
        python scripts/run_flagship_tpu.py
    rc=$?
    [ "$rc" -eq 0 ] && break
    echo "=== flagship attempt $attempt rc=$rc — reprobing" >>"$LOG/driver.log"
    probe || exit 1
done

probe || exit 1

# 3. augment the discovered genotype: accuracy-vs-epoch + honest timing
run 5400 augment_genotype env AUGMENT_EPOCHS=20 python scripts/run_augment_tpu.py

probe || exit 1

# 4. batch scaling (b96 point auto-skips unless its AOT fit-proof landed)
run 8000 batch_scaling env SCALING_CONFIGS="64:none,96:dots,128:dots" \
    python scripts/run_batch_scaling.py

probe || exit 1

# 5. Hyperband sweep serialized on the chip (redirected, copied back)
run 5400 hyperband_tpu env SWEEP_PLATFORM=axon KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_hyperband_sweep.py
[ -f "$ART/hyperband/sweep_summary.json" ] && \
    cp "$ART/hyperband/sweep_summary.json" artifacts/hyperband/sweep_summary_tpu.json

probe || exit 1

# 6. op microbench: two-point dispatch/marginal fit + unroll atoms
run 3600 op_microbench python scripts/run_op_microbench.py

probe || exit 1

# 7. full-step scan-unroll A/B (two fresh terminal compiles; keep last)
run 7200 scan_unroll_ab env UNROLL_FACTORS=1,2 BENCH_RETRIES=2 \
    python scripts/run_scan_unroll_ab.py

probe || exit 1

# 8. paper-protocol augment: one step timed at 20 cells (fit-proof gated
#    inside the harness), 600-epoch accounting — redirected + copied back
run 5400 augment_20cell env AUGMENT_LAYERS=20 AUGMENT_CHANNELS=36 \
    AUGMENT_EPOCHS=1 AUGMENT_ACCOUNT_EPOCHS=600 \
    KATIB_ARTIFACTS_DIR="$ART" python scripts/run_augment_tpu.py
for f in augment_tpu augment_aot; do
    [ -f "$ART/flagship/$f.json" ] && \
        cp "$ART/flagship/$f.json" "artifacts/flagship/${f}_20cell.json"
done

# 8b. augment batch scaling: the 20-cell step again at batch 384 — the
#     augment phase is the paper protocol's long pole and is
#     overhead-bound at b96 (1.14% MFU), so batch amortization is the
#     lever.  Gated on the committed deviceless fit-proof; the harness's
#     memo file in $ART must carry the b384 proof or it would re-pay the
#     AOT inside the window.
if [ -f artifacts/flagship/augment_aot_20cell_b384.json ]; then
    probe || exit 1
    cp artifacts/flagship/augment_aot_20cell_b384.json "$ART/flagship/augment_aot.json"
    # step 8 already wrote $ART/flagship/augment_tpu.json — remove it so a
    # failed b384 run cannot commit step 8's b96 timing under a b384 name
    rm -f "$ART/flagship/augment_tpu.json"
    run 5400 augment_20cell_b384 env AUGMENT_LAYERS=20 AUGMENT_CHANNELS=36 \
        AUGMENT_BATCH=384 AUGMENT_EPOCHS=1 AUGMENT_ACCOUNT_EPOCHS=600 \
        KATIB_ARTIFACTS_DIR="$ART" python scripts/run_augment_tpu.py
    [ -f "$ART/flagship/augment_tpu.json" ] && \
        cp "$ART/flagship/augment_tpu.json" artifacts/flagship/augment_tpu_20cell_b384.json
fi

probe || exit 1

# 9. real-data on-chip runs carried from window4
run 3600 nas_digits env DEMO_PLATFORM=axon KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_nas_real_data.py
[ -f "$ART/real_data/digits_nas.json" ] && \
    cp "$ART/real_data/digits_nas.json" artifacts/real_data/digits_nas_tpu.json

probe || exit 1

run 3600 enas_digits env ENAS_PLATFORM=axon ENAS_DATASET=digits \
    KATIB_ARTIFACTS_DIR="$ART" python scripts/run_enas_demo.py
[ -f "$ART/enas/digits_summary.json" ] && \
    cp "$ART/enas/digits_summary.json" artifacts/enas/digits_summary_tpu.json

probe || exit 1

run 3600 pbt_digits env PBT_PLATFORM=axon PBT_DATASET=digits \
    PBT_GENERATIONS=6 KATIB_ARTIFACTS_DIR="$ART" \
    python scripts/run_pbt_demo.py
[ -f "$ART/pbt/digits_summary.json" ] && \
    cp "$ART/pbt/digits_summary.json" artifacts/pbt/digits_summary_tpu.json

probe || exit 1

# 10. closing live bench: fresh on-chip memo + warm terminal cache so the
#     driver's end-of-round run completes live
run 5400 bench_final env BENCH_RETRIES=2 python bench.py

echo "=== window5 complete $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
