"""Hyperband sweep demo at the BASELINE shape: 32 trials over an 8-device
mesh with ``SliceAllocator`` sub-mesh leasing, each trial a real JAX
training loop (the MNIST-analog MLP) on its leased one-device mesh.

This is the committed-artifact half of VERDICT r1 item 4 (the invariants
half lives in ``tests/test_hyperband_e2e.py``): the run writes
``artifacts/hyperband/sweep_summary.json`` with the driver metrics —
trials/hour and best-objective@wallclock — plus the rung table, so the
BASELINE scenario (`run-e2e-experiment.py:52-60` invariants at v5e-64
scale) is demonstrable from the repo without hardware.

Run with the virtual mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/run_hyperband_sweep.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402


def main() -> int:
    # CPU-mesh demo: 8-way trial parallelism would serialize onto the one
    # TPU chip (or hang on a wedged pool); SWEEP_PLATFORM overrides
    jax = setup_jax(
        force_platform=os.environ.get("SWEEP_PLATFORM", "cpu"), virtual_devices=8
    )

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.models.data import load_mnist, using_real_data
    from katib_tpu.models.mnist import MLP, train_classifier
    from katib_tpu.orchestrator import Orchestrator
    from katib_tpu.parallel.distributed import ElasticSliceAllocator, SliceAllocator
    from katib_tpu.suggest.hyperband import I_LABEL, S_LABEL

    # SWEEP_ELASTIC=1: rung resource also sizes each trial's sub-mesh
    # (devices_per_rung + ElasticSliceAllocator) — finalists train on
    # 8-device meshes while rung-0 screens 16 one-device trials
    elastic = os.environ.get("SWEEP_ELASTIC", "") not in ("", "0")

    dataset = load_mnist(
        int(os.environ.get("SWEEP_NTRAIN", "1024")),
        int(os.environ.get("SWEEP_NTEST", "256")),
    )
    started = time.time()
    timeline: list[dict] = []

    def train(ctx):
        lr = float(ctx.params["lr"])
        epochs = int(float(ctx.params["epochs"]))

        def report(epoch, accuracy, loss):
            return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

        acc = train_classifier(
            MLP(),
            dataset,
            lr=lr,
            epochs=epochs,
            batch_size=64,
            mesh=ctx.mesh,
            report=report,
            eval_batch=256,
        )
        timeline.append(
            {
                "trial": ctx.trial_name,
                "elapsed_s": round(time.time() - started, 2),
                "accuracy": acc,
                "epochs": epochs,
            }
        )

    hb_settings = {"r_l": "16", "resource_name": "epochs", "eta": "4"}
    if elastic:
        hb_settings["devices_per_rung"] = "true"
    spec = ExperimentSpec(
        name="hyperband-elastic" if elastic else "hyperband-demo",
        algorithm=AlgorithmSpec(name="hyperband", settings=hb_settings),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.001, max=0.5)),
            ParameterSpec("epochs", ParameterType.INT, FeasibleSpace(min=1, max=16)),
        ],
        max_trial_count=32,
        parallel_trial_count=16,
        train_fn=train,
    )
    if elastic:
        allocator = ElasticSliceAllocator(devices=jax.devices())
    else:
        allocator = SliceAllocator(slice_size=1, devices=jax.devices())
    workdir = os.path.join(REPO, "katib_runs")
    exp = Orchestrator(workdir=workdir, slice_allocator=allocator).run(spec)
    wall = time.time() - started

    from katib_tpu.core.types import DEVICES_LABEL

    rungs: dict[str, int] = {}
    devices_by_rung: dict[str, int] = {}
    for t in exp.trials.values():
        key = f"s={t.labels.get(S_LABEL)} rung={t.labels.get(I_LABEL)}"
        rungs[key] = rungs.get(key, 0) + 1
        if elastic:
            # mirror the orchestrator's clamp exactly (floor 1, cap machine)
            want = int(float(t.labels.get(DEVICES_LABEL, "1")))
            granted = min(max(1, want), len(jax.devices()))
            devices_by_rung[key] = max(devices_by_rung.get(key, 0), granted)

    best_curve = []
    best = float("-inf")
    for row in sorted(timeline, key=lambda r: r["elapsed_s"]):
        if row["accuracy"] > best:
            best = row["accuracy"]
            best_curve.append({"elapsed_s": row["elapsed_s"], "best_accuracy": best})

    summary = {
        "experiment": exp.spec.name,
        "condition": exp.condition.value,
        "elastic_devices": elastic,
        "real_data": using_real_data("mnist"),
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "trials_total": len(exp.trials),
        "trials_succeeded": exp.succeeded_count,
        "wallclock_s": round(wall, 1),
        "trials_per_hour": round(len(exp.trials) / wall * 3600.0, 1),
        "best_objective": exp.optimal.objective_value if exp.optimal else None,
        "best_assignments": (
            {a.name: a.value for a in exp.optimal.assignments} if exp.optimal else None
        ),
        "rungs": dict(sorted(rungs.items())),
        "best_objective_vs_wallclock": best_curve,
    }
    if elastic:
        summary["devices_by_rung"] = dict(sorted(devices_by_rung.items()))
    write_artifact(
        "hyperband",
        "elastic_summary.json" if elastic else "sweep_summary.json",
        summary,
    )
    print(json.dumps({k: summary[k] for k in (
        "condition", "trials_total", "wallclock_s", "trials_per_hour",
        "best_objective",
    )}), flush=True)
    return 0 if exp.succeeded_count == spec.max_trial_count else 1


if __name__ == "__main__":
    sys.exit(main())
