"""Hyperband sweep at the BASELINE shape: 32 trials over an 8-device mesh
with ``SliceAllocator`` sub-mesh leasing, each trial a REAL model-scale
training run — by default ``SmallCNN`` on the bundled real UCI digits, so
``best_objective`` is a held-out accuracy, not a toy closed form.

This is the committed-artifact half of VERDICT r1 item 4 (the invariants
half lives in ``tests/test_hyperband_e2e.py``): the run writes
``artifacts/hyperband/sweep_summary.json`` with the driver metrics —
trials/hour and best-objective@wallclock — plus the rung table and
PER-TRIAL wall-clocks (the first trial on each leased mesh carries the
XLA compile; later trials hit the jitted-step cache — the compile-once
economics the BASELINE v5e-64 scenario depends on).

Run with the virtual mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/run_hyperband_sweep.py

Env knobs: KATIB_DATASET (default digits — real data; cifar10/mnist go
through the npz-or-synthetic loaders and record their provenance),
SWEEP_MODEL (cnn|mlp), SWEEP_NTRAIN/SWEEP_NTEST, SWEEP_ELASTIC=1,
SWEEP_PLATFORM.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402


def main() -> int:
    # CPU-mesh demo: 8-way trial parallelism would serialize onto the one
    # TPU chip (or hang on a wedged pool); SWEEP_PLATFORM overrides
    jax = setup_jax(
        force_platform=os.environ.get("SWEEP_PLATFORM", "cpu"), virtual_devices=8
    )

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.models.data import (
        dataset_from_env,
        is_real_data,
        load_named_dataset,
    )
    from katib_tpu.models.mnist import MLP, SmallCNN, train_classifier
    from katib_tpu.orchestrator import Orchestrator
    from katib_tpu.parallel.distributed import ElasticSliceAllocator, SliceAllocator
    from katib_tpu.suggest.hyperband import I_LABEL, S_LABEL

    from katib_tpu.utils.booleans import parse_bool

    # SWEEP_ELASTIC=1: rung resource also sizes each trial's sub-mesh
    # (devices_per_rung + ElasticSliceAllocator) — finalists train on
    # 8-device meshes while rung-0 screens 16 one-device trials
    elastic = parse_bool(os.environ.get("SWEEP_ELASTIC"))

    ds_name = dataset_from_env("digits")
    n_train = os.environ.get("SWEEP_NTRAIN")
    n_test = os.environ.get("SWEEP_NTEST")
    dataset = load_named_dataset(
        ds_name,
        int(n_train) if n_train else None,
        int(n_test) if n_test else None,
    )
    model_kind = os.environ.get("SWEEP_MODEL", "cnn")
    models = {"cnn": SmallCNN, "mlp": MLP}
    if model_kind not in models:
        print(
            f"SWEEP_MODEL must be one of {sorted(models)}, got {model_kind!r}",
            file=sys.stderr,
        )
        return 2
    make_model = models[model_kind]
    started = time.time()
    timeline: list[dict] = []

    def train(ctx):
        lr = float(ctx.params["lr"])
        epochs = int(float(ctx.params["epochs"]))

        def report(epoch, accuracy, loss):
            return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

        t0 = time.time()
        acc = train_classifier(
            make_model(),
            dataset,
            lr=lr,
            epochs=epochs,
            batch_size=64,
            mesh=ctx.mesh,
            report=report,
            eval_batch=256,
        )
        timeline.append(
            {
                "trial": ctx.trial_name,
                "elapsed_s": round(time.time() - started, 2),
                # per-trial wall-clock: the first trial per leased mesh
                # carries the XLA compile, later ones hit the step cache
                "duration_s": round(time.time() - t0, 2),
                "accuracy": acc,
                "epochs": epochs,
            }
        )

    # bounded-run knobs (integration tests / CI smoke): the BASELINE shape
    # stays the default
    r_l = int(os.environ.get("SWEEP_RL", "16"))
    max_trials = int(os.environ.get("SWEEP_MAX_TRIALS", "32"))
    parallel = int(os.environ.get("SWEEP_PARALLEL", "16"))
    hb_settings = {"r_l": str(r_l), "resource_name": "epochs", "eta": "4"}
    if elastic:
        hb_settings["devices_per_rung"] = "true"
    spec = ExperimentSpec(
        name="hyperband-elastic" if elastic else "hyperband-demo",
        algorithm=AlgorithmSpec(name="hyperband", settings=hb_settings),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.001, max=0.5)),
            ParameterSpec(
                "epochs", ParameterType.INT, FeasibleSpace(min=1, max=r_l)
            ),
        ],
        max_trial_count=max_trials,
        parallel_trial_count=parallel,
        train_fn=train,
    )
    if elastic:
        allocator = ElasticSliceAllocator(devices=jax.devices())
    else:
        allocator = SliceAllocator(slice_size=1, devices=jax.devices())
    workdir = os.path.join(REPO, "katib_runs")
    exp = Orchestrator(workdir=workdir, slice_allocator=allocator).run(spec)
    wall = time.time() - started

    from katib_tpu.core.types import DEVICES_LABEL

    rungs: dict[str, int] = {}
    devices_by_rung: dict[str, int] = {}
    for t in exp.trials.values():
        key = f"s={t.labels.get(S_LABEL)} rung={t.labels.get(I_LABEL)}"
        rungs[key] = rungs.get(key, 0) + 1
        if elastic:
            # mirror the orchestrator's clamp exactly (floor 1, cap machine)
            want = int(float(t.labels.get(DEVICES_LABEL, "1")))
            granted = min(max(1, want), len(jax.devices()))
            devices_by_rung[key] = max(devices_by_rung.get(key, 0), granted)

    best_curve = []
    best = float("-inf")
    for row in sorted(timeline, key=lambda r: r["elapsed_s"]):
        if row["accuracy"] > best:
            best = row["accuracy"]
            best_curve.append({"elapsed_s": row["elapsed_s"], "best_accuracy": best})

    durations = sorted(r["duration_s"] for r in timeline)
    summary = {
        "experiment": exp.spec.name,
        "condition": exp.condition.value,
        "elastic_devices": elastic,
        "dataset": ds_name,
        "model": model_kind,
        "real_data": is_real_data(ds_name),
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "trials_total": len(exp.trials),
        "trials_succeeded": exp.succeeded_count,
        "wallclock_s": round(wall, 1),
        "trials_per_hour": round(len(exp.trials) / wall * 3600.0, 1),
        "best_objective": exp.optimal.objective_value if exp.optimal else None,
        "best_assignments": (
            {a.name: a.value for a in exp.optimal.assignments} if exp.optimal else None
        ),
        "rungs": dict(sorted(rungs.items())),
        "best_objective_vs_wallclock": best_curve,
        # compile amortization evidence: max is a compile-carrying trial,
        # median is the cached steady state
        "per_trial_secs": {
            "max": durations[-1] if durations else None,
            "median": durations[len(durations) // 2] if durations else None,
            "min": durations[0] if durations else None,
        },
        "per_trial_timeline": sorted(timeline, key=lambda r: r["elapsed_s"]),
    }
    if elastic:
        summary["devices_by_rung"] = dict(sorted(devices_by_rung.items()))
    write_artifact(
        "hyperband",
        # NOT elastic_summary.json — that name belongs to run_elastic_ab's
        # fixed-vs-elastic A/B artifact and must not be clobbered by an
        # elastic-variant sweep run
        "sweep_summary_elastic.json" if elastic else "sweep_summary.json",
        summary,
    )
    print(json.dumps({k: summary[k] for k in (
        "condition", "trials_total", "wallclock_s", "trials_per_hour",
        "best_objective",
    )}), flush=True)
    # BASELINE shape: the e2e invariant is strict (32 trials ran, all
    # succeeded — run-e2e-experiment.py:52-60).  With an overridden budget
    # Hyperband may exhaust its brackets below max_trial_count (r_l bounds
    # the bracket table), so the invariant relaxes to "everything that ran
    # succeeded and something ran".
    if os.environ.get("SWEEP_MAX_TRIALS") or os.environ.get("SWEEP_RL"):
        ok = 0 < exp.succeeded_count == len(exp.trials)
    else:
        ok = exp.succeeded_count == spec.max_trial_count
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
