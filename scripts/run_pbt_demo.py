"""PBT demo: population evolving hyperparameters via the real PBT suggester
— truncation selection, exploit-by-checkpoint-clone (the winner's Orbax
state, fixing the reference's copy-the-loser quirk — ``suggest/pbt.py:17-21``),
explore-by-perturb.

Two workloads, selected with ``PBT_DATASET``:

- ``toy`` (default): the simple-pbt triangle-wave score (reference
  ``examples/v1beta1/trial-images/simple-pbt/pbt_test.py``) →
  ``artifacts/pbt/demo_summary.json``
- ``digits``: a REAL digits classifier whose weights + momentum ride the
  checkpoint lineage (``models/pbt_digits.py``) →
  ``artifacts/pbt/digits_summary.json``

Both record per-generation best/mean objective, lineage depth, trials/hour.

Run: python scripts/run_pbt_demo.py   (CPU; PBT_PLATFORM overrides,
PBT_POPULATION / PBT_GENERATIONS size the sweep)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402


def main() -> int:
    jax = setup_jax(
        force_platform=os.environ.get("PBT_PLATFORM", "cpu"), virtual_devices=8
    )

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.models.pbt_toy import pbt_toy_trial
    from katib_tpu.orchestrator import Orchestrator

    # score accrues ~0.02/step along a lineage, so the evolution curve only
    # becomes unmistakable with enough generations for exploit/explore to
    # compound — 8 generations gives surviving lineages room to separate
    population = int(os.environ.get("PBT_POPULATION", "8"))
    generations = int(os.environ.get("PBT_GENERATIONS", "8"))
    # PBT_DATASET=digits evolves a REAL model (digits classifier whose
    # weights + momentum ride the checkpoint lineage) instead of the toy
    # scalar workload; see models/pbt_digits.py
    dataset = os.environ.get("PBT_DATASET", "toy")
    if dataset not in ("toy", "digits"):
        print(f"PBT_DATASET must be 'toy' or 'digits', got {dataset!r}",
              file=sys.stderr)
        return 2
    exp_name = "pbt-digits" if dataset == "digits" else "pbt-demo"
    metric = "accuracy" if dataset == "digits" else "score"
    # lineage lives under the experiment workdir (durable across --resume,
    # not a leaked tempdir)
    ckpt_dir = os.path.join(REPO, "katib_runs", exp_name, "pbt-lineage")

    if dataset == "digits":
        from katib_tpu.models.pbt_digits import pbt_digits_trial as train_fn

        # lr range wide enough that explore/exploit matters: the low end
        # underfits in the per-round budget, the high end diverges
        lr_space = FeasibleSpace(min=0.001, max=1.0)
    else:
        train_fn = pbt_toy_trial
        lr_space = FeasibleSpace(min=0.0001, max=0.02)

    spec = ExperimentSpec(
        name=exp_name,
        algorithm=AlgorithmSpec(
            name="pbt",
            settings={
                "n_population": str(population),
                "truncation_threshold": "0.25",
                "suggestion_trial_dir": ckpt_dir,
            },
        ),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name=metric
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, lr_space),
        ],
        max_trial_count=population * generations,
        parallel_trial_count=4,
        train_fn=train_fn,
    )
    started = time.time()
    exp = Orchestrator(workdir=os.path.join(REPO, "katib_runs")).run(spec)
    wall = time.time() - started

    by_gen: dict[int, list[float]] = {}
    lineage_depth = 0
    for t in exp.trials.values():
        if t.observation is None:
            continue
        gen = int(t.spec.labels.get("pbt-generation", 0))
        score = next(
            (m.max for m in t.observation.metrics if m.name == metric), None
        )
        if score is not None:
            by_gen.setdefault(gen, []).append(score)
        # lineage depth: walk parents
        depth, cur = 0, t
        while cur is not None and cur.spec.labels.get("pbt-parent"):
            depth += 1
            cur = exp.trials.get(cur.spec.labels["pbt-parent"])
        lineage_depth = max(lineage_depth, depth)

    gen_curve = [
        {
            "generation": g,
            "members": len(v),
            "best_score": round(max(v), 4),
            "mean_score": round(sum(v) / len(v), 4),
        }
        for g, v in sorted(by_gen.items())
    ]

    summary = {
        "experiment": exp.spec.name,
        "condition": exp.condition.value,
        "dataset": dataset,
        "real_data": dataset == "digits",
        "platform": jax.devices()[0].platform,
        "population": population,
        "trials_total": len(exp.trials),
        "trials_succeeded": exp.succeeded_count,
        "wallclock_s": round(wall, 1),
        "trials_per_hour": round(len(exp.trials) / wall * 3600.0, 1),
        "best_objective": exp.optimal.objective_value if exp.optimal else None,
        "max_lineage_depth": lineage_depth,
        "score_per_generation": gen_curve,
    }
    write_artifact(
        "pbt",
        "digits_summary.json" if dataset == "digits" else "demo_summary.json",
        summary,
    )
    print(json.dumps({k: summary[k] for k in (
        "condition", "trials_total", "best_objective", "max_lineage_depth",
    )} | {"generations": gen_curve}), flush=True)
    ok = exp.succeeded_count == spec.max_trial_count and lineage_depth > 0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
