#!/usr/bin/env bash
# Second healthy-window queue (round 4): the follow-ups that depend on
# window 1's results — the batch-scaling rerun (its first attempt burned
# both points on the BENCH_RETRIES=1 footgun, since fixed), the op
# microbench regenerated with the measured scan-iteration floor, and the
# 32-trial Hyperband sweep serialized onto the real chip (trials/hour,
# the BASELINE driver metric, with on-chip compile-once economics).
#
# Waits for window 1 (scripts/tpu_window.sh) to release the chip first.
# Usage: bash scripts/tpu_window2.sh   (detached)
# Logs:  /tmp/tpu_window2/<step>.log
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_window2
mkdir -p "$LOG"

# wait (up to 6h) for window 1 to finish so the two queues never contend
# for the single chip
for _ in $(seq 720); do
    if grep -q "window complete" /tmp/tpu_window/driver.log 2>/dev/null; then
        break
    fi
    if ! pgrep -f "tpu_window.sh" | grep -qv $$; then
        break  # window 1 is not running at all
    fi
    sleep 30
done

run() {
    local t=$1 name=$2; shift 2
    echo "=== $name start $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    setsid "$@" >"$LOG/$name.log" 2>&1 &
    local pid=$!
    ( sleep "$t" && kill -- -"$pid" 2>/dev/null && sleep 20 \
        && kill -9 -- -"$pid" 2>/dev/null ) &
    local watcher=$!
    local rc=0
    wait "$pid" || rc=$?
    kill "$watcher" 2>/dev/null; wait "$watcher" 2>/dev/null
    kill -9 -- -"$pid" 2>/dev/null
    echo "=== $name rc=$rc end $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
}

# 1. batch scaling at the proven configs (b64 no-remat, b128 dots) with
#    the compile-locality fix — the remaining throughput lever
run 5400 batch_scaling python scripts/run_batch_scaling.py

# 2. op microbench with the explicit scan-floor measurement
run 2700 op_microbench python scripts/run_op_microbench.py

# 3. 32-trial Hyperband sweep serialized onto the real chip: real digits
#    CNN trials, per-trial wall-clocks show the compile-once economics.
#    Redirected so it can't clobber the committed CPU-mesh sweep artifact;
#    the result is copied in under its own name afterwards.
run 5400 hyperband_tpu env SWEEP_PLATFORM=axon \
    KATIB_ARTIFACTS_DIR=/tmp/tpu_window2/artifacts \
    python scripts/run_hyperband_sweep.py
if [ -f /tmp/tpu_window2/artifacts/hyperband/sweep_summary.json ]; then
    cp /tmp/tpu_window2/artifacts/hyperband/sweep_summary.json \
       artifacts/hyperband/sweep_summary_tpu.json
fi

echo "=== window2 complete $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
