"""A/B: hyperband ``devices_per_rung`` elasticity on a scalable workload.

Round 2's artifact showed elastic leasing *losing* on a toy whose step time
did not depend on device count — which is exactly the case elasticity is
not for.  This A/B runs the orchestrator + ElasticSliceAllocator end-to-end
(real scheduler, real leases, real wall-clock) on a workload whose step
time follows Amdahl scaling in the lease size:

    t(r, d) = r * T_BASE * ((1 - s) + s / d)

where ``r`` is the rung resource, ``d`` the leased device count, and ``s``
the scalable fraction.  The compute itself is mocked as sleep — this box
has one physical core, so a real matmul cannot speed up with virtual
devices; what is REAL here is the scheduling: leases, rung promotions,
contention, and elapsed time through the actual orchestrator.  Three
scalable fractions show the win and the break-even:

- s=0.9  (communication-light big-batch training): elastic should win —
  promoted survivors run near-linearly faster on bigger sub-meshes;
- s=0.5  (heavily serial): the win shrinks toward break-even;
- s=0.0  (device-count-independent, round 2's toy): elasticity pays
  nothing and costs allocator headroom — fixed should win, documenting
  that elasticity is a scale feature, not a universal default.

Writes ``artifacts/hyperband/elastic_summary.json``.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402

jax = setup_jax(force_platform="cpu", virtual_devices=8)

sys.path.insert(0, REPO)

from katib_tpu.core.types import (  # noqa: E402
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)
from katib_tpu.orchestrator import Orchestrator  # noqa: E402
from katib_tpu.parallel.distributed import ElasticSliceAllocator  # noqa: E402

T_BASE = float(os.environ.get("ELASTIC_T_BASE", "0.6"))


def run_arm(workdir: str, elastic: bool, scalable: float) -> dict:
    def train(ctx):
        d = ctx.mesh.devices.size
        r = int(float(ctx.params["epochs"]))
        acc = 1.0 - (float(ctx.params["lr"]) - 0.1) ** 2
        for step in range(r):
            # Amdahl step time; sleep in place of device compute (see module
            # doc — the scheduling around it is real)
            time.sleep(T_BASE * ((1.0 - scalable) + scalable / d))
            if not ctx.report(step=step, accuracy=acc * (step + 1) / r):
                return

    settings = {"r_l": "4", "eta": "2", "resource_name": "epochs"}
    if elastic:
        settings["devices_per_rung"] = "true"
    spec = ExperimentSpec(
        name=f"elastic-{elastic}-{scalable}",
        algorithm=AlgorithmSpec(name="hyperband", settings=settings),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.5)),
            ParameterSpec("epochs", ParameterType.INT, FeasibleSpace(min=1, max=4)),
        ],
        max_trial_count=None,
        parallel_trial_count=4,
        train_fn=train,
    )
    alloc = ElasticSliceAllocator(devices=jax.devices())
    t0 = time.perf_counter()
    exp = Orchestrator(workdir=workdir, slice_allocator=alloc).run(spec)
    wall = time.perf_counter() - t0
    best = exp.optimal.objective_value if exp.optimal else None
    return {
        "wallclock_s": round(wall, 2),
        "trials": len(exp.trials),
        "succeeded": exp.succeeded_count,
        "best_objective": round(best, 5) if best is not None else None,
    }


def main() -> None:
    import tempfile

    arms = {}
    for scalable in (0.9, 0.5, 0.0):
        for elastic in (False, True):
            with tempfile.TemporaryDirectory() as wd:
                key = f"s{scalable}_{'elastic' if elastic else 'fixed'}"
                arms[key] = run_arm(wd, elastic, scalable)
                print(key, arms[key], flush=True)

    def speedup(s):
        return round(
            arms[f"s{s}_fixed"]["wallclock_s"] / arms[f"s{s}_elastic"]["wallclock_s"],
            3,
        )

    payload = {
        "what": (
            "hyperband devices_per_rung A/B through the real orchestrator + "
            "ElasticSliceAllocator on an Amdahl-scaling mock workload "
            "t(r,d) = r*T*((1-s) + s/d); sleeps stand in for device compute "
            "(single-core host), the scheduling/lease/wall-clock path is real"
        ),
        "t_base_s": T_BASE,
        "n_devices": 8,
        "arms": arms,
        "speedup_elastic_over_fixed": {
            "s=0.9": speedup(0.9),
            "s=0.5": speedup(0.5),
            "s=0.0": speedup(0.0),
        },
        "conclusion": (
            "elasticity pays when per-step work scales with the lease "
            "(s near 1: promoted rungs finish ~linearly faster) and is a "
            "net loss for device-count-independent steps (s=0) — it is a "
            "scale feature to enable for big-batch/big-model rungs, not a "
            "universal default"
        ),
    }
    path = write_artifact("hyperband", "elastic_summary.json", payload)
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
