"""A/B: hyperband ``devices_per_rung`` elasticity with REAL model training.

VERDICT r3/r4 both flagged the previous artifact's compute being mocked as
``sleep`` — the 2x headline was a property of the mock's Amdahl knob, not
a measurement.  This version trains the actual model-scale workload the
32-trial sweep uses (``SmallCNN`` on the bundled real UCI digits,
``models/mnist.train_classifier`` data-parallel over each trial's leased
sub-mesh) through the real orchestrator with fixed vs elastic allocators,
and reports whatever speedup is true.

Honesty note recorded in the artifact: this host exposes an 8-device
VIRTUAL cpu mesh on limited physical cores, so per-step time cannot drop
with lease size the way it does across real chips — on this box the
expected true speedup is ~1.0 and the artifact says so.  What the A/B
still measures for real: the allocator/lease/promotion path end-to-end
with real XLA programs (compile + train + eval per trial), contention
between concurrent leases, and that elasticity costs nothing when it
cannot help.  The lease-size scaling story on real hardware is carried by
the sharded-step parity gate (``__graft_entry__.dryrun_multichip``) and
the BASELINE v5e-64 projection, not by this box.

Writes ``artifacts/hyperband/elastic_summary.json``.
Env: ELASTIC_TRIALS_RL (rung resource, default 4), ELASTIC_SEEDS
(default 3 — wall-clock on a shared box is noisy; report the spread).
"""

from __future__ import annotations

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402

jax = setup_jax(force_platform="cpu", virtual_devices=8)

sys.path.insert(0, REPO)

from katib_tpu.core.types import (  # noqa: E402
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)
from katib_tpu.models.data import load_named_dataset  # noqa: E402
from katib_tpu.models.mnist import SmallCNN, train_classifier  # noqa: E402
from katib_tpu.orchestrator import Orchestrator  # noqa: E402
from katib_tpu.parallel.distributed import (  # noqa: E402
    ElasticSliceAllocator,
    SliceAllocator,
)

DATASET = load_named_dataset("digits")


def run_arm(workdir: str, elastic: bool, seed: int) -> dict:
    def train(ctx):
        lr = float(ctx.params["lr"])
        epochs = int(float(ctx.params["epochs"]))

        def report(epoch, accuracy, loss):
            return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

        train_classifier(
            SmallCNN(),
            DATASET,
            lr=lr,
            epochs=epochs,
            batch_size=64,
            mesh=ctx.mesh,
            report=report,
            eval_batch=256,
        )

    settings = {
        "r_l": os.environ.get("ELASTIC_TRIALS_RL", "4"),
        "eta": "2",
        "resource_name": "epochs",
        "random_state": str(seed),
    }
    if elastic:
        settings["devices_per_rung"] = "true"
    spec = ExperimentSpec(
        name=f"elastic-ab-{'el' if elastic else 'fx'}-{seed}",
        algorithm=AlgorithmSpec(name="hyperband", settings=settings),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec(
                "lr", ParameterType.DOUBLE, FeasibleSpace(min=0.005, max=0.3)
            ),
            ParameterSpec(
                "epochs", ParameterType.INT, FeasibleSpace(min=1, max=4)
            ),
        ],
        max_trial_count=None,
        parallel_trial_count=4,
        train_fn=train,
    )
    alloc = (
        ElasticSliceAllocator(devices=jax.devices())
        if elastic
        # uniform 2-device slices: 4-way parallelism over all 8 devices,
        # the natural fixed counterpart to rung-sized elastic leases
        else SliceAllocator(slice_size=2, devices=jax.devices())
    )
    t0 = time.perf_counter()
    exp = Orchestrator(workdir=workdir, slice_allocator=alloc).run(spec)
    wall = time.perf_counter() - t0
    best = exp.optimal.objective_value if exp.optimal else None
    return {
        "wallclock_s": round(wall, 2),
        "trials": len(exp.trials),
        "succeeded": exp.succeeded_count,
        "best_objective": round(best, 5) if best is not None else None,
    }


def main() -> None:
    import tempfile

    seeds = range(int(os.environ.get("ELASTIC_SEEDS", "3")))
    arms: dict[str, list[dict]] = {"fixed": [], "elastic": []}
    for seed in seeds:
        for elastic in (False, True):
            with tempfile.TemporaryDirectory() as wd:
                key = "elastic" if elastic else "fixed"
                r = run_arm(wd, elastic, seed)
                arms[key].append(r)
                print(f"{key} seed={seed}", r, flush=True)

    def walls(key):
        return [a["wallclock_s"] for a in arms[key]]

    med_fx = statistics.median(walls("fixed"))
    med_el = statistics.median(walls("elastic"))
    payload = {
        "what": (
            "hyperband devices_per_rung A/B through the real orchestrator "
            "with REAL model training per trial (SmallCNN on bundled UCI "
            "digits, data-parallel over each leased sub-mesh) — no mocked "
            "compute; wall-clocks are end-to-end including XLA compiles"
        ),
        "n_devices": 8,
        "seeds": len(list(seeds)),
        "arms": arms,
        "median_wallclock_s": {"fixed": med_fx, "elastic": med_el},
        "speedup_elastic_over_fixed": round(med_fx / med_el, 3),
        "best_objective_range": {
            k: (
                [min(vals), max(vals)]
                if (vals := [a["best_objective"] for a in v
                             if a["best_objective"] is not None])
                else None
            )
            for k, v in arms.items()
        },
        "hardware_honesty": (
            "8 virtual cpu devices share this host's physical cores, so a "
            "bigger lease cannot reduce per-step time here — the true "
            "speedup on this box is expected ~1.0 and is reported as "
            "measured.  The value of the A/B is that the full elastic "
            "lease/promotion path runs real XLA training end-to-end; "
            "lease-size compute scaling on real chips is evidenced by the "
            "multichip dryrun's sharded-step parity gate instead"
        ),
    }
    path = write_artifact("hyperband", "elastic_summary.json", payload)
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
