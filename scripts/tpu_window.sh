#!/usr/bin/env bash
# First-healthy-window runner: executes the queued on-chip harnesses in
# priority order (driver bench first — VERDICT r3 item 1 — then the cheap
# profiling harnesses, then the long flagship search, which checkpoints
# per-epoch and resumes if the pool wedges mid-run).  Each step gets its
# own timeout and log; a failure never blocks the next step.
#
# Usage:  python scripts/pool_watch.py && bash scripts/tpu_window.sh
# Logs:   /tmp/tpu_window/<step>.log  (+ driver.log timeline)
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_window
mkdir -p "$LOG"

run() {
    local t=$1 name=$2; shift 2
    echo "=== $name start $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    timeout "$t" "$@" >"$LOG/$name.log" 2>&1
    echo "=== $name rc=$? end $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
}

# 1. the driver metric, default config (AOT memoized; terminal has the
#    program cached from round 3 — expect minutes, not the 20-min compile)
run 5400 bench python bench.py

# 2. fused-plan A/B on the same harness (BENCH_RETRIES=2 so the
#    libtpu-mismatch auto-flip to terminal-side compile can still happen)
run 5400 bench_fused env BENCH_FUSED=1 BENCH_NO_FALLBACK=1 BENCH_RETRIES=2 python bench.py

# 3. per-op costs of the supernet atoms (~15 min)
run 2700 op_microbench env KATIB_REMOTE_COMPILE=1 python scripts/run_op_microbench.py

# 4. batch scaling at the proven configs (b64 no-remat, b128 dots)
run 5400 batch_scaling python scripts/run_batch_scaling.py

# 5. compile-once TPE sweep on real digits
run 2700 tpe_digits env DEMO_TPU=1 python scripts/run_real_data_demo.py

# 6. augment phase measured on-chip (fit-proof gate runs deviceless first)
run 5400 augment python scripts/run_augment_tpu.py

# 7. the 50-epoch flagship search (VERDICT r3 item 2); per-epoch Orbax
#    checkpoints make this resumable, so a mid-run wedge costs one epoch
run 14400 flagship_50ep env FLAGSHIP_EPOCHS=50 FLAGSHIP_BATCH=64 FLAGSHIP_REMAT=0 python scripts/run_flagship_tpu.py

echo "=== window complete $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
