#!/usr/bin/env bash
# First-healthy-window runner: executes the queued on-chip harnesses in
# priority order (driver bench first — VERDICT r3 item 1 — then the cheap
# profiling harnesses, then the long flagship search, which checkpoints
# per-epoch and resumes if the pool wedges mid-run).  Each step gets its
# own timeout and log; a failure never blocks the next step.
#
# Usage:  python scripts/pool_watch.py && bash scripts/tpu_window.sh
# Logs:   /tmp/tpu_window/<step>.log  (+ driver.log timeline)
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_window
mkdir -p "$LOG"

run() {
    # each step runs as its own process GROUP (setsid) and the deadline
    # kills the whole group — a bare `timeout` would signal only the
    # top-level python and orphan bench.py's --child, which holds the
    # device grant and would contend with the next step
    local t=$1 name=$2; shift 2
    echo "=== $name start $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
    setsid "$@" >"$LOG/$name.log" 2>&1 &
    local pid=$!
    ( sleep "$t" && kill -- -"$pid" 2>/dev/null && sleep 20 \
        && kill -9 -- -"$pid" 2>/dev/null ) &
    local watcher=$!
    local rc=0
    wait "$pid" || rc=$?
    kill "$watcher" 2>/dev/null; wait "$watcher" 2>/dev/null
    # reap any group stragglers that caught the TERM (the watcher's -9
    # escalation is cancelled above once the leader exits)
    kill -9 -- -"$pid" 2>/dev/null
    echo "=== $name rc=$rc end $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
}

# 1. the driver metric, default config (AOT memoized; terminal has the
#    program cached from round 3 — expect minutes, not the 20-min compile)
run 5400 bench python bench.py

# 2. fused-plan A/B on the same harness (BENCH_RETRIES=2 so the
#    libtpu-mismatch auto-flip to terminal-side compile can still happen)
run 5400 bench_fused env BENCH_FUSED=1 BENCH_NO_FALLBACK=1 BENCH_RETRIES=2 python bench.py

# 3. per-op costs of the supernet atoms (~15 min)
run 2700 op_microbench env KATIB_REMOTE_COMPILE=1 python scripts/run_op_microbench.py

# 4. batch scaling at the proven configs (b64 no-remat, b128 dots)
run 5400 batch_scaling python scripts/run_batch_scaling.py

# 5. compile-once TPE sweep on real digits
run 2700 tpe_digits env DEMO_TPU=1 python scripts/run_real_data_demo.py

# 6. augment phase measured on-chip (fit-proof gate runs deviceless first)
run 5400 augment python scripts/run_augment_tpu.py

# 6b. flash/ring attention refresh (cheap; keeps the longcontext artifact
#     on the same libtpu build as the rest of the round's numbers)
run 2700 longcontext python scripts/run_longcontext_tpu.py

# 7. the 50-epoch flagship search (VERDICT r3 item 2); per-epoch Orbax
#    checkpoints make this resumable, so a mid-run wedge costs one epoch.
#    The evaluation plan follows the measured A/B: fused only if step 2
#    beat step 1 on-chip (both json lines present and comparable).
FUSED_FLAG=$(python - <<'PY'
import json

def record(path):
    try:
        with open(path) as f:
            for line in f:
                if line.startswith('{"metric"'):
                    rec = json.loads(line)
                    if rec.get("platform") == "tpu":
                        return rec
    except OSError:
        pass
    return None

base = record("/tmp/tpu_window/bench.log")
fused = record("/tmp/tpu_window/bench_fused.log")
comparable = False
if base and fused:
    # identical configs modulo the fused key — bench's crash-retry can
    # flip BENCH_REMAT=1, and a remat-vs-noremat comparison would credit
    # the delta to the fused plan
    cb = {k: v for k, v in (base.get("config") or {}).items() if k != "fused"}
    cf = {k: v for k, v in (fused.get("config") or {}).items() if k != "fused"}
    comparable = cb == cf
ok = (
    comparable
    and (fused.get("value") or 0.0) > (base.get("value") or 0.0)
)
print("1" if ok else "0")
PY
)
echo "=== flagship fused=$FUSED_FLAG (A/B decision)" | tee -a "$LOG/driver.log"
run 14400 flagship_50ep env FLAGSHIP_EPOCHS=50 FLAGSHIP_BATCH=64 FLAGSHIP_REMAT=0 FLAGSHIP_FUSED=$FUSED_FLAG python scripts/run_flagship_tpu.py

echo "=== window complete $(date -u +%F' '%T)" | tee -a "$LOG/driver.log"
