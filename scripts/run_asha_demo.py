"""ASHA vs Hyperband under heterogeneous trial durations.

Hyperband's rungs are synchronization barriers: the bracket can't advance
until every trial in the rung finishes, so one slow trial idles every
other slot (the reference inherits this, ``hyperband/service.py:127``).
ASHA promotes asynchronously — the exact failure mode this demo measures.

Three arms tune the same toy objective with the same parallelism and a
per-trial duration proportional to its resource (epochs) plus jitter (the
straggler): uniform ASHA, BOHB-style ASHA (``sampler: tpe`` — needs
scipy; the arm is skipped on a base install), and Hyperband.  The
artifact records, for each: wall-clock to complete the budget, best
objective, and best-objective-vs-wallclock curve.

Run: python scripts/run_asha_demo.py   (CPU)
Artifact: artifacts/asha/comparison.json
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402


def run_one(algorithm: str, settings: dict, max_trials: int, parallel: int):
    import math
    import random

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.orchestrator import Orchestrator

    def train(ctx):
        lr = float(ctx.params["lr"])
        epochs = int(float(ctx.params["epochs"]))
        # heterogeneous durations: cost scales with the resource, plus a
        # deterministic-per-config straggler factor up to 4x — the barrier
        # pathology needs real waits, not scheduler noise
        jitter = 1.0 + 3.0 * random.Random(hash(lr) & 0xFFFF).random()
        base = 1.0 - (lr - 0.1) ** 2
        for epoch in range(epochs):
            time.sleep(0.15 * jitter)
            acc = base * (1.0 - math.exp(-(epoch + 1) / 4.0))
            if not ctx.report(step=epoch, accuracy=acc):
                return

    spec = ExperimentSpec(
        name=f"{algorithm}-race",
        algorithm=AlgorithmSpec(name=algorithm, settings=settings),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE,
                          FeasibleSpace(min=0.01, max=0.5)),
            ParameterSpec("epochs", ParameterType.INT,
                          FeasibleSpace(min=1, max=9)),
        ],
        max_trial_count=max_trials,
        parallel_trial_count=parallel,
        train_fn=train,
    )
    import tempfile

    t0 = time.perf_counter()
    # fresh workdir: a leftover journal from a prior demo run would resume
    # the experiment and re-anchor the wallclock curve
    with tempfile.TemporaryDirectory(prefix="asha-demo-") as wd:
        exp = Orchestrator(workdir=wd).run(spec)
    wall = time.perf_counter() - t0
    curve = [
        {"elapsed_s": row["elapsed_s"], "best": round(row["objective_value"], 4)}
        for row in exp.optimal_history
    ]
    return {
        "algorithm": algorithm,
        "condition": exp.condition.value,
        "trials": len(exp.trials),
        "wallclock_s": round(wall, 1),
        "best_objective": (
            round(exp.optimal.objective_value, 4) if exp.optimal else None
        ),
        "best_vs_wallclock": curve,
    }


def main() -> int:
    setup_jax(force_platform=os.environ.get("DEMO_PLATFORM", "cpu"))
    # hyperband's full bracket budget for r_l=9, eta=3 is 24 — it stops
    # there (SearchExhausted); asha keeps exploring/promoting to the cap.
    # Both get the same cap and slots; the comparison metric is
    # time-to-quality, not budget consumed
    trials = int(os.environ.get("ASHA_TRIALS", "40"))
    parallel = int(os.environ.get("ASHA_PARALLEL", "9"))

    # one tiny throwaway run first: the process's first white-box trial
    # pays one-time import/init costs (~4s) that would otherwise be
    # charged to whichever algorithm happens to run first
    run_one("random", {}, 2, 2)

    asha_settings = {"r_max": "9", "r_min": "1", "eta": "3",
                     "resource_name": "epochs"}
    asha = run_one("asha", asha_settings, trials, parallel)
    print(json.dumps(asha), flush=True)
    # BOHB-style arm: SAME schedule, fresh configs from a TPE fitted on
    # the history instead of the uniform prior; scipy is an optional
    # dependency, so a base install skips the arm rather than dying after
    # the uniform arm already ran
    import importlib.util

    asha_tpe = None
    if importlib.util.find_spec("scipy") is not None:
        asha_tpe = run_one(
            "asha", {**asha_settings, "sampler": "tpe"}, trials, parallel
        )
        print(json.dumps(asha_tpe), flush=True)
    else:
        print("scipy not installed; skipping the sampler:tpe arm",
              file=sys.stderr)
    hyperband = run_one(
        "hyperband",
        {"r_l": "9", "eta": "3", "resource_name": "epochs"},
        trials, parallel,
    )
    print(json.dumps(hyperband), flush=True)

    def time_to(curve, threshold):
        for row in curve:
            if row["best"] >= threshold:
                return row["elapsed_s"]
        return None

    threshold = 0.85
    payload = {
        "scenario": (
            f"identical toy objective, {parallel} slots, trial cap "
            f"{trials} (hyperband stops at its 24-trial bracket budget, "
            "asha explores to the cap); per-trial duration ~ resource x "
            "straggler jitter (up to 4x). Headline: seconds until best "
            "objective >= 0.85 — hyperband waits at rung barriers for "
            "stragglers, asha doesn't"
        ),
        "asha": asha,
        "asha_tpe_sampler": asha_tpe,
        "hyperband": hyperband,
        "time_to_085": {
            "asha": time_to(asha["best_vs_wallclock"], threshold),
            "asha_tpe_sampler": (
                time_to(asha_tpe["best_vs_wallclock"], threshold)
                if asha_tpe else None
            ),
            "hyperband": time_to(hyperband["best_vs_wallclock"], threshold),
        },
    }
    write_artifact("asha", "comparison.json", payload)
    print(json.dumps({"time_to_085": payload["time_to_085"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
