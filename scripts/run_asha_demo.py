"""ASHA vs Hyperband under heterogeneous trial durations.

Hyperband's rungs are synchronization barriers: the bracket can't advance
until every trial in the rung finishes, so one slow trial idles every
other slot (the reference inherits this, ``hyperband/service.py:127``).
ASHA promotes asynchronously — the exact failure mode this demo measures.

Three arms tune the same objective with the same parallelism: uniform
ASHA, BOHB-style ASHA (``sampler: tpe`` — needs scipy; the arm is
skipped on a base install), and Hyperband.  The artifact records, for
each: wall-clock to complete the budget, best objective, and
best-objective-vs-wallclock curve.

Workloads (``ASHA_WORKLOAD``):

- ``model`` (default): REAL model-scale trials — ``SmallCNN`` on the
  bundled real UCI digits, per-epoch held-out accuracy, the resource
  param is epochs.  Duration heterogeneity is physical (epochs +
  first-compile), and ``best_objective`` is a real accuracy, so the
  time-to-quality comparison is a capability number, not a scheduling
  toy.
- ``toy``: closed-form objective with ``sleep``-proportional durations
  and a deterministic up-to-4x straggler factor — isolates the
  rung-barrier pathology from model noise (the round-3 artifact's
  scenario).

Run: python scripts/run_asha_demo.py   (CPU)
Artifact: artifacts/asha/comparison.json (model) / comparison_toy.json
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, setup_jax, write_artifact  # noqa: E402


def run_one(
    algorithm: str, settings: dict, max_trials: int, parallel: int,
    workload: str = "model", dataset=None,
):
    if workload == "model" and dataset is None:
        raise ValueError("workload='model' requires a dataset")
    import math
    import random

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.orchestrator import Orchestrator

    def train_toy(ctx):
        lr = float(ctx.params["lr"])
        epochs = int(float(ctx.params["epochs"]))
        # heterogeneous durations: cost scales with the resource, plus a
        # deterministic-per-config straggler factor up to 4x — the barrier
        # pathology needs real waits, not scheduler noise
        jitter = 1.0 + 3.0 * random.Random(hash(lr) & 0xFFFF).random()
        base = 1.0 - (lr - 0.1) ** 2
        for epoch in range(epochs):
            time.sleep(0.15 * jitter)
            acc = base * (1.0 - math.exp(-(epoch + 1) / 4.0))
            if not ctx.report(step=epoch, accuracy=acc):
                return

    def train_model(ctx):
        from katib_tpu.models.mnist import SmallCNN, train_classifier

        def report(epoch, accuracy, loss):
            return ctx.report(step=epoch, accuracy=accuracy, loss=loss)

        train_classifier(
            SmallCNN(),
            dataset,
            lr=float(ctx.params["lr"]),
            epochs=int(float(ctx.params["epochs"])),
            batch_size=64,
            report=report,
            eval_batch=256,
        )

    spec = ExperimentSpec(
        name=f"{algorithm}-race",
        algorithm=AlgorithmSpec(name=algorithm, settings=settings),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE,
                          FeasibleSpace(min=0.01, max=0.5)),
            ParameterSpec("epochs", ParameterType.INT,
                          FeasibleSpace(min=1, max=9)),
        ],
        max_trial_count=max_trials,
        parallel_trial_count=parallel,
        train_fn=train_model if workload == "model" else train_toy,
    )
    import tempfile

    t0 = time.perf_counter()
    # fresh workdir: a leftover journal from a prior demo run would resume
    # the experiment and re-anchor the wallclock curve
    with tempfile.TemporaryDirectory(prefix="asha-demo-") as wd:
        exp = Orchestrator(workdir=wd).run(spec)
    wall = time.perf_counter() - t0
    curve = [
        {"elapsed_s": row["elapsed_s"], "best": round(row["objective_value"], 4)}
        for row in exp.optimal_history
    ]
    return {
        "algorithm": algorithm,
        "condition": exp.condition.value,
        "trials": len(exp.trials),
        "wallclock_s": round(wall, 1),
        "best_objective": (
            round(exp.optimal.objective_value, 4) if exp.optimal else None
        ),
        "best_vs_wallclock": curve,
    }


def main() -> int:
    setup_jax(force_platform=os.environ.get("DEMO_PLATFORM", "cpu"))
    workload = os.environ.get("ASHA_WORKLOAD", "model")
    if workload not in ("model", "toy"):
        print(f"ASHA_WORKLOAD must be model|toy, got {workload!r}",
              file=sys.stderr)
        return 2
    # hyperband's full bracket budget for r_l=9, eta=3 is 24 — it stops
    # there (SearchExhausted); asha keeps exploring/promoting to the cap.
    # Both get the same cap and slots; the comparison metric is
    # time-to-quality, not budget consumed
    trials = int(os.environ.get("ASHA_TRIALS", "40"))
    parallel = int(os.environ.get("ASHA_PARALLEL", "9"))
    # digits CNN reaches ~0.97+ at good lr within the resource budget;
    # the toy's closed form tops out below 1.0 by design
    threshold = float(
        os.environ.get("ASHA_THRESHOLD", "0.97" if workload == "model" else "0.85")
    )

    dataset = None
    if workload == "model":
        from katib_tpu.models.data import load_digits_real

        dataset = load_digits_real()

    def arm(algorithm, settings):
        return run_one(
            algorithm, settings, trials, parallel,
            workload=workload, dataset=dataset,
        )

    # one tiny throwaway run first: the process's first white-box trial
    # pays one-time import/init/compile costs that would otherwise be
    # charged to whichever algorithm happens to run first (2 trials on 2
    # slots — NOT the full arm budget)
    run_one("random", {}, 2, 2, workload=workload, dataset=dataset)

    asha_settings = {"r_max": "9", "r_min": "1", "eta": "3",
                     "resource_name": "epochs"}
    asha = arm("asha", asha_settings)
    print(json.dumps(asha), flush=True)
    # BOHB-style arm: SAME schedule, fresh configs from a TPE fitted on
    # the history instead of the uniform prior; scipy is an optional
    # dependency, so a base install skips the arm rather than dying after
    # the uniform arm already ran
    import importlib.util

    asha_tpe = None
    if importlib.util.find_spec("scipy") is not None:
        asha_tpe = arm("asha", {**asha_settings, "sampler": "tpe"})
        print(json.dumps(asha_tpe), flush=True)
    else:
        print("scipy not installed; skipping the sampler:tpe arm",
              file=sys.stderr)
    hyperband = arm(
        "hyperband", {"r_l": "9", "eta": "3", "resource_name": "epochs"}
    )
    print(json.dumps(hyperband), flush=True)

    def time_to(curve, threshold):
        for row in curve:
            if row["best"] >= threshold:
                return row["elapsed_s"]
        return None

    scenario_model = (
        f"REAL model-scale trials (SmallCNN on bundled real UCI digits, "
        f"per-epoch held-out accuracy), {parallel} slots, trial cap "
        f"{trials} (hyperband stops at its ~24-trial bracket budget, asha "
        "explores to the cap — the arms consume UNEQUAL trial budgets); "
        "duration heterogeneity is physical (epochs resource + "
        f"first-compile). Headline: seconds until best accuracy >= "
        f"{threshold}. NOTE: on a serialized single core there are no "
        "idle slots, so hyperband's rung barriers cost nothing here — "
        "the barrier pathology is isolated in comparison_toy.json"
    )
    scenario_toy = (
        f"identical toy objective, {parallel} slots, trial cap "
        f"{trials} (hyperband stops at its 24-trial bracket budget, "
        "asha explores to the cap); per-trial duration ~ resource x "
        "straggler jitter (up to 4x). Headline: seconds until best "
        f"objective >= {threshold} — hyperband waits at rung barriers for "
        "stragglers, asha doesn't"
    )
    key = f"time_to_{str(threshold).replace('.', '')}"
    payload = {
        "workload": workload,
        "real_data": workload == "model",
        "scenario": scenario_model if workload == "model" else scenario_toy,
        "asha": asha,
        "asha_tpe_sampler": asha_tpe,
        "hyperband": hyperband,
        key: {
            "asha": time_to(asha["best_vs_wallclock"], threshold),
            "asha_tpe_sampler": (
                time_to(asha_tpe["best_vs_wallclock"], threshold)
                if asha_tpe else None
            ),
            "hyperband": time_to(hyperband["best_vs_wallclock"], threshold),
        },
    }
    name = "comparison.json" if workload == "model" else "comparison_toy.json"
    write_artifact("asha", name, payload)
    print(json.dumps({key: payload[key]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
