"""Flagship run: full-size second-order DARTS search executed on the TPU.

Reproduces the reference trial image's search at its CIFAR-10 configuration
(8 layers / 16 init channels / 4 nodes; ``examples/v1beta1/trial-images/
darts-cnn-cifar10/run_trial.py:148-233``) and records what BASELINE.md calls
the driver metric — best-objective@wallclock — plus the discovered genotype.

Artifacts land in ``artifacts/flagship/`` (committed, unlike the gitignored
``katib_runs/``):

- ``run_log.json``  — config, platform, per-epoch accuracy-vs-wallclock,
  step-time stats, images/sec
- ``genotype.json`` — the discovered cell architecture
- ``run_progress.jsonl`` — per-epoch stream appended AS the run goes, so
  a run cut off mid-flight (round end, pool wedge) still leaves evidence
  of every completed epoch

Dataset honesty: with no egress this runs on the structured synthetic
CIFAR-10 fallback unless a real ``cifar10.npz`` is present in
``KATIB_DATA_DIR`` (``models/data.py``); the log records which one was used
so nobody mistakes synthetic separability for CIFAR-10 accuracy.

Env knobs: FLAGSHIP_EPOCHS (default 3), FLAGSHIP_BATCH (96),
FLAGSHIP_NTRAIN (8192), FLAGSHIP_SMALL=1 (CPU smoke shapes),
KATIB_DATASET (default cifar10 — the one flag that swaps every artifact
script's dataset; with KATIB_DATA_DIR holding a real npz the whole run is
real-data), FLAGSHIP_FUSED=1 (fused mixed-op evaluation plan,
nas/darts/fused.py).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import REPO, artifacts_root, setup_jax, write_artifact  # noqa: E402


def main() -> int:
    jax = setup_jax(compile_cache=True)

    from katib_tpu.utils.booleans import parse_bool

    small = parse_bool(os.environ.get("FLAGSHIP_SMALL"))
    epochs = int(os.environ.get("FLAGSHIP_EPOCHS", "1" if small else "3"))
    batch = int(os.environ.get("FLAGSHIP_BATCH", "16" if small else "96"))
    n_train = int(os.environ.get("FLAGSHIP_NTRAIN", "256" if small else "8192"))
    num_layers = 2 if small else 8
    init_channels = 4 if small else 16
    n_nodes = 2 if small else 4
    # remat stays ON for the flagship: the unattended full-size bilevel
    # run must not die to HBM exhaustion; FLAGSHIP_REMAT=0 opts into the
    # faster no-recompute step once the config is known to fit, and
    # FLAGSHIP_REMAT_POLICY=dots selects the matmul-saveable policy
    # (cheaper recompute; see docs/performance.md batch-scaling notes)
    remat = parse_bool(os.environ.get("FLAGSHIP_REMAT"), default=True)
    remat_policy = os.environ.get("FLAGSHIP_REMAT_POLICY") or None

    from katib_tpu.models.data import (
        dataset_from_env,
        is_real_data,
        load_named_dataset,
    )
    from katib_tpu.nas.darts.architect import DartsHyper
    from katib_tpu.nas.darts.search import run_darts_search

    fused = parse_bool(os.environ.get("FLAGSHIP_FUSED"))
    platform = jax.devices()[0].platform
    ds_name = dataset_from_env("cifar10")
    if ds_name == "digits":
        # the 1797-row bundled dataset: CIFAR-scale split requests would
        # clamp the test split to ~1 sample and record a meaningless
        # accuracy as real-data evidence — use its own 1400/397 defaults
        dataset = load_named_dataset(ds_name)
        n_train = len(dataset.x_train)
    else:
        dataset = load_named_dataset(ds_name, n_train, 2048 if not small else 128)
    print(
        f"flagship: platform={platform} epochs={epochs} batch={batch} "
        f"layers={num_layers} channels={init_channels} n_train={n_train} "
        f"dataset={ds_name} real_data={is_real_data(ds_name)} fused={fused}",
        flush=True,
    )

    # config-tagged so runs at different shapes OR data can never resume
    # from each other's snapshots (a small smoke run must not poison the
    # 50-epoch run's resume state; a synthetic-data snapshot must not be
    # restored into a real-data run — shapes match, so Orbax would succeed
    # silently and poison the provenance); FLAGSHIP_CKPT overrides outright
    ckpt_tag = (
        f"b{batch}_l{num_layers}_c{init_channels}_n{n_train}_{ds_name}"
        + ("_real" if is_real_data(ds_name) else "_syn")
        + ("_fused" if fused else "")
    )
    ckpt_dir = os.environ.get("FLAGSHIP_CKPT") or os.path.join(
        REPO, f".flagship_ckpt_{ckpt_tag}"
    )
    epoch_times: list[float] = []
    last = [time.perf_counter()]

    # per-epoch progress stream: a long run cut off mid-flight (round end,
    # pool wedge) still leaves committed evidence of every completed epoch
    # (the Orbax snapshots under ckpt_dir enable resume, but they are
    # process-local state, not artifact evidence).  Best-effort
    # throughout: an unwritable artifacts dir must not block the search.
    progress_path = os.path.join(artifacts_root(), "flagship", "run_progress.jsonl")
    try:
        os.makedirs(os.path.dirname(progress_path), exist_ok=True)
    except OSError:
        pass
    # fresh run (no snapshots to resume from) gets a fresh stream for ITS
    # config — but rewrite LAZILY on the first completed epoch: rewriting at
    # startup would erase the previous run's evidence before this run
    # produced any.  The stream is shared across configs, so the rewrite
    # keeps every record whose tag differs from ours (a small smoke run
    # must never erase an interrupted full-size run's still-resumable
    # evidence, no matter what order runs interleave in) and drops only OUR
    # tag's stale records (so repeated fresh runs can't concatenate
    # duplicate epoch series under one tag).
    def _keep_other_tags() -> list[str] | None:
        """Records to preserve through the rewrite, or None when the
        existing stream could not be READ — a transient read failure must
        downgrade to append-only, never to a truncating write that would
        erase other configs' evidence."""
        if not os.path.exists(progress_path):
            return []
        try:
            with open(progress_path) as f:
                lines = [ln for ln in f if ln.strip()]
        except OSError:
            return None
        kept = []
        for ln in lines:
            try:
                if json.loads(ln).get("config") != ckpt_tag:
                    kept.append(ln if ln.endswith("\n") else ln + "\n")
            except ValueError:
                continue  # drop corrupt records
        return kept

    rewrite_first = [not os.path.isdir(ckpt_dir)]

    # mid-run stall watchdog: a wedging pool can block an epoch's scan
    # dispatch indefinitely inside the runtime (observed live: epoch 16 of
    # a 50-epoch run hung >18 min in futex_wait while steady-state epochs
    # take ~36 s).  Per-epoch Orbax snapshots make dying CHEAP — at most
    # one epoch is lost on resume — so the watchdog exits hard (code 75)
    # when no epoch completes within the deadline, letting an outer
    # queue/babysitter probe the pool and relaunch, instead of burning the
    # whole window timeout blocked.  Armed only after the first completed
    # epoch: the first one legitimately carries a multi-minute compile.
    deadline = float(os.environ.get("FLAGSHIP_EPOCH_DEADLINE", "900"))
    beat = [0.0]  # 0.0 = not armed yet

    # poll at deadline/4 (cap 30 s): frequent enough that a short test
    # deadline fires promptly, infrequent enough to cost nothing at the
    # production 900 s deadline
    def _watchdog():
        while True:
            time.sleep(min(30.0, max(0.5, deadline / 4.0)))
            if beat[0] and time.perf_counter() - beat[0] > deadline:
                print(
                    f"flagship: WATCHDOG no epoch completed in {deadline:.0f}s"
                    " — pool stall; exiting 75 (resume-safe, snapshots keep"
                    " all completed epochs)",
                    flush=True,
                )
                os._exit(75)

    if deadline > 0:
        import threading

        threading.Thread(target=_watchdog, daemon=True).start()

    # test-only stall injection: after epoch K's snapshot lands, hang the
    # epoch loop so the watchdog's exit-75/resume cycle can be exercised
    # in anger on CPU (tests/test_cifar_ready_path.py) instead of waiting
    # for a live pool wedge to prove it
    stall_after = os.environ.get("FLAGSHIP_TEST_STALL_AFTER_EPOCH")

    def report(epoch, accuracy, loss):
        beat[0] = time.perf_counter()
        now = time.perf_counter()
        epoch_times.append(now - last[0])
        last[0] = now
        print(
            f"flagship: epoch={epoch} val_acc={accuracy:.4f} loss={loss:.4f} "
            f"epoch_secs={epoch_times[-1]:.1f}",
            flush=True,
        )
        try:
            if rewrite_first[0]:
                kept = _keep_other_tags()
                if kept is None:
                    # stream exists but is unreadable: appending may leave
                    # stale same-tag records, but truncating could erase
                    # other configs' evidence — append wins
                    rewrite_first[0] = False
                else:
                    with open(progress_path, "w") as f:
                        # only a successful open consumes the rewrite — a
                        # transient OSError must not flip later epochs of
                        # a fresh run into appending after stale records
                        rewrite_first[0] = False
                        f.writelines(kept)
            with open(progress_path, "a") as f:
                f.write(
                    json.dumps(
                        {
                            "epoch": epoch,
                            "accuracy": round(float(accuracy), 4),
                            "loss": round(float(loss), 4),
                            "epoch_secs": round(epoch_times[-1], 1),
                            "platform": platform,
                            "dataset": ds_name,
                            "config": ckpt_tag,
                        }
                    )
                    + "\n"
                )
        except OSError:
            pass
        if stall_after is not None and epoch == int(stall_after):
            # after the snapshot AND the stream record have landed — the
            # real wedge stalls in the NEXT epoch's dispatch, so the
            # injected hang must not swallow this epoch's evidence
            print(f"flagship: TEST STALL injected after epoch {epoch}", flush=True)
            time.sleep(10 * deadline if deadline > 0 else 3600)
        return True

    t0 = time.perf_counter()
    result = run_darts_search(
        dataset,
        num_layers=num_layers,
        init_channels=init_channels,
        n_nodes=n_nodes,
        num_epochs=epochs,
        batch_size=batch,
        hyper=DartsHyper(unrolled=True),
        seed=0,
        report=report,
        # HBM-resident splits + one scan dispatch per epoch: on the
        # tunneled chip the per-step host->device batch path costs ~100x
        # the 5.8 ms compute step (docs/performance.md); the C++ prefetch
        # loader only hides host-side gather, not the transfer itself
        device_data=True,
        # per-epoch Orbax snapshots: a relay drop mid-run resumes from the
        # last completed epoch instead of restarting the search
        checkpoint_dir=ckpt_dir,
        remat=remat,
        remat_policy=remat_policy,
        fused=fused,
    )
    wall = time.perf_counter() - t0
    # completed: clear the snapshots so the next invocation is a fresh run
    # (a leftover final-epoch checkpoint would make it a silent no-op)
    shutil.rmtree(ckpt_dir, ignore_errors=True)

    steps_per_epoch = max(1, (len(dataset.x_train) // 2) // batch)
    total_steps = steps_per_epoch * epochs
    # first epoch carries the XLA compile; the steady-state rate excludes it
    # and is reported as null when there is no compile-free epoch to measure
    steady = epoch_times[1:]
    img_per_sec = (
        steps_per_epoch * batch * len(steady) / sum(steady) if steady else None
    )

    genotype = result["genotype"]
    write_artifact(
        "flagship",
        "genotype.json",
        {
            "normal": genotype.normal,
            "reduce": genotype.reduce,
            "best_accuracy": result["best_accuracy"],
            "rendered": genotype.render(),
        },
    )
    log = {
        "config": {
            "num_layers": num_layers,
            "init_channels": init_channels,
            "n_nodes": n_nodes,
            "num_epochs": epochs,
            "batch_size": batch,
            "n_train": n_train,
            "second_order": True,
            "remat": remat,
            "remat_policy": remat_policy,
            "fused": fused,
        },
        "platform": platform,
        "dataset": ds_name,
        "real_data": is_real_data(ds_name),
        "wallclock_s": round(wall, 1),
        "epoch_secs": [round(t, 2) for t in epoch_times],
        "steady_state_images_per_sec": (
            round(img_per_sec, 2) if img_per_sec is not None else None
        ),
        "total_bilevel_steps": total_steps,
        "best_accuracy": result["best_accuracy"],
        "accuracy_vs_wallclock": result["history"],
    }
    write_artifact("flagship", "run_log.json", log)
    print(json.dumps({k: log[k] for k in (
        "platform", "dataset", "real_data", "wallclock_s",
        "steady_state_images_per_sec", "best_accuracy",
    )}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
