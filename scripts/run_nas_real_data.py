"""Real-data NAS evidence: DARTS bilevel search on the bundled UCI digits.

The flagship CIFAR-10 runs use the structured synthetic fallback (zero
egress — ``models/data.py``), so their accuracies prove the search loop,
not learning.  This demo runs the SAME second-order bilevel search
(``nas/darts/search.py``) on the one genuinely real dataset in the image
(scikit-learn's ``load_digits``, 8x8 grayscale) and records search-phase
validation accuracy + the discovered genotype in
``artifacts/real_data/digits_nas.json`` — real-world evidence the NAS
path finds architectures that classify real images.

Sized for CPU: 4-layer / 8-channel / 2-node supernet over 1400 real
digits.  Env knobs: NAS_EPOCHS (default 6), NAS_BATCH (64),
NAS_SMALL=1 (smoke shapes for tests).

Run: python scripts/run_nas_real_data.py   (CPU)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import setup_jax, write_artifact  # noqa: E402


def main() -> int:
    setup_jax(force_platform=os.environ.get("DEMO_PLATFORM", "cpu"))

    small = os.environ.get("NAS_SMALL", "") not in ("", "0")
    epochs = int(os.environ.get("NAS_EPOCHS", "1" if small else "6"))
    batch = int(os.environ.get("NAS_BATCH", "16" if small else "64"))
    num_layers = 2 if small else 4
    init_channels = 4 if small else 8
    n_nodes = 2

    from katib_tpu.models.data import load_digits_real
    from katib_tpu.nas.darts.architect import DartsHyper
    from katib_tpu.nas.darts.search import run_darts_search

    dataset = load_digits_real(n_train=256 if small else 1400)
    # 3-way split: search validates per-epoch on the first half of the test
    # rows; the augment phase's final number is measured on the second half,
    # which NOTHING saw during search — a genuinely held-out figure
    n_val = len(dataset.x_test) // 2
    ds_search = dataset._replace(
        x_test=dataset.x_test[:n_val], y_test=dataset.y_test[:n_val]
    )
    ds_augment = dataset._replace(
        x_test=dataset.x_test[n_val:], y_test=dataset.y_test[n_val:]
    )
    history: list[dict] = []
    t0 = time.perf_counter()

    def report(epoch, accuracy, loss):
        history.append(
            {
                "epoch": epoch,
                "val_accuracy": round(float(accuracy), 4),
                "elapsed_s": round(time.perf_counter() - t0, 1),
            }
        )
        print(f"nas-real: epoch={epoch} val_acc={accuracy:.4f}", flush=True)
        return True

    result = run_darts_search(
        ds_search,
        num_layers=num_layers,
        init_channels=init_channels,
        n_nodes=n_nodes,
        num_epochs=epochs,
        batch_size=batch,
        hyper=DartsHyper(unrolled=True),
        seed=0,
        report=report,
    )
    wall = time.perf_counter() - t0

    genotype = result["genotype"]
    payload = {
        "dataset": "sklearn load_digits (UCI handwritten digits, REAL data)",
        "search": "DARTS second-order bilevel",
        "config": {
            "num_layers": num_layers,
            "init_channels": init_channels,
            "n_nodes": n_nodes,
            "num_epochs": epochs,
            "batch_size": batch,
            "train_samples": int(len(dataset.x_train)),
            "search_val_rows": n_val,
        },
        "wallclock_s": round(wall, 1),
        "best_val_accuracy": result["best_accuracy"],
        "accuracy_vs_wallclock": history,
        "genotype": {"normal": genotype.normal, "reduce": genotype.reduce},
    }
    # persist the finished search NOW — an augment-phase failure must not
    # throw away a completed multi-minute search
    if not small:
        write_artifact("real_data", "digits_nas.json", payload)

    # augment phase: train the DISCOVERED architecture as a fixed network —
    # the search's product is usable, not just printable.  The final number
    # is measured on ds_augment's holdout rows, which search never touched.
    from katib_tpu.nas.darts import train_genotype

    aug_epochs = int(os.environ.get("NAS_AUG_EPOCHS", "2" if small else "15"))
    t_aug = time.perf_counter()
    augment_acc = train_genotype(
        genotype,
        ds_augment,
        init_channels=init_channels,
        num_layers=num_layers,
        lr=0.05,
        epochs=aug_epochs,
        batch_size=batch,
    )
    aug_wall = time.perf_counter() - t_aug
    print(f"nas-real: augment acc={augment_acc:.4f}", flush=True)

    payload["augment"] = {
        "epochs": aug_epochs,
        "wallclock_s": round(aug_wall, 1),
        "holdout_rows": int(len(ds_augment.x_test)),
        "holdout_test_accuracy": round(float(augment_acc), 4),
    }
    if not small:
        write_artifact("real_data", "digits_nas.json", payload)
    print(json.dumps({"best_val_accuracy": payload["best_val_accuracy"],
                      "augment_holdout_accuracy": payload["augment"]["holdout_test_accuracy"],
                      "wallclock_s": payload["wallclock_s"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
