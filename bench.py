"""Benchmark: DARTS supernet bilevel-search throughput on the local accelerator.

Times the flagship compute path — the second-order (unrolled + Hessian
correction) DARTS search step at the reference's CIFAR-10 configuration
(batch 64, 8 layers, 16 init channels; ``darts-cnn-cifar10/run_trial.py``) —
and prints ONE JSON line.

Reported numbers:
- ``value``: images/sec through the full bilevel step (arch + weight update);
- ``mfu``: model-FLOPs utilisation — XLA's own per-step flop count
  (``katib_tpu.costmodel`` CostRecord) divided by the chip's peak from
  the per-device-kind table (``katib_tpu/costmodel/peaks.py``; v5e ≈
  197 TFLOP/s bf16 / 98.5 TFLOP/s fp32); self-contained and
  hardware-honest, unlike a cross-vendor img/s ratio;
- ``vs_baseline``: img/s against the reference PyTorch trial image running
  the same second-order search on its CI GPU class (~250 img/s on a
  V100-16GB for batch-64 second-order DARTS, derived from the DARTS paper's
  search economics; the reference repo publishes no numbers — BASELINE.json
  ``published`` is empty).

Pool-wedge hardening (the axon TPU relay grants the chip to one client at a
time; a stale grant makes device init block forever): the measurement runs
in a CHILD process with a per-attempt deadline.  A child that never
completes device init is SIGKILLed (safe — a blocked client holds no grant)
and the attempt retried with backoff, so a transiently wedged pool recovers
instead of failing the round.  Compile warming is split from timing via the
persistent compilation cache in ``.jax_cache`` — a warmed cache makes later
runs (the driver's end-of-round bench) skip the multi-minute XLA compile.

Env knobs:
  BENCH_SMALL=1           tiny shapes for CPU smoke tests
  BENCH_INIT_TIMEOUT      per-attempt device-init deadline, s (default 240)
  BENCH_ATTEMPT_TIMEOUT   per-attempt total deadline, s (default 3600)
  BENCH_RETRIES           device-init retries (default 3)
  BENCH_RETRY_BACKOFF     sleep between retries, s (default 45)
  BENCH_WARM_ONLY=1       compile + one step only (cache priming), no timing
  BENCH_STEPS             timed steps (default 20, small: 3)
  BENCH_NO_FALLBACK=1     exit 3 when every TPU attempt fails, instead of
                          the default: emit an honestly-labeled CPU
                          measurement (platform=cpu, tpu_unavailable=true,
                          vs_baseline/mfu nulled, reduced shapes recorded)
  BENCH_SKIP_AOT=1        skip the deviceless v5e AOT compile block (the
                          default runs it first: pip libtpu compiles the
                          full-size program against a v5e topology with NO
                          device grant and reports flops/HBM/roofline —
                          TPU evidence that survives a wedged pool)
  KATIB_REMOTE_COMPILE=1  compile on the terminal server instead of the
                          default local AOT compile (see below; same knob
                          as the scripts/ harnesses)
  BENCH_SKIP_PREWARM=1    skip the compile-amortization block (default:
                          two CPU children share one fresh persistent
                          cache dir — the cold child compiles, the warm
                          one deserializes; the ratio lands in the result
                          as ``compile_amortization``, memoized like AOT)
  BENCH_AMORTIZE_K        cohort width the amortization probe warms (default 4)
  BENCH_AMORTIZE_FRESH=1  re-measure instead of using the committed memo
  BENCH_COHORT_K          --cohort mode: members per cohort (default 8)
  BENCH_COHORT_STEPS      --cohort mode: timed steps (default 200, small: 50)
  BENCH_COHORT_DEVICES    --cohort mode: devices on the trial axis (default 1;
                          the --cohort-devices N flag sets this plus the
                          virtual-device XLA flag for the child)

``python bench.py --cohort`` runs a separate measurement: serial vs
vmap-batched cohort trial throughput (``runner/cohort.py``) on a tiny
model where dispatch overhead dominates — the regime the cohort engine
optimizes.  Emits its own JSON line (serial/cohort trials-per-sec,
speedup) instead of the DARTS row.

Compile locality: the axon relay's terminal-side compile
(``PALLAS_AXON_REMOTE_COMPILE=1``, the ambient default) ships the HLO to
the pool and compiles there — measured at *minutes per trivial op* through
the tunnel, and the full-size bilevel step's 100MB-class program wedged the
session outright (round-2 attempt: 22 min, then a dead grant).  The same
step compiles in ~35s client-side.  So the measurement child defaults to
``PALLAS_AXON_REMOTE_COMPILE=0``: XLA compiles locally against the v5e
target via the pip-installed ``libtpu.so`` (the plugin's documented
local-AOT path) and only *execution* crosses the relay.  The env var must
be set before interpreter start (the axon sitecustomize registers the PJRT
plugin at boot), which is exactly what spawning a child process allows.

Local compile has one hard failure mode: the terminal refuses executables
from a client whose ``libtpu`` build differs from its own
("libtpu version mismatch ... FAILED_PRECONDITION", seen live when the
pool rolled to an older build than the pip wheel).  The retry loop detects
that signature in the child's stderr and re-runs the attempt with
terminal-side compile — correct by construction (the terminal compiles
with its own libtpu) at the cost of tunnel-compile latency.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "scripts"))
sys.path.insert(0, _HERE)
from _common import remote_compile_requested  # noqa: E402

from katib_tpu.utils.booleans import parse_bool  # noqa: E402

_SMALL = parse_bool(os.environ.get("BENCH_SMALL"))
# batch is overridable for scaling studies: the supernet's convs are tiny
# (16-64 ch on 32x32), so per-op overhead dominates at the reference's
# batch 64 and throughput scales with batch until the MXU saturates
BATCH = int(os.environ.get("BENCH_BATCH", "8" if _SMALL else "64"))
NUM_LAYERS = 2 if _SMALL else 8
INIT_CHANNELS = 4 if _SMALL else 16
N_NODES = 2 if _SMALL else 4
WARMUP_STEPS = 1 if _SMALL else 2
TIMED_STEPS = max(1, int(os.environ.get("BENCH_STEPS", "3" if _SMALL else "20")))

REFERENCE_IMG_PER_SEC = 250.0
# peak flops / HBM bandwidth now come from the shared per-device-kind
# table (katib_tpu/costmodel/peaks.py) — KATIB_PEAK_FLOPS/KATIB_PEAK_BW
# override them for hardware the table doesn't know
_RESULT_TAG = "@@BENCH_RESULT@@"


def _device_barrier(jax_mod) -> None:
    """Stream barrier before a timer stops (lint code JAX105): device
    execution is in-order per stream, so blocking on a freshly enqueued
    trivial transfer implies every previously dispatched program retired.
    Complements the host-fetch integrity rule (see the 93x note in
    ``_child``) — used where the timed work leaves no value to fetch."""
    jax_mod.block_until_ready(jax_mod.device_put(0.0))


def _build_flagship(jax, jnp):
    """Build the full-size bilevel search step + inputs at the bench shapes.

    Shared by the timed child and the AOT compile-only child so the program
    that gets cost-analysed deviceless is byte-identical to the one that
    gets timed on the chip.
    """
    from katib_tpu.nas.darts.architect import (
        DartsHyper,
        init_search_state,
        make_search_step,
    )
    from katib_tpu.nas.darts.model import DartsNetwork, init_alphas
    from katib_tpu.nas.darts.ops import DEFAULT_PRIMITIVES
    from katib_tpu.parallel.train import cross_entropy_loss

    # remat off by default: at bench shapes the supernet fits HBM without
    # recompute, and the bilevel step's 5 gradient passes make recompute
    # expensive (the reference's torch trial does no remat either);
    # BENCH_REMAT=1 restores it for memory-constrained configs, and
    # BENCH_REMAT_POLICY=dots selects the matmul-saveable policy (keep
    # conv/matmul outputs, recompute only elementwise — the batch-scaling
    # configuration)
    remat = parse_bool(os.environ.get("BENCH_REMAT"))
    # BENCH_FUSED=1 evaluates the 4 depthwise-separable primitives through
    # the fused plan (2 masked depthwise + 2 batched pointwise per mixed op
    # instead of 6+6; nas/darts/fused.py) — the measured attack on the
    # small-op-bound 0.56% MFU profile
    fused = parse_bool(os.environ.get("BENCH_FUSED"))
    # BENCH_PAIRED_HESSIAN=1: the two finite-difference passes run as one
    # vmapped pass over stacked (w+, w-) — 4 sequential network passes per
    # bilevel step instead of 5 (architect.py DartsHyper.paired_hessian).
    # Math parity is f32-gated in tests; in bf16 the variants differ at
    # rounding level (the finite difference amplifies decorrelated
    # rounding), so this is an A/B-able throughput config, not a bitwise
    # twin.
    paired = parse_bool(os.environ.get("BENCH_PAIRED_HESSIAN"))
    net = DartsNetwork(
        primitives=DEFAULT_PRIMITIVES,
        init_channels=INIT_CHANNELS,
        num_layers=NUM_LAYERS,
        n_nodes=N_NODES,
        num_classes=10,
        remat_policy=os.environ.get("BENCH_REMAT_POLICY") or None,
        remat=remat,
        fused_convs=fused,
    )
    key = jax.random.PRNGKey(0)
    k_init, k_alpha, k_data = jax.random.split(key, 3)
    alphas = init_alphas(N_NODES, len(DEFAULT_PRIMITIVES), k_alpha)
    x = jax.random.normal(k_data, (BATCH, 32, 32, 3), jnp.float32)
    y = jax.random.randint(jax.random.fold_in(k_data, 1), (BATCH,), 0, 10)
    weights = net.init(k_init, x[:1], alphas)

    def loss_fn(w, a, batch):
        xb, yb = batch
        return cross_entropy_loss(net.apply(w, xb, a), yb)

    hyper = DartsHyper(
        total_steps=max(TIMED_STEPS, 1), unrolled=True, paired_hessian=paired
    )
    step = make_search_step(loss_fn, hyper, mesh=None)
    state = init_search_state(weights, alphas, hyper)
    return step, state, (x, y), net, remat


def _aot_child() -> None:
    """Compile the full-size bilevel step against a deviceless v5e
    topology (``jax.experimental.topologies``) and report the XLA cost +
    HBM analysis.  Needs NO device grant: the pip ``libtpu`` compiles the
    program client-side against the v5e target, so this works even while
    the axon pool is wedged — the pool-proof slice of TPU evidence.

    Emits: flops_per_step, HBM footprint (args+temps+code), whether it
    fits v5e's 16 GiB, and a roofline estimate — step time bounded below
    by max(compute at peak, bytes-accessed at peak HBM bandwidth), which
    yields an *upper bound* on achievable MFU for this program.
    """
    import jax
    import jax.numpy as jnp

    from katib_tpu.costmodel import aot as cm_aot
    from katib_tpu.costmodel import peaks as cm_peaks
    from katib_tpu.costmodel.record import CostRecord, cost_of_compiled

    jax.config.update("jax_platforms", "cpu")  # host math only; TPU is a target
    # persist the executable: the full-size TPU-target compile runs ~27 min
    # on this host, so the driver's end-of-round bench must be a cache hit
    cache_dir = os.path.join(_HERE, ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass
    t0 = time.perf_counter()
    dev = cm_aot.topology_device("v5e:1x1x1")
    topo_secs = time.perf_counter() - t0  # lint: unguarded-ok(deviceless AOT: topology lookup is host-only, no program dispatched)

    step, state, batch, net, remat = _build_flagship(jax, jnp)
    compiled, compile_secs = cm_aot.aot_compile(step, (state, batch, batch), dev)

    dtype_key = "bf16" if net.dtype == jnp.bfloat16 else "f32"
    rec = cost_of_compiled(compiled, program="bench.aot", dtype=dtype_key)
    if rec is None:  # cost analysis is backend-dependent; keep the report
        rec = CostRecord(program="bench.aot", dtype=dtype_key)
    peaks = cm_peaks.peaks_for("v5e")
    roof = rec.roofline(peaks)
    flops = rec.flops
    bytes_accessed = rec.bytes_accessed
    hbm_bytes = rec.hbm_bytes
    compute_secs = roof["compute_floor_step_secs"]
    memory_secs = roof["prefusion_bw_step_secs"]
    print(
        _RESULT_TAG
        + json.dumps(
            {
                "target": "v5e:1x1x1 (deviceless AOT, local libtpu)",
                "device_kind": getattr(dev, "device_kind", "?"),
                "flops_per_step": flops,
                "bytes_accessed": bytes_accessed,
                "hbm_bytes": hbm_bytes,
                "hbm_gib": round(hbm_bytes / 1024**3, 3),
                "hbm_fits_v5e": hbm_bytes < peaks.hbm_bytes,
                "dtype": dtype_key,
                # step-time band, not a point estimate: the compute floor
                # assumes MFU=1; the bandwidth figure charges XLA's
                # PRE-FUSION "bytes accessed" (every op's operands+results)
                # entirely to HBM, which overstates real traffic — the
                # measured step lands between the two
                "roofline": {
                    "compute_floor_step_secs": round(compute_secs, 6),
                    "compute_floor_img_per_sec": (
                        round(BATCH / compute_secs, 1) if compute_secs else None
                    ),
                    "prefusion_bw_step_secs": round(memory_secs, 6),
                    "prefusion_bw_img_per_sec": (
                        round(BATCH / memory_secs, 1) if memory_secs else None
                    ),
                },
                "compile_secs": round(compile_secs, 1),
                "topology_secs": round(topo_secs, 1),
                # single source with the memo-key derivation: a child
                # whose self-report drifted from _aot_expected_config would
                # silently mis-key the committed memos
                "config": _aot_expected_config(),
            }
        )
    )


def _memo_path(config: dict, stem: str) -> str:
    """Default config memoizes to the committed ``<stem>.json``;
    exploration configs (BENCH_BATCH / BENCH_REMAT / BENCH_REMAT_POLICY /
    BENCH_FUSED / BENCH_PAIRED_HESSIAN overrides) get their own file so a
    scaling study can never
    clobber the artifact the driver's end-of-round bench relies on.  One
    tag builder for BOTH the AOT and on-chip-capture memos, so the two
    can never key differently for the same config."""
    default = {
        "batch": 8 if config["small_shapes"] else 64,
        "num_layers": config["num_layers"],
        "init_channels": config["init_channels"],
        "small_shapes": config["small_shapes"],
        "remat": False,
    }
    if config == default:
        name = f"{stem}.json"
    else:
        tag = f"b{config['batch']}" + ("_remat" if config.get("remat") else "")
        if config.get("remat_policy"):
            tag += f"_{config['remat_policy']}"
        if config.get("fused"):
            tag += "_fused"
        if config.get("paired_hessian"):
            tag += "_pairhess"
        name = f"{stem}_{tag}.json"
    return os.path.join(_HERE, "artifacts", "flagship", name)


def _aot_memo_path(config: dict) -> str:
    return _memo_path(config, "aot_v5e")


def _aot_expected_config() -> dict:
    """The config block the current env would produce (must match the
    child's self-report for a memoized result to be valid)."""
    small = parse_bool(os.environ.get("BENCH_SMALL"))
    remat = parse_bool(os.environ.get("BENCH_REMAT"))
    cfg = {
        "batch": int(os.environ.get("BENCH_BATCH", "8" if small else "64")),
        "num_layers": 2 if small else 8,
        "init_channels": 4 if small else 16,
        "small_shapes": small,
        "remat": remat,
    }
    if os.environ.get("BENCH_REMAT_POLICY"):
        cfg["remat_policy"] = os.environ["BENCH_REMAT_POLICY"]
    if parse_bool(os.environ.get("BENCH_FUSED")):
        cfg["fused"] = True
    if parse_bool(os.environ.get("BENCH_PAIRED_HESSIAN")):
        cfg["paired_hessian"] = True
    return cfg


def _bench_memo_path(config: dict) -> str:
    """Committed on-chip capture for this config (bench_tpu.json for the
    driver-metric default, suffixed files for exploration configs)."""
    return _memo_path(config, "bench_tpu")


def _persist_tpu_result(result: dict) -> None:
    """Write a successful full-shape on-chip measurement to the committed
    artifact so (a) mid-round captures survive, and (b) a driver-time
    wedge can fall back to the real number instead of a CPU stand-in
    (round-3 verdict: the official bench row never said "tpu" because the
    pool wedged exactly during the driver's capture window)."""
    if (
        result.get("platform") != "tpu"
        or "config" not in result  # warm-only results carry no config
        or result["config"].get("small_shapes")
    ):
        return
    try:
        import jax as _jax

        rec = dict(result)
        rec["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        rec["jax_version"] = _jax.__version__
        path = _bench_memo_path(rec["config"])
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(rec, f, indent=2)
        print(f"bench: on-chip capture persisted to {path}", file=sys.stderr)
    except OSError as e:
        print(f"bench: could not persist capture ({e})", file=sys.stderr)


def _committed_tpu_result() -> dict | None:
    """A committed on-chip capture matching the current config + jax
    version, or None.  Used ONLY when every live attempt failed: the
    emitted row keeps platform="tpu" (the number IS a chip measurement)
    with explicit provenance fields so nobody mistakes it for a live
    capture."""
    cfg = _aot_expected_config()
    try:
        with open(_bench_memo_path(cfg)) as f:
            memo = json.load(f)
        import jax as _jax

        if (
            memo.get("platform") == "tpu"
            and memo.get("config") == cfg
            and memo.get("jax_version") == _jax.__version__
        ):
            memo["from_committed_artifact"] = True
            memo["pool_wedged_at_capture_time"] = True
            return memo
    except (OSError, ValueError):
        pass
    return None


def _run_aot(timeout: float | None = None) -> dict | None:
    """Run the AOT compile-only child; returns its block or None.

    The child gets a scrubbed env: ``PALLAS_AXON_POOL_IPS`` removed so the
    sitecustomize never registers the axon plugin (nothing may touch the
    relay), plus the libtpu identity vars a deviceless topology needs.

    The result is memoized in ``artifacts/flagship/aot_v5e.json``: the
    block is pure static analysis of a fixed program, and the deviceless
    ``lower().compile()`` path bypasses JAX's persistent executable cache,
    so without the memo every bench invocation would re-pay the ~27 min
    full-size compile.  The memo is keyed on the config block and the
    jax version; ``BENCH_AOT_FRESH=1`` forces a recompile.
    """
    memo_path = _aot_memo_path(_aot_expected_config())
    if not parse_bool(os.environ.get("BENCH_AOT_FRESH")):
        try:
            with open(memo_path) as f:
                memo = json.load(f)
            import jax as _jax

            if (
                memo.get("config") == _aot_expected_config()
                and memo.get("jax_version") == _jax.__version__
            ):
                memo.setdefault("from_memo", True)
                return memo
        except (OSError, ValueError):
            pass
    if timeout is None:
        # the TPU-target compile of the full bilevel program is heavy
        # (~2.5 min at SMALL shapes); give full shapes real headroom
        timeout = float(os.environ.get("BENCH_AOT_TIMEOUT", "2700"))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")
    env.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--aot-child"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        print("bench: AOT compile-only child timed out", file=sys.stderr)
        return None
    for line in (out or "").splitlines():
        if line.startswith(_RESULT_TAG):
            try:
                block = json.loads(line[len(_RESULT_TAG):])
            except json.JSONDecodeError:
                continue
            if block.get("config") != _aot_expected_config():
                # the child resolved the env differently than the parent —
                # a memo written now would key to the wrong file and
                # clobber a committed fit-proof; keep the result, skip
                # the write
                print(
                    "bench: AOT child config "
                    f"{block.get('config')} != expected "
                    f"{_aot_expected_config()}; not memoizing",
                    file=sys.stderr,
                )
                return block
            try:  # memoize for the next invocation (see docstring)
                import jax as _jax

                block["jax_version"] = _jax.__version__
                os.makedirs(os.path.dirname(memo_path), exist_ok=True)
                with open(memo_path, "w") as f:
                    json.dump(block, f, indent=2)
            except OSError:
                pass
            return block
    print(
        f"bench: AOT compile-only child failed rc={proc.returncode}:\n"
        + (err or "")[-2000:],
        file=sys.stderr,
    )
    return None


def _amortize_child() -> None:
    """Compile-amortization probe child: wire the persistent cache the
    parent points at, run the packaged mnist prewarm twin once (trace +
    compile + first dispatch), and report how long that took.  Run twice
    against one cache dir by ``_run_compile_amortization``, the second
    process pays deserialization instead of XLA — the fleet-amortization
    effect ``katib-tpu prewarm`` and the in-run worker bank on."""
    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    from katib_tpu.compile import artifacts
    from katib_tpu.compile.prewarm import PrewarmRequest
    from katib_tpu.compile.registry import REGISTRY
    from katib_tpu.models.mnist import mnist_prewarm, mnist_trial
    from katib_tpu.runner.cohort import cohort_fn_of
    from katib_tpu.runner.trial_runner import init_compile_cache

    init_compile_cache(os.environ.get("KATIB_COMPILE_CACHE"))
    artifacts.ARTIFACTS.configure(None)  # KATIB_ARTIFACT_DIR (if any) wins
    shared = {
        "units": 16,
        "num_layers": 1,
        "n_train": 512,
        "n_test": 128,
        "batch_size": 64,
    }
    k = int(os.environ.get("BENCH_AMORTIZE_K", "4"))
    req = PrewarmRequest(
        train_fn=mnist_trial,
        shared=shared,
        k=k,
        program_fn=cohort_fn_of(mnist_trial) if k > 1 else None,
    )
    if os.environ.get("BENCH_AMORTIZE_MODE") == "fetch":
        # simulated new host: fresh local XLA cache, shared artifact tier
        # pre-published by the cold child — first step = fetch +
        # deserialize + one real dispatch of each loaded executable
        t0 = time.perf_counter()
        loaded = artifacts.ARTIFACTS.fetch_family(req.signature())
        for la in loaded:
            la(*la.dummy_args())
        _device_barrier(jax)
        first = time.perf_counter() - t0
        print(
            _RESULT_TAG
            + json.dumps(
                {
                    "first_step_secs": round(first, 4),
                    "fetched": len(loaded),
                    "registry_signatures": len(REGISTRY.signatures()),
                }
            )
        )
        return
    artifacts.clear_observed()
    t0 = time.perf_counter()
    mnist_prewarm(shared, k, None)
    # prewarm's dummy step is dispatched async; without the barrier this
    # timer measured trace+compile+enqueue, not the executed first step
    _device_barrier(jax)
    first = time.perf_counter() - t0
    # publish the observed programs into the artifact tiers (untimed: the
    # shared-fetch phase measures the consumer side)
    published = (
        artifacts.publish_observed(req.signature())
        if artifacts.ARTIFACTS.shared_dir()
        else 0
    )
    print(
        _RESULT_TAG
        + json.dumps(
            {
                "first_step_secs": round(first, 4),
                "published": published,
                "registry_signatures": len(REGISTRY.signatures()),
            }
        )
    )


def _run_compile_amortization() -> dict | None:
    """Cold-vs-warm first-step measurement (parent side): two child
    processes share one fresh persistent-cache dir; the first compiles,
    the second deserializes.  Memoized like the AOT block (the number is a
    property of the toolchain, not the pool) in
    ``artifacts/flagship/compile_amortization.json``;
    ``BENCH_AMORTIZE_FRESH=1`` forces a re-measure and
    ``BENCH_SKIP_PREWARM=1`` (checked by the caller) skips the block."""
    import tempfile

    expected = {
        "small_shapes": _SMALL,
        "k": int(os.environ.get("BENCH_AMORTIZE_K", "4")),
        # schema marker: memos measured before the shared-tier point
        # existed re-measure instead of reporting a two-phase block
        "tiers": "cold/warm/shared_fetch",
    }
    memo_path = os.path.join(
        _HERE, "artifacts", "flagship", "compile_amortization.json"
    )
    if not parse_bool(os.environ.get("BENCH_AMORTIZE_FRESH")):
        try:
            with open(memo_path) as f:
                memo = json.load(f)
            import jax as _jax

            if (
                memo.get("config") == expected
                and memo.get("jax_version") == _jax.__version__
            ):
                memo.setdefault("from_memo", True)
                return memo
        except (OSError, ValueError):
            pass
    env = dict(os.environ)
    # CPU children, relay scrubbed: the ratio measures the cache, and the
    # pool must not be touched (nor can a wedged pool break the block)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    def _phase(phase: str, env: dict) -> dict | None:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--amortize-child"],
                capture_output=True,
                text=True,
                env=env,
                timeout=float(os.environ.get("BENCH_AMORTIZE_TIMEOUT", "900")),
            )
        except subprocess.TimeoutExpired:
            print(
                f"bench: compile-amortization {phase} child timed out",
                file=sys.stderr,
            )
            return None
        block = None
        for line in (proc.stdout or "").splitlines():
            if line.startswith(_RESULT_TAG):
                try:
                    block = json.loads(line[len(_RESULT_TAG):])
                except json.JSONDecodeError:
                    continue
        if block is None:
            print(
                f"bench: compile-amortization {phase} child failed "
                f"rc={proc.returncode}:\n" + (proc.stderr or "")[-1500:],
                file=sys.stderr,
            )
        return block

    runs = []
    fetch_block = None
    with tempfile.TemporaryDirectory(prefix="katib-amortize-") as cache, \
            tempfile.TemporaryDirectory(prefix="katib-artifacts-") as artdir:
        env["KATIB_COMPILE_CACHE"] = cache
        for phase in ("cold", "warm"):
            penv = dict(env)
            penv.pop("BENCH_AMORTIZE_MODE", None)
            if phase == "cold":
                # the cold child publishes serialized executables into the
                # shared tier; warm stays artifact-blind so it measures the
                # pure persistent-XLA-cache deserialize path
                penv["KATIB_ARTIFACT_DIR"] = artdir
            else:
                penv.pop("KATIB_ARTIFACT_DIR", None)
            block = _phase(phase, penv)
            if block is None:
                return None
            runs.append(block)
        # simulated new host: FRESH local cache, only the shared artifact
        # tier pre-published — the zero-cold-start fleet point
        with tempfile.TemporaryDirectory(prefix="katib-newhost-") as fresh:
            fenv = dict(env)
            fenv["KATIB_COMPILE_CACHE"] = fresh
            fenv["KATIB_ARTIFACT_DIR"] = artdir
            fenv["BENCH_AMORTIZE_MODE"] = "fetch"
            fetch_block = _phase("shared_fetch", fenv)
    cold = float(runs[0]["first_step_secs"])
    warm = float(runs[1]["first_step_secs"])
    result = {
        "config": expected,
        "cold_first_step_secs": cold,
        "warm_first_step_secs": warm,
        "speedup": round(cold / warm, 2) if warm > 0 else None,
        "platform": "cpu",
    }
    if fetch_block is not None and fetch_block.get("fetched"):
        fetch = float(fetch_block["first_step_secs"])
        result["shared_fetch_first_step_secs"] = fetch
        result["shared_fetch_artifacts"] = int(fetch_block["fetched"])
        result["published_by_cold"] = int(runs[0].get("published", 0))
        # < 1 means fetching another host's executable beats even this
        # host's own persistent-cache deserialize; the acceptance bar is
        # "within 2x of local-warm" (vs the cold compile's much larger gap)
        result["fetch_vs_warm"] = round(fetch / warm, 2) if warm > 0 else None
    try:
        import jax as _jax

        result["jax_version"] = _jax.__version__
        os.makedirs(os.path.dirname(memo_path), exist_ok=True)
        with open(memo_path, "w") as f:
            json.dump(result, f, indent=2)
    except OSError:
        pass
    return result


def _child() -> None:
    """Runs in the spawned measurement process: init devices, build the
    full-size bilevel step, warm the compile cache, time it, print the
    result line tagged for the parent."""
    import threading

    import jax
    import jax.numpy as jnp

    # the axon PJRT plugin ignores the JAX_PLATFORMS env var; honor it
    # explicitly so BENCH_SMALL=1 JAX_PLATFORMS=cpu smoke tests work
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass  # cache flags are version-dependent; the bench still runs

    # in-child watchdog: the parent also enforces a deadline, but exiting
    # here gives it a clean "init timed out" signal instead of a SIGKILL
    init_done = threading.Event()
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "240"))

    def watchdog():
        if not init_done.wait(init_timeout):
            print(f"bench: device init exceeded {init_timeout:.0f}s", file=sys.stderr)
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    t_init0 = time.perf_counter()
    devices = jax.devices()
    init_done.set()
    init_secs = time.perf_counter() - t_init0  # lint: unguarded-ok(client/runtime init timing: jax.devices() dispatches no program)
    platform = devices[0].platform

    step, state, batch, net, remat = _build_flagship(jax, jnp)

    # XLA's own flop count for one step (per-device); basis for MFU.
    # The jitted dispatch path is ALSO the timed path: executing the
    # lower().compile() object directly under the axon relay returns
    # optimistically-resolved futures — block_until_ready comes back in
    # microseconds while the chip is still working, which once inflated
    # this benchmark 93x (5.8 ms/step reported vs 539 ms/step measured by
    # a host-fetch-forced probe AND by the flagship run's epoch math).
    from katib_tpu.costmodel.record import cost_of_compiled

    runner = jax.jit(step)
    # MFU numerator/denominator dtypes must match the COMPUTE dtype (the
    # supernet casts to its flax compute dtype internally — f32 inputs
    # still run bf16 matmuls)
    dtype_key = "bf16" if net.dtype == jnp.bfloat16 else "f32"
    cost_rec = None
    flops_per_step = 0.0
    compile_secs = 0.0
    try:
        lowered = runner.lower(state, batch, batch)
        t_c0 = time.perf_counter()
        compiled = lowered.compile()
        compile_secs = time.perf_counter() - t_c0  # lint: unguarded-ok(client-side compile is synchronous host work)
        cost_rec = cost_of_compiled(
            compiled, program="bench.step", dtype=dtype_key
        )
        flops_per_step = cost_rec.flops_per_step if cost_rec is not None else 0.0
    except Exception as e:  # cost analysis is backend-dependent
        print(f"bench: cost analysis unavailable ({e})", file=sys.stderr)

    # a tiny reduction whose result is FETCHED to the host ends the timed
    # section: real bytes computed on the chip cannot be faked by an
    # eagerly-resolved future (docs/performance.md, measurement integrity)
    @jax.jit
    def _redsum(s):
        return sum(
            jnp.sum(a.astype(jnp.float32)) for a in jax.tree_util.tree_leaves(s)
        )

    for _ in range(WARMUP_STEPS):
        state, metrics = runner(state, batch, batch)
    float(_redsum(metrics))  # warm the reducer too
    jax.block_until_ready(state)  # warmup fully retired before the clock starts

    if parse_bool(os.environ.get("BENCH_WARM_ONLY")):
        print(
            _RESULT_TAG
            + json.dumps(
                {
                    "warm_only": True,
                    "platform": platform,
                    "init_secs": round(init_secs, 1),
                    "compile_secs": round(compile_secs, 1),
                }
            )
        )
        return

    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = runner(state, batch, batch)
    float(_redsum(metrics))  # host fetch = the clock cannot stop early
    jax.block_until_ready(state)  # and the carry itself is retired (JAX105)
    dt = time.perf_counter() - t0

    img_per_sec = BATCH * TIMED_STEPS / dt
    step_secs = dt / TIMED_STEPS

    # fused-loop point: the SAME step folded lax.scan-style into one
    # dispatch per window (the search's default execution path since the
    # device-resident step loop flip) — the per-dispatch Python/transfer
    # overhead the eager numbers above pay per STEP is paid once per
    # WINDOW here, so (fused - eager) is the measured dispatch tax the
    # ROADMAP item-1 10x target collects on.  BENCH_STEP_LOOP_WINDOW
    # overrides the fold (default: TIMED_STEPS, one dispatch per timing).
    loop_window = max(
        1, int(os.environ.get("BENCH_STEP_LOOP_WINDOW", str(TIMED_STEPS)))
    )

    def _fused_loop(s, b):
        def body(c, _):
            c, m = step(c, b, b)
            return c, m["train_loss"]

        return jax.lax.scan(body, s, None, length=loop_window)

    loop_runner = jax.jit(_fused_loop, donate_argnums=(0,))
    t_lc0 = time.perf_counter()
    state, losses = loop_runner(state, batch)
    float(jnp.sum(losses))  # warm: trace+compile+first execution
    jax.block_until_ready(state)
    loop_compile_secs = time.perf_counter() - t_lc0
    loop_dispatches = max(1, TIMED_STEPS // loop_window)
    t_l0 = time.perf_counter()
    for _ in range(loop_dispatches):
        state, losses = loop_runner(state, batch)
    float(jnp.sum(losses))  # host fetch, same integrity rule as above
    jax.block_until_ready(state)  # donated carry retired before the clock stops
    loop_dt = time.perf_counter() - t_l0
    loop_steps = loop_window * loop_dispatches
    loop_img_per_sec = BATCH * loop_steps / loop_dt
    loop_step_secs = loop_dt / loop_steps
    from katib_tpu.costmodel.peaks import peaks_for

    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peaks = peaks_for(gen)  # unknown generations fall back to v5e
    mfu = cost_rec.mfu(step_secs, peaks) if cost_rec is not None else 0.0
    fused_note = (
        {
            "flops_note": (
                "cost-analysis flops for the fused plan count the masked "
                "grouped convs as if dense (13x the unfused program's "
                "count, aot_v5e_b64_fused.json vs aot_v5e.json) — compare "
                "plans by img/s, not MFU"
            )
        }
        if parse_bool(os.environ.get("BENCH_FUSED"))
        else {}
    )
    print(
        _RESULT_TAG
        + json.dumps(
            {
                "metric": "darts_bilevel_search_throughput",
                **fused_note,
                "value": round(float(img_per_sec), 2),
                "unit": "images/sec",
                "vs_baseline": round(float(img_per_sec) / REFERENCE_IMG_PER_SEC, 3),
                "mfu": round(mfu, 6),
                "dtype": dtype_key,
                "platform": platform,
                "step_secs": round(step_secs, 4),
                # the eager numbers above dispatch one step per host call
                "steps_per_dispatch": 1,
                "fused_loop": {
                    "metric": "darts_fused_loop_throughput",
                    "value": round(float(loop_img_per_sec), 2),
                    "unit": "images/sec",
                    "step_secs": round(loop_step_secs, 4),
                    "steps_per_dispatch": loop_window,
                    "dispatches": loop_dispatches,
                    "compile_secs": round(loop_compile_secs, 1),
                    "mfu": round(
                        cost_rec.mfu(loop_step_secs, peaks)
                        if cost_rec is not None
                        else 0.0,
                        6,
                    ),
                },
                "flops_per_step": flops_per_step,
                "init_secs": round(init_secs, 1),
                "compile_secs": round(compile_secs, 1),
                # self-reported so recorded provenance can never drift from
                # what actually ran
                # single source with the memo-key derivation: a child
                # whose self-report drifted from _aot_expected_config would
                # silently mis-key the committed memos
                "config": _aot_expected_config(),
            }
        )
    )


def _cohort_child() -> None:
    """Measure serial vs vmap-cohort trial throughput (runner/cohort.py's
    optimization) in the regime it targets: per-step jitted dispatch of a
    tiny model, where Python/runtime dispatch — not FLOPs — bounds a sweep.
    K serial trials pay K×steps dispatches; one cohort pays steps dispatches
    of a [K]-batched program.  Prints one tagged JSON line with
    serial/cohort trials-per-sec and the speedup."""
    import jax
    import jax.numpy as jnp
    import optax

    from katib_tpu.parallel.mesh import (
        TRIAL_AXIS,
        make_mesh,
        padded_cohort_size,
        shard_members,
    )
    from katib_tpu.parallel.train import (
        TrainState,
        make_cohort_train_step,
        make_train_step,
        stack_pytrees,
    )

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    platform = jax.devices()[0].platform

    k = int(os.environ.get("BENCH_COHORT_K", "8"))
    steps = int(os.environ.get("BENCH_COHORT_STEPS", "50" if _SMALL else "200"))
    devices = int(os.environ.get("BENCH_COHORT_DEVICES", "1"))
    mesh = None
    if devices > 1:
        devs = jax.devices()
        if len(devs) < devices:
            # a backend that ignores the forced-host-platform flag (real
            # TPU pool) can't carve the trial axis; fall back honestly
            print(
                f"bench: only {len(devs)} devices for --cohort-devices "
                f"{devices}; measuring single-device cohort",
                file=sys.stderr,
            )
            devices = 1
        else:
            mesh = make_mesh({TRIAL_AXIS: devices}, devices=devs[:devices])
    dim, nbatch = 32, 256

    key = jax.random.PRNGKey(0)
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (nbatch, dim), jnp.float32)
    y = jnp.sum(x, axis=1, keepdims=True)
    batch = (x, y)

    def loss_fn(params, b):
        xb, yb = b
        return jnp.mean((xb @ params["w"] + params["b"] - yb) ** 2)

    # same inject_hyperparams seam the mnist sweep uses: lr is a runtime
    # operand, so serial AND cohort each compile exactly one program
    tx = optax.inject_hyperparams(optax.sgd)(learning_rate=0.0)
    params = {
        "w": jax.random.normal(kw, (dim, 1), jnp.float32) * 0.01,
        "b": jnp.zeros((1,), jnp.float32),
    }
    lrs = [0.001 * (i + 1) for i in range(k)]

    def member_state(lr):
        # fresh buffers per member: the step donates its state input, and a
        # donated buffer shared with `params` would poison later members
        p = jax.tree_util.tree_map(jnp.array, params)
        s = TrainState.create(p, tx)
        hp = dict(s.opt_state.hyperparams)
        hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
        return s._replace(opt_state=s.opt_state._replace(hyperparams=hp))

    # ghost-pad the member dimension to fill the trial axis (k itself stays
    # the trials/sec denominator — ghosts are execution filler, not trials)
    k_exec = padded_cohort_size(k, mesh)
    exec_lrs = lrs + lrs[: k_exec - k]

    def cohort_state():
        s = stack_pytrees([TrainState.create(params, tx)] * k_exec)
        hp = dict(s.opt_state.hyperparams)
        hp["learning_rate"] = jnp.asarray(exec_lrs, jnp.float32)
        s = s._replace(opt_state=s.opt_state._replace(hyperparams=hp))
        return shard_members(s, mesh) if mesh is not None else s

    serial_step = make_train_step(loss_fn, tx)
    cohort_step = make_cohort_train_step(loss_fn, tx, mesh=mesh)
    cohort_batch = (
        jax.device_put(batch, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()
        ))
        if mesh is not None
        else batch
    )

    # warm both traces outside the clocks (steps donate their state input)
    s = member_state(0.01)
    for _ in range(3):
        s, _m = serial_step(s, batch)
    jax.block_until_ready(s)
    c = cohort_state()
    for _ in range(3):
        c, _m = cohort_step(c, cohort_batch)
    jax.block_until_ready(c)

    t0 = time.perf_counter()
    finals = []
    for lr in lrs:
        s = member_state(lr)
        for _ in range(steps):
            s, _m = serial_step(s, batch)
        finals.append(s)
    jax.block_until_ready(finals)
    t_serial = time.perf_counter() - t0

    t0 = time.perf_counter()
    c = cohort_state()
    for _ in range(steps):
        c, _m = cohort_step(c, cohort_batch)
    jax.block_until_ready(c)
    t_cohort = time.perf_counter() - t0

    serial_tps = k / t_serial
    cohort_tps = k / t_cohort
    print(
        _RESULT_TAG
        + json.dumps(
            {
                "metric": "cohort_vmap_trial_throughput",
                "serial_trials_per_sec": round(serial_tps, 3),
                "cohort_trials_per_sec": round(cohort_tps, 3),
                "speedup": round(cohort_tps / serial_tps, 2),
                "k": k,
                "devices": devices,
                "members_per_device": k_exec // max(devices, 1),
                "steps": steps,
                "platform": platform,
            }
        )
    )


def _run_cohort() -> None:
    """Parent side of ``--cohort``: run the measurement in a child (scrubbed
    env, CPU by default — this is a dispatch-overhead benchmark, not a chip
    benchmark) and print its JSON line.  ``--cohort-devices N`` shards the
    cohort's trial axis over N virtual CPU devices (the child gets the
    forced-host-platform flag), recording trials/sec vs device count."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the relay
    env.setdefault("JAX_PLATFORMS", "cpu")
    if "--cohort-devices" in sys.argv:
        try:
            n = int(sys.argv[sys.argv.index("--cohort-devices") + 1])
        except (IndexError, ValueError):
            print("bench: --cohort-devices needs an integer", file=sys.stderr)
            sys.exit(2)
        env["BENCH_COHORT_DEVICES"] = str(n)
        flags = env.get("XLA_FLAGS", "")
        if n > 1 and "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--cohort-child"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )
    try:
        out, err = proc.communicate(timeout=900)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        print("bench: cohort child timed out", file=sys.stderr)
        sys.exit(3)
    for line in (out or "").splitlines():
        if line.startswith(_RESULT_TAG):
            try:
                result = json.loads(line[len(_RESULT_TAG):])
            except json.JSONDecodeError:
                continue
            print(json.dumps(result))
            return
    print(
        f"bench: cohort child failed rc={proc.returncode}:\n" + (err or "")[-2000:],
        file=sys.stderr,
    )
    sys.exit(3)


def _async_occupancy_child() -> None:
    """Measure the async orchestrator (orchestrator/async_loops.py) against
    the synchronous loop in the regime it targets: a slow suggester (default
    0.5 s per call — a remote BO service or heavy acquisition optimizer)
    feeding short trials.  The sync loop pays the suggester on the dispatch
    critical path once per batch; the async loop banks ``suggestLookahead``
    proposals so the mesh never waits.  Prints one tagged JSON line with
    sync/async trials-per-sec, the speedup, and sustained occupancy."""
    import tempfile
    import time as _time

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.orchestrator import Orchestrator
    from katib_tpu.orchestrator import orchestrator as orch_mod
    from katib_tpu.suggest.base import make_suggester as _real_make

    trials = int(os.environ.get("BENCH_ASYNC_TRIALS", "1000"))
    delay = float(os.environ.get("BENCH_ASYNC_SUGGEST_DELAY", "0.5"))
    train_secs = float(os.environ.get("BENCH_ASYNC_TRAIN_SECS", "0.2"))
    parallel = int(os.environ.get("BENCH_ASYNC_PARALLEL", "8"))

    def train_fn(ctx):
        _time.sleep(train_secs)
        ctx.report(step=1, loss=float(ctx.params["x"]) ** 2)

    class _Delayed:
        def __init__(self, inner):
            self.inner = inner
            self.adaptive = inner.adaptive
            self.spec = inner.spec
            self.calls = 0

        def get_suggestions(self, experiment, count):
            self.calls += 1
            _time.sleep(delay)
            return self.inner.get_suggestions(experiment, count)

    def sweep(mode: str) -> dict:
        spec = ExperimentSpec(
            name=f"bench-async-{mode}",
            objective=ObjectiveSpec(
                type=ObjectiveType.MINIMIZE, objective_metric_name="loss"
            ),
            algorithm=AlgorithmSpec(name="random", settings={"seed": "7"}),
            parameters=[
                ParameterSpec(
                    "x", ParameterType.DOUBLE, FeasibleSpace(min=-1.0, max=1.0)
                )
            ],
            train_fn=train_fn,
            parallel_trial_count=parallel,
            max_trial_count=trials,
            async_orch=(mode == "async"),
        )
        suggester_calls = []
        orig = orch_mod.make_suggester

        def delayed_make(s):
            d = _Delayed(_real_make(s))
            suggester_calls.append(d)
            return d

        with tempfile.TemporaryDirectory() as wd:
            orch_mod.make_suggester = delayed_make
            try:
                t0 = _time.perf_counter()
                orch = Orchestrator(workdir=wd)
                exp = orch.run(spec)
                # trials may have enqueued device work (here they sleep,
                # but the number must survive a real train_fn): quiesce
                # the stream before the clock stops
                import jax

                _device_barrier(jax)
                elapsed = _time.perf_counter() - t0
            finally:
                orch_mod.make_suggester = orig
        settled = sum(
            1 for t in exp.trials.values() if t.condition.is_terminal()
        )
        block = {
            "mode": mode,
            "trials": settled,
            "elapsed_secs": round(elapsed, 3),
            "trials_per_sec": round(settled / elapsed, 3),
            # slot-time actually spent training / slot-time available: an
            # apples-to-apples occupancy both loops can be scored on
            "derived_occupancy": round(
                settled * train_secs / (elapsed * parallel), 4
            ),
            "suggester_calls": suggester_calls[0].calls if suggester_calls else 0,
            "condition": exp.condition.value,
        }
        if orch.async_stats is not None:
            block["sustained_occupancy"] = orch.async_stats["sustained_occupancy"]
            block["lookahead"] = orch.async_stats["lookahead"]
            # supervision summary: a benched run that silently burned loop
            # restarts (or fell back to sync) is not a clean measurement
            block["loop_restarts"] = orch.async_stats["loop_restarts"]
            block["fallback"] = orch.async_stats["fallback"]
        return block

    sync = sweep("sync")
    async_ = sweep("async")
    result = {
        "benchmark": "async_occupancy",
        "platform": "cpu",
        "suggest_delay_secs": delay,
        "train_secs": train_secs,
        "parallel_trial_count": parallel,
        "sync": sync,
        "async": async_,
        "speedup": round(async_["trials_per_sec"] / sync["trials_per_sec"], 3),
        "note": (
            "dispatch-overhead benchmark on CPU: trials sleep "
            f"{train_secs}s, the suggester {delay}s/call; measures the "
            "control plane, not the chip"
        ),
    }
    print(_RESULT_TAG + json.dumps(result))


def _run_async_occupancy() -> None:
    """Parent side of ``--async-occupancy``: run the sync-vs-async sweep in
    a scrubbed-env CPU child and print its JSON line."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the relay
    env.pop("KATIB_ASYNC_ORCH", None)  # the spec flag drives each arm
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--async-occupancy-child"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )
    try:
        out, err = proc.communicate(timeout=1800)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        print("bench: async-occupancy child timed out", file=sys.stderr)
        sys.exit(3)
    for line in (out or "").splitlines():
        if line.startswith(_RESULT_TAG):
            try:
                result = json.loads(line[len(_RESULT_TAG):])
            except json.JSONDecodeError:
                continue
            print(json.dumps(result))
            return
    print(
        f"bench: async-occupancy child failed rc={proc.returncode}:\n"
        + (err or "")[-2000:],
        file=sys.stderr,
    )
    sys.exit(3)


def _pbt_child() -> None:
    """Host-vs-on-device PBT A/B (parallel/pbt.py): the same digits
    workload evolved by the host ``pbt`` suggester (one orchestrator trial
    per member per generation, exploit = Orbax checkpoint copy) and by
    ``pbt-ondevice`` (the whole population as one stacked cohort, selection
    an on-device permutation inside the compiled generation step).  Equal
    training compute per arm: population × generations × steps SGD steps
    at the same batch.  Prints one tagged JSON line with generations/sec,
    population-img/sec, and the speedup."""
    import tempfile
    import time as _time

    from katib_tpu.core.types import (
        AlgorithmSpec,
        ExperimentSpec,
        FeasibleSpace,
        ObjectiveSpec,
        ObjectiveType,
        ParameterSpec,
        ParameterType,
    )
    from katib_tpu.models.pbt_digits import pbt_digits_trial
    from katib_tpu.orchestrator import Orchestrator

    population = int(os.environ.get("BENCH_PBT_POPULATION", "16"))
    generations = int(os.environ.get("BENCH_PBT_GENERATIONS", "10"))
    steps = int(os.environ.get("BENCH_PBT_STEPS", "300"))
    batch = 64  # pbt_digits default on both paths

    def host_train(ctx):
        # pin the per-round budget so both arms do identical training work
        ctx.params.setdefault("steps_per_round", steps)
        ctx.params.setdefault("batch", batch)
        pbt_digits_trial(ctx)

    def sweep(mode: str) -> dict:
        settings = {
            "n_population": str(population),
            "truncation_threshold": "0.25",
            "random_state": "7",
        }
        if mode == "ondevice":
            settings["generations"] = str(generations)
            settings["steps_per_generation"] = str(steps)
            algo, max_trials, train_fn = "pbt-ondevice", population, pbt_digits_trial
        else:
            # host turnover: one pool of `population` trials per generation
            algo, max_trials, train_fn = "pbt", population * generations, host_train
        with tempfile.TemporaryDirectory() as wd:
            if mode != "ondevice":
                settings["suggestion_trial_dir"] = os.path.join(wd, "lineage")
            spec = ExperimentSpec(
                name=f"bench-pbt-{mode}",
                objective=ObjectiveSpec(
                    type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
                ),
                algorithm=AlgorithmSpec(name=algo, settings=settings),
                parameters=[
                    ParameterSpec(
                        "lr", ParameterType.DOUBLE, FeasibleSpace(min=0.005, max=0.5)
                    )
                ],
                train_fn=train_fn,
                parallel_trial_count=population,
                max_trial_count=max_trials,
            )
            t0 = _time.perf_counter()
            exp = Orchestrator(workdir=wd).run(spec)
            import jax

            _device_barrier(jax)
            elapsed = _time.perf_counter() - t0
        settled = sum(1 for t in exp.trials.values() if t.condition.is_terminal())
        metric_name = spec.objective.objective_metric_name
        best = max(
            (
                m.value
                for t in exp.trials.values()
                if t.observation is not None
                for m in [t.observation.get(metric_name)]
                if m is not None
            ),
            default=None,
        )
        return {
            "mode": mode,
            "trials": settled,
            "generations": generations,
            "elapsed_secs": round(elapsed, 3),
            "generations_per_sec": round(generations / elapsed, 4),
            "population_imgs_per_sec": round(
                population * generations * steps * batch / elapsed, 1
            ),
            "best_accuracy": round(float(best), 4) if best is not None else None,
            "condition": exp.condition.value,
        }

    host = sweep("host")
    ondevice = sweep("ondevice")
    result = {
        "benchmark": "pbt_ondevice",
        "platform": "cpu",
        "population": population,
        "generations": generations,
        "steps_per_generation": steps,
        "batch": batch,
        "host": host,
        "ondevice": ondevice,
        "speedup": round(
            ondevice["generations_per_sec"] / host["generations_per_sec"], 3
        ),
        "note": (
            "same digits workload and per-member compute on CPU; host pays "
            "per-trial dispatch + Orbax checkpoint copies per generation, "
            "on-device runs the population as one compiled scan with "
            "selection as an in-program permutation"
        ),
    }
    print(_RESULT_TAG + json.dumps(result))


def _run_pbt() -> None:
    """Parent side of ``--pbt``: run the host-vs-on-device PBT A/B in a
    scrubbed-env CPU child and print its JSON line."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the relay
    env.pop("KATIB_ASYNC_ORCH", None)
    env.pop("KATIB_PBT_ONDEVICE", None)  # the algorithm name drives each arm
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--pbt-child"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )
    try:
        out, err = proc.communicate(timeout=1800)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        print("bench: pbt child timed out", file=sys.stderr)
        sys.exit(3)
    for line in (out or "").splitlines():
        if line.startswith(_RESULT_TAG):
            try:
                result = json.loads(line[len(_RESULT_TAG):])
            except json.JSONDecodeError:
                continue
            print(json.dumps(result))
            return
    print(
        f"bench: pbt child failed rc={proc.returncode}:\n" + (err or "")[-2000:],
        file=sys.stderr,
    )
    sys.exit(3)


def _run_attempt(
    deadline: float, env: dict | None = None
) -> tuple[int, dict | None, str]:
    """One measurement attempt in a child process.  Returns
    (returncode, parsed result or None, stderr tail)."""
    child_env = dict(os.environ if env is None else env)
    # local AOT compile by default — the terminal-side compile path is both
    # slow (minutes/op over the tunnel) and wedge-prone (see module doc).
    # The ambient env exports PALLAS_AXON_REMOTE_COMPILE=1, so this must
    # override, not setdefault; KATIB_REMOTE_COMPILE=1 restores remote
    # (read from child_env too so the retry loop can flip it per-attempt
    # after a libtpu-mismatch failure).
    remote = (
        parse_bool(child_env.get("KATIB_REMOTE_COMPILE"))
        or remote_compile_requested()
    )
    child_env["PALLAS_AXON_REMOTE_COMPILE"] = "1" if remote else "0"
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=child_env,
    )
    try:
        out, err = proc.communicate(timeout=deadline)
    except subprocess.TimeoutExpired:
        proc.kill()  # safe: a client blocked in init holds no grant
        out, err = proc.communicate()
        return -9, None, (err or "")[-2000:]
    result = None
    for line in (out or "").splitlines():
        if line.startswith(_RESULT_TAG):
            try:
                result = json.loads(line[len(_RESULT_TAG):])
            except json.JSONDecodeError:
                pass
        else:
            # forward the child's informational stdout
            print(line, file=sys.stderr)
    return proc.returncode, result, (err or "")[-2000:]


def main() -> None:
    if "--child" in sys.argv:
        _child()
        return
    if "--aot-child" in sys.argv:
        _aot_child()
        return
    if "--amortize-child" in sys.argv:
        _amortize_child()
        return
    if "--cohort-child" in sys.argv:
        _cohort_child()
        return
    if "--cohort" in sys.argv:
        _run_cohort()
        return
    if "--async-occupancy-child" in sys.argv:
        _async_occupancy_child()
        return
    if "--async-occupancy" in sys.argv:
        _run_async_occupancy()
        return
    if "--pbt-child" in sys.argv:
        _pbt_child()
        return
    if "--pbt" in sys.argv:
        _run_pbt()
        return

    retries = int(os.environ.get("BENCH_RETRIES", "3"))
    backoff = float(os.environ.get("BENCH_RETRY_BACKOFF", "45"))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "3600"))

    # Device preflight before any timed phase: a bounded probe of every
    # visible device in a killable child (utils/meshhealth.py).  A wedged
    # pool — the exact failure that produced the empty r01–r04 rounds — now
    # yields a diagnosable partial artifact with a per-device health block
    # and exit 3 in ~BENCH_PREFLIGHT_DEADLINE seconds, instead of burning
    # retries × attempt_timeout hanging in device init.
    health = None
    if not parse_bool(os.environ.get("BENCH_SKIP_PREFLIGHT")):
        from katib_tpu.utils import meshhealth

        pf_deadline = float(os.environ.get("BENCH_PREFLIGHT_DEADLINE", "120"))
        # BENCH_SIMULATE_WEDGE=0,1 rehearses the wedged-pool path (CI smoke)
        sim = [
            int(x)
            for x in os.environ.get("BENCH_SIMULATE_WEDGE", "").split(",")
            if x.strip()
        ]
        pf_report = meshhealth.doctor_report(
            deadline=pf_deadline, simulate_wedge=sim or None
        )
        health = pf_report.to_dict()
        print(f"bench: preflight {pf_report.summary()}", file=sys.stderr)
        if not pf_report.ok():
            aot_block = None
            if not parse_bool(os.environ.get("BENCH_SKIP_AOT")):
                aot_block = _run_aot()  # deviceless: safe on a wedged pool
            committed = _committed_tpu_result()
            if committed is not None:
                committed["live_failure_rc"] = 3
                committed["health"] = health
                if aot_block is not None:
                    committed["aot_tpu"] = aot_block
                print(
                    "bench: preflight says the pool is wedged but a committed "
                    "on-chip capture of this exact config exists — emitting it",
                    file=sys.stderr,
                )
                print(json.dumps(committed))
                return
            _emit_aot_only(aot_block, 3, health=health)
            sys.exit(3)

    # Pool-proof evidence first: AOT-compile the full-size program against
    # a deviceless v5e topology.  Never touches the relay, and pins
    # flops/HBM/roofline even if every on-chip attempt fails.  A warm
    # persistent cache makes this ~1 min; a COLD full-shape compile runs
    # ~27 min on this host (hence BENCH_AOT_TIMEOUT=2700 and the
    # BENCH_SKIP_AOT=1 escape for smoke tests).
    aot_block = None
    if not parse_bool(os.environ.get("BENCH_SKIP_AOT")):
        aot_block = _run_aot()
        if aot_block is not None:
            print(
                "bench: AOT v5e compile ok — "
                f"hbm={aot_block['hbm_gib']} GiB "
                f"(fits={aot_block['hbm_fits_v5e']}), "
                f"step band [{aot_block['roofline']['compute_floor_step_secs']}, "
                f"{aot_block['roofline']['prefusion_bw_step_secs']}] s",
                file=sys.stderr,
            )

    # Compile amortization: cold vs warm first step through the persistent
    # cache (two CPU children, one cache dir).  CPU-only and pool-proof,
    # memoized; BENCH_SKIP_PREWARM=1 skips it for smoke tests.
    amortize_block = None
    if not parse_bool(os.environ.get("BENCH_SKIP_PREWARM")):
        amortize_block = _run_compile_amortization()
        if amortize_block is not None:
            fetch = amortize_block.get("shared_fetch_first_step_secs")
            print(
                "bench: compile amortization — cold "
                f"{amortize_block['cold_first_step_secs']}s vs warm "
                f"{amortize_block['warm_first_step_secs']}s "
                f"({amortize_block['speedup']}x)"
                + (
                    f", new-host shared fetch {fetch}s "
                    f"({amortize_block['fetch_vs_warm']}x of warm)"
                    if fetch is not None
                    else ""
                ),
                file=sys.stderr,
            )

    last_rc, last_err = 0, ""
    saw_wedge = False
    extra_env: dict[str, str] = {}
    for attempt in range(1, retries + 1):
        env = {**os.environ, **extra_env} if extra_env else None
        rc, result, err = _run_attempt(attempt_timeout, env=env)
        if result is not None:
            _persist_tpu_result(result)
            if aot_block is not None:
                result["aot_tpu"] = aot_block
            if amortize_block is not None:
                result["compile_amortization"] = amortize_block
            if health is not None:
                result["health"] = health
            print(json.dumps(result))
            return
        last_rc, last_err = rc, err
        wedged = rc in (3, -9)
        saw_wedge = saw_wedge or wedged
        mismatch = "libtpu version mismatch" in (err or "")
        print(
            f"bench: attempt {attempt}/{retries} failed rc={rc}"
            + (" (device init blocked — TPU pool wedged?)" if wedged else "")
            + (f"\n{err}" if err else ""),
            file=sys.stderr,
        )
        if mismatch and attempt < retries:
            # the terminal runs a different libtpu build than the local
            # wheel and rejects locally-compiled executables outright;
            # compiling on the terminal sidesteps the version skew
            print(
                "bench: local libtpu does not match the terminal runtime; "
                "switching to terminal-side compile (KATIB_REMOTE_COMPILE=1)",
                file=sys.stderr,
            )
            extra_env["KATIB_REMOTE_COMPILE"] = "1"
            continue  # config flip, not pool recovery — no backoff needed
        elif (
            attempt < retries
            and not wedged
            and not parse_bool(os.environ.get("BENCH_REMAT"))
            and "BENCH_REMAT" not in extra_env
        ):
            # the child ran but crashed — plausibly HBM exhaustion from the
            # no-recompute default; retry with activation checkpointing
            print(
                "bench: retrying with BENCH_REMAT=1 (activation recompute) "
                "in case the failure was memory",
                file=sys.stderr,
            )
            extra_env["BENCH_REMAT"] = "1"
        if attempt < retries:
            time.sleep(backoff)
    # a committed on-chip capture of THIS config beats any CPU stand-in —
    # but ONLY when the failures look like a wedged pool (rc 3 / SIGKILL
    # on device init).  A bench-code regression (other rcs) must stay
    # loudly broken, not be masked by an old healthy number.
    committed = _committed_tpu_result() if saw_wedge else None
    if committed is not None:
        committed["live_failure_rc"] = last_rc
        print(
            f"bench: all {retries} live attempts failed (last rc={last_rc}) "
            "but a committed on-chip capture of this exact config exists — "
            f"emitting it (measured_at={committed.get('measured_at')})",
            file=sys.stderr,
        )
        if aot_block is not None:
            committed["aot_tpu"] = aot_block
        if amortize_block is not None:
            committed["compile_amortization"] = amortize_block
        if health is not None:
            committed["health"] = health
        print(json.dumps(committed))
        return
    print(
        f"bench: all {retries} attempts failed (last rc={last_rc}); "
        "the TPU pool looks wedged (stale grant on the axon relay) — "
        "falling back to an honestly-labeled CPU measurement "
        "(platform/tpu_unavailable fields mark it; set BENCH_NO_FALLBACK=1 "
        "to get exit 3 instead)",
        file=sys.stderr,
    )
    if parse_bool(os.environ.get("BENCH_NO_FALLBACK")):
        _emit_aot_only(aot_block, last_rc, health=health)
        sys.exit(3)
    # honest fallback: a real measurement of the same step at reduced shapes
    # on CPU, explicitly labeled — a recorded number the reader can see is
    # not a TPU number beats an empty round
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SMALL"] = "1"  # full shapes would take hours on CPU
    rc, result, err = _run_attempt(1800.0, env=env)
    if result is not None:
        result["tpu_unavailable"] = True
        result["tpu_failure"] = f"rc={last_rc}"
        # small-shape CPU numbers are not comparable to the full-shape
        # baseline ratio, and MFU against a TPU peak is meaningless on CPU
        result["vs_baseline"] = None
        result["mfu"] = None
        if aot_block is not None:
            # ...but the deviceless v5e compile is still real TPU evidence:
            # the full-size program's flops, HBM fit, and roofline ceiling
            result["aot_tpu"] = aot_block
        if amortize_block is not None:
            result["compile_amortization"] = amortize_block
        if health is not None:
            result["health"] = health
        print(json.dumps(result))
        return
    print(f"bench: CPU fallback also failed rc={rc}:\n{err}", file=sys.stderr)
    _emit_aot_only(aot_block, last_rc, health=health)
    sys.exit(3)


def _emit_aot_only(
    aot_block: dict | None, last_rc: int, health: dict | None = None
) -> None:
    """Total-failure exits still print the diagnosable evidence: a JSON line
    carrying the deviceless v5e compile block (no measured value) and the
    per-device preflight health report, so the round's record keeps the
    flops/HBM/roofline facts — and WHY nothing executed — even when nothing
    could execute anywhere."""
    if aot_block is None and health is None:
        return
    blob = {
        "metric": "darts_bilevel_search_throughput",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "mfu": None,
        "tpu_unavailable": True,
        "tpu_failure": f"rc={last_rc}",
        "execution_failed": True,
    }
    if aot_block is not None:
        blob["aot_tpu"] = aot_block
    if health is not None:
        blob["health"] = health
    print(json.dumps(blob))


if __name__ == "__main__":
    main()
