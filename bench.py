"""Benchmark: DARTS supernet bilevel-search throughput on the local accelerator.

Times the flagship compute path — the second-order (unrolled + Hessian
correction) DARTS search step at the reference's CIFAR-10 configuration
(batch 64, 8 layers, 16 init channels; ``darts-cnn-cifar10/run_trial.py``) —
and prints ONE JSON line.

``vs_baseline`` compares images/sec against the reference PyTorch trial image
running the same second-order search on its CI GPU class (~250 img/s on a
V100-16GB for batch-64 second-order DARTS, derived from the DARTS paper's
1-day/4-epoch-search economics; the reference repo publishes no numbers —
BASELINE.json ``published`` is empty).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp

REFERENCE_IMG_PER_SEC = 250.0

# full size by default (the driver's TPU run); BENCH_SMALL=1 shrinks the
# supernet so a CPU smoke test finishes in seconds
_SMALL = os.environ.get("BENCH_SMALL", "") not in ("", "0")
BATCH = 8 if _SMALL else 64
NUM_LAYERS = 2 if _SMALL else 8
INIT_CHANNELS = 4 if _SMALL else 16
N_NODES = 2 if _SMALL else 4
WARMUP_STEPS = 1 if _SMALL else 3
TIMED_STEPS = 3 if _SMALL else 20


def main() -> None:
    # the axon PJRT plugin ignores the JAX_PLATFORMS env var; honor it
    # explicitly so BENCH_SMALL=1 JAX_PLATFORMS=cpu smoke tests work
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    # persistent compilation cache: the bilevel DARTS step is a large XLA
    # graph; warming the cache once makes every later bench run (and the
    # driver's end-of-round run) skip the multi-minute compile
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:
        pass  # cache flags are version-dependent; the bench still runs

    # device-init watchdog: a wedged TPU pool makes jax.devices() block
    # forever (stale grant on the axon relay); fail fast instead of hanging
    # the driver's bench run
    init_done = threading.Event()
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "300"))

    def watchdog():
        if not init_done.wait(init_timeout):
            print(
                f"bench: device init did not complete in {init_timeout:.0f}s "
                "(TPU pool wedged?); aborting",
                file=sys.stderr,
            )
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    n_devices = len(jax.devices())
    init_done.set()
    del n_devices

    from katib_tpu.nas.darts.architect import (
        DartsHyper,
        init_search_state,
        make_search_step,
    )
    from katib_tpu.nas.darts.model import DartsNetwork, init_alphas
    from katib_tpu.nas.darts.ops import DEFAULT_PRIMITIVES
    from katib_tpu.parallel.train import cross_entropy_loss

    net = DartsNetwork(
        primitives=DEFAULT_PRIMITIVES,
        init_channels=INIT_CHANNELS,
        num_layers=NUM_LAYERS,
        n_nodes=N_NODES,
        num_classes=10,
    )
    key = jax.random.PRNGKey(0)
    k_init, k_alpha, k_data = jax.random.split(key, 3)
    alphas = init_alphas(N_NODES, len(DEFAULT_PRIMITIVES), k_alpha)
    x = jax.random.normal(k_data, (BATCH, 32, 32, 3), jnp.float32)
    y = jax.random.randint(jax.random.fold_in(k_data, 1), (BATCH,), 0, 10)
    weights = net.init(k_init, x[:1], alphas)

    def loss_fn(w, a, batch):
        xb, yb = batch
        return cross_entropy_loss(net.apply(w, xb, a), yb)

    hyper = DartsHyper(total_steps=TIMED_STEPS, unrolled=True)
    step = make_search_step(loss_fn, hyper, mesh=None)
    state = init_search_state(weights, alphas, hyper)
    batch = (x, y)

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch, batch)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = step(state, batch, batch)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    img_per_sec = BATCH * TIMED_STEPS / dt
    print(
        json.dumps(
            {
                "metric": "darts_bilevel_search_throughput",
                "value": round(float(img_per_sec), 2),
                "unit": "images/sec",
                "vs_baseline": round(float(img_per_sec) / REFERENCE_IMG_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
