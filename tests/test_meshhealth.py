"""Device-layer fault tolerance: mesh health gate, compile watchdog, and
elastic cohort degradation.

Covers the PR's acceptance properties:
- bounded-time device probing classifies healthy / wedged / absent without
  burning the full deadline on injected wedges,
- ``narrowed_trial_mesh`` shrinks only the trial axis and never widens,
- a stuck compile settles as the retryable ``FailureKind.COMPILE_HANG``
  (and the budget is disarmed by the first metric report),
- a DEVICE fault under a sharded cohort rebuilds the mesh from survivors
  and resumes members from their checkpoints — zero lost trials,
- the orchestrator preflight gate fails a wedged pool fast.
"""

from __future__ import annotations

import os
import threading
import time

import jax
import pytest

from katib_tpu.core.types import (
    ObjectiveSpec,
    ObjectiveType,
    ParameterAssignment,
    Trial,
    TrialCondition,
    TrialSpec,
)
from katib_tpu.parallel.mesh import (
    TRIAL_AXIS,
    make_mesh,
    narrowed_trial_mesh,
    trial_axis_size,
)
from katib_tpu.runner.cohort import attach_cohort_fn, run_cohort
from katib_tpu.runner.trial_runner import run_trial
from katib_tpu.store.base import MemoryObservationStore
from katib_tpu.utils import meshhealth
from katib_tpu.utils import observability as obs
from katib_tpu.utils.faults import (
    FailureKind,
    FaultInjector,
    InjectedFault,
    classify_exception,
    classify_traceback,
)
from katib_tpu.utils.watchdog import Watchdog

OBJECTIVE = ObjectiveSpec(type=ObjectiveType.MINIMIZE, objective_metric_name="loss")


class TestFailureKinds:
    def test_device_and_compile_hang_are_retryable(self):
        assert FailureKind.DEVICE.retryable
        assert FailureKind.COMPILE_HANG.retryable
        assert not FailureKind.PERMANENT.retryable

    def test_injected_fault_kind_passthrough(self):
        e = InjectedFault("injected device fault", FailureKind.DEVICE)
        assert classify_exception(e) is FailureKind.DEVICE

    def test_device_markers_classify_from_text(self):
        assert (
            classify_traceback("RuntimeError: device is in an invalid state")
            is FailureKind.DEVICE
        )
        assert (
            classify_exception(RuntimeError("chip has been disabled on host"))
            is FailureKind.DEVICE
        )

    def test_device_marker_wins_over_transient(self):
        # a preemption message that also names a dead chip is a device
        # fault first: retry must go through the mesh-health path
        text = "worker preempted: device not found (slice health check)"
        assert classify_traceback(text) is FailureKind.DEVICE


class TestProbe:
    def test_all_devices_healthy(self):
        devs = jax.devices()
        report = meshhealth.probe_devices(devs, deadline=30.0)
        assert report.ok()
        assert report.status == meshhealth.HEALTHY
        assert report.healthy_count == len(devs)
        assert report.wedged_count == 0
        for d in report.devices:
            assert d.status == meshhealth.HEALTHY
            assert d.error == ""

    def test_injected_wedge_is_immediate(self):
        devs = jax.devices()
        injector = FaultInjector().wedge_device(devs[1].id)
        t0 = time.monotonic()
        report = meshhealth.probe_devices(devs, deadline=30.0, injector=injector)
        assert time.monotonic() - t0 < 15.0  # injected wedge burns no deadline
        assert not report.ok()
        assert report.status == meshhealth.WEDGED
        assert report.wedged_count == 1
        wedged = [d for d in report.devices if d.status == meshhealth.WEDGED]
        assert wedged[0].error == "injected device wedge"
        assert any(e.get("seam") == "device-probe" for e in injector.log)
        assert "wedged" in report.summary()

    def test_slow_probe_hits_deadline_bounded(self):
        devs = jax.devices()[:2]

        def stuck_prober(device):
            time.sleep(10.0)

        t0 = time.monotonic()
        report = meshhealth.probe_devices(devs, deadline=0.3, prober=stuck_prober)
        assert time.monotonic() - t0 < 5.0  # bounded, not 10s per device
        assert report.status == meshhealth.WEDGED
        assert report.wedged_count == 2
        for d in report.devices:
            assert "did not complete" in d.error

    def test_expected_but_missing_devices_are_absent(self):
        devs = jax.devices()[:2]
        present = {d.id for d in devs}
        report = meshhealth.probe_devices(
            devs, deadline=30.0, expect_ids=sorted(present) + [99]
        )
        assert not report.ok()
        assert report.status == meshhealth.ABSENT
        absent = [d for d in report.devices if d.status == meshhealth.ABSENT]
        assert len(absent) == 1 and absent[0].device == "?:99"

    def test_empty_pool_is_not_ok(self):
        report = meshhealth.probe_devices([], deadline=1.0)
        assert not report.ok()
        assert report.status == meshhealth.ABSENT

    def test_healthy_devices_filter(self):
        devs = jax.devices()
        injector = FaultInjector().wedge_device(devs[0].id)
        report = meshhealth.probe_devices(devs, deadline=30.0, injector=injector)
        alive = meshhealth.healthy_devices(devs, report)
        assert devs[0] not in alive
        assert len(alive) == len(devs) - 1


class TestNarrowedMesh:
    def test_none_mesh(self):
        assert narrowed_trial_mesh(None, jax.devices()) is None

    def test_mesh_without_trial_axis(self):
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        assert narrowed_trial_mesh(mesh, jax.devices()[:1]) is None

    def test_narrows_four_to_three(self):
        devs = jax.devices()
        mesh = make_mesh({TRIAL_AXIS: 4}, devices=devs[:4])
        survivors = [devs[0], devs[2], devs[3]]
        narrowed = narrowed_trial_mesh(mesh, survivors)
        assert narrowed is not None
        assert trial_axis_size(narrowed) == 3
        assert [d.id for d in narrowed.devices.flat] == [d.id for d in survivors]

    def test_no_survivors_degrades_to_none(self):
        devs = jax.devices()
        mesh = make_mesh({TRIAL_AXIS: 2}, devices=devs[:2])
        assert narrowed_trial_mesh(mesh, []) is None

    def test_never_widens(self):
        devs = jax.devices()
        mesh = make_mesh({TRIAL_AXIS: 4}, devices=devs[:4])
        assert narrowed_trial_mesh(mesh, devs) is None  # 8 survivors > 4


def _whitebox_trial(name, compile_deadline=None):
    def train_fn(ctx):
        if not ctx.report(step=0, loss=1.0):
            return
        ctx.report(step=1, loss=0.5)

    return Trial(
        name=name,
        experiment_name="meshhealth-test",
        spec=TrialSpec(
            assignments=[ParameterAssignment("x", 1.0)],
            train_fn=train_fn,
            compile_deadline_seconds=compile_deadline,
        ),
    )


class TestCompileWatchdog:
    def test_compile_hang_settles_as_retryable_compile_hang(self):
        trial = _whitebox_trial("compile-wedge", compile_deadline=0.25)
        injector = FaultInjector().compile_hang(trial.name, attempt=1)
        store = MemoryObservationStore()
        wd = Watchdog(interval=0.05)
        hangs_before = obs.compile_hangs.get()
        try:
            result = run_trial(
                trial, store, OBJECTIVE, None, threading.Event(), injector,
                watchdog=wd,
            )
        finally:
            wd.stop()
        assert result.condition is TrialCondition.FAILED
        assert result.failure_kind is FailureKind.COMPILE_HANG
        assert result.failure_kind.retryable
        assert "compile watchdog" in result.message
        assert obs.compile_hangs.get() == hangs_before + 1
        assert any(e.get("seam") == "compile-hang" for e in injector.log)

    def test_first_report_disarms_the_compile_budget(self):
        # the trial outlives its compile budget in wall-clock but reports
        # BEFORE the budget expires: the one-shot heartbeat must be closed,
        # not fired mid-training
        def slow_after_first_report(ctx):
            ctx.report(step=0, loss=1.0)  # disarms the compile watchdog
            time.sleep(0.45)
            ctx.report(step=1, loss=0.5)

        trial = Trial(
            name="compile-ok",
            experiment_name="meshhealth-test",
            spec=TrialSpec(
                assignments=[],
                train_fn=slow_after_first_report,
                compile_deadline_seconds=0.2,
            ),
        )
        store = MemoryObservationStore()
        wd = Watchdog(interval=0.05)
        hangs_before = obs.compile_hangs.get()
        try:
            result = run_trial(
                trial, store, OBJECTIVE, None, threading.Event(), watchdog=wd,
            )
        finally:
            wd.stop()
        assert result.condition is TrialCondition.SUCCEEDED, result.message
        assert obs.compile_hangs.get() == hangs_before

    def test_orchestrator_retries_compile_hang_to_success(self, tmp_path):
        from katib_tpu.orchestrator.orchestrator import Orchestrator
        from tests.helpers import make_spec

        spec = make_spec(
            train_fn=lambda ctx: ctx.report(loss=1.0),
            max_trial_count=1,
            parallel_trial_count=1,
            max_retries=2,
            retry_backoff_seconds=0.01,
            compile_deadline_seconds=0.3,
        )
        injector = FaultInjector().compile_hang(0, attempt=1)
        exp = Orchestrator(
            workdir=str(tmp_path), fault_injector=injector
        ).run(spec)
        (trial,) = exp.trials.values()
        assert trial.condition is TrialCondition.SUCCEEDED, trial.message
        assert trial.retry_count == 1
        assert trial.failure_kind == FailureKind.COMPILE_HANG


def _cohort_member(name, x, train_fn, ckpt_dir=None):
    t = Trial(
        name=name,
        experiment_name="meshhealth-test",
        spec=TrialSpec(
            assignments=[ParameterAssignment("x", x)],
            train_fn=train_fn,
        ),
    )
    t.checkpoint_dir = ckpt_dir
    return t


def _progress(ckpt_dir):
    path = os.path.join(ckpt_dir, "progress.txt")
    if os.path.exists(path):
        with open(path) as f:
            return int(f.read())
    return 0


def _checkpoint_cohort_fn(steps=3, on_step=None, calls=None, starts_log=None):
    """Checkpoint-aware cohort twin: resumes every member from its
    progress file, reports one loss row per member per step."""

    def cohort_fn(cctx):
        if calls is not None:
            calls.append(cctx.trial_devices)
        starts = [_progress(d) for d in cctx.checkpoint_dirs]
        if starts_log is not None:
            starts_log.append(min(starts))
        for step in range(min(starts), steps):
            xs = [p.get("x", 0.0) for p in cctx.params_list]
            alive = cctx.report(step=step, loss=[abs(x) + 1.0 / (step + 1) for x in xs])
            for d in cctx.checkpoint_dirs:
                with open(os.path.join(d, "progress.txt"), "w") as f:
                    f.write(str(step + 1))
            if on_step is not None:
                on_step(step)
            if not alive:
                break

    return cohort_fn


class TestElasticDegradation:
    def _members(self, tmp_path, train_fn, k=4):
        members = []
        for i in range(k):
            d = str(tmp_path / f"m{i}")
            os.makedirs(d, exist_ok=True)
            members.append(_cohort_member(f"m{i}", 0.1 * (i + 1), train_fn, d))
        return members

    def test_upfront_wedge_degrades_and_completes_all(self, tmp_path):
        devs = jax.devices()
        mesh = make_mesh({TRIAL_AXIS: 4}, devices=devs[:4])
        injector = FaultInjector().wedge_device(devs[1].id)
        calls = []
        train_fn = lambda ctx: ctx.report(loss=1.0)  # noqa: E731
        attach_cohort_fn(train_fn, _checkpoint_cohort_fn(calls=calls))
        members = self._members(tmp_path, train_fn)
        store = MemoryObservationStore()
        degraded_before = obs.mesh_degraded.get()

        results = run_cohort(members, store, OBJECTIVE, mesh=mesh, injector=injector)

        # one degradation: the wedged device is probed out, the cohort
        # re-runs on a 3-wide trial axis, nothing falls back to serial
        assert obs.mesh_degraded.get() == degraded_before + 1
        assert any(e.get("seam") == "cohort-device" for e in injector.log)
        assert calls == [3]
        for t in members:
            assert results[t.name].condition is TrialCondition.SUCCEEDED, (
                t.name,
                results[t.name].message,
            )
            assert store.observation_for(t.name, OBJECTIVE) is not None
        key = f"{devs[1].platform}:{devs[1].id}"
        assert obs.device_healthy.get(device=key, platform=devs[1].platform) == 0.0

    def test_midflight_fault_resumes_members_from_checkpoint(self, tmp_path):
        devs = jax.devices()
        mesh = make_mesh({TRIAL_AXIS: 4}, devices=devs[:4])
        injector = FaultInjector()
        calls, starts_log = [], []

        def die_once(step):
            # tier 0, after step 0's checkpoints landed: the chip dies
            if step == 0 and len(calls) == 1:
                injector.wedge_device(devs[1].id)
                raise InjectedFault(
                    "injected device fault: chip has been disabled",
                    FailureKind.DEVICE,
                )

        train_fn = lambda ctx: ctx.report(loss=1.0)  # noqa: E731
        attach_cohort_fn(
            train_fn,
            _checkpoint_cohort_fn(on_step=die_once, calls=calls, starts_log=starts_log),
        )
        members = self._members(tmp_path, train_fn)
        store = MemoryObservationStore()
        degraded_before = obs.mesh_degraded.get()

        results = run_cohort(members, store, OBJECTIVE, mesh=mesh, injector=injector)

        assert obs.mesh_degraded.get() == degraded_before + 1
        assert calls == [4, 3]  # trial-axis width per tier
        assert starts_log == [0, 1]  # tier 1 resumed past the checkpointed step
        for t in members:
            assert results[t.name].condition is TrialCondition.SUCCEEDED, (
                t.name,
                results[t.name].message,
            )
            # metrics intact across the degradation: step-0 rows from tier 0
            # plus the resumed rows from tier 1
            assert store.observation_for(t.name, OBJECTIVE) is not None
        for m in members:
            assert _progress(m.checkpoint_dir) == 3

    def test_non_device_failure_falls_back_to_serial(self, tmp_path):
        def train_fn(ctx):
            ctx.report(loss=float(ctx.params.get("x", 0.0)))

        def broken_cohort(cctx):
            raise RuntimeError("cohort exploded")

        attach_cohort_fn(train_fn, broken_cohort)
        members = self._members(tmp_path, train_fn)
        store = MemoryObservationStore()
        fallbacks_before = obs.cohort_fallbacks.get()
        degraded_before = obs.mesh_degraded.get()

        results = run_cohort(members, store, OBJECTIVE)

        assert obs.cohort_fallbacks.get() == fallbacks_before + 1
        assert obs.mesh_degraded.get() == degraded_before  # not a device fault
        for t in members:
            assert results[t.name].condition is TrialCondition.SUCCEEDED


class TestPreflightGate:
    def test_wedged_pool_fails_the_experiment_fast(self, tmp_path):
        from katib_tpu.orchestrator.orchestrator import Orchestrator
        from tests.helpers import make_spec

        injector = FaultInjector()
        for d in jax.devices():
            injector.wedge_device(d.id)
        spec = make_spec(
            train_fn=lambda ctx: ctx.report(loss=1.0),
            max_trial_count=1,
            parallel_trial_count=1,
        )
        orch = Orchestrator(
            workdir=str(tmp_path), fault_injector=injector, preflight=True
        )
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="preflight"):
            orch.run(spec)
        assert time.monotonic() - t0 < 30.0
        report = meshhealth.last_report()
        assert report is not None and report.status == meshhealth.WEDGED

    def test_healthy_pool_passes_the_gate(self, tmp_path):
        from katib_tpu.orchestrator.orchestrator import Orchestrator
        from tests.helpers import make_spec

        spec = make_spec(
            train_fn=lambda ctx: ctx.report(loss=1.0),
            max_trial_count=1,
            parallel_trial_count=1,
        )
        exp = Orchestrator(workdir=str(tmp_path), preflight=True).run(spec)
        assert exp.succeeded_count == 1
        report = meshhealth.last_report()
        assert report is not None and report.ok()
        # the preflight verdict rides into status.json for the UI
        from katib_tpu.orchestrator.status import read_status

        status = read_status(str(tmp_path), exp.name)
        assert status is not None
        assert status["device_health"]["status"] == meshhealth.HEALTHY
