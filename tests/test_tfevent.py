"""TFEvent collector: tfrecord framing, protobuf decoding, writer round-trip,
black-box trial integration — parity coverage for the reference tfevent
metrics collector (``test/unit/v1beta1/metricscollector``), with synthesized
event files instead of a TF trainer run."""

from __future__ import annotations

import struct
import sys

from katib_tpu.core.types import (
    MetricsCollectorKind,
    MetricsCollectorSpec,
    ObjectiveSpec,
    ObjectiveType,
    Trial,
    TrialCondition,
    TrialSpec,
)
from katib_tpu.runner.tfevent import (
    TFEventWriter,
    _field,
    _masked_crc,
    _varint,
    crc32c,
    parse_tfevent_dir,
    parse_tfevent_file,
)
from katib_tpu.runner.trial_runner import run_trial
from katib_tpu.store.base import MemoryObservationStore


class TestCrc32c:
    def test_known_vector(self):
        # RFC 3720 B.4 test vector
        assert crc32c(b"123456789") == 0xE3069283

    def test_empty(self):
        assert crc32c(b"") == 0


class TestRoundTrip:
    def test_writer_reader(self, tmp_path):
        w = TFEventWriter(str(tmp_path))
        w.add_scalar("accuracy", 0.5, step=1, wall_time=100.0)
        w.add_scalar("accuracy", 0.75, step=2, wall_time=101.0)
        w.add_scalar("loss", 1.5, step=1, wall_time=100.0)
        w.close()
        logs = parse_tfevent_file(w.path)
        assert [(l.metric_name, l.step) for l in logs] == [
            ("accuracy", 1), ("accuracy", 2), ("loss", 1),
        ]
        assert abs(logs[1].value - 0.75) < 1e-6
        assert logs[0].timestamp == 100.0

    def test_metric_filter(self, tmp_path):
        w = TFEventWriter(str(tmp_path))
        w.add_scalar("keep", 1.0, step=0, wall_time=1.0)
        w.add_scalar("drop", 2.0, step=0, wall_time=1.0)
        w.close()
        logs = parse_tfevent_file(w.path, ["keep"])
        assert [l.metric_name for l in logs] == ["keep"]

    def test_dir_scan_merges_sorted(self, tmp_path):
        (tmp_path / "sub").mkdir()
        w1 = TFEventWriter(str(tmp_path))
        w1.add_scalar("m", 1.0, step=2, wall_time=200.0)
        w1.close()
        w2 = TFEventWriter(str(tmp_path / "sub"))
        w2.add_scalar("m", 0.5, step=1, wall_time=100.0)
        w2.close()
        logs = parse_tfevent_dir(str(tmp_path))
        assert [l.value for l in logs] == [0.5, 1.0]  # wall-time order
        assert parse_tfevent_dir(str(tmp_path / "nothing")) == []

    def test_truncated_tail_is_ignored(self, tmp_path):
        w = TFEventWriter(str(tmp_path))
        w.add_scalar("m", 1.0, step=0, wall_time=1.0)
        w.flush()
        # simulate a live trial mid-write: garbage half-frame at the tail
        with open(w.path, "ab") as f:
            f.write(struct.pack("<Q", 10_000) + b"\x00\x01\x02")
        logs = parse_tfevent_file(w.path)
        assert len(logs) == 1
        w.close()

    def test_corrupt_crc_stops_cleanly(self, tmp_path):
        w = TFEventWriter(str(tmp_path))
        w.add_scalar("m", 1.0, step=0, wall_time=1.0)
        w.add_scalar("m", 2.0, step=1, wall_time=2.0)
        w.close()
        raw = bytearray(open(w.path, "rb").read())
        raw[14] ^= 0xFF  # flip a payload byte in the first record
        open(w.path, "wb").write(bytes(raw))
        assert parse_tfevent_file(w.path) == []


class TestTF2TensorEncoding:
    def test_tensor_scalar_summary(self, tmp_path):
        # hand-build an Event whose Summary.Value carries a TensorProto
        # (dtype=DT_FLOAT, float_val=[0.625]) instead of simple_value — the
        # TF2 tf.summary.scalar encoding
        tensor = _field(1, 0) + _varint(1) + _field(5, 2) + _varint(4) + struct.pack("<f", 0.625)
        tag = b"acc"
        value = (
            _field(1, 2) + _varint(len(tag)) + tag
            + _field(8, 2) + _varint(len(tensor)) + tensor
        )
        summary = _field(1, 2) + _varint(len(value)) + value
        event = (
            _field(1, 1) + struct.pack("<d", 5.0)
            + _field(2, 0) + _varint(7)
            + _field(5, 2) + _varint(len(summary)) + summary
        )
        path = tmp_path / "events.out.tfevents.123.host"
        with open(path, "wb") as f:
            header = struct.pack("<Q", len(event))
            f.write(header + struct.pack("<I", _masked_crc(header)))
            f.write(event + struct.pack("<I", _masked_crc(event)))
        logs = parse_tfevent_file(str(path))
        assert [(l.metric_name, l.value, l.step) for l in logs] == [("acc", 0.625, 7)]


class TestBlackboxIntegration:
    def test_tfevent_collector_trial(self, tmp_path):
        """Black-box trial writes event files; collector parses them after
        exit (reference ``tfevent-metricscollector/main.py:47-79`` flow)."""
        logdir = tmp_path / "logs"
        script = tmp_path / "train.py"
        script.write_text(
            "import sys\n"
            "sys.path.insert(0, %r)\n"
            "from katib_tpu.runner.tfevent import TFEventWriter\n"
            "w = TFEventWriter(%r)\n"
            "w.add_scalar('val_acc', 0.875, step=1, wall_time=1.0)\n"
            "w.close()\n"
            "print('val_acc=0.111')  # stdout must NOT be scraped for TFEvent kind\n"
            % (str(__import__('pathlib').Path(__file__).resolve().parents[1]), str(logdir))
        )
        store = MemoryObservationStore()
        obj = ObjectiveSpec(type=ObjectiveType.MAXIMIZE, objective_metric_name="val_acc")
        trial = Trial(
            name="tfe",
            experiment_name="e",
            spec=TrialSpec(
                command=[sys.executable, str(script)],
                metrics_collector=MetricsCollectorSpec(
                    kind=MetricsCollectorKind.TFEVENT, path=str(logdir)
                ),
            ),
        )
        result = run_trial(trial, store, obj)
        assert result.condition is TrialCondition.SUCCEEDED
        logs = store.get("tfe", "val_acc")
        assert [l.value for l in logs] == [0.875]


class TestTfeventValidation:
    def test_tfevent_requires_path(self):
        import pytest as _pytest

        from katib_tpu.core.validation import ValidationError, validate_experiment

        from helpers import make_spec

        spec = make_spec("random")
        spec.train_fn = None
        spec.command = ["echo", "x"]
        spec.metrics_collector = MetricsCollectorSpec(kind=MetricsCollectorKind.TFEVENT)
        with _pytest.raises(ValidationError, match="requires a path"):
            validate_experiment(spec)

    def test_tfevent_rejects_early_stopping(self):
        import pytest as _pytest

        from katib_tpu.core.types import EarlyStoppingSpec
        from katib_tpu.core.validation import ValidationError, validate_experiment

        from helpers import make_spec

        spec = make_spec("random")
        spec.train_fn = None
        spec.command = ["echo", "x"]
        spec.metrics_collector = MetricsCollectorSpec(
            kind=MetricsCollectorKind.TFEVENT, path="/tmp/events"
        )
        spec.early_stopping = EarlyStoppingSpec(name="medianstop")
        with _pytest.raises(ValidationError, match="early stopping"):
            validate_experiment(spec)
