"""The CIFAR-10 fetch/verify/unpack pipeline (scripts/fetch_cifar10.py).

The real archive can't be downloaded in this zero-egress image, so these
tests prove the pipeline around it: a structurally-correct archive unpacks
into exactly the npz the framework's loaders consume, and a wrong archive
is refused before anything is written (sha256 pin).  When a real
``cifar-10-python.tar.gz`` drops, the same code path upgrades every
cifar10-based artifact with no code change (reference downloads at
container start, ``darts-cnn-cifar10/run_trial.py:100-111``).
"""

from __future__ import annotations

import importlib.util
import io
import os
import pickle
import sys
import tarfile

import numpy as np
import pytest

_SPEC = importlib.util.spec_from_file_location(
    "fetch_cifar10",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts", "fetch_cifar10.py"),
)
fetch = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(fetch)


def _fake_archive(path: str, n_per_batch: int = 4) -> None:
    """A miniature cifar-10-python.tar.gz with the real member layout."""
    rng = np.random.default_rng(0)

    def member(name: str, start_label: int):
        data = rng.integers(0, 256, size=(n_per_batch, 3072), dtype=np.uint16)
        payload = pickle.dumps({
            b"data": data.astype(np.uint8),
            b"labels": [(start_label + i) % 10 for i in range(n_per_batch)],
        })
        info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
        info.size = len(payload)
        return info, io.BytesIO(payload)

    with tarfile.open(path, "w:gz") as tf:
        # deliberately out of order: unpack() must sort batches itself
        for name, lbl in (("data_batch_2", 1), ("test_batch", 5),
                          ("data_batch_1", 0), ("data_batch_4", 3),
                          ("data_batch_3", 2), ("data_batch_5", 4)):
            info, fobj = member(name, lbl)
            tf.addfile(info, fobj)


class TestUnpack:
    def test_layout_and_dtypes(self, tmp_path):
        tar = str(tmp_path / "fake.tar.gz")
        _fake_archive(tar)
        arrays = fetch.unpack(tar, expect_full=False)
        assert arrays["x_train"].shape == (20, 32, 32, 3)
        assert arrays["x_train"].dtype == np.uint8
        assert arrays["x_test"].shape == (4, 32, 32, 3)
        assert arrays["y_train"].dtype == np.int32
        # batch order is data_batch_1..5 regardless of tar member order
        assert list(arrays["y_train"][:4]) == [0, 1, 2, 3]

    def test_npz_feeds_the_framework_loader(self, tmp_path, monkeypatch):
        """End-to-end: unpacked npz in KATIB_DATA_DIR is what
        models.data.load_cifar10 picks up (real-data path, not synthetic)."""
        tar = str(tmp_path / "fake.tar.gz")
        _fake_archive(tar)
        arrays = fetch.unpack(tar, expect_full=False)
        np.savez_compressed(str(tmp_path / "cifar10.npz"), **arrays)
        monkeypatch.setenv("KATIB_DATA_DIR", str(tmp_path))
        from katib_tpu.models import data as data_mod

        assert data_mod.using_real_data("cifar10")
        ds = data_mod.load_cifar10()  # real npz is served whole
        assert ds.x_train.shape == (20, 32, 32, 3)
        assert ds.x_train.dtype == np.float32
        assert float(ds.x_train.max()) <= 1.0  # uint8 got normalized
        assert ds.num_classes == 10


class TestVerify:
    def test_wrong_archive_refused(self, tmp_path):
        bad = str(tmp_path / "bad.tar.gz")
        with open(bad, "wb") as f:
            f.write(b"not cifar")
        with pytest.raises(SystemExit, match="integrity check FAILED"):
            fetch.verify(bad)

    def test_pins_are_wellformed(self):
        assert len(fetch.SHA256) == 64 and int(fetch.SHA256, 16)
        assert len(fetch.MD5) == 32 and int(fetch.MD5, 16)
