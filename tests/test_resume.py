"""Durable resume across process restarts (VERDICT r1 item 2).

The reference resurrects experiments from CR state + the suggestion PVC
(``suggestion_controller.go:181-193`` FromVolume, ``experiment_controller.go:
187-206`` re-open on raised maxTrialCount).  Here the journal is
``status.json`` + ``suggester_state.pkl``; these tests prove:

- the journal round-trips into an equivalent ``Experiment``;
- a SIGKILLed orchestrator process resumes and completes with the combined
  trial history (the headline scenario);
- orphaned in-flight trials are resubmitted under their original names;
- ENAS/PBT suggester state survives the pickle round trip.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentCondition,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    ResumePolicy,
    TrialCondition,
)
from katib_tpu.orchestrator import Orchestrator
from katib_tpu.orchestrator.resume import (
    experiment_from_dict,
    load_suggester_state,
    save_suggester_state,
)
from katib_tpu.orchestrator.status import read_status


def make_spec(name="resume-exp", train_fn=None, **kw):
    kw.setdefault("max_trial_count", 6)
    kw.setdefault("parallel_trial_count", 2)
    kw.setdefault("resume_policy", ResumePolicy.FROM_VOLUME)
    return ExperimentSpec(
        name=name,
        algorithm=AlgorithmSpec(name=kw.pop("algorithm", "random")),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.5)),
            ParameterSpec("units", ParameterType.INT, FeasibleSpace(min=4, max=32)),
        ],
        train_fn=train_fn or _quick_trainer,
        **kw,
    )


def _quick_trainer(ctx):
    acc = 1.0 - (float(ctx.params["lr"]) - 0.1) ** 2
    for step in range(2):
        if not ctx.report(step=step, accuracy=acc * (step + 1) / 2):
            return


class TestJournalRoundTrip:
    def test_reconstruct_completed_experiment(self, tmp_path):
        spec = make_spec(name="rt-exp")
        orch = Orchestrator(workdir=str(tmp_path))
        exp = orch.run(spec)
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED

        status = read_status(str(tmp_path), "rt-exp")
        rebuilt = experiment_from_dict(spec, status)
        assert rebuilt.condition is exp.condition
        assert set(rebuilt.trials) == set(exp.trials)
        assert rebuilt.succeeded_count == exp.succeeded_count
        assert rebuilt.optimal is not None
        assert rebuilt.optimal.trial_name == exp.optimal.trial_name
        assert rebuilt.optimal.objective_value == pytest.approx(
            exp.optimal.objective_value
        )
        # assignment types survive the JSON round trip
        t = next(iter(rebuilt.trials.values()))
        params = t.params()
        assert isinstance(params["lr"], float)
        assert isinstance(params["units"], int)

    def test_algorithm_settings_persisted(self, tmp_path):
        spec = make_spec(name="as-exp")
        orch = Orchestrator(workdir=str(tmp_path))
        exp = orch.run(spec)
        exp.algorithm_settings["_probe"] = "42"
        from katib_tpu.orchestrator.status import write_status

        write_status(exp, str(tmp_path))
        rebuilt = experiment_from_dict(spec, read_status(str(tmp_path), "as-exp"))
        assert rebuilt.algorithm_settings["_probe"] == "42"

    def test_load_experiment_none_without_journal(self, tmp_path):
        orch = Orchestrator(workdir=str(tmp_path))
        assert orch.load_experiment(make_spec(name="ghost")) is None

    def test_optimal_history_survives_round_trip(self, tmp_path):
        """The journaled convergence curve is restored verbatim and the
        post-load recompute extends it rather than restarting it."""
        spec = make_spec(name="curve-exp")
        exp = Orchestrator(workdir=str(tmp_path)).run(spec)
        assert exp.optimal_history, "a completed run must have curve rows"
        status = read_status(str(tmp_path), "curve-exp")
        assert status["optimal_history"] == exp.optimal_history
        rebuilt = experiment_from_dict(spec, status)
        # recompute found the same optimal -> same rows, no restart/dupe
        assert rebuilt.optimal_history == exp.optimal_history


class TestOrphanResubmission:
    def test_orphaned_trial_reruns_under_original_name(self, tmp_path):
        """A journaled non-terminal trial is resubmitted (same name), not
        abandoned — the analog of trial jobs surviving controller restarts."""
        spec = make_spec(name="orphan-exp", max_trial_count=3)
        orch = Orchestrator(workdir=str(tmp_path))
        exp = orch.run(spec)
        # forge a crash: mark one trial as if it had been in flight
        victim = next(iter(exp.trials.values()))
        status = read_status(str(tmp_path), "orphan-exp")
        status["trials"][victim.name]["condition"] = "Running"
        status["trials"][victim.name]["observation"] = None
        status["condition"] = "Running"
        rebuilt = experiment_from_dict(spec, status)
        assert rebuilt.trials[victim.name].condition is TrialCondition.PENDING

        resumed = Orchestrator(workdir=str(tmp_path)).run(spec, experiment=rebuilt)
        assert resumed.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert resumed.trials[victim.name].condition is TrialCondition.SUCCEEDED
        assert resumed.trials[victim.name].observation is not None
        # budget unchanged: re-run consumed no extra slot
        assert len(resumed.trials) == 3


class TestKillAndResume:
    def test_sigkill_mid_run_then_resume_completes(self, tmp_path):
        """The headline scenario: SIGKILL an orchestrator subprocess
        mid-experiment, resume in a fresh process, end with combined
        history and the full budget accounted for."""
        workdir = str(tmp_path / "runs")
        script = textwrap.dedent(
            f"""
            import sys, time
            sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
            import jax
            jax.config.update("jax_platforms", "cpu")
            from tests.test_resume import make_spec
            from katib_tpu.orchestrator import Orchestrator

            def slow_trainer(ctx):
                acc = 1.0 - (float(ctx.params["lr"]) - 0.1) ** 2
                for step in range(40):
                    if not ctx.report(step=step, accuracy=acc * (step + 1) / 40):
                        return
                    time.sleep(0.15)

            spec = make_spec(name="kill-exp", train_fn=slow_trainer,
                             max_trial_count=4, parallel_trial_count=2)
            print("READY", flush=True)
            Orchestrator(workdir={workdir!r}).run(spec)
            """
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            # wait for the journal to show in-flight trials
            deadline = time.time() + 60
            while time.time() < deadline:
                s = read_status(workdir, "kill-exp")
                if s and any(
                    t["condition"] == "Running" for t in s.get("trials", {}).values()
                ):
                    break
                time.sleep(0.1)
            else:
                pytest.fail("subprocess never journaled a running trial")
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()

        status = read_status(workdir, "kill-exp")
        assert status is not None
        orphans = [
            n for n, t in status["trials"].items() if t["condition"] == "Running"
        ]
        assert orphans, "expected orphaned running trials in the journal"

        # resume in this process with a fast trainer (the train_fn comes
        # from the spec, not the journal)
        spec = make_spec(name="kill-exp", max_trial_count=4, parallel_trial_count=2)
        orch = Orchestrator(workdir=workdir)
        exp = orch.run(spec, resume=True)
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert exp.succeeded_count == 4
        for name in orphans:
            assert exp.trials[name].condition is TrialCondition.SUCCEEDED
        assert exp.optimal is not None

    def test_resume_never_policy_refuses_terminal(self, tmp_path):
        spec = make_spec(name="never-exp", resume_policy=ResumePolicy.NEVER,
                         max_trial_count=2)
        orch = Orchestrator(workdir=str(tmp_path))
        orch.run(spec)
        with pytest.raises(RuntimeError, match="Never"):
            Orchestrator(workdir=str(tmp_path)).run(spec, resume=True)

    def test_resume_long_running_reopens_on_raised_budget(self, tmp_path):
        spec = make_spec(name="lr-exp", resume_policy=ResumePolicy.LONG_RUNNING,
                         max_trial_count=2)
        Orchestrator(workdir=str(tmp_path)).run(spec)
        spec2 = make_spec(name="lr-exp", resume_policy=ResumePolicy.LONG_RUNNING,
                          max_trial_count=5)
        exp = Orchestrator(workdir=str(tmp_path)).run(spec2, resume=True)
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert len(exp.trials) == 5
        assert exp.succeeded_count == 5


class TestLosslessResumeStore:
    def test_resumed_medianstop_rules_equal_no_restart_rules(self, tmp_path):
        """Resumable experiments auto-upgrade a defaulted store to durable
        sqlite, so a restarted orchestrator's medianstop computes rules
        over the TRUE multi-point series — identical to an uninterrupted
        run — instead of _backfill_store's one-point approximation (which
        would substitute each trial's reduced value for its early head and
        shift the median)."""
        from types import SimpleNamespace

        from katib_tpu.core.types import EarlyStoppingSpec
        from katib_tpu.earlystop.rules import make_early_stopper

        def ramp_trainer(ctx):
            # 5-point ramp: head average (start_step=3) != reduced max, so
            # a one-point backfill would provably change the rule value
            acc = 1.0 - (float(ctx.params["lr"]) - 0.1) ** 2
            for step in range(5):
                if not ctx.report(step=step, accuracy=acc * (step + 1) / 5):
                    return

        def spec_for(n):
            return make_spec(
                name="lossless-es", resume_policy=ResumePolicy.LONG_RUNNING,
                max_trial_count=n, train_fn=ramp_trainer,
                early_stopping=EarlyStoppingSpec(
                    "medianstop",
                    {"min_trials_required": "2", "start_step": "3"},
                ),
            )

        spec = spec_for(4)
        orch1 = Orchestrator(workdir=str(tmp_path))
        exp1 = orch1.run(spec)
        ms1 = make_early_stopper(spec)
        ms1.bind_store(orch1.store)
        rules_before = ms1.get_rules(exp1)
        assert rules_before, "median rules must exist after 4 succeeded trials"

        # process restart: fresh orchestrator, same workdir, raised budget
        orch2 = Orchestrator(workdir=str(tmp_path))
        exp2 = orch2.run(spec_for(6), resume=True)
        assert len(exp2.trials) == 6

        # the original trials' series survived in full (not one backfilled point)
        first = next(iter(exp1.trials))
        assert len(orch2.store.get(first, "accuracy")) == 5

        # rules over the SAME trial subset are identical pre/post restart
        ms2 = make_early_stopper(spec)
        ms2.bind_store(orch2.store)
        subset = SimpleNamespace(
            trials={k: v for k, v in exp2.trials.items() if k in exp1.trials}
        )
        rules_after = ms2.get_rules(subset)
        assert [(r.name, r.value, r.comparison, r.start_step) for r in rules_after] \
            == [(r.name, r.value, r.comparison, r.start_step) for r in rules_before]


class TestSuggesterStatePersistence:
    def test_pbt_state_round_trip(self, tmp_path):
        spec = make_spec(
            name="pbt-state",
            algorithm="pbt",
        )
        spec.algorithm.settings.update(
            n_population=8,
            truncation_threshold=0.25,
            suggestion_trial_dir=str(tmp_path / "pbt-ckpts"),
        )
        from katib_tpu.suggest.pbt import PbtSuggester

        s1 = PbtSuggester(spec)
        from katib_tpu.core.types import Experiment

        exp = Experiment(spec=spec)
        proposals = s1.get_suggestions(exp, 4)
        assert save_suggester_state(s1, str(tmp_path), "pbt-state")

        s2 = PbtSuggester(spec)
        assert load_suggester_state(s2, str(tmp_path), "pbt-state")
        assert [j.uid for j in s2.pending] == [j.uid for j in s1.pending]
        assert set(s2.running) == {p.name for p in proposals}
        # identical RNG continuation: both propose the same next batch
        n1 = s1.get_suggestions(exp, 2)
        n2 = s2.get_suggestions(exp, 2)
        assert [p.name for p in n1] == [p.name for p in n2]
        assert [p.as_dict() for p in n1] == [p.as_dict() for p in n2]

    @pytest.mark.slow
    def test_enas_state_round_trip(self, tmp_path):
        import numpy as np

        from katib_tpu.core.types import (
            Experiment,
            GraphConfig,
            NasConfig,
            NasOperation,
        )
        from katib_tpu.nas.enas.service import EnasSuggester

        spec = make_spec(name="enas-state", algorithm="enas")
        spec.parameters = []
        spec.nas_config = NasConfig(
            graph_config=GraphConfig(num_layers=3),
            operations=(
                NasOperation("separable_convolution"),
                NasOperation("skip_connection"),
            ),
        )
        s1 = EnasSuggester(spec)
        exp = Experiment(spec=spec)
        s1.get_suggestions(exp, 2)
        assert save_suggester_state(s1, str(tmp_path), "enas-state")

        s2 = EnasSuggester(spec)
        assert load_suggester_state(s2, str(tmp_path), "enas-state")
        assert s2.round == s1.round
        import jax

        leaves1 = jax.tree_util.tree_leaves(s1.state)
        leaves2 = jax.tree_util.tree_leaves(s2.state)
        for a, b in zip(leaves1, leaves2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_orchestrator_persists_and_reloads(self, tmp_path):
        """End-to-end: a PBT run journals suggester state; a resumed run
        reloads it (no duplicate population seeding)."""
        spec = make_spec(
            name="pbt-e2e",
            algorithm="pbt",
            max_trial_count=6,
            parallel_trial_count=2,
        )
        spec.algorithm.settings.update(
            n_population=5,
            truncation_threshold=0.2,
            suggestion_trial_dir=str(tmp_path / "lineage"),
        )
        orch = Orchestrator(workdir=str(tmp_path))
        exp = orch.run(spec)
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        from katib_tpu.orchestrator.resume import suggester_state_path

        assert os.path.exists(suggester_state_path(str(tmp_path), "pbt-e2e"))

        # raise the budget and resume: PBT continues its journaled queue
        spec2 = make_spec(
            name="pbt-e2e",
            algorithm="pbt",
            max_trial_count=9,
            parallel_trial_count=2,
        )
        spec2.algorithm.settings.update(
            n_population=5,
            truncation_threshold=0.2,
            suggestion_trial_dir=str(tmp_path / "lineage"),
        )
        exp2 = Orchestrator(workdir=str(tmp_path)).run(spec2, resume=True)
        assert exp2.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert exp2.succeeded_count >= 9 - 1  # requeues tolerated
        assert len(exp2.trials) >= 9


class TestStatusPathSafety:
    def test_read_status_rejects_traversal_names(self, tmp_path):
        import json, os
        outside = tmp_path / "outside"
        inside = tmp_path / "runs" / "ok"
        inside.mkdir(parents=True)
        (tmp_path / "runs").mkdir(exist_ok=True)
        outside.mkdir()
        (outside / "status.json").write_text(json.dumps({"name": "evil"}))
        (inside / "status.json").write_text(json.dumps({"name": "ok"}))
        workdir = str(tmp_path / "runs")
        assert read_status(workdir, "ok") == {"name": "ok"}
        for bad in ("..", ".", "", "../outside", "a/b", f"..{os.sep}outside"):
            assert read_status(workdir, bad) is None
