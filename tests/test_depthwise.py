"""DepthwiseConv/PointwiseConv (ops/depthwise.py): partitioner-safe convs.

Two properties pinned:
1. Numerical equality with ``nn.Conv(feature_group_count=C)`` on a single
   device — same math, same kernel shape, so the shift-MAC form is a
   drop-in.
2. Gradient parity across a dp x model mesh — the exact configuration
   where the grouped-conv formulation's filter gradient comes back 100%
   wrong from the SPMD partitioner (measured: max|diff| == max|grad| vs an
   f64 ground truth on jax 0.9.0 CPU).  This test is the regression gate
   for that miscompile.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from katib_tpu.ops.depthwise import DepthwiseConv
from katib_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    replicate,
    replicated,
)


class TestEqualsGroupedConv:
    @pytest.mark.parametrize("kernel,stride,dilation", [
        (3, 1, 1), (3, 2, 1), (5, 1, 1), (3, 1, 2), (5, 2, 2),
    ])
    def test_forward_matches(self, kernel, stride, dilation):
        c = 6
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12, c), jnp.float32)
        dw = DepthwiseConv(kernel=kernel, stride=stride, dilation=dilation,
                           dtype=jnp.float32, safe=True)
        grouped = nn.Conv(
            c, (kernel, kernel), strides=(stride, stride), padding="SAME",
            kernel_dilation=(dilation, dilation), feature_group_count=c,
            use_bias=False, dtype=jnp.float32,
        )
        kern = jax.random.normal(jax.random.PRNGKey(2), (kernel, kernel, 1, c))
        out_dw = dw.apply({"params": {"kernel": kern}}, x)
        out_g = grouped.apply({"params": {"kernel": kern}}, x)
        # atol covers 25-tap summation-order noise on O(10) activations
        np.testing.assert_allclose(
            np.asarray(out_dw), np.asarray(out_g), rtol=1e-5, atol=1e-5
        )

    def test_init_shape_and_scale(self):
        dw = DepthwiseConv(kernel=3, dtype=jnp.float32)
        safe = DepthwiseConv(kernel=3, dtype=jnp.float32, safe=True)
        x0 = jnp.zeros((1, 8, 8, 5))
        # flipping `safe` must never change the parameter tree
        p_fast = dw.init(jax.random.PRNGKey(0), x0)
        p_safe = safe.init(jax.random.PRNGKey(0), x0)
        assert jax.tree_util.tree_structure(p_fast) == jax.tree_util.tree_structure(p_safe)
        np.testing.assert_array_equal(
            np.asarray(p_fast["params"]["kernel"]),
            np.asarray(p_safe["params"]["kernel"]),
        )
        x = jnp.zeros((1, 8, 8, 5))
        params = dw.init(jax.random.PRNGKey(0), x)
        assert params["params"]["kernel"].shape == (3, 3, 1, 5)
        assert params["params"]["kernel"].dtype == jnp.float32


class TestMeshGradParity:
    def test_filter_grad_parity_on_model_axis_mesh(self):
        """The regression the module exists for: kernel gradients on a
        dp x model mesh equal the single-device gradients."""
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        c = 8
        x = jax.random.normal(jax.random.PRNGKey(7), (8, 12, 12, c), jnp.float32)
        dw = DepthwiseConv(kernel=3, dtype=jnp.float32, safe=True)
        params = dw.init(jax.random.PRNGKey(0), x[:1])

        def loss(p, xb):
            out = dw.apply(p, xb)
            return (out * out).mean()

        g0 = jax.device_get(jax.jit(jax.grad(loss))(params, x))
        mesh = make_mesh({DATA_AXIS: 4, MODEL_AXIS: 2}, devices=devs[:8])
        from jax.sharding import NamedSharding, PartitionSpec

        ss = replicated(mesh)
        bs = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
        gm = jax.jit(jax.grad(loss), in_shardings=(ss, bs), out_shardings=ss)
        g42 = jax.device_get(gm(replicate(params, mesh), jax.device_put(x, bs)))
        np.testing.assert_allclose(
            np.asarray(g0["params"]["kernel"]),
            np.asarray(g42["params"]["kernel"]),
            rtol=1e-5, atol=1e-7,
            err_msg="depthwise filter gradient diverges on the model-axis "
                    "mesh — the partitioner regression is back",
        )
