"""YAML experiment loader, status journal, CLI commands, observability
registry — the user/ops surface (reference analogs: example experiment CRs,
UI backend handlers ``backend.go:86-617``, Prometheus metrics)."""

from __future__ import annotations

import json
import sys
import urllib.request

import pytest

from katib_tpu.core.types import (
    Distribution,
    ExperimentCondition,
    MetricsCollectorKind,
    MetricStrategyType,
    ObjectiveType,
    ParameterType,
    ResumePolicy,
)
from katib_tpu.sdk.yaml_spec import SpecError, experiment_spec_from_dict, load_experiment_yaml

from helpers import make_spec


KATIB_CR = """
apiVersion: kubeflow.org/v1beta1
kind: Experiment
metadata:
  name: random-example
spec:
  objective:
    type: maximize
    goal: 0.99
    objectiveMetricName: Validation-accuracy
    additionalMetricNames: [Train-accuracy]
    metricStrategies:
      - {name: Train-accuracy, value: latest}
  algorithm:
    algorithmName: random
    algorithmSettings:
      - {name: random_state, value: "42"}
  parallelTrialCount: 3
  maxTrialCount: 12
  maxFailedTrialCount: 3
  resumePolicy: LongRunning
  parameters:
    - name: lr
      parameterType: double
      feasibleSpace: {min: "0.01", max: "0.03", distribution: logUniform}
    - name: num-layers
      parameterType: int
      feasibleSpace: {min: "2", max: "5", step: "1"}
    - name: optimizer
      parameterType: categorical
      feasibleSpace: {list: [sgd, adam, ftrl]}
    - name: momentum
      parameterType: discrete
      feasibleSpace: {list: ["0.5", "0.9"]}
  metricsCollectorSpec:
    collector: {kind: StdOut}
  trialTemplate:
    command:
      - python
      - train.py
      - "--lr=${trialParameters.lr}"
"""


class TestYamlLoader:
    def test_katib_cr_shape(self, tmp_path):
        p = tmp_path / "exp.yaml"
        p.write_text(KATIB_CR)
        spec = load_experiment_yaml(str(p))
        assert spec.name == "random-example"
        assert spec.objective.type is ObjectiveType.MAXIMIZE
        assert spec.objective.goal == 0.99
        assert spec.objective.additional_metric_names == ("Train-accuracy",)
        assert spec.objective.strategy_for("Train-accuracy") is MetricStrategyType.LATEST
        assert spec.algorithm.name == "random"
        assert spec.algorithm.settings["random_state"] == "42"
        assert spec.parallel_trial_count == 3
        assert spec.max_trial_count == 12
        assert spec.resume_policy is ResumePolicy.LONG_RUNNING
        lr = spec.parameter("lr")
        assert lr.type is ParameterType.DOUBLE
        assert lr.feasible.distribution is Distribution.LOG_UNIFORM
        layers = spec.parameter("num-layers")
        assert layers.type is ParameterType.INT and layers.feasible.step == 1
        assert spec.parameter("optimizer").feasible.list == ("sgd", "adam", "ftrl")
        assert spec.parameter("momentum").feasible.list == (0.5, 0.9)
        assert spec.metrics_collector.kind is MetricsCollectorKind.STDOUT
        assert spec.command == ["python", "train.py", "--lr=${trialParameters.lr}"]

    def test_flat_shape(self):
        spec = experiment_spec_from_dict(
            {
                "name": "flat",
                "objective": {"type": "minimize", "objectiveMetricName": "loss"},
                "algorithm": {"name": "tpe", "settings": {"n_startup": "5"}},
                "parameters": [
                    {
                        "name": "x",
                        "type": "double",
                        "feasible": {"min": 0, "max": 1},
                    }
                ],
                "command": ["echo", "${trialParameters.x}"],
            }
        )
        assert spec.algorithm.name == "tpe"
        assert spec.algorithm.settings == {"n_startup": "5"}

    @pytest.mark.parametrize(
        "mutation, match",
        [
            ({"metadata": {}}, "name missing"),
            ({"spec": {}}, "objective"),
        ],
    )
    def test_errors(self, mutation, match):
        base = {"metadata": {"name": "x"}, "spec": {"objective": {"type": "minimize", "objectiveMetricName": "m"}}}
        base.update(mutation)
        with pytest.raises(SpecError, match=match):
            experiment_spec_from_dict(base)

    def test_unknown_distribution(self):
        with pytest.raises(SpecError, match="distribution"):
            experiment_spec_from_dict(
                {
                    "name": "x",
                    "objective": {"type": "minimize", "objectiveMetricName": "m"},
                    "parameters": [
                        {
                            "name": "p",
                            "type": "double",
                            "feasible": {"min": 0, "max": 1, "distribution": "zipf"},
                        }
                    ],
                }
            )


class TestStatusJournal:
    def test_status_written_and_listed(self, tmp_path):
        from katib_tpu.orchestrator.orchestrator import Orchestrator
        from katib_tpu.orchestrator.status import list_statuses, read_status

        def train(ctx):
            ctx.report(loss=(ctx.params["x"]) ** 2)

        spec = make_spec("random", train_fn=train, max_trial_count=2,
                         parallel_trial_count=1)
        orch = Orchestrator(workdir=str(tmp_path))
        exp = orch.run(spec)
        status = read_status(str(tmp_path), exp.name)
        assert status["condition"] == "MaxTrialsReached"
        assert status["counts"]["succeeded"] == 2
        assert status["optimal"]["trial_name"] in status["trials"]
        trial = status["trials"][status["optimal"]["trial_name"]]
        assert trial["observation"][0]["name"] == "loss"
        assert [s["name"] for s in list_statuses(str(tmp_path))] == [exp.name]


class TestCli:
    def test_run_list_describe(self, tmp_path, capsys):
        from katib_tpu.cli import main

        exp_yaml = tmp_path / "exp.yaml"
        exp_yaml.write_text(
            """
metadata: {name: cli-exp}
spec:
  objective: {type: minimize, objectiveMetricName: loss}
  algorithm: {algorithmName: grid}
  maxTrialCount: 3
  parallelTrialCount: 1
  parameters:
    - name: x
      parameterType: int
      feasibleSpace: {min: "0", max: "2", step: "1"}
  command: [%s, -c, "print('loss=' + str(float(%s) ** 2))"]
"""
            % (json.dumps(sys.executable), '${trialParameters.x}')
        )
        workdir = str(tmp_path / "runs")
        rc = main(["run", str(exp_yaml), "--workdir", workdir])
        out = capsys.readouterr().out
        assert rc == 0
        assert "cli-exp" in out and "optimal trial" in out
        # best x is 0 -> loss 0.0
        assert "x = 0" in out

        rc = main(["list", "--workdir", workdir])
        out = capsys.readouterr().out
        assert rc == 0 and "cli-exp" in out and "MaxTrialsReached" in out

        rc = main(["describe", "cli-exp", "--workdir", workdir])
        out = capsys.readouterr().out
        assert rc == 0 and "Optimal:" in out and out.count("cli-exp-") >= 3

        rc = main(["describe", "ghost", "--workdir", workdir])
        assert rc == 1

        # export: CSV header + one row per trial, JSONL round-trips
        rc = main(["export", "cli-exp", "--workdir", workdir])
        out = capsys.readouterr().out
        assert rc == 0
        lines = [l for l in out.strip().splitlines() if l]
        assert lines[0].startswith("trial,condition,x,loss")
        assert len(lines) == 4  # header + 3 trials
        rc = main(["export", "cli-exp", "--workdir", workdir, "--format", "jsonl"])
        out = capsys.readouterr().out
        rows = [json.loads(l) for l in out.strip().splitlines()]
        assert len(rows) == 3 and all("loss" in r and "x" in r for r in rows)
        rc = main(["export", "ghost", "--workdir", workdir])
        assert rc == 1

    def test_export_metric_param_collision_is_order_independent(
        self, tmp_path, capsys
    ):
        """A metric whose name collides with a parameter that only a LATER
        trial introduces must still land in the metric: namespace (the
        rename pre-scans all trials' parameters, so it can't depend on
        trial iteration order)."""
        from katib_tpu.cli import main

        exp_dir = tmp_path / "col-exp"
        exp_dir.mkdir()
        (exp_dir / "status.json").write_text(json.dumps({
            "name": "col-exp",
            "condition": "MaxTrialsReached",
            "trials": {
                # trial 1 reports metric "y" and has no parameter "y"
                "t1": {"name": "t1", "condition": "Succeeded",
                       "assignments": {"x": 1},
                       "observation": [{"name": "y", "value": 0.5}]},
                # trial 2 introduces parameter "y" (e.g. a PBT mutation)
                "t2": {"name": "t2", "condition": "Succeeded",
                       "assignments": {"x": 2, "y": 7},
                       "observation": [{"name": "y", "value": 0.25}]},
            },
        }))
        rc = main(["export", "col-exp", "--workdir", str(tmp_path),
                   "--format", "jsonl"])
        out = capsys.readouterr().out
        assert rc == 0
        rows = [json.loads(l) for l in out.strip().splitlines()]
        # both trials' metrics use the SAME namespaced key; t2's parameter
        # keeps the bare column
        assert rows[0]["metric:y"] == 0.5 and "y" not in rows[0]
        assert rows[1]["metric:y"] == 0.25 and rows[1]["y"] == 7

        rc = main(["export", "col-exp", "--workdir", str(tmp_path)])
        out = capsys.readouterr().out
        header = out.strip().splitlines()[0].split(",")
        assert rc == 0 and len(header) == len(set(header))  # no dup columns

    def test_doctor_reports_devices_with_deadline(self, capsys, monkeypatch):
        """doctor probes device init in a killable child so a wedged pool
        yields a diagnosis instead of a hang; healthy CPU path reports."""
        from katib_tpu.cli import main

        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        rc = main(["doctor", "--device-timeout", "60"])
        out = capsys.readouterr().out
        assert rc == 0
        # per-device health report over the virtual CPU pool
        assert "pool healthy" in out
        assert "cpu:0" in out
        assert "native runtime" in out

    def test_run_without_command_errors(self, tmp_path, capsys):
        from katib_tpu.cli import main

        exp_yaml = tmp_path / "exp.yaml"
        exp_yaml.write_text(
            """
metadata: {name: no-cmd}
spec:
  objective: {type: minimize, objectiveMetricName: loss}
  parameters:
    - name: x
      parameterType: double
      feasibleSpace: {min: "0", max: "1"}
"""
        )
        rc = main(["run", str(exp_yaml), "--workdir", str(tmp_path / "runs")])
        assert rc == 2
        assert "no trial command" in capsys.readouterr().err


class TestDbManagerCommand:
    def test_daemon_serves_and_journals(self, tmp_path):
        """``katib-tpu db-manager --db`` runs the native daemon standalone
        (reference ``cmd/db-manager`` parity); a client round-trips points
        and they survive a SIGKILL + restart on the same journal."""
        import os
        import signal
        import subprocess
        import time

        from katib_tpu.native import native_available
        from katib_tpu.native.dbmanager import RemoteObservationStore

        if not native_available():
            pytest.skip("native runtime unavailable")
        db = str(tmp_path / "obs.journal")

        def launch():
            proc = subprocess.Popen(
                [sys.executable, "-m", "katib_tpu", "db-manager",
                 "--port", "0", "--db", db],
                stdout=subprocess.PIPE, text=True,
            )
            line = proc.stdout.readline()
            assert "db-manager:" in line, line
            port = int(line.split()[2].rsplit(":", 1)[1])
            return proc, port

        proc, port = launch()
        try:
            client = RemoteObservationStore(port=port)
            client.report_point("t", "loss", 0.5, step=1)
            client.close()
        finally:
            # SIGKILL the wrapper: PDEATHSIG must take the native daemon
            # down with it (no orphan holding the port/journal), making
            # this a REAL daemon-crash durability exercise
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait()
        # generous: PDEATHSIG delivery is prompt, but a loaded 1-core box
        # can starve the probe loop itself (observed flaking at 5s under a
        # full parallel suite while passing in isolation)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            try:
                probe = RemoteObservationStore(port=port, timeout=0.3)
                probe.ping()
                probe.close()
                time.sleep(0.1)  # daemon still up; PDEATHSIG is async
            except (ConnectionError, OSError):
                break
        else:
            raise AssertionError("daemon outlived its SIGKILLed CLI wrapper")

        proc, port = launch()
        try:
            client = RemoteObservationStore(port=port)
            assert [(l.value, l.step) for l in client.get("t", "loss")] == [(0.5, 1)]
            client.close()
        finally:
            proc.terminate()
            proc.wait()
            assert proc.returncode == 0, proc.returncode  # clean SIGTERM exit


class TestObservability:
    def test_counters_and_render(self):
        from katib_tpu.utils.observability import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("test_total", "help text")
        g = reg.gauge("test_current")
        c.inc()
        c.inc(2, algorithm="tpe")
        g.set(5)
        text = reg.render()
        assert "# HELP test_total help text" in text
        assert "# TYPE test_total counter" in text
        assert "test_total 1" in text
        assert 'test_total{algorithm="tpe"} 2' in text
        assert "test_current 5" in text

    def test_orchestrator_increments(self, tmp_path):
        from katib_tpu.orchestrator.orchestrator import Orchestrator
        from katib_tpu.utils import observability as obs

        created0 = obs.trials_created.get()
        succ0 = obs.trials_succeeded.get()
        exp_done0 = obs.experiments_succeeded.get(algorithm="random")

        def train(ctx):
            ctx.report(loss=1.0)

        spec = make_spec("random", train_fn=train, max_trial_count=2,
                         parallel_trial_count=1)
        exp = Orchestrator(workdir=str(tmp_path)).run(spec)
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert obs.trials_created.get() - created0 == 2
        assert obs.trials_succeeded.get() - succ0 == 2
        assert obs.experiments_succeeded.get(algorithm="random") - exp_done0 == 1
        assert obs.experiments_current.get() == 0

    def test_http_exposition(self):
        from katib_tpu.utils.observability import REGISTRY

        server = REGISTRY.serve(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5
            ).read().decode()
            assert "katib_experiment_created_total" in body
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/nope", timeout=5
                )
        finally:
            server.stop()


class TestConformanceCommand:
    def test_conformance_passes(self, capsys):
        from katib_tpu.cli import main

        rc = main(["conformance", "--max-trials", "4"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "CONFORMANCE PASS" in out
        assert "MaxTrialsReached" in out
