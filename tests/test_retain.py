"""``retain`` semantics: orchestrator prunes successful trials' checkpoint
steps unless retained (reference deletes the trial job unless ``retain``,
``trial_controller.go:297-306``); PBT lineage dirs are exempt."""

import os

import jax.numpy as jnp

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)
from katib_tpu.orchestrator import Orchestrator


def _spec(tmp_path, retain: bool, name: str):
    def trainer(ctx):
        ctx.save_checkpoint({"w": jnp.ones(4)}, step=1)
        ctx.report(accuracy=0.9, step=0)

    return ExperimentSpec(
        name=name,
        algorithm=AlgorithmSpec(name="random"),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0)),
        ],
        max_trial_count=2,
        parallel_trial_count=1,
        train_fn=trainer,
        retain=retain,
    )


def _step_dirs(trial):
    d = trial.checkpoint_dir
    if not os.path.isdir(d):
        return []
    return [n for n in os.listdir(d) if n.startswith("step_")]


class TestRetain:
    def test_default_prunes_checkpoint_steps(self, tmp_path):
        spec = _spec(tmp_path, retain=False, name="no-retain")
        exp = Orchestrator(workdir=str(tmp_path / "runs")).run(spec)
        for t in exp.trials.values():
            assert _step_dirs(t) == [], "steps should be pruned by default"

    def test_retain_keeps_checkpoints(self, tmp_path):
        spec = _spec(tmp_path, retain=True, name="retained")
        exp = Orchestrator(workdir=str(tmp_path / "runs")).run(spec)
        for t in exp.trials.values():
            assert _step_dirs(t), "retained trials keep their checkpoints"
