"""Preemption-safe drain, hang watchdog, and last-good checkpoint recovery.

Covers the robustness PR end to end: the heartbeat watchdog (fake-clock unit
tests + orchestrator-level hang→HANG→retry recovery for white- and black-box
trials), graceful drain (SIGTERM semantics: running trials checkpoint-and-
exit, journal stays resumable, resume continues from the checkpointed step
instead of step 0), and checkpoint verification (manifest sidecars, corrupt-
latest fallback with quarantine, crash-atomic PBT lineage copies).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time

import numpy as np
import pytest

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    MetricsCollectorKind,
    MetricsCollectorSpec,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    ResumePolicy,
    Trial,
    TrialCondition,
    TrialSpec,
)
from katib_tpu.orchestrator import Orchestrator
from katib_tpu.orchestrator.resume import trial_from_dict
from katib_tpu.orchestrator.status import read_status
from katib_tpu.runner.trial_runner import run_trial
from katib_tpu.store.base import MemoryObservationStore
from katib_tpu.utils import observability as obs
from katib_tpu.utils.checkpoint import TrialCheckpointer, copy_checkpoint_tree
from katib_tpu.utils.faults import FailureKind, FaultInjector
from katib_tpu.utils.watchdog import Watchdog

OBJECTIVE = ObjectiveSpec(type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy")


def make_spec(name, train_fn, **kw) -> ExperimentSpec:
    kw.setdefault("max_trial_count", 1)
    kw.setdefault("parallel_trial_count", 1)
    kw.setdefault("retry_backoff_seconds", 0.01)
    return ExperimentSpec(
        name=name,
        algorithm=AlgorithmSpec(name="random", settings={"seed": "0"}),
        objective=OBJECTIVE,
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0))
        ],
        train_fn=train_fn,
        **kw,
    )


# ---------------------------------------------------------------------------
# watchdog unit behavior (fake clock, synchronous scans)
# ---------------------------------------------------------------------------


class TestWatchdog:
    def _manual(self):
        """Watchdog whose monitor thread effectively never scans — every
        scan in these tests is an explicit, deterministic check_now()."""
        t = [0.0]
        wd = Watchdog(interval=3600.0, clock=lambda: t[0])
        return wd, t

    def test_fires_after_deadline_exactly_once(self):
        wd, t = self._manual()
        fired = []
        hb = wd.register("t1", deadline=1.0, on_hang=fired.append)
        try:
            assert wd.check_now() == []
            t[0] = 0.9
            assert wd.check_now() == []
            assert not hb.fired
            t[0] = 1.5
            assert wd.check_now() == ["t1"]
            assert hb.fired and fired == ["t1"]
            # a hang is classified once; later scans stay silent
            t[0] = 50.0
            assert wd.check_now() == []
            assert wd.hang_count == 1
        finally:
            wd.stop()

    def test_beat_resets_the_stall_clock(self):
        wd, t = self._manual()
        hb = wd.register("t1", deadline=1.0)
        try:
            t[0] = 0.9
            hb.beat()
            t[0] = 1.8  # only 0.9 since the beat
            assert wd.check_now() == []
            t[0] = 3.0
            assert wd.check_now() == ["t1"]
        finally:
            wd.stop()

    def test_unregistered_heartbeat_never_fires(self):
        wd, t = self._manual()
        hb = wd.register("t1", deadline=1.0)
        try:
            hb.close()
            t[0] = 10.0
            assert wd.check_now() == []
        finally:
            wd.stop()

    def test_independent_deadlines(self):
        wd, t = self._manual()
        wd.register("fast", deadline=1.0)
        wd.register("slow", deadline=5.0)
        try:
            t[0] = 2.0
            assert wd.check_now() == ["fast"]
            t[0] = 6.0
            assert wd.check_now() == ["slow"]
        finally:
            wd.stop()

    def test_bad_on_hang_callback_is_swallowed(self):
        wd, t = self._manual()
        wd.register("t1", deadline=1.0, on_hang=lambda name: 1 / 0)
        try:
            t[0] = 2.0
            assert wd.check_now() == ["t1"]  # ZeroDivisionError must not escape
        finally:
            wd.stop()

    def test_metric_counts_hangs(self):
        before = obs.trial_hangs.get()
        wd, t = self._manual()
        wd.register("t1", deadline=0.5)
        try:
            t[0] = 1.0
            wd.check_now()
        finally:
            wd.stop()
        assert obs.trial_hangs.get() - before == 1


# ---------------------------------------------------------------------------
# hang -> HANG classification -> retry recovery
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestHangRecovery:
    def test_whitebox_hang_is_classified_and_retried(self, tmp_path):
        inj = FaultInjector(seed=0)
        inj.hang_trial(0, attempt=1)

        def trainer(ctx):
            ctx.report(step=0, accuracy=0.9)

        spec = make_spec(
            "hang-retry", trainer, max_retries=2, progress_deadline_seconds=0.4
        )
        exp = Orchestrator(workdir=str(tmp_path), fault_injector=inj).run(spec)
        trial = next(iter(exp.trials.values()))
        # attempt 1 wedged in maybe_hang until the watchdog flagged it,
        # attempt 2 (no injection) ran clean from the same checkpoint dir
        assert trial.condition is TrialCondition.SUCCEEDED
        assert trial.retry_count == 1
        assert trial.failure_kind == FailureKind.HANG.value
        assert any(e.get("seam") == "hang" for e in inj.log)

    def test_whitebox_hang_without_retry_budget_fails_as_hang(self, tmp_path):
        inj = FaultInjector(seed=0)
        inj.hang_trial(0, attempt=1)

        def trainer(ctx):
            ctx.report(step=0, accuracy=0.9)

        spec = make_spec(
            "hang-fail", trainer, max_retries=0, progress_deadline_seconds=0.4
        )
        exp = Orchestrator(workdir=str(tmp_path), fault_injector=inj).run(spec)
        trial = next(iter(exp.trials.values()))
        assert trial.condition is TrialCondition.FAILED
        assert trial.failure_kind == FailureKind.HANG.value
        assert "watchdog" in trial.message

    def test_blackbox_hang_escalates_to_kill(self):
        # a subprocess that prints nothing and touches no metrics file makes
        # no progress; the watchdog must interrupt it long before the 60s nap
        trial = Trial(
            name="bb-hang",
            spec=TrialSpec(
                assignments=[],
                command=[sys.executable, "-c", "import time; time.sleep(60)"],
                metrics_collector=MetricsCollectorSpec(
                    kind=MetricsCollectorKind.STDOUT
                ),
                progress_deadline_seconds=0.5,
            ),
        )
        wd = Watchdog(interval=0.1)
        t0 = time.monotonic()
        try:
            result = run_trial(
                trial, MemoryObservationStore(), OBJECTIVE, watchdog=wd
            )
        finally:
            wd.stop()
        assert time.monotonic() - t0 < 30
        assert result.condition is TrialCondition.FAILED
        assert result.failure_kind is FailureKind.HANG


# ---------------------------------------------------------------------------
# graceful drain + resume
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestDrain:
    def test_drain_checkpoints_journal_and_resume_continues(self, tmp_path):
        release = threading.Event()
        gate_open = threading.Event()
        starts: list[int] = []

        def trainer(ctx):
            os.makedirs(ctx.checkpoint_dir, exist_ok=True)
            marker = os.path.join(ctx.checkpoint_dir, "progress.txt")
            start = 0
            if os.path.exists(marker):
                with open(marker) as f:
                    start = int(f.read().strip() or 0)
            starts.append(start)
            for step in range(start, 4):
                cont = ctx.report(step=step, accuracy=(step + 1) / 4.0)
                # marker after the report: the metric is durable (sqlite
                # store) before the checkpoint claims the step happened
                with open(marker, "w") as f:
                    f.write(str(step + 1))
                if not cont:
                    return
                if step == 0 and start == 0:
                    gate_open.set()
                    # deterministic drain window: hold here until the test
                    # drains the orchestrator (or releases us on resume)
                    while not release.is_set() and not ctx.should_stop():
                        time.sleep(0.005)

        spec = make_spec(
            "drain-resume",
            trainer,
            max_trial_count=2,
            resume_policy=ResumePolicy.LONG_RUNNING,
            drain_grace_seconds=10.0,
        )
        orch = Orchestrator(workdir=str(tmp_path))
        runner = threading.Thread(target=lambda: orch.run(spec))
        runner.start()
        assert gate_open.wait(timeout=30)
        orch.drain()
        runner.join(timeout=30)
        assert not runner.is_alive()
        assert orch.drained

        status = read_status(str(tmp_path), "drain-resume")
        assert status is not None
        drained = [
            n for n, d in status["trials"].items() if d["condition"] == "Drained"
        ]
        assert drained, f"no Drained trial journaled: {status['trials']}"
        assert status["counts"]["drained"] == len(drained)
        # the drained trial checkpointed at least one step before exiting
        ckpt = status["trials"][drained[0]]["checkpoint_dir"]
        with open(os.path.join(ckpt, "progress.txt")) as f:
            assert int(f.read()) >= 1

        release.set()
        orch2 = Orchestrator(workdir=str(tmp_path))
        exp2 = orch2.run(spec, experiment=orch2.load_experiment(spec))
        assert exp2.condition.is_terminal()
        assert all(
            t.condition is TrialCondition.SUCCEEDED for t in exp2.trials.values()
        )
        # the resubmitted trial resumed from its checkpointed step, not 0
        assert len(starts) >= 2
        assert starts[1] >= 1, f"resume restarted from scratch: starts={starts}"

    def test_drained_condition_is_not_terminal(self):
        assert not TrialCondition.DRAINED.is_terminal()

    def test_drained_journal_entry_resubmits_with_checkpoint(self, tmp_path):
        spec = make_spec("resub", lambda ctx: None)
        t = trial_from_dict(
            spec,
            {
                "name": "resub-abc",
                "condition": "Drained",
                "assignments": {"lr": 0.5},
                "checkpoint_dir": str(tmp_path / "resub-abc"),
                "retry_count": 1,
            },
        )
        assert t.condition is TrialCondition.PENDING
        assert t.checkpoint_dir == str(tmp_path / "resub-abc")
        assert t.retry_count == 1  # spent budget survives the drain

    def test_drain_before_any_trial_still_resumable(self, tmp_path):
        spec = make_spec("drain-early", lambda ctx: ctx.report(step=0, accuracy=1.0))
        orch = Orchestrator(workdir=str(tmp_path))
        orch.drain()  # sticky: requested before run() enters its loop
        exp = orch.run(spec)
        assert orch.drained
        assert not exp.condition.is_terminal()


# ---------------------------------------------------------------------------
# checkpoint verification, quarantine, last-good fallback
# ---------------------------------------------------------------------------


class TestCheckpointRecovery:
    def _tree(self, k: float):
        return {"w": np.arange(4, dtype=np.float32) + k, "step": np.float32(k)}

    def test_save_writes_verifiable_manifest(self, tmp_path):
        ck = TrialCheckpointer(str(tmp_path / "ck"), max_to_keep=5)
        ck.save(self._tree(1.0), step=1)
        assert ck.verify_step(1) is True
        manifest = os.path.join(ck.directory, "step_00000001.manifest.json")
        with open(manifest) as f:
            doc = json.load(f)
        assert doc["step"] == 1
        assert doc["files"] and doc["tree_digest"]

    def test_corrupt_latest_falls_back_to_previous_good_step(self, tmp_path):
        ck = TrialCheckpointer(str(tmp_path / "ck"), max_to_keep=5)
        ck.save(self._tree(1.0), step=1)
        ck.save(self._tree(2.0), step=2)
        # truncate one payload file of step 2 (a preemption mid-write)
        step2 = os.path.join(ck.directory, "step_00000002")
        victim = None
        for root, _, files in os.walk(step2):
            for fname in files:
                full = os.path.join(root, fname)
                if os.path.getsize(full) > 0:
                    victim = full
                    break
            if victim:
                break
        assert victim is not None
        with open(victim, "w") as f:
            f.write("x")
        assert ck.verify_step(2) is False

        before = obs.checkpoint_fallbacks.get()
        restored = ck.restore()
        assert restored is not None
        tree, step = restored
        assert step == 1
        np.testing.assert_allclose(np.asarray(tree["w"]), self._tree(1.0)["w"])
        assert obs.checkpoint_fallbacks.get() - before == 1
        # the damaged step is quarantined, not silently retried forever
        assert ck.all_steps() == [1]
        quarantined = [
            n for n in os.listdir(ck.directory) if n.startswith("quarantine-")
        ]
        assert quarantined

    def test_manifestless_legacy_step_still_restores(self, tmp_path):
        ck = TrialCheckpointer(str(tmp_path / "ck"))
        ck.save(self._tree(3.0), step=7)
        os.unlink(os.path.join(ck.directory, "step_00000007.manifest.json"))
        assert ck.verify_step(7) is None  # unverifiable, not condemned
        restored = ck.restore()
        assert restored is not None
        assert restored[1] == 7

    def test_all_steps_corrupt_means_cold_start(self, tmp_path):
        ck = TrialCheckpointer(str(tmp_path / "ck"))
        ck.save(self._tree(1.0), step=1)
        manifest = os.path.join(ck.directory, "step_00000001.manifest.json")
        with open(manifest) as f:
            doc = json.load(f)
        doc["files"] = {rel: size + 1 for rel, size in doc["files"].items()}
        with open(manifest, "w") as f:
            json.dump(doc, f)
        before = obs.checkpoint_fallbacks.get()
        assert ck.restore() is None
        assert obs.checkpoint_fallbacks.get() - before == 1
        assert ck.all_steps() == []


# ---------------------------------------------------------------------------
# crash-atomic PBT lineage copies
# ---------------------------------------------------------------------------


class TestAtomicCopy:
    def _seed_src(self, tmp_path):
        src = tmp_path / "parent"
        (src / "step_00000001").mkdir(parents=True)
        (src / "step_00000001" / "data").write_text("parent-weights")
        return str(src)

    def test_copy_lands_complete(self, tmp_path):
        src = self._seed_src(tmp_path)
        dst = str(tmp_path / "child")
        assert copy_checkpoint_tree(src, dst) is True
        assert (
            open(os.path.join(dst, "step_00000001", "data")).read()
            == "parent-weights"
        )
        assert not os.path.exists(dst + ".tmp")

    def test_missing_parent_cold_starts(self, tmp_path):
        assert copy_checkpoint_tree(str(tmp_path / "nope"), str(tmp_path / "c")) is False

    def test_kill_mid_copy_leaves_old_destination_intact(self, tmp_path, monkeypatch):
        import katib_tpu.utils.checkpoint as ckpt_mod

        src = self._seed_src(tmp_path)
        dst = tmp_path / "child"
        (dst / "step_00000000").mkdir(parents=True)
        (dst / "step_00000000" / "data").write_text("old-but-consistent")

        real_copytree = shutil.copytree

        def dies_midway(*args, **kw):
            real_copytree(*args, **kw)  # bytes hit the .tmp sibling...
            raise OSError("simulated preemption during PBT exploit copy")

        monkeypatch.setattr(ckpt_mod.shutil, "copytree", dies_midway)
        with pytest.raises(OSError):
            copy_checkpoint_tree(src, str(dst))
        # the old lineage is untouched — never a half-copied destination
        assert (
            open(dst / "step_00000000" / "data").read() == "old-but-consistent"
        )
        assert not (dst / "step_00000001").exists()

        monkeypatch.setattr(ckpt_mod.shutil, "copytree", real_copytree)
        # retry after the crash: the leftover .tmp is swept and replaced
        assert copy_checkpoint_tree(src, str(dst)) is True
        assert (dst / "step_00000001" / "data").read_text() == "parent-weights"
        assert not (dst / "step_00000000").exists()
