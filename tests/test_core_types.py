"""Core type-layer tests (parity targets: reference experiment/trial CRD
semantics + webhook validation, see SURVEY.md §2.1)."""

import math

import pytest

from katib_tpu.core.types import (
    AlgorithmSpec,
    ComparisonOp,
    Distribution,
    Experiment,
    ExperimentSpec,
    FeasibleSpace,
    Metric,
    MetricStrategy,
    MetricStrategyType,
    MetricsCollectorKind,
    MetricsCollectorSpec,
    Observation,
    ObjectiveSpec,
    ObjectiveType,
    ParameterAssignment,
    ParameterSpec,
    ParameterType,
    Trial,
    TrialCondition,
    TrialSpec,
)
from katib_tpu.core.validation import ValidationError, validate_experiment


def make_objective(**kw):
    defaults = dict(
        type=ObjectiveType.MAXIMIZE,
        objective_metric_name="accuracy",
        goal=0.99,
        additional_metric_names=("loss",),
    )
    defaults.update(kw)
    return ObjectiveSpec(**defaults)


def make_spec(**kw):
    defaults = dict(
        name="exp",
        objective=make_objective(),
        algorithm=AlgorithmSpec(name="random"),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.1)),
            ParameterSpec(
                "units", ParameterType.INT, FeasibleSpace(min=8, max=64, step=8)
            ),
            ParameterSpec(
                "opt", ParameterType.CATEGORICAL, FeasibleSpace(list=("sgd", "adam"))
            ),
        ],
        train_fn=lambda ctx: None,
    )
    defaults.update(kw)
    return ExperimentSpec(**defaults)


class TestFeasibleSpace:
    def test_double_requires_bounds(self):
        with pytest.raises(ValueError):
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0.1))

    def test_log_requires_positive_min(self):
        with pytest.raises(ValueError):
            ParameterSpec(
                "x",
                ParameterType.DOUBLE,
                FeasibleSpace(min=0.0, max=1.0, distribution=Distribution.LOG_UNIFORM),
            )

    def test_categorical_requires_list(self):
        with pytest.raises(ValueError):
            ParameterSpec("x", ParameterType.CATEGORICAL, FeasibleSpace())

    def test_int_grid_values(self):
        p = ParameterSpec("x", ParameterType.INT, FeasibleSpace(min=1, max=10, step=3))
        assert p.grid_values() == [1, 4, 7, 10]

    def test_double_grid_with_step(self):
        p = ParameterSpec(
            "x", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0, step=0.25)
        )
        assert p.grid_values() == pytest.approx([0.0, 0.25, 0.5, 0.75, 1.0])

    def test_cast(self):
        p = ParameterSpec("x", ParameterType.INT, FeasibleSpace(min=0, max=10))
        assert p.cast("3.0") == 3
        assert p.cast(3.6) == 4

    def test_contains(self):
        p = ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0))
        assert p.contains(0.5)
        assert not p.contains(1.5)


class TestObjective:
    def test_better(self):
        assert ObjectiveType.MINIMIZE.better(0.1, 0.2)
        assert ObjectiveType.MAXIMIZE.better(0.9, 0.2)

    def test_default_strategies(self):
        obj = make_objective()
        # maximize objective -> max strategy; additional metrics -> latest
        assert obj.strategy_for("accuracy") is MetricStrategyType.MAX
        assert obj.strategy_for("loss") is MetricStrategyType.LATEST

    def test_explicit_strategy_overrides(self):
        obj = make_objective(
            metric_strategies=(MetricStrategy("accuracy", MetricStrategyType.LATEST),)
        )
        assert obj.strategy_for("accuracy") is MetricStrategyType.LATEST

    def test_goal(self):
        obj = make_objective(goal=0.95)
        assert obj.is_goal_reached(0.96)
        assert not obj.is_goal_reached(0.94)
        mini = make_objective(type=ObjectiveType.MINIMIZE, goal=0.1)
        assert mini.is_goal_reached(0.05)

    def test_strategy_reduce(self):
        vals = [3.0, 1.0, 2.0]
        assert MetricStrategyType.MIN.reduce(vals) == 1.0
        assert MetricStrategyType.MAX.reduce(vals) == 3.0
        assert MetricStrategyType.LATEST.reduce(vals) == 2.0


class TestComparison:
    def test_ops(self):
        assert ComparisonOp.LESS.holds(0.1, 0.2)
        assert ComparisonOp.GREATER.holds(0.3, 0.2)
        assert ComparisonOp.EQUAL.holds(0.2, 0.2)


class TestValidation:
    def test_valid(self):
        validate_experiment(make_spec())

    def test_missing_params(self):
        with pytest.raises(ValidationError, match="parameters"):
            validate_experiment(make_spec(parameters=[]))

    def test_grid_needs_finite_space(self):
        spec = make_spec(algorithm=AlgorithmSpec(name="grid"))
        with pytest.raises(ValidationError, match="finite"):
            validate_experiment(spec)

    def test_grid_ok_with_steps(self):
        spec = make_spec(
            algorithm=AlgorithmSpec(name="grid"),
            parameters=[
                ParameterSpec(
                    "lr", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0, step=0.5)
                )
            ],
        )
        validate_experiment(spec)

    def test_nas_requires_config(self):
        with pytest.raises(ValidationError, match="nas_config"):
            validate_experiment(make_spec(algorithm=AlgorithmSpec(name="darts")))

    def test_exactly_one_entry_point(self):
        with pytest.raises(ValidationError, match="train_fn or command"):
            validate_experiment(make_spec(train_fn=None))

    def test_command_placeholder_check(self):
        spec = make_spec(
            train_fn=None,
            command=["python", "train.py", "--lr=${trialParameters.nope}"],
            metrics_collector=MetricsCollectorSpec(kind=MetricsCollectorKind.STDOUT),
        )
        with pytest.raises(ValidationError, match="nope"):
            validate_experiment(spec)

    def test_duplicate_param_names(self):
        spec = make_spec(
            parameters=[
                ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0)),
                ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0)),
            ]
        )
        with pytest.raises(ValidationError, match="duplicate"):
            validate_experiment(spec)


class TestExperimentStatus:
    def _trial(self, name, cond, acc=None):
        t = Trial(name=name, spec=TrialSpec(), condition=cond)
        if acc is not None:
            t.observation = Observation(
                metrics=[Metric(name="accuracy", value=acc, latest=acc)]
            )
        return t

    def test_optimal_tracking(self):
        exp = Experiment(spec=make_spec())
        exp.trials["a"] = self._trial("a", TrialCondition.SUCCEEDED, 0.8)
        exp.trials["b"] = self._trial("b", TrialCondition.SUCCEEDED, 0.9)
        exp.trials["c"] = self._trial("c", TrialCondition.FAILED, 0.99)  # ignored
        exp.trials["d"] = self._trial("d", TrialCondition.EARLY_STOPPED, 0.85)
        exp.update_optimal()
        assert exp.optimal.trial_name == "b"
        assert exp.optimal.objective_value == 0.9

    def test_optimal_history_curve(self):
        """best-objective@wallclock: one row per improvement, idempotent
        under recompute (the BASELINE driver metric)."""
        exp = Experiment(spec=make_spec())
        exp.trials["a"] = self._trial("a", TrialCondition.SUCCEEDED, 0.8)
        exp.update_optimal()
        exp.update_optimal()  # no change -> no duplicate row
        assert [r["objective_value"] for r in exp.optimal_history] == [0.8]
        exp.trials["b"] = self._trial("b", TrialCondition.SUCCEEDED, 0.7)
        exp.update_optimal()  # worse trial -> optimal unchanged -> no row
        assert len(exp.optimal_history) == 1
        exp.trials["c"] = self._trial("c", TrialCondition.SUCCEEDED, 0.95)
        exp.update_optimal()
        assert [r["objective_value"] for r in exp.optimal_history] == [0.8, 0.95]
        assert exp.optimal_history[-1]["trial_name"] == "c"
        assert exp.optimal_history[-1]["elapsed_s"] >= 0

    def test_counts(self):
        exp = Experiment(spec=make_spec())
        exp.trials["a"] = self._trial("a", TrialCondition.SUCCEEDED, 0.8)
        exp.trials["b"] = self._trial("b", TrialCondition.RUNNING)
        exp.trials["c"] = self._trial("c", TrialCondition.FAILED)
        exp.trials["d"] = self._trial("d", TrialCondition.EARLY_STOPPED, 0.7)
        assert exp.succeeded_count == 1
        assert exp.failed_count == 1
        assert exp.running_count == 1
        # completed = succeeded + early-stopped (reference experiment_controller.go:449-461)
        assert exp.completed_count == 2

    def test_search_space_size(self):
        spec = make_spec()
        assert math.isinf(spec.search_space_size())  # lr double w/o step
        spec2 = make_spec(
            parameters=[
                ParameterSpec("units", ParameterType.INT, FeasibleSpace(min=8, max=24, step=8)),
                ParameterSpec("opt", ParameterType.CATEGORICAL, FeasibleSpace(list=("a", "b"))),
            ]
        )
        assert spec2.search_space_size() == 6
