"""Guards for bench.py's committed on-chip capture memo (the round-3
failure mode: a real TPU measurement existed mid-round, but the driver's
end-of-round bench hit a wedged pool and recorded a CPU fallback)."""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(monkeypatch):
    sys.argv = ["bench"]
    for var in (
        "BENCH_SMALL",
        "BENCH_REMAT",
        "BENCH_REMAT_POLICY",
        "BENCH_BATCH",
        "BENCH_FUSED",
    ):
        monkeypatch.delenv(var, raising=False)
    spec = importlib.util.spec_from_file_location(
        "bench_capture_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_memo_paths_mirror_aot_naming(bench, monkeypatch):
    assert bench._bench_memo_path(bench._aot_expected_config()).endswith(
        "bench_tpu.json"
    )
    monkeypatch.setenv("BENCH_FUSED", "1")
    assert bench._bench_memo_path(bench._aot_expected_config()).endswith(
        "bench_tpu_b64_fused.json"
    )


def test_committed_capture_resolves_for_default_config(bench):
    """The committed round-3 capture must keep satisfying the memo's
    config + jax-version keying — this is the driver's wedged-pool
    fallback to a REAL number."""
    rec = bench._committed_tpu_result()
    assert rec is not None
    assert rec["platform"] == "tpu"
    assert rec["from_committed_artifact"] is True
    assert rec["pool_wedged_at_capture_time"] is True
    assert rec["measured_at"]
    assert rec["config"] == bench._aot_expected_config()


def test_committed_capture_rejected_on_config_drift(bench, monkeypatch):
    """An exploration config must never silently reuse the default
    capture."""
    monkeypatch.setenv("BENCH_BATCH", "128")
    assert bench._committed_tpu_result() is None


def test_persist_refuses_cpu_and_small(bench, tmp_path, monkeypatch):
    """Only full-shape on-chip measurements may become the committed
    capture."""
    calls = []
    monkeypatch.setattr(
        bench.json, "dump", lambda *a, **k: calls.append(a)
    )
    bench._persist_tpu_result(
        {"platform": "cpu", "config": {"small_shapes": False}}
    )
    bench._persist_tpu_result(
        {"platform": "tpu", "config": {"small_shapes": True}}
    )
    # warm-only child results have no config key at all; must not crash
    bench._persist_tpu_result({"warm_only": True, "platform": "tpu"})
    assert calls == []
