"""TLS layer: cert generation + rotation (``utils/certgen.py``) and HTTPS
serving on both network surfaces.

The reference maintains a rotated self-signed CA + webhook serving cert
(``pkg/certgenerator/v1beta1/generator.go:37-58``); these tests pin the same
contract — CA signs the leaf, SANs cover the serving address, rotation
replaces an expiring bundle — plus a real TLS round-trip against the
suggestion service and UI backend with the client trusting only our CA."""

import datetime
import json
import os
import ssl
import urllib.error
import urllib.request

import pytest

from katib_tpu.utils.certgen import (
    CA_NAME,
    ORGANIZATION,
    client_ssl_context,
    ensure_certs,
    generate_certs,
    server_ssl_context,
)


def _load(path):
    from cryptography import x509

    with open(path, "rb") as f:
        return x509.load_pem_x509_certificate(f.read())


class TestGeneration:
    def test_bundle_files_and_permissions(self, tmp_path):
        b = generate_certs(str(tmp_path / "certs"))
        assert os.path.exists(b.ca_cert)
        assert os.path.exists(b.cert)
        assert os.path.exists(b.key)
        assert (os.stat(b.key).st_mode & 0o777) == 0o600
        # the CA private key must NOT be persisted (rotation regenerates)
        assert not any(
            "ca" in f and f.endswith(".key") for f in os.listdir(tmp_path / "certs")
        )

    def test_ca_signs_leaf_with_reference_names(self, tmp_path):
        from cryptography.x509.oid import NameOID

        b = generate_certs(str(tmp_path), dns_names=("suggest.local", "localhost"))
        ca, leaf = _load(b.ca_cert), _load(b.cert)
        assert ca.subject.get_attributes_for_oid(NameOID.COMMON_NAME)[0].value == CA_NAME
        org = ca.subject.get_attributes_for_oid(NameOID.ORGANIZATION_NAME)[0].value
        assert org == ORGANIZATION
        assert leaf.issuer == ca.subject
        leaf.verify_directly_issued_by(ca)  # raises on bad signature

    def test_leaf_sans(self, tmp_path):
        from cryptography import x509

        b = generate_certs(
            str(tmp_path), dns_names=("a.example",), ip_addresses=("127.0.0.1",)
        )
        san = _load(b.cert).extensions.get_extension_for_class(
            x509.SubjectAlternativeName
        ).value
        assert "a.example" in san.get_values_for_type(x509.DNSName)
        assert [str(i) for i in san.get_values_for_type(x509.IPAddress)] == ["127.0.0.1"]

    def test_ensure_reuses_fresh_bundle(self, tmp_path):
        b1 = ensure_certs(str(tmp_path))
        serial1 = _load(b1.cert).serial_number
        b2 = ensure_certs(str(tmp_path))
        assert _load(b2.cert).serial_number == serial1

    def test_ensure_rotates_expiring_leaf(self, tmp_path):
        b1 = ensure_certs(str(tmp_path))
        serial1 = _load(b1.cert).serial_number
        # a leaf with < rotate_before_days of life left must be replaced
        b2 = ensure_certs(str(tmp_path), rotate_before_days=400)
        assert _load(b2.cert).serial_number != serial1

    def test_ensure_rotates_on_san_mismatch(self, tmp_path):
        """A bundle minted for another host must be regenerated even when
        unexpired — otherwise pinned clients fail verification for a year."""
        b1 = ensure_certs(str(tmp_path), dns_names=("localhost",))
        serial1 = _load(b1.cert).serial_number
        b2 = ensure_certs(str(tmp_path), dns_names=("localhost", "other.host"))
        assert _load(b2.cert).serial_number != serial1
        # and the rotated leaf now covers the wider set → stable again
        b3 = ensure_certs(str(tmp_path), dns_names=("localhost", "other.host"))
        assert _load(b3.cert).serial_number == _load(b2.cert).serial_number

    def test_ensure_regenerates_missing_file(self, tmp_path):
        b1 = ensure_certs(str(tmp_path))
        os.remove(b1.key)
        b2 = ensure_certs(str(tmp_path))
        assert os.path.exists(b2.key)
        # key and cert must match again (context construction validates)
        server_ssl_context(b2)

    def test_leaf_validity_window(self, tmp_path):
        b = generate_certs(str(tmp_path))
        leaf = _load(b.cert)
        now = datetime.datetime.now(datetime.timezone.utc)
        assert leaf.not_valid_before_utc <= now <= leaf.not_valid_after_utc


class TestHttpsServing:
    def test_suggest_service_over_tls(self, tmp_path):
        from katib_tpu.suggest.service import serve_suggestions

        bundle = ensure_certs(str(tmp_path))
        svc = serve_suggestions(ssl_context=server_ssl_context(bundle))
        try:
            ctx = client_ssl_context(bundle.ca_cert)
            with urllib.request.urlopen(
                f"https://127.0.0.1:{svc.port}/healthz", timeout=5, context=ctx
            ) as r:
                assert json.loads(r.read())["status"] == "serving"
            # a client with default trust (no our-CA pin) must be rejected
            with pytest.raises((urllib.error.URLError, ssl.SSLError)):
                urllib.request.urlopen(
                    f"https://127.0.0.1:{svc.port}/healthz", timeout=5
                )
        finally:
            svc.stop()

    def test_ui_over_tls(self, tmp_path):
        from katib_tpu.ui import start_ui

        bundle = ensure_certs(str(tmp_path / "certs"))
        ui = start_ui(
            str(tmp_path / "runs"), ssl_context=server_ssl_context(bundle)
        )
        try:
            ctx = client_ssl_context(bundle.ca_cert)
            with urllib.request.urlopen(
                f"https://127.0.0.1:{ui.port}/api/experiments", timeout=5, context=ctx
            ) as r:
                assert r.status == 200
        finally:
            ui.stop()

    def test_plain_http_client_fails_against_tls_server(self, tmp_path):
        from katib_tpu.suggest.service import serve_suggestions

        bundle = ensure_certs(str(tmp_path))
        svc = serve_suggestions(ssl_context=server_ssl_context(bundle))
        try:
            with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{svc.port}/healthz", timeout=5
                )
        finally:
            svc.stop()
