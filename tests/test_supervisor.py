"""Loop supervision (orchestrator/supervisor.py) and its engine wiring:
stall-vs-starvation classification, crash restarts with generation
fencing, restart-budget exhaustion degrading to the sync path,
speculative straggler re-dispatch with first-settle-wins, and the
deadline-bounded suggester call.

The unit tests drive :class:`LoopSupervisor` with a fake clock and bare
threads; the engine tests kill real loop threads mid-run through the
``FaultInjector`` seams and assert recovery with zero lost or duplicated
settlements (journal replay is the referee).
"""

import os
import threading
import time

import pytest

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentCondition,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    TrialCondition,
)
from katib_tpu.orchestrator import Orchestrator
from katib_tpu.orchestrator import journal as jr
from katib_tpu.orchestrator import supervisor as sup_mod
from katib_tpu.orchestrator.supervisor import LoopSupervisor
from katib_tpu.suggest.base import Suggester, call_suggester, make_suggester
from katib_tpu.utils.faults import Backoff, CircuitBreaker, FaultInjector

OBJ = ObjectiveSpec(type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy")


def grid_spec(points=8, **kw):
    defaults = dict(
        name=kw.pop("name", f"sup-exp-{time.time_ns()}"),
        objective=OBJ,
        algorithm=AlgorithmSpec(name="grid"),
        parameters=[
            ParameterSpec(
                "x",
                ParameterType.DOUBLE,
                FeasibleSpace(min=0.0, max=float(points - 1), step=1.0),
            )
        ],
        max_trial_count=points,
        parallel_trial_count=4,
        async_orch=True,
        train_fn=lambda ctx: ctx.report(
            step=1, accuracy=1.0 - 0.01 * (float(ctx.params["x"]) - 2.0) ** 2
        ),
    )
    defaults.update(kw)
    return ExperimentSpec(**defaults)


def assert_exactly_once(workdir, exp):
    """Journal replay is the settlement referee: zero duplicate settle
    records, and every in-memory terminal trial terminal in the replay."""
    state, stats = jr.replay_journal(workdir, exp.name)
    assert stats.duplicates == 0, f"double-settled records: {stats.duplicates}"
    replayed = (state or {}).get("trials") or {}
    for t in exp.trials.values():
        if t.condition.is_terminal():
            assert t.name in replayed, f"settled trial lost: {t.name}"
            assert replayed[t.name]["condition"] == t.condition.value


# ---------------------------------------------------------------------------
# supervisor units (fake clock, bare threads)
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def alive_spawn(gen):
    """An 'alive' loop: parks on an event until the test ends."""
    t = threading.Thread(target=threading.Event().wait, daemon=True)
    t.start()
    return t


def dead_spawn(gen):
    """A loop that dies instantly (already joined when returned)."""
    t = threading.Thread(target=lambda: None, daemon=True)
    t.start()
    t.join()
    return t


def make_sup(clock, **kw):
    kw.setdefault("stall_deadline", 10.0)
    kw.setdefault("backoff", Backoff(base=1.0, factor=1.0, cap=1.0, jitter=0.0))
    return LoopSupervisor(clock=clock, **kw)


class TestClassification:
    def test_fresh_loop_is_ok(self):
        clk = FakeClock()
        sup = make_sup(clk)
        sup.add("a", alive_spawn)
        assert sup.tick() == {"a": sup_mod.OK}

    def test_no_work_is_starved_not_stalled(self):
        clk = FakeClock()
        sup = make_sup(clk)
        sup.add("a", alive_spawn, has_work=lambda: False)
        clk.advance(100.0)  # way past the deadline — but there was no work
        assert sup.tick()["a"] == sup_mod.STARVED
        clk.advance(100.0)
        assert sup.tick()["a"] == sup_mod.STARVED

    def test_starved_loop_gets_fresh_deadline_when_work_arrives(self):
        clk = FakeClock()
        work = [False]
        sup = make_sup(clk)
        sup.add("a", alive_spawn, has_work=lambda: work[0])
        clk.advance(100.0)
        assert sup.tick()["a"] == sup_mod.STARVED
        work[0] = True
        # the idle century must not count: the first tick after work
        # arrives re-arms the watermark instead of declaring a stall
        assert sup.tick()["a"] == sup_mod.OK
        clk.advance(9.0)
        assert sup.tick()["a"] == sup_mod.OK  # still inside the deadline
        clk.advance(2.0)
        assert sup.tick()["a"] == sup_mod.STALLED  # now it is overdue

    def test_beat_defers_stall(self):
        clk = FakeClock()
        sup = make_sup(clk)
        sup.add("a", alive_spawn)
        for _ in range(5):
            clk.advance(9.0)
            sup.beat("a")
            assert sup.tick()["a"] == sup_mod.OK

    def test_finished_dead_loop_is_done(self):
        clk = FakeClock()
        sup = make_sup(clk)
        sup.add("a", dead_spawn, finished=lambda: True)
        assert sup.tick()["a"] == sup_mod.DONE

    def test_dead_unfinished_loop_is_crashed(self):
        clk = FakeClock()
        sup = make_sup(clk)
        sup.add("a", dead_spawn)
        assert sup.tick()["a"] == sup_mod.CRASHED


class TestRestartsAndFallback:
    def test_crash_restarts_with_generation_bump(self):
        clk = FakeClock()
        spawned = []

        def spawn(gen):
            spawned.append(gen)
            return alive_spawn(gen) if gen > 0 else dead_spawn(gen)

        restarts = []
        sup = make_sup(clk, on_restart=lambda *a: restarts.append(a))
        sup.add("a", spawn)
        assert sup.tick()["a"] == sup_mod.CRASHED  # schedules the restart
        assert sup.tick()["a"] == sup_mod.RESTARTING  # backoff not yet due
        clk.advance(1.5)
        assert sup.tick()["a"] == sup_mod.OK  # restarted
        assert spawned == [0, 1]
        assert sup.generation("a") == 1
        assert sup.restart_counts() == {"a": 1}
        assert restarts == [("a", 1, sup_mod.CRASHED, 1)]
        assert sup.tick()["a"] == sup_mod.OK  # the replacement is healthy

    def test_budget_exhaustion_raises_fallback(self):
        clk = FakeClock()
        reasons = []
        sup = make_sup(
            clk, restart_budget=2, on_fallback=lambda r: reasons.append(r)
        )
        sup.add("a", dead_spawn)  # every generation dies instantly
        for _ in range(2):
            assert sup.tick()["a"] == sup_mod.CRASHED
            clk.advance(1.5)
            assert sup.tick()["a"] == sup_mod.OK  # restart burned
        assert sup.tick()["a"] == sup_mod.CRASHED  # third death: budget gone
        assert sup.fallback
        assert "'a'" in sup.fallback_reason and "crashed" in sup.fallback_reason
        assert reasons == [sup.fallback_reason]
        # frozen after fallback: no further restarts are scheduled
        assert sup.restart_counts() == {"a": 2}
        sup.tick()
        assert sup.restart_counts() == {"a": 2}

    def test_zero_budget_falls_back_on_first_crash(self):
        clk = FakeClock()
        sup = make_sup(clk, restart_budget=0)
        sup.add("a", dead_spawn)
        assert sup.tick()["a"] == sup_mod.CRASHED
        assert sup.fallback

    def test_stalled_loop_restarts_too(self):
        clk = FakeClock()
        spawned = []

        def spawn(gen):
            spawned.append(gen)
            return alive_spawn(gen)

        sup = make_sup(clk)
        sup.add("a", spawn)
        clk.advance(11.0)  # work available, watermark frozen
        assert sup.tick()["a"] == sup_mod.STALLED
        clk.advance(1.5)
        assert sup.tick()["a"] == sup_mod.OK
        assert spawned == [0, 1]


class TestBackoffJitter:
    def test_full_jitter_bounded_and_seeded(self):
        a = Backoff(base=2.0, factor=2.0, cap=5.0, full_jitter=True, seed=7)
        b = Backoff(base=2.0, factor=2.0, cap=5.0, full_jitter=True, seed=7)
        for attempt in range(1, 8):
            da, db = a.delay(attempt), b.delay(attempt)
            assert da == db  # same seed, same schedule
            assert 0.0 <= da <= min(2.0 * 2.0 ** (attempt - 1), 5.0)


# ---------------------------------------------------------------------------
# engine: kill each loop mid-run, recover exactly-once
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestLoopKillRecovery:
    @pytest.mark.parametrize("loop", ["suggest", "schedule", "harvest"])
    def test_killed_loop_recovers_without_loss_or_dup(self, loop, tmp_path):
        # iteration 1 = the loop dies before doing ANY work, so the run
        # can only complete if the supervisor actually restarts it (a
        # later kill can race a fast experiment to completion)
        injector = FaultInjector(seed=0).kill_loop(loop, at_iteration=1)
        spec = grid_spec(points=8, loop_restart_budget=3)
        orch = Orchestrator(workdir=str(tmp_path), fault_injector=injector)
        exp = orch.run(spec)
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert len(exp.trials) == 8
        assert all(
            t.condition is TrialCondition.SUCCEEDED for t in exp.trials.values()
        )
        st = orch.async_stats
        assert st is not None and st["fallback"] is None
        assert st["loop_restarts"].get(loop, 0) >= 1, st
        assert any(e.get("seam") == "kill-loop" for e in injector.log)
        assert_exactly_once(str(tmp_path), exp)

    def test_budget_exhaustion_degrades_to_sync_path(self, tmp_path):
        # every suggest generation dies on its first iteration: the budget
        # burns down and the engine must hand the experiment to the sync
        # loop, which still completes it
        injector = FaultInjector(seed=0)
        for it in range(1, 13):
            injector.kill_loop("suggest", at_iteration=it)
        spec = grid_spec(points=6, loop_restart_budget=2)
        orch = Orchestrator(workdir=str(tmp_path), fault_injector=injector)
        exp = orch.run(spec)
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert len(exp.trials) == 6
        assert all(
            t.condition is TrialCondition.SUCCEEDED for t in exp.trials.values()
        )
        st = orch.async_stats
        assert st is not None
        assert st["fallback"] and "suggest" in st["fallback"]
        assert st["loop_restarts"]["suggest"] == 2
        assert_exactly_once(str(tmp_path), exp)


# ---------------------------------------------------------------------------
# engine: speculative straggler re-dispatch
# ---------------------------------------------------------------------------


def straggler_trainer(ctx):
    """x == 0 is a rigged straggler — but only on its ORIGINAL dispatch;
    the speculative rival (checkpoint dir suffixed ``-speculative``) runs
    fast, so the rival must win the settle race."""
    x = float(ctx.params["x"])
    if x == 0.0 and not ctx.checkpoint_dir.endswith("-speculative"):
        deadline = time.monotonic() + 1.5
        while time.monotonic() < deadline:
            time.sleep(0.05)
    ctx.report(step=1, accuracy=1.0 - 0.01 * (x - 2.0) ** 2)


@pytest.mark.chaos
class TestSpeculation:
    def test_straggler_respeculated_first_settle_wins(self, tmp_path):
        spec = grid_spec(
            points=8,
            speculative_redispatch=True,
            straggler_factor=2.0,
            train_fn=straggler_trainer,
        )
        orch = Orchestrator(workdir=str(tmp_path))
        t0 = time.monotonic()
        exp = orch.run(spec)
        elapsed = time.monotonic() - t0
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert len(exp.trials) == 8
        assert all(
            t.condition is TrialCondition.SUCCEEDED for t in exp.trials.values()
        )
        st = orch.async_stats
        assert st is not None
        assert st["speculative_dispatches"] >= 1, st
        # a win proves the rival settled FIRST and the straggler's later
        # settle was discarded (pool teardown still joins the orphan, so
        # wall-clock alone cannot prove the race)
        assert st["speculative_wins"] >= 1, st
        assert elapsed < 10.0, f"speculation run took too long: {elapsed:.1f}s"
        assert_exactly_once(str(tmp_path), exp)

    def test_speculation_off_by_default(self, tmp_path):
        spec = grid_spec(points=4)
        orch = Orchestrator(workdir=str(tmp_path))
        orch.run(spec)
        assert orch.async_stats["speculative_dispatches"] == 0


# ---------------------------------------------------------------------------
# deadline-bounded suggester call
# ---------------------------------------------------------------------------


class WedgedSuggester(Suggester):
    """get_suggestions blocks far past any reasonable deadline."""

    name = "wedged"
    adaptive = False

    def __init__(self, inner):
        self.inner = inner
        self.spec = inner.spec

    def get_suggestions(self, experiment, count):
        time.sleep(30.0)
        return self.inner.get_suggestions(experiment, count)


class TestSuggesterDeadline:
    def test_deadline_abandons_call_and_records_breaker_failure(self):
        spec = grid_spec(points=4)
        sug = WedgedSuggester(make_suggester(spec))
        breaker = CircuitBreaker(threshold=3)
        from katib_tpu.core.types import Experiment

        exp = Experiment(spec=spec)
        t0 = time.monotonic()
        proposals, outcome = call_suggester(
            sug, exp, 2, breaker, None, deadline=0.3
        )
        assert time.monotonic() - t0 < 2.0  # returned, not wedged
        assert proposals == [] and outcome == "error"
        assert breaker.failures == 1
        assert "deadline" in breaker.last_failure

    def test_wedged_suggester_fails_diagnosed_not_hung(self, tmp_path):
        import katib_tpu.orchestrator.orchestrator as orch_mod

        spec = grid_spec(
            points=4,
            loop_stall_deadline_seconds=0.5,
            suggester_max_errors=2,
        )
        orig = make_suggester
        orch_mod.make_suggester = lambda s: WedgedSuggester(orig(s))
        try:
            orch = Orchestrator(workdir=str(tmp_path))
            t0 = time.monotonic()
            exp = orch.run(spec)
            elapsed = time.monotonic() - t0
        finally:
            orch_mod.make_suggester = orig
        assert exp.condition is ExperimentCondition.FAILED
        assert "deadline" in (exp.message or "")
        assert elapsed < 20.0, "wedged suggester froze the run"


# ---------------------------------------------------------------------------
# bounded soak smoke (excluded from tier-1: slow + soak markers)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.soak
def test_soak_smoke():
    from katib_tpu.orchestrator.soak import run_soak

    assert run_soak(seconds=30, seed=1, trials=8) == 0
