"""Guards of the performance harnesses (scripts/run_batch_scaling.py,
bench.py AOT memoization) — the parts whose failure modes involve a real
chip (terminal-crashing compiles, clobbered memo fast-paths) and so must
be pinned without one."""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def scaling():
    return _load("rbs", "scripts/run_batch_scaling.py")


class TestBatchScalingGuards:
    def test_parse_configs(self, scaling):
        assert scaling.parse_configs("64:none,128:dots, 256:dots") == [
            (64, None, False, None),
            (128, "dots", False, None),
            (256, "dots", False, None),
        ]
        assert scaling.parse_configs("64") == [(64, None, False, None)]

    def test_parse_configs_variant_fields(self, scaling):
        """'ph' and 'w<N>' compose in either order; anything else must
        raise — a typo'd variant silently parsed as the plain program
        would burn a chip point on the wrong measurement."""
        assert scaling.parse_configs("128:dots:ph") == [(128, "dots", True, None)]
        assert scaling.parse_configs("128:dots:w8") == [(128, "dots", False, 8)]
        assert scaling.parse_configs("128:dots:ph:w8") == [(128, "dots", True, 8)]
        assert scaling.parse_configs("128:dots:w8:ph") == [(128, "dots", True, 8)]
        for bad in ("128:dots:hp", "128:dots:w0", "128:dots:wx", "128:dots:w"):
            with pytest.raises(ValueError, match="variant field"):
                scaling.parse_configs(bad)

    def test_known_configs_have_committed_aot_proofs(self, scaling):
        """The default study configs must be runnable: each carries a
        committed deviceless-AOT block that says it fits."""
        for batch, policy, _ph, _w in scaling.parse_configs("64:none,128:dots"):
            blk = scaling.aot_block_for(batch, policy)
            assert blk is not None, (batch, policy)
            assert blk["hbm_fits_v5e"] is True
            assert blk["config"]["batch"] == batch

    def test_unproven_config_has_no_block(self, scaling):
        """batch-512 crashed the pool terminal once; it must never have a
        fit-proof unless someone deliberately AOT-compiles it."""
        assert scaling.aot_block_for(512, "dots") is None


class TestAotMemoKeying:
    def test_default_and_exploration_paths_differ(self, monkeypatch):
        sys.argv = ["bench"]
        monkeypatch.delenv("BENCH_BATCH", raising=False)
        monkeypatch.delenv("BENCH_REMAT", raising=False)
        monkeypatch.delenv("BENCH_REMAT_POLICY", raising=False)
        monkeypatch.delenv("BENCH_SMALL", raising=False)
        bench = _load("bench_memo_test", "bench.py")
        default = bench._aot_memo_path(bench._aot_expected_config())
        assert default.endswith("aot_v5e.json")

        monkeypatch.setenv("BENCH_BATCH", "128")
        monkeypatch.setenv("BENCH_REMAT", "1")
        monkeypatch.setenv("BENCH_REMAT_POLICY", "dots")
        explore = bench._aot_memo_path(bench._aot_expected_config())
        assert explore.endswith("aot_v5e_b128_remat_dots.json")
        assert explore != default

    def test_committed_default_memo_matches_default_config(self, monkeypatch):
        """The driver's end-of-round bench relies on this memo hit to skip
        a ~26 min AOT recompile; a drifted config key would silently cost
        the round that time."""
        sys.argv = ["bench"]
        for var in ("BENCH_BATCH", "BENCH_REMAT", "BENCH_REMAT_POLICY", "BENCH_SMALL"):
            monkeypatch.delenv(var, raising=False)
        bench = _load("bench_memo_test2", "bench.py")
        cfg = bench._aot_expected_config()
        with open(bench._aot_memo_path(cfg)) as f:
            memo = json.load(f)
        assert memo["config"] == cfg
        import jax

        assert memo["jax_version"] == jax.__version__
