"""Serialized-executable artifact cache (``katib_tpu/compile/artifacts.py``).

Covers the acceptance properties of the zero-cold-start layer:
- envelope integrity: pack/unpack round-trips, and every corruption mode
  (bad magic, torn body, flipped checksum) raises ``ArtifactCorrupt``
  instead of misloading;
- publish -> fetch round-trips a real jitted program with bit-identical
  outputs on CPU;
- invalidation: a changed environment fingerprint changes the content
  address, so another env's artifact is a miss (stale, never misloaded);
- degradation: corrupt/misaddressed envelopes quarantine and the fetch
  returns empty — a trial always falls back to the cold compile;
- atomicity: concurrent publishers of one signature leave exactly one
  intact envelope and no temp files (tmp + rename);
- the prewarm worker publishes each observed program once and satisfies
  duplicate requests from the tier instead of recompiling;
- the shape registry compacts duplicate JSONL rows on open while keeping
  the journal's torn-tail tolerance;
- per-tier hit/miss/publish/quarantine counters feed ``/api/status``.

CPU-only: conftest forces 8 virtual CPU devices; ``serialize_executable``
round-trips fine on the host platform.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

import katib_tpu.compile.artifacts as artifacts
from katib_tpu.compile.artifacts import (
    ArtifactCache,
    ArtifactCorrupt,
    DirectoryBackend,
    artifact_name,
    env_fingerprint,
    fingerprint_key,
    fsck_artifacts,
    is_artifact_dir,
    pack_envelope,
    publish_observed,
    read_header,
    resolve,
    scan_dir,
    serialize_compiled,
    sig_from_key,
    unpack_envelope,
)
from katib_tpu.compile.prewarm import (
    PrewarmRequest,
    PrewarmWorker,
    attach_prewarm_fn,
)
from katib_tpu.compile.registry import (
    REGISTRY,
    CompileSignature,
    ShapeRegistry,
)
from katib_tpu.utils import observability as obs


def _tier_total(metric, tier: str) -> float:
    return sum(
        v for labels, v in metric.samples() if (labels or {}).get("tier") == tier
    )


def _sig(program: str = "artifact_test.step", k: int = 2) -> CompileSignature:
    return CompileSignature(program=program, shapes=(("units", "8"),), k=k)


def _jit_step():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x, y):
        return x @ y + jnp.tanh(x).sum()

    return step


def _args():
    rng = np.random.default_rng(7)
    return (
        np.asarray(rng.normal(size=(4, 8)), dtype=np.float32),
        np.asarray(rng.normal(size=(8, 8)), dtype=np.float32),
    )


@pytest.fixture
def tiers(tmp_path, monkeypatch):
    """A fresh two-tier world: local under ``tmp_path/xla/artifacts``,
    shared at ``tmp_path/shared`` — the module singleton reset around it."""
    monkeypatch.delenv("KATIB_ARTIFACT_DIR", raising=False)
    monkeypatch.setattr(artifacts, "_cache_dir", lambda: str(tmp_path / "xla"))
    artifacts.ARTIFACTS.reset()
    artifacts.clear_observed()
    cache = ArtifactCache()
    cache.configure(str(tmp_path / "shared"))
    yield cache, tmp_path
    artifacts.ARTIFACTS.reset()
    artifacts.clear_observed()


class TestEnvelope:
    def test_pack_unpack_roundtrip(self):
        sig = _sig()
        fp = env_fingerprint()
        data = pack_envelope(
            sig, fp, b"payload-bytes", None, None,
            avals=[[[4, 8], "float32"]], cost={"flops": 12.0}, parent="p-key",
        )
        header, body = unpack_envelope(data)
        assert header["key"] == sig.key()
        assert header["program"] == sig.program
        assert header["fingerprint"] == fp
        assert header["cost"] == {"flops": 12.0}
        assert header["parent"] == "p-key"
        assert body["payload"] == b"payload-bytes"
        # header-only parse sees the same identity without the unpickle
        assert read_header(data)["key"] == sig.key()

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda d: b"NOTMAGIC" + d[8:],  # bad magic
            lambda d: d[:-3],  # torn body
            lambda d: d[:-3] + b"xyz",  # flipped content, same length
            lambda d: artifacts.MAGIC + b"not json\n" + d[-4:],  # bad header
        ],
    )
    def test_corruption_raises(self, mutate):
        data = pack_envelope(_sig(), env_fingerprint(), b"payload", None, None)
        with pytest.raises(ArtifactCorrupt):
            unpack_envelope(mutate(data))
        with pytest.raises(ArtifactCorrupt):
            read_header(mutate(data))

    def test_sig_key_roundtrip(self):
        sig = _sig(k=4)
        assert sig_from_key(sig.key()).key() == sig.key()

    def test_name_changes_with_fingerprint_and_sig(self):
        fp = env_fingerprint()
        other_env = dict(fp, jax="999.0.0")
        name = artifact_name(_sig().key(), fp)
        assert name.endswith(artifacts.SUFFIX)
        assert artifact_name(_sig().key(), other_env) != name
        assert artifact_name(_sig(k=4).key(), fp) != name


class TestPublishFetch:
    def test_round_trip_bit_identical(self, tiers):
        cache, tmp = tiers
        step, args = _jit_step(), _args()
        want = np.asarray(step(*args))
        compiled = serialize_compiled(step, args)
        sig = _sig()
        written = cache.publish(sig, compiled, cost={"flops": 5.0})
        assert sorted(written) == ["local", "shared"]
        # a different process: fresh cache object, same tiers on disk
        other = ArtifactCache()
        other.configure(str(tmp / "shared"))
        la = other.fetch(sig)
        assert la is not None and la.tier == "local"
        assert la.cost == {"flops": 5.0}
        got = np.asarray(la(*args))
        np.testing.assert_array_equal(got, want)  # bit-identical, not close

    def test_publish_dedupes_on_content_address(self, tiers):
        cache, _ = tiers
        compiled = serialize_compiled(_jit_step(), _args())
        assert cache.publish(_sig(), compiled)
        p0 = _tier_total(obs.artifact_publishes, "shared")
        assert cache.publish(_sig(), compiled) == []  # both tiers exist
        assert _tier_total(obs.artifact_publishes, "shared") == p0

    def test_fingerprint_invalidation(self, tiers, monkeypatch):
        cache, tmp = tiers
        compiled = serialize_compiled(_jit_step(), _args())
        sig = _sig()
        cache.publish(sig, compiled)
        # same dirs, different toolchain: the address changes, so the old
        # artifact is simply never looked up
        monkeypatch.setattr(
            artifacts, "_FP_CACHE", dict(env_fingerprint(), jax="999.0.0")
        )
        upgraded = ArtifactCache()
        upgraded.configure(str(tmp / "shared"))
        m0 = _tier_total(obs.artifact_misses, "shared")
        assert upgraded.fetch(sig) is None
        assert upgraded.fetch_family(sig) == []
        assert _tier_total(obs.artifact_misses, "shared") > m0
        # the other env's envelope is stale, not corrupt: fsck leaves it
        report = fsck_artifacts(str(tmp / "shared"))
        assert report.stale and not report.corrupt and report.consistent

    def test_corrupt_artifact_quarantined_and_fetch_degrades(self, tiers):
        cache, tmp = tiers
        compiled = serialize_compiled(_jit_step(), _args())
        sig = _sig()
        cache.publish(sig, compiled)
        shared = tmp / "shared"
        for d in (tmp / "xla" / "artifacts", shared):
            for name in os.listdir(d):
                p = d / name
                p.write_bytes(p.read_bytes()[:-16])  # tear both copies
        q0 = _tier_total(obs.artifact_quarantines, "shared")
        other = ArtifactCache()
        other.configure(str(shared))
        assert other.fetch(sig) is None  # degraded, no raise
        assert _tier_total(obs.artifact_quarantines, "shared") == q0 + 1
        names = os.listdir(shared)
        assert all(n.endswith(artifacts.QUARANTINE_SUFFIX) for n in names)
        # a later fetch of the emptied tier is a plain miss
        assert other.fetch(sig) is None

    def test_shared_hit_promotes_to_local_tier(self, tiers, monkeypatch):
        cache, tmp = tiers
        compiled = serialize_compiled(_jit_step(), _args())
        sig = _sig()
        # publish from a host with no local tier: shared-only
        monkeypatch.setattr(artifacts, "_cache_dir", lambda: None)
        assert cache.publish(sig, compiled) == ["shared"]
        # the fetching host has a local tier again
        monkeypatch.setattr(
            artifacts, "_cache_dir", lambda: str(tmp / "xla")
        )
        h0 = _tier_total(obs.artifact_hits, "shared")
        other = ArtifactCache()
        other.configure(str(tmp / "shared"))
        la = other.fetch(sig)
        assert la is not None and la.tier == "shared"
        assert _tier_total(obs.artifact_hits, "shared") == h0 + 1
        promoted = os.listdir(tmp / "xla" / "artifacts")
        assert promoted == [artifact_name(sig.key(), env_fingerprint())]

    def test_concurrent_publish_atomic(self, tiers):
        cache, tmp = tiers
        compiled = serialize_compiled(_jit_step(), _args())
        sig, n = _sig(), 8
        barrier = threading.Barrier(n)
        errors: list[BaseException] = []

        def racer():
            try:
                barrier.wait(10.0)
                ArtifactCache().publish(sig, compiled)
            except BaseException as e:  # pragma: no cover - fail loudly
                errors.append(e)

        threads = [threading.Thread(target=racer) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert not errors
        names = os.listdir(tmp / "xla" / "artifacts")
        # exactly one envelope, intact, and no .pub- temp residue
        assert names == [artifact_name(sig.key(), env_fingerprint())]
        data = (tmp / "xla" / "artifacts" / names[0]).read_bytes()
        assert unpack_envelope(data)[0]["key"] == sig.key()

    def test_no_tiers_is_noop(self, tmp_path, monkeypatch):
        monkeypatch.delenv("KATIB_ARTIFACT_DIR", raising=False)
        monkeypatch.setattr(artifacts, "_cache_dir", lambda: None)
        cache = ArtifactCache()
        assert not cache.enabled()
        assert cache.publish(_sig(), object()) == []
        assert cache.fetch(_sig()) is None
        assert cache.fetch_family(_sig()) == []


class TestFamilyFetch:
    def test_parent_link_collects_derived_programs(self, tiers):
        cache, _ = tiers
        step, args = _jit_step(), _args()
        parent = _sig("mnist_trial", k=4)
        derived = CompileSignature(
            program="mnist_trial.step", shapes=parent.shapes, k=parent.k
        )
        cache.publish(
            derived, serialize_compiled(step, args), parent=parent.key()
        )
        REGISTRY.reset()
        cache.reset()  # drop the in-process adoption; force a tier scan
        cache.configure(os.environ.get("KATIB_ARTIFACT_DIR"))
        loaded = cache.fetch_family(parent)
        assert [la.program for la in loaded] == ["mnist_trial.step"]
        assert loaded[0].parent == parent.key()
        # any family hit marks the request signature warm for first steps
        assert REGISTRY.seen(parent)
        # an unrelated signature collects nothing
        assert cache.fetch_family(_sig("unrelated", k=8)) == []

    def test_negative_cache_cleared_by_publish(self, tiers):
        cache, _ = tiers
        sig = _sig("neg.step")
        m0 = _tier_total(obs.artifact_misses, "shared")
        assert cache.fetch_family(sig) == []
        assert cache.fetch_family(sig) == []  # negative-cached: no rescan
        assert _tier_total(obs.artifact_misses, "shared") == m0 + 1
        cache.publish(sig, serialize_compiled(_jit_step(), _args()))
        assert cache.fetch_family(sig)


class TestDispatchSeam:
    def test_resolve_adopts_matching_artifact(self, tiers):
        step, args = _jit_step(), _args()
        artifacts.ARTIFACTS.configure(
            str(tiers[1] / "shared")
        )
        artifacts.ARTIFACTS.publish(
            _sig("seam.step"), serialize_compiled(step, args)
        )
        wrapped = resolve(step, program="seam.step")
        assert wrapped.source == "jit"
        np.testing.assert_array_equal(
            np.asarray(wrapped(*args)), np.asarray(step(*args))
        )
        assert wrapped.source == "artifact"
        assert hasattr(wrapped, "lower")  # costmodel still sees the jit fn

    def test_resolve_stays_jit_without_aval_match(self, tiers):
        step, args = _jit_step(), _args()
        artifacts.ARTIFACTS.configure(str(tiers[1] / "shared"))
        artifacts.ARTIFACTS.publish(
            _sig("seam2.step"), serialize_compiled(step, args)
        )
        other_args = (args[0][:2], args[1])  # different avals
        wrapped = resolve(step, program="seam2.step")
        wrapped(*other_args)
        assert wrapped.source == "jit"

    def test_dispatch_failure_falls_back_to_jit(self, tiers):
        step, args = _jit_step(), _args()

        class Exploding:
            args_info = ()

            def __call__(self, *a):
                raise RuntimeError("dead executable")

        la = artifacts.LoadedArtifact(
            sig_key=_sig("boom.step").key(),
            program="boom.step",
            compiled=Exploding(),
            tier="local",
            avals=artifacts._aval_list(args),
            aval_key=artifacts.aval_digest(args),
        )
        artifacts.ARTIFACTS._adopt(la)
        wrapped = resolve(step, program="boom.step")
        np.testing.assert_array_equal(
            np.asarray(wrapped(*args)), np.asarray(step(*args))
        )
        assert wrapped.source == "jit-fallback"
        wrapped(*args)  # permanent: later calls stay on the jit path

    def test_dummy_args_unwrap_args_info(self, tiers):
        cache, tmp = tiers
        step, args = _jit_step(), _args()
        cache.publish(_sig("dummy.step"), serialize_compiled(step, args))
        other = ArtifactCache()
        other.configure(str(tmp / "shared"))
        la = other.fetch(_sig("dummy.step"))
        dummies = la.dummy_args()
        assert [tuple(d.shape) for d in dummies] == [(4, 8), (8, 8)]
        la(*dummies)  # a fetched executable that cannot run is useless


class TestObservedPublish:
    def test_publish_observed_links_parent_and_drains(self, tiers):
        cache, tmp = tiers
        artifacts.ARTIFACTS.configure(str(tmp / "shared"))
        step, args = _jit_step(), _args()
        sig = _sig("request", k=4)
        artifacts.note_observed(
            step, args, program="request.step", cost={"flops": 3.0}
        )
        assert publish_observed(sig) == 1
        assert publish_observed(sig) == 0  # drained
        rows = scan_dir(str(tmp / "shared"))
        assert [r["program"] for r in rows] == ["request.step"]
        data = (tmp / "shared" / rows[0]["name"]).read_bytes()
        assert read_header(data)["parent"] == sig.key()

    def test_prewarm_worker_publishes_once_then_fetches(self, tiers):
        cache, tmp = tiers
        artifacts.ARTIFACTS.configure(str(tmp / "shared"))
        step, args = _jit_step(), _args()

        def train_fn(ctx):  # pragma: no cover - never run here
            pass

        def twin(shared, k, mesh=None):
            step(*args)  # "compile" the step program
            artifacts.note_observed(step, args, program="worker.step")

        attach_prewarm_fn(train_fn, twin)
        req = PrewarmRequest(train_fn=train_fn, shared={"units": 8}, k=4)
        worker = PrewarmWorker(registry=ShapeRegistry(), force=True)
        try:
            assert worker.submit(req)
            assert worker.drain(timeout=30.0)
            assert (worker.compiled, worker.published) == (1, 1)
            # the re-run finds its own artifact: fetch, don't recompile
            assert worker.submit(req)
            assert worker.drain(timeout=30.0)
            assert worker.fetched == 1
            assert worker.compiled == 1  # no second twin run
        finally:
            worker.stop()
        assert len(os.listdir(tmp / "shared")) == 1

    def test_fetch_only_worker_never_compiles(self, tiers):
        cache, tmp = tiers
        artifacts.ARTIFACTS.configure(str(tmp / "shared"))
        ran = threading.Event()

        def train_fn(ctx):  # pragma: no cover
            pass

        attach_prewarm_fn(train_fn, lambda s, k, m=None: ran.set())
        worker = PrewarmWorker(
            registry=ShapeRegistry(), fetch_only=True, force=True
        )
        try:
            assert worker.submit(PrewarmRequest(train_fn=train_fn, k=2))
            assert worker.drain(timeout=10.0)
            assert worker.compiled == 0 and not ran.is_set()
        finally:
            worker.stop()


class TestFsckAndScan:
    def _publish_one(self, tiers):
        cache, tmp = tiers
        cache.publish(_sig(), serialize_compiled(_jit_step(), _args()))
        return tmp / "shared"

    def test_is_artifact_dir(self, tiers, tmp_path):
        shared = self._publish_one(tiers)
        assert is_artifact_dir(str(shared))
        assert not is_artifact_dir(str(tmp_path / "nope"))

    def test_fsck_quarantines_corrupt_and_misaddressed(self, tiers):
        shared = self._publish_one(tiers)
        (shared / "deadbeef.katibx").write_bytes(b"garbage")
        good = next(n for n in os.listdir(shared) if n != "deadbeef.katibx")
        os.rename(shared / good, shared / ("0" * 64 + ".katibx"))
        report = fsck_artifacts(str(shared), repair=False)
        assert report.corrupt == ["deadbeef.katibx"]
        assert report.misaddressed == ["0" * 64 + ".katibx"]
        assert not report.consistent
        report = fsck_artifacts(str(shared))
        assert sorted(report.quarantined) == sorted(
            ["deadbeef.katibx", "0" * 64 + ".katibx"]
        )
        assert report.consistent
        # rerun on the repaired dir is clean
        report = fsck_artifacts(str(shared))
        assert report.consistent and not report.corrupt

    def test_scan_dir_rows(self, tiers):
        shared = self._publish_one(tiers)
        (shared / ("1" * 64 + ".katibx")).write_bytes(b"garbage")
        rows = {r["status"]: r for r in scan_dir(str(shared))}
        assert rows["ok"]["program"] == "artifact_test.step"
        assert rows["ok"]["k"] == 2
        assert rows["ok"]["jax"] == env_fingerprint()["jax"]
        assert rows["corrupt"]["name"] == "1" * 64 + ".katibx"


class TestRegistryCompaction:
    def _registry_file(self, tmp_path, monkeypatch):
        import katib_tpu.compile.registry as registry_mod

        monkeypatch.setattr(registry_mod, "_cache_dir", lambda: str(tmp_path))
        return tmp_path / "shape_registry.jsonl"

    def test_duplicate_rows_compact_on_open(self, tmp_path, monkeypatch):
        path = self._registry_file(tmp_path, monkeypatch)
        sig = _sig("compact.step")
        row = {
            "key": sig.key(), "program": sig.program, "k": sig.k,
            "mesh": sig.mesh, "shapes": dict(sig.shapes),
            "donation": sig.donation, "source": "trial",
        }
        lines = [dict(row), dict(row, cost={"flops": 1.0}),
                 dict(row, cost={"flops": 2.0})]
        path.write_text("".join(json.dumps(r) + "\n" for r in lines))
        reg = ShapeRegistry()
        assert reg.seen(sig)  # triggers load + compaction
        # the freshest cost won the merge
        assert reg.cost_of(sig) == {"flops": 2.0}
        kept = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(kept) == 1
        assert kept[0]["cost"] == {"flops": 2.0}
        # no temp residue from the durable rewrite
        assert os.listdir(tmp_path) == ["shape_registry.jsonl"]

    def test_unique_rows_left_alone(self, tmp_path, monkeypatch):
        path = self._registry_file(tmp_path, monkeypatch)
        rows = [
            {"key": _sig(f"p{i}.step").key(), "program": f"p{i}.step",
             "k": 2, "mesh": "", "shapes": {}, "donation": True,
             "source": "trial"}
            for i in range(3)
        ]
        body = "".join(json.dumps(r) + "\n" for r in rows)
        path.write_text(body)
        reg = ShapeRegistry()
        assert len(reg.signatures()) == 3
        assert path.read_text() == body  # byte-identical: no rewrite

    def test_torn_tail_with_dupes_heals(self, tmp_path, monkeypatch):
        path = self._registry_file(tmp_path, monkeypatch)
        sig = _sig("torn.step")
        row = {
            "key": sig.key(), "program": sig.program, "k": sig.k,
            "mesh": sig.mesh, "shapes": dict(sig.shapes),
            "donation": sig.donation, "source": "trial",
        }
        path.write_text(
            json.dumps(row) + "\n" + json.dumps(row) + "\n" + '{"key": "to'
        )
        with pytest.warns(RuntimeWarning, match="torn"):
            reg = ShapeRegistry()
            assert reg.seen(sig)
        # compaction rewrote the file: dupes merged, torn tail gone
        kept = path.read_text()
        assert kept.endswith("\n") and len(kept.splitlines()) == 1
        assert ShapeRegistry().seen(sig)
