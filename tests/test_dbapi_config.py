"""Config surface for the external-SQL store backends."""

from __future__ import annotations

import pytest

from katib_tpu.core.config import ConfigError, StoreConfig, _parse_dsn


def test_dsn_parse_full():
    assert _parse_dsn("katib:secret@db.example:3307/katib", 3306) == (
        "katib",
        "secret",
        "db.example",
        3307,
        "katib",
    )


def test_dsn_parse_defaults_port():
    assert _parse_dsn("u:p@h/katib", 5432) == ("u", "p", "h", 5432, "katib")


@pytest.mark.parametrize("bad", ["", "nohost", "u:p@/db", "u:p@h:port/db", "u:p@h:1"])
def test_dsn_parse_rejects(bad):
    with pytest.raises(ConfigError):
        _parse_dsn(bad, 3306)


def test_store_config_accepts_sql_backends():
    cfg = StoreConfig.from_dict(
        {"backend": "mysql", "dsn": "u:p@h:3306/katib"}
    )
    assert cfg.backend == "mysql" and cfg.dsn == "u:p@h:3306/katib"
    cfg = StoreConfig.from_dict({"backend": "postgres", "dsn": "u:p@h/katib"})
    assert cfg.backend == "postgres"


def test_make_store_without_driver_raises_clear_error():
    """No MySQL driver is installed in this image — the error must say
    which modules would satisfy the backend, not crash obscurely."""
    cfg = StoreConfig(backend="mysql", dsn="u:p@h:3306/katib")
    with pytest.raises(ConfigError, match="pymysql"):
        cfg.make_store()
