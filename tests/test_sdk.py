"""SDK surface tests: search helpers, tune(), KatibClient lifecycle."""

from __future__ import annotations

import math

import pytest

from katib_tpu.core.types import ExperimentCondition, ParameterType
from katib_tpu.sdk import KatibClient, make_experiment_spec, search, tune


def _quadratic(params):
    # max at x=2, y=-1
    return -((params["x"] - 2.0) ** 2) - (params["y"] + 1.0) ** 2


class TestSearchHelpers:
    def test_double(self):
        p = search.make_parameters({"lr": search.double(0.001, 0.1)})[0]
        assert p.name == "lr"
        assert p.type is ParameterType.DOUBLE
        assert p.feasible.min == 0.001 and p.feasible.max == 0.1

    def test_loguniform(self):
        p = search.make_parameters({"lr": search.loguniform(1e-5, 1e-1)})[0]
        assert p.feasible.is_log_scaled()

    def test_int(self):
        p = search.make_parameters({"units": search.int_(16, 256, step=16)})[0]
        assert p.type is ParameterType.INT
        assert p.feasible.step == 16

    def test_categorical_and_discrete(self):
        ps = search.make_parameters(
            {
                "opt": search.categorical(["sgd", "adam"]),
                "bs": search.discrete([32, 64, 128]),
            }
        )
        assert ps[0].type is ParameterType.CATEGORICAL
        assert ps[1].type is ParameterType.DISCRETE

    def test_literal_shorthands(self):
        ps = search.make_parameters({"lr": (0.01, 0.1), "opt": ["sgd", "adam"]})
        assert ps[0].type is ParameterType.DOUBLE
        assert ps[1].type is ParameterType.CATEGORICAL

    def test_bad_entry(self):
        with pytest.raises(TypeError):
            search.make_parameters({"x": object()})


class TestTune:
    def test_tune_returns_optimal(self, tmp_path):
        exp = tune(
            _quadratic,
            {"x": search.double(0.0, 4.0), "y": search.double(-3.0, 1.0)},
            name="tune-quad",
            algorithm="tpe",
            max_trial_count=20,
            parallel_trial_count=4,
            workdir=str(tmp_path),
        )
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert exp.optimal is not None
        assert exp.optimal.objective_value > -8.0  # found something decent

    def test_tune_goal_short_circuit(self, tmp_path):
        exp = tune(
            lambda p: 1.0,
            {"x": search.double(0.0, 1.0)},
            name="tune-goal",
            goal=0.5,
            max_trial_count=50,
            workdir=str(tmp_path),
        )
        assert exp.condition is ExperimentCondition.GOAL_REACHED
        assert len(exp.trials) < 50

    def test_tune_minimize(self, tmp_path):
        exp = tune(
            lambda p: (p["x"] - 1.0) ** 2,
            {"x": search.double(0.0, 2.0)},
            name="tune-min",
            objective_type="minimize",
            algorithm="random",
            max_trial_count=15,
            workdir=str(tmp_path),
        )
        assert exp.optimal.objective_value < 0.5

    def test_objective_returning_dict(self, tmp_path):
        exp = tune(
            lambda p: {"objective": p["x"], "aux": 1.0},
            {"x": search.double(0.0, 1.0)},
            name="tune-dict",
            additional_metric_names=("aux",),
            max_trial_count=3,
            workdir=str(tmp_path),
        )
        t = next(iter(exp.trials.values()))
        assert t.observation.get("aux") is not None

    def test_objective_with_ctx(self, tmp_path):
        def obj(params, ctx):
            for step in range(3):
                ctx.report(step=step, objective=params["x"] * (step + 1))

        exp = tune(
            obj,
            {"x": search.double(0.5, 1.0)},
            name="tune-ctx",
            max_trial_count=3,
            workdir=str(tmp_path),
        )
        assert exp.optimal is not None


class TestClient:
    def test_async_lifecycle(self, tmp_path):
        client = KatibClient(workdir=str(tmp_path))
        spec = make_experiment_spec(
            "cl-exp",
            {"x": search.double(0.0, 1.0)},
            objective=lambda p: p["x"],
            max_trial_count=6,
            parallel_trial_count=2,
        )
        client.create_experiment(spec)
        exp = client.wait_for_experiment_condition("cl-exp", timeout=60)
        assert client.is_experiment_succeeded("cl-exp")
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        best = client.get_optimal_hyperparameters("cl-exp")
        assert "x" in best and 0.0 <= best["x"] <= 1.0
        assert len(client.get_trials("cl-exp")) == 6
        assert client.list_experiments() == [exp]
        client.delete_experiment("cl-exp")
        assert client.list_experiments() == []

    def test_duplicate_running_rejected(self, tmp_path):
        client = KatibClient(workdir=str(tmp_path))
        spec = make_experiment_spec(
            "cl-dup",
            {"x": search.double(0.0, 1.0)},
            objective=lambda p: p["x"],
            max_trial_count=200,
            parallel_trial_count=1,
        )
        client.create_experiment(spec)
        with pytest.raises(ValueError):
            client.create_experiment(spec)
        client.delete_experiment("cl-dup")

    def test_requires_exactly_one_entrypoint(self):
        with pytest.raises(ValueError):
            make_experiment_spec("x", {}, objective=None, command=None)
        with pytest.raises(ValueError):
            make_experiment_spec(
                "x", {}, objective=lambda p: 0.0, command=["echo", "hi"]
            )
