"""Real 2-process ``jax.distributed`` group test (VERDICT r1 item 5).

Round 1 only unit-tested env parsing; this spawns an actual coordinator +
worker process pair (2 virtual CPU devices each → a 4-device global mesh
with gloo collectives) and runs one data-parallel train step through
``initialize_distributed`` + ``make_train_step`` — the exact glue the
multi-host v5e story depends on (SURVEY.md §2.4 "JAX multi-host runner";
the reference delegates this to TFJob/PyTorchJob operators, ``job_util.go:59``).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

# spawns real coordinator+worker process pairs: merge-gate tier
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

from katib_tpu.parallel.distributed import initialize_distributed
from katib_tpu.parallel.mesh import DATA_AXIS, make_mesh, replicated
from katib_tpu.parallel.train import TrainState, make_train_step

import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec

pid = int(sys.argv[1])
port = sys.argv[2]

assert initialize_distributed(f"127.0.0.1:{{port}}", 2, pid)
assert jax.device_count() == 4, jax.device_count()
assert jax.local_device_count() == 2

mesh = make_mesh({{DATA_AXIS: 4}})

def loss_fn(params, batch):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)

params = {{"w": jnp.ones((4, 1), jnp.float32), "b": jnp.zeros((1,), jnp.float32)}}
tx = optax.sgd(0.1)
state = TrainState.create(params, tx)
rep = replicated(mesh)
state = jax.device_put(state, rep)

# global batch 8, each process provides its local half (rows differ by pid
# so the gradient all-reduce is actually exercised)
rng = np.random.RandomState(pid)
x_local = rng.randn(4, 4).astype(np.float32)
y_local = rng.randn(4, 1).astype(np.float32)
batch_sharding = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
x = jax.make_array_from_process_local_data(batch_sharding, x_local, (8, 4))
y = jax.make_array_from_process_local_data(batch_sharding, y_local, (8, 1))

step = make_train_step(loss_fn, tx, mesh=mesh, donate=False)
state, metrics = step(state, (x, y))
state, metrics = step(state, (x, y))
loss = float(metrics["loss"])
w0 = float(np.asarray(jax.device_get(state.params["w"]))[0, 0])
assert np.isfinite(loss)
print(f"RESULT pid={{pid}} loss={{loss:.10f}} w0={{w0:.10f}}", flush=True)
"""


RING_WORKER = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from katib_tpu.parallel.distributed import initialize_distributed
from katib_tpu.parallel.mesh import SEQ_AXIS, make_mesh
from katib_tpu.parallel.ring_attention import (
    make_sequence_parallel_attention,
    reference_attention_with_lse,
)

pid = int(sys.argv[1]); port = sys.argv[2]
strategy = sys.argv[3] if len(sys.argv) > 3 else "ring"
assert initialize_distributed(f"127.0.0.1:{{port}}", 2, pid)
assert jax.device_count() == 4

# sequence axis spans BOTH processes: the collective (ppermute K/V rotation
# for ring, all-to-all head scatter for ulysses) crosses the process
# boundary — the DCN leg of the v5e multi-host story
mesh = make_mesh({{SEQ_AXIS: 4}})
B, H, S, D = 1, 4, 32, 8

# identical global tensors on both processes (same seed)
rng = np.random.RandomState(0)
q = rng.randn(B, H, S, D).astype(np.float32)
k = rng.randn(B, H, S, D).astype(np.float32)
v = rng.randn(B, H, S, D).astype(np.float32)

sharding = NamedSharding(mesh, PartitionSpec(None, None, SEQ_AXIS, None))
local_slice = lambda a: a[:, :, pid * (S // 2):(pid + 1) * (S // 2), :]
qg = jax.make_array_from_process_local_data(sharding, local_slice(q), (B, H, S, D))
kg = jax.make_array_from_process_local_data(sharding, local_slice(k), (B, H, S, D))
vg = jax.make_array_from_process_local_data(sharding, local_slice(v), (B, H, S, D))

attn = make_sequence_parallel_attention(mesh, strategy=strategy, causal=True)
out = jax.jit(attn)(qg, kg, vg)

dense, _ = reference_attention_with_lse(
    jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True
)
dense = np.asarray(dense)

# each process checks its OWN addressable shards against the dense slice
for shard in out.addressable_shards:
    s0 = shard.index[2].start or 0
    got = np.asarray(shard.data)
    want = dense[:, :, s0:s0 + got.shape[2], :]
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
print(f"RESULT pid={{pid}} ok=1 shards={{len(out.addressable_shards)}}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_pair(tmp_path, source, timeout=150, extra_args=()):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(source.format(repo=REPO))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), *extra_args],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=str(tmp_path),
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker hung")
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                parts = dict(kv.split("=") for kv in line.split()[1:])
                results[parts["pid"]] = parts
    return results


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_two_process_sequence_parallel_matches_dense(tmp_path, strategy):
    """Sequence parallelism with the seq axis spanning two processes: the
    collective (ppermute for ring, all-to-all for ulysses) crosses the
    process boundary, and every process's output shards must match the
    dense reference."""
    results = _run_pair(tmp_path, RING_WORKER, timeout=180, extra_args=(strategy,))
    assert set(results) == {"0", "1"}
    assert all(r["ok"] == "1" for r in results.values())


def test_two_process_data_parallel_step(tmp_path):
    results = _run_pair(tmp_path, WORKER)
    assert set(results) == {"0", "1"}
    # SPMD consistency: both processes computed identical global loss and
    # identical post-update (all-reduced) weights
    assert (results["0"]["loss"], results["0"]["w0"]) == (
        results["1"]["loss"], results["1"]["w0"]
    )
