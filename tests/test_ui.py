"""UI REST backend over the status journal + observation store.

Parity target: the reference UI backend's endpoint set
(``pkg/ui/v1beta1/backend.go:86,181,463``; NAS graph ``nas.go``) exercised
through real HTTP against a journaled experiment."""

import json
import urllib.request

import pytest

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)
from katib_tpu.orchestrator import Orchestrator
from katib_tpu.store.base import MemoryObservationStore
from katib_tpu.ui import start_ui
from katib_tpu.ui.backend import _darts_graph, _enas_graph, nas_graph_for_trial


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.headers.get("Content-Type"), r.read()


@pytest.fixture(scope="class")
def served(tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("runs"))
    store = MemoryObservationStore()

    def trainer(ctx):
        x = ctx.params["x"]
        ctx.report(accuracy=1.0 - 0.1 * (x - 2.0) ** 2, step=0)

    spec = ExperimentSpec(
        name="ui-exp",
        algorithm=AlgorithmSpec(name="random"),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=4.0)),
        ],
        max_trial_count=3,
        parallel_trial_count=1,
        train_fn=trainer,
    )
    exp = Orchestrator(store=store, workdir=workdir).run(spec)
    ui = start_ui(workdir, store)
    yield ui.port, exp
    ui.stop()


class TestUiEndpoints:
    def test_dashboard_html(self, served):
        port, _ = served
        status, ctype, body = _get(port, "/")
        assert status == 200 and "text/html" in ctype
        assert b"katib-tpu" in body

    def test_list_experiments(self, served):
        port, _ = served
        status, _, body = _get(port, "/api/experiments")
        exps = json.loads(body)
        assert status == 200
        assert [e["name"] for e in exps] == ["ui-exp"]
        assert exps[0]["counts"]["succeeded"] == 3
        assert exps[0]["optimal"] is not None

    def test_experiment_detail_and_trials(self, served):
        port, exp = served
        status, _, body = _get(port, "/api/experiment/ui-exp")
        detail = json.loads(body)
        assert status == 200 and len(detail["trials"]) == 3

        status, _, body = _get(port, "/api/experiment/ui-exp/trials")
        rows = json.loads(body)
        assert status == 200 and len(rows) == 3
        assert all("x" in r["assignments"] for r in rows)
        assert all("accuracy" in r["metrics"] for r in rows)

    def test_trial_metrics_from_store(self, served):
        port, exp = served
        trial = next(iter(exp.trials))
        status, _, body = _get(port, f"/api/trial/{trial}/metrics")
        logs = json.loads(body)
        assert status == 200 and logs
        assert logs[0]["metric_name"] == "accuracy"

    def test_unknown_routes_404(self, served):
        port, _ = served
        import urllib.error

        for path in ("/api/experiment/nope", "/api/bogus"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(port, path)
            assert e.value.code == 404


class TestNasGraphs:
    def test_darts_graph_shape(self):
        # per-node pair lists, the extract_genotype serialization
        genotype = {
            "normal": [
                [["sep_conv_3x3", 0], ["skip_connect", 1]],
                [["sep_conv_3x3", 2], ["max_pool_3x3", 0]],
            ],
            "reduce": [[["max_pool_3x3", 0], ["max_pool_3x3", 1]]],
        }
        g = _darts_graph(genotype)
        assert g["type"] == "darts"
        # 2 inputs + 2 normal nodes + 1 reduce node
        assert len(g["nodes"]) == 5
        assert len(g["edges"]) == 6
        # node 1 of the normal cell consumes intermediate node 0 (src=2)
        assert {"from": "normal-0", "to": "normal-1", "op": "sep_conv_3x3"} in g["edges"]

    def test_enas_graph_shape(self):
        arc = [[3], [1, 1], [0, 0, 1]]
        g = _enas_graph(arc)
        assert g["type"] == "enas"
        assert len(g["nodes"]) == 5  # input + 3 layers + output
        skips = [e for e in g["edges"] if e["op"] == "skip"]
        assert len(skips) == 2

    def test_recover_from_trial_assignment(self):
        trial = {"assignments": {"architecture": json.dumps([[2], [1, 0]])}}
        g = nas_graph_for_trial(trial)
        assert g is not None and g["type"] == "enas"

    def test_recover_from_genotype_file(self, tmp_path):
        ckpt = tmp_path / "t0"
        ckpt.mkdir()
        (ckpt / "genotype.json").write_text(
            json.dumps({"normal": [[["skip_connect", 0], ["none", 1]]], "reduce": []})
        )
        g = nas_graph_for_trial({"assignments": {}, "checkpoint_dir": str(ckpt)})
        assert g is not None and g["type"] == "darts"

    def test_no_artifact_returns_none(self, tmp_path):
        assert nas_graph_for_trial({"assignments": {}, "checkpoint_dir": str(tmp_path)}) is None
