"""UI REST backend over the status journal + observation store.

Parity target: the reference UI backend's endpoint set
(``pkg/ui/v1beta1/backend.go:86,181,463``; NAS graph ``nas.go``) exercised
through real HTTP against a journaled experiment."""

import json
import urllib.request

import pytest

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)
from katib_tpu.orchestrator import Orchestrator
from katib_tpu.store.base import MemoryObservationStore
from katib_tpu.ui import start_ui
from katib_tpu.ui.backend import _darts_graph, _enas_graph, nas_graph_for_trial


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.headers.get("Content-Type"), r.read()


@pytest.fixture(scope="class")
def served(tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("runs"))
    store = MemoryObservationStore()

    def trainer(ctx):
        x = ctx.params["x"]
        ctx.report(accuracy=1.0 - 0.1 * (x - 2.0) ** 2, step=0)

    spec = ExperimentSpec(
        name="ui-exp",
        algorithm=AlgorithmSpec(name="random"),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=4.0)),
        ],
        max_trial_count=3,
        parallel_trial_count=1,
        train_fn=trainer,
    )
    exp = Orchestrator(store=store, workdir=workdir).run(spec)
    ui = start_ui(workdir, store)
    yield ui.port, exp
    ui.stop()


class TestUiEndpoints:
    def test_dashboard_html(self, served):
        port, _ = served
        status, ctype, body = _get(port, "/")
        assert status == 200 and "text/html" in ctype
        assert b"katib-tpu" in body

    def test_list_experiments(self, served):
        port, _ = served
        status, _, body = _get(port, "/api/experiments")
        exps = json.loads(body)
        assert status == 200
        assert [e["name"] for e in exps] == ["ui-exp"]
        assert exps[0]["counts"]["succeeded"] == 3
        assert exps[0]["optimal"] is not None

    def test_experiment_detail_and_trials(self, served):
        port, exp = served
        status, _, body = _get(port, "/api/experiment/ui-exp")
        detail = json.loads(body)
        assert status == 200 and len(detail["trials"]) == 3

        status, _, body = _get(port, "/api/experiment/ui-exp/trials")
        rows = json.loads(body)
        assert status == 200 and len(rows) == 3
        assert all("x" in r["assignments"] for r in rows)
        assert all("accuracy" in r["metrics"] for r in rows)

    def test_trial_metrics_from_store(self, served):
        port, exp = served
        trial = next(iter(exp.trials))
        status, _, body = _get(port, f"/api/trial/{trial}/metrics")
        logs = json.loads(body)
        assert status == 200 and logs
        assert logs[0]["metric_name"] == "accuracy"

    def test_dashboard_carries_drilldown_renderers(self, served):
        """The single-file page ships the per-trial metric chart and the
        NAS graph renderer wired to the endpoints that feed them (the
        reference UI's trial-detail charts + browser NAS render,
        ``pkg/ui/v1beta1/nas.go`` / frontend trial views)."""
        port, _ = served
        _, _, body = _get(port, "/")
        page = body.decode()
        for hook in ("function metricChart", "function nasGraph",
                     "showTrial", "trialdetail", "/metrics", "/nas?trial="):
            assert hook in page, hook

    def test_nas_endpoint_feeds_graph_for_trial_query(self, tmp_path):
        """/api/experiment/<name>/nas?trial=<t> recovers an ENAS arc from
        the trial's architecture assignment and returns render-ready
        nodes/edges."""
        import json as _json
        import os

        workdir = str(tmp_path)
        os.makedirs(os.path.join(workdir, "nas-exp"))
        with open(os.path.join(workdir, "nas-exp", "status.json"), "w") as f:
            _json.dump({
                "name": "nas-exp",
                "condition": "MaxTrialsReached",
                "trials": {
                    "nas-exp-t0": {
                        "name": "nas-exp-t0",
                        "condition": "Succeeded",
                        "assignments": {
                            "architecture": _json.dumps([[2], [1, 1]]),
                        },
                    },
                },
            }, f)
        ui = start_ui(workdir, MemoryObservationStore())
        try:
            status, _, body = _get(ui.port, "/api/experiment/nas-exp/nas?trial=nas-exp-t0")
            g = json.loads(body)
            assert status == 200 and g["type"] == "enas"
            assert g["trial"] == "nas-exp-t0"
            assert any(e["op"] == "skip" for e in g["edges"])
        finally:
            ui.stop()

    def test_dashboard_carries_creation_wizard(self, served):
        """The create form ships a client-side wizard (parameter rows,
        algorithm/objective fields, YAML builder) — the single-file answer
        to the reference SPA's experiment-creation wizard."""
        port, _ = served
        _, _, body = _get(port, "/")
        page = body.decode()
        for hook in ("w_build", "w_params", "addParamRow", "trialTemplate",
                     "feasibleSpace", "w_algo"):
            assert hook in page, hook

    def test_wizard_shaped_yaml_round_trips_through_create(self, tmp_path):
        """Exactly the YAML shape w_build assembles (JSON-quoted scalars,
        list feasible spaces, one command arg per line) must parse and run
        through POST /api/experiments."""
        import urllib.request

        ui = start_ui(str(tmp_path), MemoryObservationStore())
        try:
            yaml_text = (
                'apiVersion: kubeflow.org/v1beta1\n'
                'kind: Experiment\n'
                'metadata:\n  name: "wizard-exp"\nspec:\n'
                '  objective:\n    type: minimize\n'
                '    objectiveMetricName: "loss"\n    goal: 0.0001\n'
                '  algorithm:\n    algorithmName: random\n'
                '  parallelTrialCount: 2\n  maxTrialCount: 3\n'
                '  parameters:\n'
                '    - name: "lr"\n      parameterType: double\n'
                '      feasibleSpace: {min: "0.01", max: "0.05"}\n'
                '    - name: "opt"\n      parameterType: categorical\n'
                '      feasibleSpace: {list: ["sgd", "adam"]}\n'
                '  trialTemplate:\n    command:\n'
                '      - "python"\n      - "-c"\n'
                '      - "print(\'loss=\' + str((${trialParameters.lr}-0.03)**2))"\n'
            )
            req = urllib.request.Request(
                f"http://127.0.0.1:{ui.port}/api/experiments",
                data=json.dumps({"yaml": yaml_text}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req) as r:
                body = json.loads(r.read())
            assert r.status in (200, 201, 202), body
            assert "error" not in body, body
            # the run completes in the background; poll briefly
            import time

            for _ in range(120):
                status, _, raw = _get(ui.port, "/api/experiment/wizard-exp")
                st = json.loads(raw)
                if st.get("condition") in ("MaxTrialsReached", "GoalReached",
                                           "Succeeded", "Failed"):
                    break
                time.sleep(0.25)
            assert st["condition"] in ("MaxTrialsReached", "GoalReached"), st["condition"]
        finally:
            ui.stop()

    def test_unknown_routes_404(self, served):
        port, _ = served
        import urllib.error

        for path in ("/api/experiment/nope", "/api/bogus"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(port, path)
            assert e.value.code == 404


class TestNasGraphs:
    def test_darts_graph_shape(self):
        # per-node pair lists, the extract_genotype serialization
        genotype = {
            "normal": [
                [["sep_conv_3x3", 0], ["skip_connect", 1]],
                [["sep_conv_3x3", 2], ["max_pool_3x3", 0]],
            ],
            "reduce": [[["max_pool_3x3", 0], ["max_pool_3x3", 1]]],
        }
        g = _darts_graph(genotype)
        assert g["type"] == "darts"
        # 2 inputs + 2 normal nodes + 1 reduce node
        assert len(g["nodes"]) == 5
        assert len(g["edges"]) == 6
        # node 1 of the normal cell consumes intermediate node 0 (src=2)
        assert {"from": "normal-0", "to": "normal-1", "op": "sep_conv_3x3"} in g["edges"]

    def test_enas_graph_shape(self):
        arc = [[3], [1, 1], [0, 0, 1]]
        g = _enas_graph(arc)
        assert g["type"] == "enas"
        assert len(g["nodes"]) == 5  # input + 3 layers + output
        skips = [e for e in g["edges"] if e["op"] == "skip"]
        assert len(skips) == 2

    def test_recover_from_trial_assignment(self):
        trial = {"assignments": {"architecture": json.dumps([[2], [1, 0]])}}
        g = nas_graph_for_trial(trial)
        assert g is not None and g["type"] == "enas"

    def test_recover_from_genotype_file(self, tmp_path):
        ckpt = tmp_path / "t0"
        ckpt.mkdir()
        (ckpt / "genotype.json").write_text(
            json.dumps({"normal": [[["skip_connect", 0], ["none", 1]]], "reduce": []})
        )
        g = nas_graph_for_trial({"assignments": {}, "checkpoint_dir": str(ckpt)})
        assert g is not None and g["type"] == "darts"

    def test_no_artifact_returns_none(self, tmp_path):
        assert nas_graph_for_trial({"assignments": {}, "checkpoint_dir": str(tmp_path)}) is None


EXP_YAML = """
metadata:
  name: {name}
spec:
  maxTrialCount: 2
  parallelTrialCount: 1
  objective:
    type: maximize
    objectiveMetricName: score
  algorithm:
    algorithmName: random
  parameters:
    - name: x
      parameterType: double
      feasibleSpace: {{min: "0.0", max: "1.0"}}
  trialTemplate:
    command:
      - python
      - -c
      - "print('score=' + str(float('${{trialParameters.x}}')))"
"""


def _post(port, path, payload, token=None):
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(payload).encode(),
        headers=headers,
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _delete(port, path, token=None):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method="DELETE", headers=headers
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestUiWritePath:
    """POST create / stop + DELETE — parity with ``backend.go:86-181``."""

    def test_create_runs_and_delete(self, tmp_path):
        import time as _time

        ui = start_ui(str(tmp_path), MemoryObservationStore())
        try:
            status, reply = _post(
                ui.port, "/api/experiments", {"yaml": EXP_YAML.format(name="ui-created")}
            )
            assert status == 201 and reply["name"] == "ui-created"
            deadline = _time.time() + 60
            while _time.time() < deadline:
                s, _, body = _get(ui.port, "/api/experiment/ui-created")
                if s == 200 and json.loads(body)["condition"] == "MaxTrialsReached":
                    break
                _time.sleep(0.2)
            else:
                raise AssertionError("UI-created experiment never completed")
            # duplicate name refused while journal exists
            status, reply = _post(
                ui.port, "/api/experiments", {"yaml": EXP_YAML.format(name="ui-created")}
            )
            assert status == 409
            status, reply = _delete(ui.port, "/api/experiment/ui-created")
            assert status == 200
            s, _, _body = _get_raw_status(ui.port, "/api/experiment/ui-created")
            assert s == 404
        finally:
            ui.stop()

    def test_create_requires_command(self, tmp_path):
        ui = start_ui(str(tmp_path))
        try:
            bad = EXP_YAML.format(name="no-cmd").replace("trialTemplate", "ignored")
            status, reply = _post(ui.port, "/api/experiments", {"yaml": bad})
            assert status == 400 and "command" in reply["error"]
        finally:
            ui.stop()

    def test_stop_winds_down_running_experiment(self, tmp_path):
        import time as _time

        slow_yaml = EXP_YAML.format(name="ui-slow").replace(
            "print('score=' + str(float('${trialParameters.x}')))",
            "import time; print('score=0.5', flush=True); time.sleep(60)",
        ).replace("maxTrialCount: 2", "maxTrialCount: 50")
        ui = start_ui(str(tmp_path))
        try:
            status, _ = _post(ui.port, "/api/experiments", {"yaml": slow_yaml})
            assert status == 201
            deadline = _time.time() + 30
            while _time.time() < deadline:
                s, _, body = _get(ui.port, "/api/experiment/ui-slow")
                if s == 200:
                    break
                _time.sleep(0.2)
            status, reply = _post(ui.port, "/api/experiment/ui-slow/stop", {})
            assert status == 202
            deadline = _time.time() + 60
            while _time.time() < deadline:
                s, _, body = _get(ui.port, "/api/experiment/ui-slow")
                if s == 200 and json.loads(body)["condition"] == "Failed":
                    break
                _time.sleep(0.2)
            else:
                raise AssertionError("stop did not wind the experiment down")
            # delete while "running thread" has finished is allowed
            status, _ = _delete(ui.port, "/api/experiment/ui-slow")
            assert status == 200
        finally:
            ui.stop()

    def test_post_rejects_non_json_content_type(self, tmp_path):
        """CSRF guard: a browser "simple" request (text/plain, as sent by a
        cross-origin no-cors fetch) must be refused before it can reach the
        command-executing create endpoint."""
        ui = start_ui(str(tmp_path))
        try:
            body = json.dumps({"yaml": EXP_YAML.format(name="csrf")}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ui.port}/api/experiments", data=body,
                headers={"Content-Type": "text/plain"},
            )
            try:
                urllib.request.urlopen(req)
                raise AssertionError("expected 415")
            except urllib.error.HTTPError as e:
                assert e.code == 415
        finally:
            ui.stop()

    def test_delete_refuses_foreign_running_journal(self, tmp_path):
        """A non-terminal journal may belong to an orchestrator in another
        process; DELETE must refuse it without ?force=1 (else it rmtree's a
        live workdir mid-run)."""
        import os

        exp_dir = tmp_path / "other-proc"
        os.makedirs(exp_dir)
        (exp_dir / "status.json").write_text(json.dumps(
            {"name": "other-proc", "condition": "Running", "trials": {}}
        ))
        ui = start_ui(str(tmp_path))
        try:
            status, reply = _delete(ui.port, "/api/experiment/other-proc")
            assert status == 409 and "force" in reply["error"]
            assert exp_dir.exists()
            status, _ = _delete(ui.port, "/api/experiment/other-proc?force=1")
            assert status == 200
            assert not exp_dir.exists()
        finally:
            ui.stop()

    def test_trial_logs_served_after_run(self, tmp_path):
        """Captured black-box stdout is servable after the trial exits —
        parity with the reference UI's pod-log fetch (backend.go:463)."""
        import time as _time

        ui = start_ui(str(tmp_path), MemoryObservationStore())
        try:
            status, reply = _post(
                ui.port, "/api/experiments", {"yaml": EXP_YAML.format(name="logs-exp")}
            )
            assert status == 201
            deadline = _time.time() + 60
            while _time.time() < deadline:
                s, _, body = _get(ui.port, "/api/experiment/logs-exp")
                if s == 200 and json.loads(body)["condition"] == "MaxTrialsReached":
                    break
                _time.sleep(0.2)
            s, _, body = _get(ui.port, "/api/experiment/logs-exp/trials")
            trial = json.loads(body)[0]["name"]
            s, _, body = _get(ui.port, f"/api/trial/{trial}/logs")
            assert s == 200
            payload = json.loads(body)
            assert "score=" in payload["log"]
            s, _, _b = _get_raw_status(ui.port, "/api/trial/no-such-trial/logs")
            assert s == 404
        finally:
            ui.stop()

    def test_tokenless_writes_reject_foreign_host(self, tmp_path):
        """DNS-rebinding guard: with no token configured, a write whose Host
        header names a foreign domain (a rebound attacker origin) is 403."""
        ui = start_ui(str(tmp_path))
        try:
            body = json.dumps({"yaml": EXP_YAML.format(name="rebind")}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{ui.port}/api/experiments", data=body,
                headers={"Content-Type": "application/json", "Host": "evil.example"},
            )
            try:
                urllib.request.urlopen(req)
                raise AssertionError("expected 403")
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # the normal localhost Host header still works
            status, reply = _post(
                ui.port, "/api/experiments", {"yaml": EXP_YAML.format(name="rebind")}
            )
            assert status == 201
        finally:
            ui.stop()

    def test_write_auth_token(self, tmp_path):
        ui = start_ui(str(tmp_path), token="hunter2")
        try:
            status, reply = _post(
                ui.port, "/api/experiments", {"yaml": EXP_YAML.format(name="authed")}
            )
            assert status == 401
            # reads stay open
            s, _, _b = _get(ui.port, "/api/experiments")
            assert s == 200
            status, reply = _post(
                ui.port,
                "/api/experiments",
                {"yaml": EXP_YAML.format(name="authed")},
                token="hunter2",
            )
            assert status == 201
        finally:
            ui.stop()


def _get_raw_status(port, path):
    try:
        return _get(port, path)
    except urllib.error.HTTPError as e:
        return e.code, None, e.read()


class TestTrialLogResolution:
    def test_checkpoint_dir_preferred_over_convention(self, tmp_path):
        """find_trial_log resolves via the journal's recorded checkpoint_dir
        first (PBT lineage dirs live outside <workdir>/<exp>/<trial>)."""
        import os

        from katib_tpu.orchestrator.status import find_trial_log, read_trial_log

        outside = tmp_path / "lineage" / "t-1"
        os.makedirs(outside)
        (outside / "trial.log").write_text("from-lineage\n")
        exp_dir = tmp_path / "runs" / "exp-a"
        os.makedirs(exp_dir)
        (exp_dir / "status.json").write_text(json.dumps({
            "name": "exp-a", "condition": "Succeeded",
            "trials": {"t-1": {"name": "t-1", "condition": "Succeeded",
                               "assignments": {},
                               "checkpoint_dir": str(outside)}},
        }))
        workdir = str(tmp_path / "runs")
        assert find_trial_log(workdir, "t-1") == str(outside / "trial.log")
        assert read_trial_log(workdir, "t-1") == "from-lineage\n"
        # conventional fallback still works when the journal lacks the dir
        conv = exp_dir / "t-2"
        os.makedirs(conv)
        (conv / "trial.log").write_text("conventional\n")
        assert read_trial_log(workdir, "t-2") == "conventional\n"
        # unsafe names refuse
        assert find_trial_log(workdir, "../t-1") is None


class TestFlagshipProgress:
    """/api/flagship/progress serves the per-epoch run stream, grouped by
    config tag — the dashboard's live view of a long NAS search (fed by the
    same run_progress.jsonl that survives a mid-run cutoff)."""

    def test_grouped_by_config_and_garbage_tolerant(self, tmp_path):
        from katib_tpu.ui.backend import UiServer

        art = tmp_path / "art" / "flagship"
        art.mkdir(parents=True)
        rows = [
            {"epoch": 0, "accuracy": 0.5, "config": "b64", "platform": "tpu"},
            {"epoch": 1, "accuracy": 0.6, "config": "b64", "platform": "tpu"},
            {"epoch": 0, "accuracy": 0.1, "config": "b16", "platform": "cpu"},
        ]
        (art / "run_progress.jsonl").write_text(
            "\n".join(json.dumps(r) for r in rows)
            # garbage classes the reader must skip, not 500 on: broken
            # syntax, valid-JSON non-records, and truncated bytes from a
            # crash mid-append
            + "\nnot json\nnull\n[1,2]\n"
        )
        with open(art / "run_progress.jsonl", "ab") as f:
            f.write(b'{"epoch": 9, "accuracy": 0.9, "config": "b64\xc3')
        ui = UiServer(str(tmp_path), artifacts_dir=str(tmp_path / "art"))
        status, payload = ui.route("api/flagship/progress", {})
        assert status == 200
        assert [r["epoch"] for r in payload["runs"]["b64"]] == [0, 1]
        assert payload["runs"]["b16"][0]["platform"] == "cpu"

    def test_missing_stream_is_empty_not_error(self, tmp_path):
        from katib_tpu.ui.backend import UiServer

        ui = UiServer(str(tmp_path), artifacts_dir=str(tmp_path / "nope"))
        assert ui.route("api/flagship/progress", {}) == (200, {"runs": {}})

    def test_dashboard_carries_flagship_panel(self, tmp_path):
        from katib_tpu.ui.backend import DASHBOARD_HTML

        for hook in ("flagshipRuns", "/api/flagship/progress", 'id="flagship"'):
            assert hook in DASHBOARD_HTML, hook
