"""32-trial Hyperband sweep e2e on the 8-device virtual mesh (VERDICT r1
item 4 — the BASELINE v5e-64 scenario demonstrated at CPU scale).

Asserts the reference e2e invariants (``run-e2e-experiment.py:52-60``: best
objective exists; MaxTrialsReached ⇒ completed == maxTrialCount) plus the
Hyperband-specific ones: rung promotion via labels, the resource parameter
raised per rung, and ``SliceAllocator`` leasing disjoint one-device
sub-meshes to at most ``parallel_trial_count`` concurrent trials.

r_l=16, eta=4 ⇒ brackets s=2 (16@1, 4@4, 1@16), s=1 (6@4, 2@16), s=0 (3@16)
— exactly 32 trials.
"""

from __future__ import annotations

import math
import threading

import jax
import pytest

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentCondition,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)
from katib_tpu.orchestrator import Orchestrator
from katib_tpu.parallel.distributed import SliceAllocator
from katib_tpu.suggest.hyperband import I_LABEL, S_LABEL


@pytest.mark.slow
def test_hyperband_32_trial_sweep_with_slice_leasing(tmp_path):
    concurrency = {"now": 0, "peak": 0}
    seen_devices: list = []
    lock = threading.Lock()

    def train(ctx):
        with lock:
            concurrency["now"] += 1
            concurrency["peak"] = max(concurrency["peak"], concurrency["now"])
            seen_devices.append(tuple(d.id for d in ctx.mesh.devices.flat))
        try:
            assert ctx.mesh is not None and ctx.mesh.devices.size == 1
            lr = float(ctx.params["lr"])
            epochs = int(float(ctx.params["epochs"]))
            base = 1.0 - (lr - 0.1) ** 2
            for epoch in range(epochs):
                # run the epoch's "compute" on the leased sub-mesh so the
                # lease is actually exercised on-device
                with ctx.mesh:
                    x = jax.numpy.full((4, 4), lr)
                    val = float(jax.jit(lambda a: (a @ a).sum())(x))
                assert math.isfinite(val)
                acc = base * (1.0 - math.exp(-(epoch + 1) / 4.0))
                if not ctx.report(step=epoch, accuracy=acc):
                    return
        finally:
            with lock:
                concurrency["now"] -= 1

    spec = ExperimentSpec(
        name="hyperband-sweep",
        algorithm=AlgorithmSpec(
            name="hyperband",
            settings={"r_l": "16", "resource_name": "epochs", "eta": "4"},
        ),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.5)),
            ParameterSpec(
                "epochs", ParameterType.INT, FeasibleSpace(min=1, max=16)
            ),
        ],
        max_trial_count=32,
        # hyperband validation needs >= eta^s_max = 16 slots so a full rung
        # can be in flight; the 8-slice allocator still caps the number of
        # trials actually on a device at 8
        parallel_trial_count=16,
        train_fn=train,
    )
    allocator = SliceAllocator(slice_size=1, devices=jax.devices())
    assert allocator.n_slices == 8
    exp = Orchestrator(workdir=str(tmp_path), slice_allocator=allocator).run(spec)

    # reference e2e invariants
    assert exp.condition in (
        ExperimentCondition.MAX_TRIALS_REACHED,
        ExperimentCondition.SUCCEEDED,
    ), exp.message
    assert exp.optimal is not None
    assert exp.succeeded_count == 32
    if exp.condition is ExperimentCondition.MAX_TRIALS_REACHED:
        assert len(exp.trials) == 32

    # rung structure: every trial labeled; bracket s=2 rung 0 has 16 trials
    rungs: dict[tuple[str, str], list] = {}
    for t in exp.trials.values():
        key = (t.labels[S_LABEL], t.labels[I_LABEL])
        rungs.setdefault(key, []).append(t)
    assert len(rungs["2", "0"]) == 16
    assert len(rungs["2", "1"]) == 4
    assert len(rungs["2", "2"]) == 1
    assert len(rungs["1", "0"]) == 6
    assert len(rungs["1", "1"]) == 2
    assert len(rungs["0", "0"]) == 3

    # promotion: each promoted trial names a parent in the previous rung,
    # keeps its lr, and raises the resource parameter eta-fold
    promoted = [t for t in exp.trials.values() if "hyperband-parent" in t.labels]
    assert promoted
    for t in promoted:
        parent = exp.trials[t.labels["hyperband-parent"]]
        assert parent.labels[S_LABEL] == t.labels[S_LABEL]
        assert int(parent.labels[I_LABEL]) == int(t.labels[I_LABEL]) - 1
        assert t.params()["lr"] == parent.params()["lr"]
        assert int(float(t.params()["epochs"])) == 4 * int(
            float(parent.params()["epochs"])
        )
    # rung 0 of bracket s=2 ran at the minimum resource, top rung at r_l
    assert all(int(float(t.params()["epochs"])) == 1 for t in rungs["2", "0"])
    assert all(int(float(t.params()["epochs"])) == 16 for t in rungs["2", "2"])
    # more resource helped: the optimum came from a full-resource rung
    assert int(float(dict((a.name, a.value) for a in exp.optimal.assignments)["epochs"])) >= 4

    # slice leasing: never more than 8 concurrent, every lease a 1-device mesh
    assert 1 < concurrency["peak"] <= 8
    assert len(seen_devices) == 32
    assert all(len(d) == 1 for d in seen_devices)


def test_devices_per_rung_scales_leases(tmp_path):
    """hyperband setting devices_per_rung=true: the rung resource value also
    sizes each trial's sub-mesh lease — promoted survivors run on more
    chips (ElasticSliceAllocator elasticity, SURVEY §7 hard part b)."""
    from katib_tpu.parallel.distributed import ElasticSliceAllocator

    seen: dict[str, int] = {}
    lock = threading.Lock()

    def train(ctx):
        with lock:
            seen[ctx.trial_name] = ctx.mesh.devices.size
        acc = 1.0 - (float(ctx.params["lr"]) - 0.1) ** 2
        for epoch in range(int(float(ctx.params["epochs"]))):
            if not ctx.report(step=epoch, accuracy=acc * (epoch + 1)):
                return

    spec = ExperimentSpec(
        name="hb-devices",
        algorithm=AlgorithmSpec(
            name="hyperband",
            settings={
                "r_l": "4", "resource_name": "epochs", "eta": "2",
                "devices_per_rung": "true",
            },
        ),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.01, max=0.5)),
            ParameterSpec("epochs", ParameterType.INT, FeasibleSpace(min=1, max=4)),
        ],
        max_trial_count=None,
        parallel_trial_count=4,
        train_fn=train,
    )
    alloc = ElasticSliceAllocator(devices=jax.devices())
    exp = Orchestrator(workdir=str(tmp_path), slice_allocator=alloc).run(spec)
    assert exp.succeeded_count >= 4
    # every trial's mesh matched its rung resource (epochs == devices here)
    for t in exp.trials.values():
        want = int(float(t.params()["epochs"]))
        assert seen[t.name] == min(want, alloc.n_devices), (t.name, want)
    # at least one promoted trial ran on a bigger mesh than its parent
    grew = [
        t for t in exp.trials.values()
        if t.labels.get("hyperband-parent")
        and seen[t.name] > seen[t.labels["hyperband-parent"]]
    ]
    assert grew, "no promotion increased the device budget"
    assert alloc.available() == alloc.n_devices


def test_asha_async_sweep_e2e(tmp_path):
    """ASHA through the orchestrator: asynchronous promotions (no rung
    barrier), same reference e2e invariants, promotions present and the
    resource parameter raised for promoted children."""

    def train(ctx):
        lr = float(ctx.params["lr"])
        epochs = int(float(ctx.params["epochs"]))
        base = 1.0 - (lr - 0.1) ** 2
        for epoch in range(epochs):
            acc = base * (1.0 - math.exp(-(epoch + 1) / 4.0))
            if not ctx.report(step=epoch, accuracy=acc):
                return

    spec = ExperimentSpec(
        name="asha-sweep",
        algorithm=AlgorithmSpec(
            name="asha",
            settings={"r_max": "9", "r_min": "1", "eta": "3",
                      "resource_name": "epochs"},
        ),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE,
                          FeasibleSpace(min=0.01, max=0.5)),
            ParameterSpec("epochs", ParameterType.INT,
                          FeasibleSpace(min=1, max=9)),
        ],
        max_trial_count=24,
        parallel_trial_count=4,
        train_fn=train,
    )
    exp = Orchestrator(workdir=str(tmp_path)).run(spec)

    assert exp.condition in (
        ExperimentCondition.MAX_TRIALS_REACHED,
        ExperimentCondition.SUCCEEDED,
    ), exp.message
    assert exp.optimal is not None
    assert exp.succeeded_count == 24

    promoted = [t for t in exp.trials.values()
                if t.labels.get("asha-parent")]
    assert promoted, "no asynchronous promotions happened in 24 trials"
    for t in promoted:
        parent = exp.trials[t.labels["asha-parent"]]
        child_r = int(float(next(a.value for a in t.spec.assignments
                                 if a.name == "epochs")))
        parent_r = int(float(next(a.value for a in parent.spec.assignments
                                  if a.name == "epochs")))
        assert child_r > parent_r  # promotion raises the resource
        # and keeps the config: every non-resource assignment identical
        child_lr = next(a.value for a in t.spec.assignments if a.name == "lr")
        parent_lr = next(a.value for a in parent.spec.assignments
                         if a.name == "lr")
        assert child_lr == parent_lr


def test_asha_devices_per_rung_scales_leases(tmp_path):
    """asha's devices_per_rung: promoted children lease sub-meshes sized by
    their rung resource, asynchronously (no bracket barrier)."""
    from katib_tpu.parallel.distributed import ElasticSliceAllocator

    seen: dict[str, int] = {}
    lock = threading.Lock()

    def train(ctx):
        with lock:
            seen[ctx.trial_name] = ctx.mesh.devices.size
        acc = 1.0 - (float(ctx.params["lr"]) - 0.1) ** 2
        for epoch in range(int(float(ctx.params["epochs"]))):
            if not ctx.report(step=epoch, accuracy=acc * (epoch + 1)):
                return

    spec = ExperimentSpec(
        name="asha-devices",
        algorithm=AlgorithmSpec(
            name="asha",
            settings={
                "r_max": "4", "eta": "2", "resource_name": "epochs",
                "devices_per_rung": "true",
            },
        ),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("lr", ParameterType.DOUBLE,
                          FeasibleSpace(min=0.01, max=0.5)),
            ParameterSpec("epochs", ParameterType.INT,
                          FeasibleSpace(min=1, max=4)),
        ],
        max_trial_count=16,
        parallel_trial_count=4,
        train_fn=train,
    )
    alloc = ElasticSliceAllocator(devices=jax.devices())
    exp = Orchestrator(workdir=str(tmp_path), slice_allocator=alloc).run(spec)
    assert exp.succeeded_count == 16
    for t in exp.trials.values():
        want = int(float(t.params()["epochs"]))
        assert seen[t.name] == min(want, alloc.n_devices), (t.name, want)
    grew = [
        t for t in exp.trials.values()
        if t.labels.get("asha-parent")
        and seen[t.name] > seen[t.labels["asha-parent"]]
    ]
    assert grew, "no asha promotion increased the device budget"
    assert alloc.available() == alloc.n_devices
