"""The static-analysis suite (``katib_tpu/analysis``): one fixture per
hazard code for both AST passes, the runtime lock-order witness, the
baseline ratchet, and the repo-clean gate CI relies on.

Fixture modules are SOURCE STRINGS, not imports — both checkers are
AST-only by design (they must lint jax-touching files without jax
installed), so the fixtures never execute.
"""

from __future__ import annotations

import json
import os
import textwrap
import threading

import pytest

from katib_tpu.analysis import guards as G
from katib_tpu.analysis import jaxcheck, lockcheck, witness
from katib_tpu.analysis.lint import (
    BASELINE_DEFAULT,
    load_baseline,
    run_lint,
    write_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(findings):
    return sorted(f.code for f in findings)


def lock_findings(src):
    return lockcheck.check_source(textwrap.dedent(src), "fixture.py")


def jax_findings(src, timing=False):
    return jaxcheck.check_source(textwrap.dedent(src), "fixture.py", timing=timing)


# -- annotation grammar ------------------------------------------------------


def test_guarded_by_returns_attr_to_lock_map():
    assert G.guarded_by(_lock=("_a", "_b"), _other=("_c",)) == {
        "_a": "_lock", "_b": "_lock", "_c": "_other"
    }


def test_guarded_by_rejects_empty_and_double_guarding():
    with pytest.raises(ValueError):
        G.guarded_by(_lock=())
    with pytest.raises(ValueError):
        G.guarded_by(_lock=("_a",), _other=("_a",))


def test_parse_annotations_reads_suppressions_and_holds():
    src = textwrap.dedent(
        """
        x = 1  # lint: unguarded-ok(wind-down only)
        def f():  # lint: holds(_lock, _other)
            pass
        """
    )
    suppressed, holds = G.parse_annotations(src)
    assert suppressed == {2: "wind-down only"}
    assert holds == {3: ("_lock", "_other")}


# -- LCK001: guarded access outside the lock ---------------------------------

_LCK_FIXTURE = """
    import threading
    from katib_tpu.analysis import guarded_by

    class Box:
        _GUARDS = guarded_by(_lock=("_items",))

        def __init__(self):
            self._lock = threading.Lock()
            self._items = []            # __init__ is exempt

        def good(self):
            with self._lock:
                return len(self._items)

        def bad(self):
            return len(self._items)     # LCK001

        def waved(self):
            return len(self._items)     # lint: unguarded-ok(test fixture)

        def helper(self):  # lint: holds(_lock)
            return len(self._items)
"""


def test_lck001_flags_only_the_bare_access():
    findings = lock_findings(_LCK_FIXTURE)
    assert codes(findings) == ["LCK001"]
    (f,) = findings
    assert f.symbol == "Box.bad"
    assert f.detail == "_items"
    assert "_lock" in f.message


def test_lck001_multi_lock_class_tracks_each_lock():
    findings = lock_findings(
        """
        class Engine:
            _GUARDS = guarded_by(_queue_lock=("_ready",), _futures_lock=("futures",))

            def wrong_lock(self):
                with self._futures_lock:
                    return list(self._ready)   # held the OTHER lock: LCK001

            def right(self):
                with self._queue_lock:
                    with self._futures_lock:
                        return list(self._ready) + list(self.futures)
        """
    )
    assert codes(findings) == ["LCK001"]
    assert findings[0].symbol == "Engine.wrong_lock"


def test_lck001_nested_function_inherits_lexical_held_set():
    findings = lock_findings(
        """
        class Box:
            _GUARDS = guarded_by(_lock=("_items",))

            def f(self):
                with self._lock:
                    def peek():
                        return self._items  # lexically under the with: clean
                    return peek()
        """
    )
    assert findings == []


# -- LCK002: escape to another thread ----------------------------------------


def test_lck002_thread_and_executor_escapes():
    findings = lock_findings(
        """
        import threading

        class Box:
            _GUARDS = guarded_by(_lock=("_items",))

            def leak_thread(self):
                t = threading.Thread(target=self._work, args=(self._items,))
                t.start()

            def leak_submit(self, pool):
                return pool.submit(sum, self._items)
        """
    )
    assert codes(findings) == ["LCK002", "LCK002"]
    assert {f.symbol for f in findings} == {"Box.leak_thread", "Box.leak_submit"}


def test_lck002_takes_precedence_over_lck001_on_the_same_node():
    findings = lock_findings(
        """
        import threading

        class Box:
            _GUARDS = guarded_by(_lock=("_items",))

            def leak(self):
                threading.Thread(target=print, args=(self._items,)).start()
        """
    )
    # one LCK002, and NOT an additional LCK001 for the same attribute node
    assert codes(findings) == ["LCK002"]


def test_lck002_suppression_silences_both_codes():
    findings = lock_findings(
        """
        import threading

        class Box:
            _GUARDS = guarded_by(_lock=("_items",))

            def leak(self):
                threading.Thread(target=print, args=(self._items,)).start()  # lint: unguarded-ok(receiver is read-only)
        """
    )
    assert findings == []


# -- JAX101: host sync in a hot body -----------------------------------------


def test_jax101_host_sync_in_scan_body():
    findings = jax_findings(
        """
        import jax

        def body(carry, x):
            loss = float(carry)        # JAX101
            return carry, x

        def train(xs):
            return jax.lax.scan(body, 0.0, xs)
        """
    )
    assert codes(findings) == ["JAX101"]
    assert findings[0].detail == "float()"
    assert findings[0].symbol == "body"


def test_jax101_loop_inside_jitted_fn_and_fori_body():
    findings = jax_findings(
        """
        import jax
        import numpy as np

        @jax.jit
        def step(xs):
            out = []
            for x in xs:
                out.append(np.asarray(x))   # JAX101 (loop in jitted fn)
            return out

        def fbody(i, val):
            return val + val.item()         # JAX101 (fori body)

        def run(n, v0):
            return jax.lax.fori_loop(0, n, fbody, v0)
        """
    )
    assert codes(findings) == ["JAX101", "JAX101"]
    assert {f.detail for f in findings} == {"np.asarray()", ".item()"}


def test_jax101_clean_body_passes():
    findings = jax_findings(
        """
        import jax

        def body(carry, x):
            return carry + x, x

        def train(xs):
            return jax.lax.scan(body, 0.0, xs)
        """
    )
    assert findings == []


# -- JAX102: jit constructed in a loop ---------------------------------------


def test_jax102_jit_in_loop():
    findings = jax_findings(
        """
        import jax

        def sweep(fns, x):
            outs = []
            for fn in fns:
                outs.append(jax.jit(fn)(x))   # JAX102
            return outs
        """
    )
    assert codes(findings) == ["JAX102"]
    assert findings[0].symbol == "sweep"


# -- JAX103: non-hashable static argument ------------------------------------


def test_jax103_list_literal_at_static_position():
    findings = jax_findings(
        """
        import jax

        g = jax.jit(lambda shape, x: x, static_argnums=(0,))

        def call(x):
            return g([4, 4], x)            # JAX103

        def direct(x):
            return jax.jit(lambda s, x: x, static_argnums=(0,))({"k": 1}, x)  # JAX103
        """
    )
    assert codes(findings) == ["JAX103", "JAX103"]


def test_jax103_hashable_static_argument_passes():
    findings = jax_findings(
        """
        import jax

        g = jax.jit(lambda shape, x: x, static_argnums=(0,))

        def call(x):
            return g((4, 4), x)
        """
    )
    assert findings == []


# -- JAX104: donated-buffer reuse --------------------------------------------


def test_jax104_read_after_donation():
    findings = jax_findings(
        """
        import jax

        step = jax.jit(lambda s: s, donate_argnums=(0,))

        def train(state):
            out = step(state)
            return state                    # JAX104: donated buffer read
        """
    )
    assert codes(findings) == ["JAX104"]
    assert findings[0].detail == "state"


def test_jax104_rebinding_revives_the_name():
    findings = jax_findings(
        """
        import jax

        step = jax.jit(lambda s: s, donate_argnums=(0,))

        def train(state, n):
            for _ in range(n):
                state = step(state)         # rebind revives: clean
            return state
        """
    )
    assert findings == []


# -- JAX105: unsynced timing boundary (bench files only) ---------------------


def test_jax105_timer_without_sync():
    src = """
        import time

        def bench(step, state):
            t0 = time.perf_counter()
            state = step(state)
            elapsed = time.perf_counter() - t0   # JAX105: dispatch, not work
            return elapsed
    """
    findings = jax_findings(src, timing=True)
    assert codes(findings) == ["JAX105"]
    assert findings[0].detail == "t0"
    # the same source is NOT checked when the file isn't a bench entry point
    assert jax_findings(src, timing=False) == []


def test_jax105_block_until_ready_or_host_fetch_satisfies():
    findings = jax_findings(
        """
        import jax, time

        def bench_barrier(step, state):
            t0 = time.perf_counter()
            state = step(state)
            jax.block_until_ready(state)
            return time.perf_counter() - t0

        def bench_fetch(step, state):
            t0 = time.perf_counter()
            loss = float(step(state))
            return time.perf_counter() - t0
        """,
        timing=True,
    )
    assert findings == []


# -- the runtime lock-order witness ------------------------------------------


@pytest.fixture
def witnessed(monkeypatch):
    monkeypatch.setenv(witness.ENV_VAR, "1")
    witness.witness_reset()
    yield
    witness.witness_reset()


def test_make_lock_is_plain_lock_when_disabled(monkeypatch):
    monkeypatch.delenv(witness.ENV_VAR, raising=False)
    lk = witness.make_lock("test.plain")
    assert not isinstance(lk, witness.WitnessLock)
    with lk:
        pass


def test_witness_records_acquisition_graph(witnessed):
    a = witness.make_lock("test.a")
    b = witness.make_lock("test.b")
    assert isinstance(a, witness.WitnessLock)
    with a:
        with b:
            pass
    snap = witness.witness_summary()
    assert snap["acquires"] == {"test.a": 1, "test.b": 1}
    assert ("test.a", "test.b", 1) in snap["edges"]
    assert witness.witness_cycles() == []


def test_witness_raises_on_lock_order_inversion(witnessed):
    a = witness.make_lock("test.a")
    b = witness.make_lock("test.b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(witness.LockOrderInversion):
            a.acquire()
    # the inversion was recorded for the soak report, and the failed
    # acquire did NOT take the lock (raise-before-acquire)
    assert witness.witness_cycles()
    assert not a.locked()


def test_witness_same_role_reacquisition_records_no_edge(witnessed):
    # two instances of one role (every _Metric._lock shares "metrics.metric"):
    # nesting them must not self-edge, and must not poison later ordering
    m1 = witness.make_lock("test.metric")
    m2 = witness.make_lock("test.metric")
    with m1:
        with m2:
            pass
    assert witness.witness_summary()["edges"] == []
    assert witness.witness_cycles() == []


def test_witness_transitive_inversion_detected(witnessed):
    a = witness.make_lock("test.a")
    b = witness.make_lock("test.b")
    c = witness.make_lock("test.c")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(witness.LockOrderInversion):
            with a:
                pass


# -- lint driver: baseline ratchet + repo gate -------------------------------

_DIRTY_MODULE = textwrap.dedent(
    """
    from katib_tpu.analysis import guarded_by

    class Box:
        _GUARDS = guarded_by(_lock=("_items",))

        def bad(self):
            return len(self._items)
    """
)


def _mini_repo(tmp_path, dirty=True):
    pkg = tmp_path / "katib_tpu"
    pkg.mkdir()
    (pkg / "box.py").write_text(_DIRTY_MODULE if dirty else "x = 1\n")
    return str(tmp_path)


def test_run_lint_fails_on_new_finding(tmp_path):
    report = run_lint(root=_mini_repo(tmp_path))
    assert report.exit_code == 1
    assert codes(report.new) == ["LCK001"]
    assert report.baselined == []


def test_baseline_ratchet_accepts_then_reports_stale(tmp_path):
    root = _mini_repo(tmp_path)
    baseline = os.path.join(root, "baseline.json")
    report = run_lint(root=root)
    write_baseline(baseline, report.findings)

    # baselined: same findings, exit 0
    again = run_lint(root=root, baseline_path=baseline)
    assert again.exit_code == 0
    assert codes(again.baselined) == ["LCK001"]
    assert again.new == []

    # fingerprints are line-number-free: moving the code keeps the ratchet
    (tmp_path / "katib_tpu" / "box.py").write_text("\n\n\n" + _DIRTY_MODULE)
    moved = run_lint(root=root, baseline_path=baseline)
    assert moved.exit_code == 0 and moved.new == []

    # fixing the finding leaves a stale entry the report names for pruning
    (tmp_path / "katib_tpu" / "box.py").write_text("x = 1\n")
    fixed = run_lint(root=root, baseline_path=baseline)
    assert fixed.exit_code == 0
    assert len(fixed.stale_baseline) == 1
    assert fixed.stale_baseline[0].startswith("LCK001:")


def test_cli_lint_verb_exit_codes(tmp_path, capsys):
    from katib_tpu.cli import main

    root = _mini_repo(tmp_path)
    assert main(["lint", "--root", root]) == 1
    assert "LCK001" in capsys.readouterr().out

    baseline = os.path.join(root, "artifacts", "lint", "baseline.json")
    assert main(["lint", "--root", root, "--update-baseline"]) == 0
    doc = json.loads(open(baseline).read())
    assert doc["findings"] and doc["findings"][0].startswith("LCK001:")
    assert main(["lint", "--root", root]) == 0


def test_repo_is_lint_clean_against_committed_baseline():
    """The acceptance gate: ``katib-tpu lint`` exits 0 at HEAD.  Every true
    positive was fixed, not baselined — the committed baseline is empty."""
    baseline = os.path.join(REPO_ROOT, BASELINE_DEFAULT)
    report = run_lint(root=REPO_ROOT, baseline_path=baseline)
    assert report.new == [], "\n".join(f.render() for f in report.new)
    assert load_baseline(baseline) == []
    assert report.stale_baseline == []
    assert report.files_scanned > 50
