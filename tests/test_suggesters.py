"""Suggester algorithm tests — behavioral parity targets from the reference's
python suggestion-service unit tests (test/unit/v1beta1/suggestion/)."""

import math

import numpy as np
import pytest

from katib_tpu.core.types import (
    Experiment,
    FeasibleSpace,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    TrialCondition,
)
from katib_tpu.suggest import (
    SearchExhausted,
    SpaceEncoder,
    SuggesterError,
    SuggestionsNotReady,
    make_suggester,
)
from tests.helpers import best_value, complete_trial, make_spec, run_loop

sphere = lambda p: p["x"] ** 2 + p["y"] ** 2


def new_exp(spec):
    return Experiment(spec=spec)


class TestSpaceEncoder:
    def test_roundtrip_linear(self):
        spec = make_spec()
        enc = SpaceEncoder(spec.parameters)
        d = {"x": 1.5, "y": -3.0}
        assert enc.decode(enc.encode(d)) == pytest.approx({"x": 1.5, "y": -3.0})

    def test_log_scaling(self):
        from katib_tpu.core.types import Distribution

        p = [
            ParameterSpec(
                "lr",
                ParameterType.DOUBLE,
                FeasibleSpace(min=1e-5, max=1e-1, distribution=Distribution.LOG_UNIFORM),
            )
        ]
        enc = SpaceEncoder(p)
        # midpoint of unit interval = geometric mean
        assert enc.decode(np.array([0.5]))["lr"] == pytest.approx(1e-3)
        rng = np.random.default_rng(0)
        samples = [enc.sample(rng)["lr"] for _ in range(500)]
        # log-uniform: about half of samples below geometric mean
        frac_low = np.mean([s < 1e-3 for s in samples])
        assert 0.4 < frac_low < 0.6

    def test_categorical_onehot(self):
        p = [
            ParameterSpec("opt", ParameterType.CATEGORICAL, FeasibleSpace(list=("a", "b", "c"))),
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0.0, max=1.0)),
        ]
        enc = SpaceEncoder(p)
        v = enc.encode_onehot({"opt": "b", "x": 0.25})
        assert v.tolist() == [0.0, 1.0, 0.0, 0.25]
        assert enc.onehot_dims() == 4

    def test_int_step_decode(self):
        p = [ParameterSpec("n", ParameterType.INT, FeasibleSpace(min=8, max=64, step=8))]
        enc = SpaceEncoder(p)
        for u in np.linspace(0, 1, 17):
            v = enc.decode(np.array([u]))["n"]
            assert v % 8 == 0 and 8 <= v <= 64


class TestRandom:
    def test_in_bounds_and_deterministic(self):
        spec = make_spec("random")
        s1, s2 = make_suggester(spec), make_suggester(spec)
        exp = new_exp(spec)
        a = s1.get_suggestions(exp, 5)
        b = s2.get_suggestions(exp, 5)
        assert [t.as_dict() for t in a] == [t.as_dict() for t in b]
        for t in a:
            d = t.as_dict()
            assert -5 <= d["x"] <= 5 and -5 <= d["y"] <= 5

    def test_adjacent_seeds_produce_independent_streams(self):
        """Regression: additive seed composition (base + extra) made seed
        s+1's stream a one-step shift of seed s's — multi-seed replicates
        silently shared 95%+ of their draws.  Hash-mixed composition keeps
        them independent."""
        def draws(seed):
            spec = make_spec("random", settings={"random_state": str(seed)})
            s = make_suggester(spec)
            exp = new_exp(spec)
            out = []
            for _ in range(10):
                p = s.get_suggestions(exp, 1)[0]
                out.append(round(p.as_dict()["x"], 9))
                complete_trial(exp, p, 0.0)
            return out

        v1, v2 = draws(1), draws(2)
        # the additive bug: v2[:-1] == v1[1:] (a slid window); and more
        # generally the two streams shared almost every value
        assert v2[:-1] != v1[1:]
        assert len(set(v1) & set(v2)) == 0

    def test_stream_advances_with_history(self):
        spec = make_spec("random")
        s = make_suggester(spec)
        exp = new_exp(spec)
        first = s.get_suggestions(exp, 1)[0]
        complete_trial(exp, first, 1.0)
        second = s.get_suggestions(exp, 1)[0]
        assert first.as_dict() != second.as_dict()


class TestGrid:
    def _spec(self):
        return make_spec(
            "grid",
            parameters=[
                ParameterSpec("a", ParameterType.INT, FeasibleSpace(min=0, max=2, step=1)),
                ParameterSpec("b", ParameterType.CATEGORICAL, FeasibleSpace(list=("u", "v"))),
            ],
        )

    def test_enumerates_product_then_exhausts(self):
        spec = self._spec()
        s = make_suggester(spec)
        exp = new_exp(spec)
        seen = set()
        for _ in range(3):
            for p in s.get_suggestions(exp, 2):
                seen.add(tuple(sorted(p.as_dict().items())))
                complete_trial(exp, p, 0.0)
        assert len(seen) == 6
        with pytest.raises(SearchExhausted):
            s.get_suggestions(exp, 1)

    def test_rejects_infinite_space(self):
        with pytest.raises(SuggesterError):
            make_suggester(make_spec("grid"))  # doubles without step


class TestSobol:
    def test_low_discrepancy_and_resume(self):
        spec = make_spec("sobol")
        s = make_suggester(spec)
        exp = new_exp(spec)
        batch1 = s.get_suggestions(exp, 4)
        for p in batch1:
            complete_trial(exp, p, sphere(p.as_dict()))
        batch2 = s.get_suggestions(exp, 4)
        pts = {tuple(p.as_dict().values()) for p in batch1 + batch2}
        assert len(pts) == 8  # stream continues, no repeats

    def test_fresh_instance_continues_stream(self):
        spec = make_spec("sobol")
        exp = new_exp(spec)
        b1 = make_suggester(spec).get_suggestions(exp, 2)
        for p in b1:
            complete_trial(exp, p, 0.0)
        b2 = make_suggester(spec).get_suggestions(exp, 2)
        assert {tuple(p.as_dict().values()) for p in b1}.isdisjoint(
            {tuple(p.as_dict().values()) for p in b2}
        )


class TestTPE:
    @pytest.mark.parametrize("algo", ["tpe", "multivariate-tpe"])
    def test_beats_random_on_sphere(self, algo):
        spec = make_spec(algo, settings={"n_startup_trials": "8", "random_state": "7"})
        s = make_suggester(spec)
        exp = run_loop(s, new_exp(spec), sphere, rounds=40)
        tpe_best = best_value(exp)

        rspec = make_spec("random", settings={"random_state": "7"})
        rexp = run_loop(make_suggester(rspec), new_exp(rspec), sphere, rounds=40)
        rand_best = best_value(rexp)
        assert tpe_best < 1.0
        assert tpe_best <= rand_best * 1.5  # should generally be much better

    def test_categorical_dims(self):
        spec = make_spec(
            "tpe",
            settings={"n_startup_trials": "5"},
            parameters=[
                ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=-5.0, max=5.0)),
                ParameterSpec("kind", ParameterType.CATEGORICAL, FeasibleSpace(list=("good", "bad"))),
            ],
        )
        fn = lambda p: p["x"] ** 2 + (0.0 if p["kind"] == "good" else 10.0)
        exp = run_loop(make_suggester(spec), new_exp(spec), fn, rounds=30)
        exp.update_optimal()
        chosen = dict((a.name, a.value) for a in exp.optimal.assignments)
        assert chosen["kind"] == "good"

    def test_batch_suggestions_are_distinct(self):
        spec = make_spec("tpe", settings={"n_startup_trials": "2"})
        s = make_suggester(spec)
        exp = new_exp(spec)
        for _ in range(3):
            for p in s.get_suggestions(exp, 1):
                complete_trial(exp, p, sphere(p.as_dict()))
        batch = s.get_suggestions(exp, 4)
        pts = {tuple(p.as_dict().values()) for p in batch}
        assert len(pts) == 4

    def test_settings_validation(self):
        with pytest.raises(SuggesterError):
            make_suggester(make_spec("tpe", settings={"gamma": "1.5"}))
        with pytest.raises(SuggesterError):
            make_suggester(make_spec("tpe", settings={"prior_weight": "0"}))

    def test_reference_setting_spellings(self):
        """Upstream Katib YAMLs spell the candidate-count key
        ``n_EI_candidates`` (``hyperopt/service.py:72``) and may set
        ``prior_weight``; both must be honored, not silently ignored."""
        spec = make_spec(
            "tpe",
            settings={
                "n_EI_candidates": "8",
                "prior_weight": "2.0",
                "n_startup_trials": "3",
                "random_state": "5",
            },
        )
        s = make_suggester(spec)
        exp = run_loop(s, new_exp(spec), sphere, rounds=12)
        assert best_value(exp) < 25.0  # the search actually ran
        with pytest.raises(SuggesterError):
            make_suggester(make_spec("tpe", settings={"n_EI_candidates": "0"}))


class TestBayesOpt:
    def test_converges_on_quadratic(self):
        spec = make_spec(
            "bayesianoptimization",
            settings={"n_initial_points": "6", "random_state": "3"},
        )
        exp = run_loop(make_suggester(spec), new_exp(spec), sphere, rounds=25)
        assert best_value(exp) < 1.0

    def test_acq_func_validation(self):
        with pytest.raises(SuggesterError):
            make_suggester(make_spec("bayesianoptimization", settings={"acq_func": "nope"}))
        with pytest.raises(SuggesterError):
            make_suggester(
                make_spec("bayesianoptimization", settings={"base_estimator": "RF"})
            )
        with pytest.raises(SuggesterError):
            make_suggester(
                make_spec("bayesianoptimization", settings={"acq_optimizer": "nope"})
            )

    def test_gp_hedge_and_skopt_spellings(self):
        """The reference defaults to acq_func=gp_hedge and skopt spells the
        functions upper-case; both must work (``skopt/base_service.py:33``)."""
        spec = make_spec(
            "bayesianoptimization",
            settings={
                "acq_func": "gp_hedge",
                "acq_optimizer": "auto",
                "n_initial_points": "5",
                "random_state": "2",
            },
        )
        exp = run_loop(make_suggester(spec), new_exp(spec), sphere, rounds=15)
        assert best_value(exp) < 5.0
        make_suggester(
            make_spec("bayesianoptimization", settings={"acq_func": "LCB"})
        )  # case-insensitive accept

    def test_categorical_support(self):
        spec = make_spec(
            "bayesianoptimization",
            settings={"n_initial_points": "5", "random_state": "1"},
            parameters=[
                ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=-2.0, max=2.0)),
                ParameterSpec("m", ParameterType.CATEGORICAL, FeasibleSpace(list=("p", "q"))),
            ],
        )
        fn = lambda p: p["x"] ** 2 + (0 if p["m"] == "p" else 5)
        exp = run_loop(make_suggester(spec), new_exp(spec), fn, rounds=15)
        assert best_value(exp) < 5.0


class TestCmaEs:
    def test_generation_barrier_and_convergence(self):
        spec = make_spec("cmaes", settings={"random_state": "11"})
        s = make_suggester(spec)
        exp = new_exp(spec)
        # run several generations manually
        for _ in range(12):
            try:
                proposals = s.get_suggestions(exp, 50)
            except SuggestionsNotReady:
                pytest.fail("should not block when all trials terminal")
            for p in proposals:
                assert "cmaes-generation" in p.labels
                complete_trial(exp, p, sphere(p.as_dict()))
        assert best_value(exp) < 0.5

    def test_not_ready_with_pending_generation(self):
        spec = make_spec("cmaes")
        s = make_suggester(spec)
        exp = new_exp(spec)
        proposals = s.get_suggestions(exp, 50)
        # leave them running (non-terminal)
        for p in proposals:
            t = complete_trial(exp, p, 0.0, condition=TrialCondition.RUNNING)
            t.observation = None
        with pytest.raises(SuggestionsNotReady):
            s.get_suggestions(exp, 50)

    def test_failed_member_retried_same_point(self):
        spec = make_spec("cmaes")
        s = make_suggester(spec)
        exp = new_exp(spec)
        proposals = s.get_suggestions(exp, 50)
        failed = proposals[0]
        complete_trial(exp, failed, 0.0, condition=TrialCondition.FAILED)
        for p in proposals[1:]:
            complete_trial(exp, p, sphere(p.as_dict()))
        retry = s.get_suggestions(exp, 50)
        assert len(retry) == 1
        assert retry[0].labels == failed.labels
        assert retry[0].as_dict() == pytest.approx(failed.as_dict())

    def test_rejects_categorical(self):
        with pytest.raises(SuggesterError):
            make_suggester(
                make_spec(
                    "cmaes",
                    parameters=[
                        ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min=0, max=1)),
                        ParameterSpec("c", ParameterType.CATEGORICAL, FeasibleSpace(list=("a",))),
                    ],
                )
            )


class TestAsha:
    def _spec(self, r_max=9.0, eta=3, **kw):
        return make_spec(
            "asha",
            settings={"r_max": str(r_max), "eta": str(eta),
                      "resource_name": "epochs"},
            parameters=[
                ParameterSpec("lr", ParameterType.DOUBLE,
                              FeasibleSpace(min=0.001, max=0.1)),
                ParameterSpec("epochs", ParameterType.INT,
                              FeasibleSpace(min=1, max=9)),
            ],
            objective_type=ObjectiveType.MAXIMIZE,
            **kw,
        )

    def test_validation(self):
        with pytest.raises(SuggesterError, match="r_max"):
            make_suggester(make_spec("asha", settings={"resource_name": "x"}))
        with pytest.raises(SuggesterError, match="resource_name"):
            make_suggester(make_spec(
                "asha", settings={"r_max": "9", "resource_name": "ghost"}))

    def test_devices_per_rung_accepts_parse_bool_spellings(self):
        """The setting goes through the shared parse_bool, so 'on' (truthy
        on every other boolean surface) enables leasing here too instead of
        being silently ignored by an ad-hoc whitelist."""
        from katib_tpu.core.types import DEVICES_LABEL

        spec = self._spec()
        spec.algorithm.settings["devices_per_rung"] = "on"
        s = make_suggester(spec)
        exp = Experiment(spec=spec)
        batch = s.get_suggestions(exp, 3)
        assert all(p.labels.get(DEVICES_LABEL) == "1" for p in batch)

    def test_resource_bounds_must_fit_feasible_range(self):
        """cast() rounds but does not clamp — r_max beyond the declared
        range would assign trials outside the search space, so submission
        must reject it (same for an r_min below the range floor)."""
        with pytest.raises(SuggesterError, match="feasible range"):
            make_suggester(self._spec(r_max=50))  # epochs feasible is [1, 9]
        floor = make_spec(
            "asha",
            settings={"r_max": "9", "r_min": "1", "resource_name": "epochs"},
            parameters=[
                ParameterSpec("epochs", ParameterType.INT,
                              FeasibleSpace(min=2, max=9)),
            ],
        )
        with pytest.raises(SuggesterError, match="feasible range"):
            make_suggester(floor)

    def test_async_never_blocks_and_promotes_top(self):
        spec = self._spec(r_max=9.0, eta=3)  # rungs 0,1,2 at r=1,3,9
        s = make_suggester(spec)
        exp = new_exp(spec)

        # cold start: all fresh configs at rung 0, resource 1
        batch = s.get_suggestions(exp, 3)
        assert len(batch) == 3
        assert all(p.labels["asha-rung"] == "0" for p in batch)
        assert all(p.as_dict()["epochs"] == 1 for p in batch)
        trials = [complete_trial(exp, p, p.as_dict()["lr"]) for p in batch]

        # 3 completed at rung 0 -> floor(3/3)=1 promotable (the best lr);
        # next ask promotes it to rung 1 (r=3) and fills with fresh configs
        batch2 = s.get_suggestions(exp, 2)
        promoted = [p for p in batch2 if p.labels.get("asha-parent")]
        assert len(promoted) == 1
        best = max(trials, key=lambda t: float(t.spec.assignments[0].value))
        assert promoted[0].labels["asha-parent"] == best.name
        assert promoted[0].labels["asha-rung"] == "1"
        assert promoted[0].as_dict()["epochs"] == 3
        # the same parent is never promoted twice (in-batch or later)
        complete_trial(exp, promoted[0], 0.5)
        again = s.get_suggestions(exp, 4)
        assert not any(
            p.labels.get("asha-parent") == best.name for p in again
        )

    def test_promotion_reaches_top_rung_and_in_batch_dedup(self):
        spec = self._spec(r_max=9.0, eta=2)  # rungs 0..3 at r = 1,2,4,9
        s = make_suggester(spec)
        exp = new_exp(spec)
        for p in s.get_suggestions(exp, 4):
            complete_trial(exp, p, p.as_dict()["lr"])
        # floor(4/2)=2 promotable; one batch must promote both distinct
        # parents, not the same one twice
        batch = s.get_suggestions(exp, 2)
        parents = [p.labels.get("asha-parent") for p in batch]
        assert all(parents) and len(set(parents)) == 2
        assert all(p.as_dict()["epochs"] == 2 for p in batch)
        for p in batch:
            complete_trial(exp, p, p.as_dict()["lr"])
        # floor(2/2)=1 from rung 1 -> rung 2 (r=4)
        mid = [p for p in s.get_suggestions(exp, 1)
               if p.labels.get("asha-rung") == "2"]
        assert len(mid) == 1 and mid[0].as_dict()["epochs"] == 4
        complete_trial(exp, mid[0], 1.0)
        # rung 2 has 1 completed: floor(1/2)=0 promotable — the top rung
        # needs another member first; asks keep yielding fresh rung-0 work
        nxt = s.get_suggestions(exp, 1)
        assert nxt[0].labels["asha-rung"] == "0"
        complete_trial(exp, nxt[0], 2.0)
        # second rung-0 completion doesn't change rung 2; promote the new
        # strong config up: rung0 has 5 done, floor(5/2)=2 top -> one
        # unclaimed parent promotes
        batch2 = s.get_suggestions(exp, 1)
        assert batch2[0].labels.get("asha-parent")
        # the TOP rung, when reached, runs at full fidelity r_max=9 even
        # though 1*2^3 = 8 undershoots it
        assert s._resource(3) == 9

    def test_restart_safe_from_labels_alone(self):
        spec = self._spec(r_max=9.0, eta=3)
        s = make_suggester(spec)
        exp = new_exp(spec)
        for p in s.get_suggestions(exp, 3):
            complete_trial(exp, p, p.as_dict()["lr"])
        expected = s.get_suggestions(exp, 2)
        # a brand-new suggester (process restart) proposes identically:
        # all state is in the trial labels + the deterministic rng stream
        s2 = make_suggester(spec)
        got = s2.get_suggestions(exp, 2)
        assert [p.as_dict() for p in got] == [p.as_dict() for p in expected]
        assert [p.labels for p in got] == [p.labels for p in expected]

    def test_tpe_sampler_bohb_style(self):
        """sampler: tpe — fresh rung-0 configs come from a TPE fitted on
        completed trials (BOHB); promotions and restart determinism are
        unchanged."""
        spec = make_spec(
            "asha",
            settings={"r_max": "9", "eta": "3", "resource_name": "epochs",
                      "sampler": "tpe", "n_startup_trials": "3"},
            parameters=[
                ParameterSpec("lr", ParameterType.DOUBLE,
                              FeasibleSpace(min=0.001, max=0.1)),
                ParameterSpec("epochs", ParameterType.INT,
                              FeasibleSpace(min=1, max=9)),
            ],
            objective_type=ObjectiveType.MAXIMIZE,
        )
        s = make_suggester(spec)
        exp = new_exp(spec)
        for p in s.get_suggestions(exp, 3):
            assert p.labels["asha-rung"] == "0"
            assert p.as_dict()["epochs"] == 1  # rung resource still applies
            # interior optimum: a boundary optimum would make TPE clamp
            # every model-phase draw to the same bound value, which is
            # legitimate TPE behavior but defeats the distinctness check
            complete_trial(exp, p, -((p.as_dict()["lr"] - 0.05) ** 2))
        batch = s.get_suggestions(exp, 3)
        # one promotion (floor(3/3)) + model-based fresh configs
        assert sum(1 for p in batch if p.labels.get("asha-parent")) == 1
        # fresh configs within one batch must be DISTINCT (one delegate
        # call diversifies; per-slot calls would duplicate the same draw)
        fresh_lrs = [p.as_dict()["lr"] for p in batch
                     if not p.labels.get("asha-parent")]
        assert len(fresh_lrs) == len(set(fresh_lrs)) == 2
        # the resource value is a rung artifact, never a modeled dim
        assert all(p.as_dict()["epochs"] == 1 for p in batch
                   if not p.labels.get("asha-parent"))
        # restart determinism: a fresh suggester proposes identically
        s2 = make_suggester(spec)
        again = s2.get_suggestions(exp, 3)
        assert [p.as_dict() for p in again] == [p.as_dict() for p in batch]
        # bad sampler rejected at submission
        with pytest.raises(SuggesterError, match="sampler"):
            make_suggester(make_spec(
                "asha",
                settings={"r_max": "9", "resource_name": "epochs",
                          "sampler": "cmaes"},
                parameters=spec.parameters,
            ))

    def test_failed_trials_never_promote_or_deadlock(self):
        spec = self._spec(r_max=9.0, eta=3)
        s = make_suggester(spec)
        exp = new_exp(spec)
        for p in s.get_suggestions(exp, 3):
            complete_trial(exp, p, 0.0, condition=TrialCondition.FAILED)
        # nothing promotable; asks still yield fresh work immediately
        batch = s.get_suggestions(exp, 2)
        assert len(batch) == 2
        assert all(p.labels["asha-rung"] == "0" for p in batch)
        assert not any(p.labels.get("asha-parent") for p in batch)


class TestHyperband:
    def _spec(self, r_l=9.0, eta=3):
        return make_spec(
            "hyperband",
            settings={"r_l": str(r_l), "eta": str(eta), "resource_name": "epochs"},
            parameters=[
                ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.001, max=0.1)),
                ParameterSpec("epochs", ParameterType.INT, FeasibleSpace(min=1, max=9)),
            ],
            parallel_trial_count=9,
            objective_type=ObjectiveType.MAXIMIZE,
        )

    def test_validation(self):
        bad = self._spec()
        object.__setattr__(bad, "parallel_trial_count", 2)
        with pytest.raises(SuggesterError, match="parallel_trial_count"):
            make_suggester(bad)
        with pytest.raises(SuggesterError, match="r_l"):
            make_suggester(make_spec("hyperband", settings={"resource_name": "x"}))

    def test_rung_resources_must_fit_feasible_range(self):
        """r_l above the resource parameter's declared max would emit
        assignments outside the search space (cast does not clamp)."""
        spec = make_spec(
            "hyperband",
            settings={"r_l": "27", "eta": "3", "resource_name": "epochs"},
            parameters=[
                ParameterSpec("epochs", ParameterType.INT,
                              FeasibleSpace(min=1, max=9)),
            ],
            parallel_trial_count=27,
        )
        with pytest.raises(SuggesterError, match="feasible range"):
            make_suggester(spec)

    def test_bracket_progression(self):
        spec = self._spec(r_l=9.0, eta=3)  # s_max=2: brackets s=2,1,0
        s = make_suggester(spec)
        exp = new_exp(spec)
        # bracket s=2 rung 0: n0 = ceil(3*9/3) = 9 trials at resource 1
        rung0 = s.get_suggestions(exp, 20)
        assert len(rung0) == 9
        assert all(p.as_dict()["epochs"] == 1 for p in rung0)
        assert all(p.labels["hyperband-s"] == "2" for p in rung0)
        # quality = lr (maximize): higher lr wins
        for p in rung0:
            complete_trial(exp, p, p.as_dict()["lr"])
        # rung 1: top ceil(9/3)=3 promoted at resource 3
        rung1 = s.get_suggestions(exp, 20)
        assert len(rung1) == 3
        assert all(p.as_dict()["epochs"] == 3 for p in rung1)
        top_lrs = sorted(p.as_dict()["lr"] for p in rung1)
        all_lrs = sorted((p.as_dict()["lr"] for p in rung0), reverse=True)[:3]
        assert top_lrs == sorted(all_lrs)
        for p in rung1:
            complete_trial(exp, p, p.as_dict()["lr"])
        # rung 2: top 1 at resource 9
        rung2 = s.get_suggestions(exp, 20)
        assert len(rung2) == 1
        assert rung2[0].as_dict()["epochs"] == 9
        for p in rung2:
            complete_trial(exp, p, p.as_dict()["lr"])
        # bracket s=1: n0 = ceil(3*3/2) = 5 at resource 3
        b1 = s.get_suggestions(exp, 20)
        assert len(b1) == 5
        assert all(p.as_dict()["epochs"] == 3 for p in b1)

    def test_not_ready_while_rung_running(self):
        spec = self._spec()
        s = make_suggester(spec)
        exp = new_exp(spec)
        rung0 = s.get_suggestions(exp, 20)
        for p in rung0[:-1]:
            complete_trial(exp, p, 1.0)
        t = complete_trial(exp, rung0[-1], 0.0, condition=TrialCondition.RUNNING)
        t.observation = None
        with pytest.raises(SuggestionsNotReady):
            s.get_suggestions(exp, 20)

    def test_runs_to_exhaustion(self):
        spec = self._spec()
        s = make_suggester(spec)
        exp = run_loop(s, new_exp(spec), lambda p: p["lr"], rounds=100, batch=20)
        with pytest.raises(SearchExhausted):
            s.get_suggestions(exp, 20)
        # total trials = sum of all rungs over brackets
        assert len(exp.trials) == s.total_budget()

    def test_state_survives_new_instance(self):
        spec = self._spec()
        s = make_suggester(spec)
        exp = new_exp(spec)
        rung0 = s.get_suggestions(exp, 20)
        for p in rung0:
            complete_trial(exp, p, p.as_dict()["lr"])
        s.get_suggestions(exp, 20)  # advances persisted state
        fresh = make_suggester(spec)
        rung1_again = fresh.get_suggestions(exp, 20)
        assert all(p.labels["hyperband-i"] == "1" for p in rung1_again)


class TestPbt(object):
    def _spec(self, tmp_path):
        return make_spec(
            "pbt",
            settings={
                "n_population": "8",
                "truncation_threshold": "0.25",
                "suggestion_trial_dir": str(tmp_path),
            },
            objective_type=ObjectiveType.MAXIMIZE,
        )

    def test_validation(self):
        with pytest.raises(SuggesterError, match="n_population"):
            make_suggester(make_spec("pbt", settings={"truncation_threshold": "0.2"}))
        with pytest.raises(SuggesterError, match=">= 5"):
            make_suggester(
                make_spec("pbt", settings={"n_population": "2", "truncation_threshold": "0.2"})
            )

    def test_population_lifecycle(self, tmp_path):
        import os

        spec = self._spec(tmp_path)
        s = make_suggester(spec)
        exp = new_exp(spec)
        gen0 = s.get_suggestions(exp, 8)
        assert all(p.labels["pbt-generation"] == "0" for p in gen0)
        assert all(os.path.isdir(s.checkpoint_dir_for(p.name)) for p in gen0)
        # score = x (maximize)
        for p in gen0:
            # leave a checkpoint marker behind to verify lineage copy
            with open(os.path.join(s.checkpoint_dir_for(p.name), "ckpt.txt"), "w") as f:
                f.write(p.name)
            complete_trial(exp, p, p.as_dict()["x"])
        gen1 = s.get_suggestions(exp, 8)
        assert all(p.labels["pbt-generation"] == "1" for p in gen1)
        assert all("pbt-parent" in p.labels for p in gen1)
        # lineage: children inherit parent checkpoints
        for p in gen1:
            marker = os.path.join(s.checkpoint_dir_for(p.name), "ckpt.txt")
            assert os.path.exists(marker)
            with open(marker) as f:
                assert f.read() == p.labels["pbt-parent"]

    def test_exploit_clones_winner_params(self, tmp_path):
        spec = self._spec(tmp_path)
        s = make_suggester(spec)
        exp = new_exp(spec)
        gen0 = s.get_suggestions(exp, 8)
        scores = {p.name: p.as_dict()["x"] for p in gen0}
        for p in gen0:
            complete_trial(exp, p, scores[p.name])
        ranked = sorted(scores.items(), key=lambda kv: kv[1])
        losers = {ranked[0][0], ranked[1][0]}
        winners = {ranked[-1][0], ranked[-2][0]}
        gen1 = s.get_suggestions(exp, 8)
        exploit_children = [p for p in gen1 if p.labels["pbt-parent"] in winners]
        # someone exploited a winner: params equal to a winner's params
        assert exploit_children, "expected at least one exploit child of a top member"

    def test_small_count_still_exploits(self, tmp_path):
        """Regression: ``n_exploit = int(count * truncation)`` floored to 0
        whenever count < 1/truncation — a small population / partial refill
        silently degenerated into random search (no member ever cloned a
        winner).  Rounds half-up with a floor of 1 when anyone is below
        the quantile."""
        spec = self._spec(tmp_path)
        s = make_suggester(spec)
        # pool of 8 scored members, segment a partial refill of count=3:
        # old code: int(3 * 0.25) = 0 exploiters, forever
        exp = new_exp(spec)
        gen0 = s.get_suggestions(exp, 8)
        for i, p in enumerate(gen0):
            complete_trial(exp, p, float(i))
        s._sync(exp)
        exploit, explore, upper = s._segment(s.pool_current, 3)
        assert len(exploit) == 1
        assert exploit[0].score < min(j.score for j in upper)
        # round half-up: count=6 at 0.25 -> 1.5 -> 2 exploiters (old: 1)
        exploit6, _, _ = s._segment(s.pool_current, 6)
        assert len(exploit6) == 2

    def test_failed_members_requeued(self, tmp_path):
        spec = self._spec(tmp_path)
        s = make_suggester(spec)
        exp = new_exp(spec)
        gen0 = s.get_suggestions(exp, 8)
        dead = gen0[0]
        complete_trial(exp, dead, 0.0, condition=TrialCondition.FAILED)
        for p in gen0[1:]:
            complete_trial(exp, p, p.as_dict()["x"])
        nxt = s.get_suggestions(exp, 1)[0]
        assert nxt.as_dict() == pytest.approx(dead.as_dict())
        assert nxt.labels["pbt-generation"] == "0"


class TestReviewRegressions:
    """Regression tests for defects found in review."""

    def test_bayesopt_count_exceeding_startup_budget(self):
        spec = make_spec("bayesianoptimization", settings={"n_initial_points": "2"})
        s = make_suggester(spec)
        exp = new_exp(spec)
        out = s.get_suggestions(exp, 5)  # previously crashed on np.stack([])
        assert len(out) == 5

    def test_hyperband_smax_exact_power(self):
        from katib_tpu.suggest.hyperband import _s_max

        assert _s_max(1000.0, 10) == 3
        assert _s_max(243.0, 3) == 5
        assert _s_max(27.0, 3) == 3

    def test_hyperband_eta_validation_strict(self):
        base = dict(
            parameters=[
                ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.001, max=0.1)),
                ParameterSpec("epochs", ParameterType.INT, FeasibleSpace(min=1, max=9)),
            ],
            parallel_trial_count=100,
        )
        for bad_eta in ("0", "0.5", "1", "abc"):
            with pytest.raises(SuggesterError, match="eta"):
                make_suggester(
                    make_spec(
                        "hyperband",
                        settings={"r_l": "9", "resource_name": "epochs", "eta": bad_eta},
                        **base,
                    )
                )

    def test_hyperband_survivor_shortfall_no_deadlock(self):
        spec = make_spec(
            "hyperband",
            settings={"r_l": "9", "eta": "3", "resource_name": "epochs"},
            parameters=[
                ParameterSpec("lr", ParameterType.DOUBLE, FeasibleSpace(min=0.001, max=0.1)),
                ParameterSpec("epochs", ParameterType.INT, FeasibleSpace(min=1, max=9)),
            ],
            parallel_trial_count=9,
            objective_type=ObjectiveType.MAXIMIZE,
        )
        s = make_suggester(spec)
        exp = new_exp(spec)
        rung0 = s.get_suggestions(exp, 20)
        # 8 of 9 fail; only 1 survivor for a rung that nominally needs 3
        complete_trial(exp, rung0[0], 0.9)
        for p in rung0[1:]:
            complete_trial(exp, p, 0.0, condition=TrialCondition.FAILED)
        rung1 = s.get_suggestions(exp, 20)
        assert len(rung1) == 1  # shrunk to survivor count, not empty-forever
        complete_trial(exp, rung1[0], 0.9)
        nxt = s.get_suggestions(exp, 20)  # advances to next rung/bracket
        assert nxt, "must keep making progress after shrunken rung"

    def test_cmaes_restart_labels_monotonic(self):
        spec = make_spec("cmaes", settings={"restart_strategy": "ipop", "random_state": "5"})
        s = make_suggester(spec)
        exp = new_exp(spec)
        # constant objective => permanent stagnation => restart fires
        for _ in range(16):
            try:
                props = s.get_suggestions(exp, 50)
            except SuggestionsNotReady:
                pytest.fail("livelock: all trials terminal but not ready")
            assert props, "must keep proposing after restart"
            for p in props:
                complete_trial(exp, p, 1.0)
        gens = sorted({int(t.labels["cmaes-generation"]) for t in exp.trials.values()})
        assert gens == list(range(len(gens)))  # no label reuse/collisions

    def test_cmaes_missing_objective_metric_skipped(self):
        from katib_tpu.core.types import Metric, Observation

        spec = make_spec("cmaes")
        s = make_suggester(spec)
        exp = new_exp(spec)
        props = s.get_suggestions(exp, 50)
        for i, p in enumerate(props):
            t = complete_trial(exp, p, 1.0)
            if i == 0:  # observation lacks the objective metric entirely
                t.observation = Observation(metrics=[Metric(name="other", value=1.0)])
        # must not crash; the bad trial is treated as not-yet-complete
        s.get_suggestions(exp, 50)
