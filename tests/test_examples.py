"""The examples/ tree (reference ``examples/v1beta1`` analog) and
reference-CR round-tripping: an unmodified upstream Katib YAML must load,
with the primary container's argv extracted from the nested K8s Job and its
``${trialParameters.X}`` placeholders rewritten to the referenced
experiment parameters (``manifest/generator.go:79-126`` semantics)."""

from __future__ import annotations

import glob
import os

import pytest

from katib_tpu.core.validation import validate_experiment
from katib_tpu.orchestrator import Orchestrator
from katib_tpu.sdk.yaml_spec import experiment_spec_from_dict, load_experiment_yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# examples/sim/ holds simulator scenarios, not experiment specs; they are
# loaded/validated by tests/test_sim.py instead.
EXAMPLES = sorted(
    p
    for p in glob.glob(os.path.join(REPO, "examples", "**", "*.yaml"), recursive=True)
    if os.path.basename(os.path.dirname(p)) != "sim"
)
REFERENCE_EXAMPLES = "/root/reference/examples/v1beta1"


class TestShippedExamples:
    @pytest.mark.parametrize("path", EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
    def test_loads_and_validates(self, path):
        spec = load_experiment_yaml(path)
        validate_experiment(spec)
        assert spec.train_fn is not None or spec.command, path

    def test_random_example_runs_e2e(self, tmp_path):
        spec = load_experiment_yaml(
            os.path.join(REPO, "examples", "hp-tuning", "random.yaml")
        )
        exp = Orchestrator(workdir=str(tmp_path)).run(spec)
        # goal 0.001 may or may not be met; both terminal-success shapes ok
        assert exp.condition.value in ("Succeeded", "MaxTrialsReached", "GoalReached")
        assert exp.optimal is not None
        assert exp.succeeded_count >= 1

    @pytest.mark.slow
    def test_grid_example_covers_lattice(self, tmp_path):
        spec = load_experiment_yaml(
            os.path.join(REPO, "examples", "hp-tuning", "grid.yaml")
        )
        exp = Orchestrator(workdir=str(tmp_path)).run(spec)
        assert exp.succeeded_count == 12  # 4 lr x 3 num_layers


@pytest.mark.skipif(
    not os.path.isdir(REFERENCE_EXAMPLES), reason="reference tree not mounted"
)
class TestReferenceCrRoundTrip:
    def test_nested_trial_spec_command_extraction(self):
        spec = load_experiment_yaml(
            os.path.join(REFERENCE_EXAMPLES, "hp-tuning", "random.yaml")
        )
        validate_experiment(spec)
        assert spec.command is not None
        joined = " ".join(spec.command)
        # trialParameter names (learningRate/momentum) rewritten to the
        # experiment parameters they reference (lr/momentum)
        assert "${trialParameters.lr}" in joined
        assert "${trialParameters.momentum}" in joined
        assert "${trialParameters.learningRate}" not in joined
        assert spec.max_trial_count == 12
        assert {p.name for p in spec.parameters} == {"lr", "momentum"}

    def test_every_reference_hp_example_loads(self):
        for path in sorted(
            glob.glob(os.path.join(REFERENCE_EXAMPLES, "hp-tuning", "*.yaml"))
        ):
            spec = load_experiment_yaml(path)
            assert spec.parameters, path
            assert spec.command, path

class TestTrialSpecExtractionEdgeCases:
    def _template(self, trial_spec, params=(), primary=None):
        t = {"trialSpec": trial_spec, "trialParameters": list(params)}
        if primary:
            t["primaryContainerName"] = primary
        return t

    def test_primary_container_in_later_replica(self):
        """A multi-replica job's primary container may live in any pod
        template; the first containers-list must not win by position."""
        from katib_tpu.sdk.yaml_spec import _command_from_trial_spec

        trial_spec = {
            "spec": {
                "pytorchReplicaSpecs": {
                    "Master": {"template": {"spec": {"containers": [
                        {"name": "init-sidecar", "command": ["sleep", "1"]}
                    ]}}},
                    "Worker": {"template": {"spec": {"containers": [
                        {"name": "pytorch",
                         "command": ["python", "train.py",
                                     "--lr=${trialParameters.learningRate}"]}
                    ]}}},
                }
            }
        }
        cmd = _command_from_trial_spec(self._template(
            trial_spec,
            params=[{"name": "learningRate", "reference": "lr"}],
            primary="pytorch",
        ))
        assert cmd == ["python", "train.py", "--lr=${trialParameters.lr}"]

    def test_renames_do_not_chain(self):
        """Simultaneous substitution: a rewritten placeholder must not be
        rewritten again when its target is also a trialParameter name."""
        from katib_tpu.sdk.yaml_spec import _command_from_trial_spec

        trial_spec = {"spec": {"containers": [{
            "name": "c",
            "command": ["--lr", "${trialParameters.learningRate}",
                        "--wd", "${trialParameters.weightDecay}"],
        }]}}
        cmd = _command_from_trial_spec(self._template(
            trial_spec,
            params=[
                {"name": "learningRate", "reference": "weightDecay"},
                {"name": "weightDecay", "reference": "wd"},
            ],
        ))
        assert cmd == ["--lr", "${trialParameters.weightDecay}",
                       "--wd", "${trialParameters.wd}"]
