"""Span tracing: journal semantics, restart resume, Chrome-trace export, and
the orchestrator producing matching spans for every trial of a CPU run."""

import json
import os

from katib_tpu.core.types import (
    AlgorithmSpec,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
)
from katib_tpu.utils import tracing


class TestTracer:
    def test_span_records_jsonl(self, tmp_path):
        path = tracing.trace_path(str(tmp_path), "exp")
        tracer = tracing.Tracer(path, experiment="exp")
        with tracer.span("work", trial="t1") as sp:
            sp.set(condition="Succeeded")
        tracer.close()
        (rec,) = tracing.read_journal(path)
        assert rec["name"] == "work"
        assert rec["dur"] >= 0
        assert rec["args"] == {
            "trial": "t1",
            "condition": "Succeeded",
            "experiment": "exp",
        }

    def test_span_tags_error_and_reraises(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        tracer = tracing.Tracer(path)
        try:
            with tracer.span("boom"):
                raise ValueError("x")
        except ValueError:
            pass
        tracer.close()
        (rec,) = tracing.read_journal(path)
        assert rec["args"]["error"] == "ValueError"

    def test_resume_continues_elapsed_base(self, tmp_path):
        """A reopened journal appends with ts past the prior max(ts+dur) —
        the restart-safe monotonic base (darts elapsed_s pattern)."""
        path = str(tmp_path / "t.jsonl")
        t1 = tracing.Tracer(path)
        t1.record("first", 0.0, 5.0)
        t1.close()
        t2 = tracing.Tracer(path)
        with t2.span("second"):
            pass
        t2.close()
        first, second = tracing.read_journal(path)
        assert second["ts"] >= first["ts"] + first["dur"] - 1e-6

    def test_corrupt_lines_skipped(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        with open(path, "w") as f:
            f.write('{"name": "ok", "ts": 0.0, "dur": 1.0}\n')
            f.write("{torn half-wri\n")
            f.write("null\n")
        assert [r["name"] for r in tracing.read_journal(path)] == ["ok"]
        t = tracing.Tracer(path)  # resume over the corrupt tail must not raise
        t.close()

    def test_ambient_tracer_noop_without_activation(self, tmp_path):
        # must not raise, and sp.set must be absorbed
        with tracing.span("orphan") as sp:
            sp.set(x=1)
        tracing.record_span("orphan", 0.1)
        path = str(tmp_path / "t.jsonl")
        tracer = tracing.Tracer(path)
        with tracing.use_tracer(tracer):
            assert tracing.current_tracer() is tracer
            with tracing.span("seen"):
                pass
            tracing.record_span("timed", 0.25, tag="x")
        assert tracing.current_tracer() is None
        tracer.close()
        recs = tracing.read_journal(path)
        assert [r["name"] for r in recs] == ["seen", "timed"]
        assert abs(recs[1]["dur"] - 0.25) < 1e-6


class TestChromeTraceExport:
    def test_export_validity(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        tracer = tracing.Tracer(path, experiment="e")
        with tracer.span("a", trial="t1"):
            pass
        tracer.record("b", 1.0, 2.5, step=3)
        tracer.close()
        out = str(tmp_path / "trace.json")
        assert tracing.export_chrome_trace(path, out) == 2
        doc = json.loads(open(out).read())
        assert doc["displayTimeUnit"] == "ms"
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(events) == 2
        for e in events:
            assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid", "args"}
            assert e["ts"] >= 0 and e["dur"] >= 0
        b = next(e for e in events if e["name"] == "b")
        assert b["ts"] == 1.0e6 and b["dur"] == 2.5e6
        # metadata rows label the emitting process
        assert any(e["ph"] == "M" for e in doc["traceEvents"])

    def test_export_empty_journal(self, tmp_path):
        out = str(tmp_path / "trace.json")
        assert tracing.export_chrome_trace(str(tmp_path / "missing.jsonl"), out) == 0
        assert not os.path.exists(out)

    def test_summarize(self):
        recs = [
            {"name": "a", "ts": 0, "dur": 1.0},
            {"name": "a", "ts": 1, "dur": 3.0},
            {"name": "b", "ts": 2, "dur": 0.5},
        ]
        summary = tracing.summarize(recs)
        assert [s["name"] for s in summary] == ["a", "b"]  # by total desc
        a = summary[0]
        assert a["count"] == 2 and a["total_s"] == 4.0 and a["mean_s"] == 2.0
        assert a["max_s"] == 3.0


def _spec(name: str, n_trials: int = 3) -> ExperimentSpec:
    def train_fn(ctx):
        ctx.report(accuracy=float(ctx.params["x"]))

    return ExperimentSpec(
        name=name,
        algorithm=AlgorithmSpec(name="random"),
        objective=ObjectiveSpec(
            type=ObjectiveType.MAXIMIZE, objective_metric_name="accuracy"
        ),
        parameters=[
            ParameterSpec("x", ParameterType.DOUBLE, FeasibleSpace(min="0", max="1"))
        ],
        max_trial_count=n_trials,
        parallel_trial_count=2,
        train_fn=train_fn,
    )


class TestOrchestratorTracing:
    def test_every_trial_has_a_span(self, tmp_path):
        from katib_tpu.orchestrator.orchestrator import Orchestrator
        from katib_tpu.utils import observability as obs

        orch = Orchestrator(workdir=str(tmp_path))
        exp = orch.run(_spec("trace-e2e"))
        assert exp.condition.is_terminal()

        journal = tracing.trace_path(str(tmp_path), "trace-e2e")
        recs = tracing.read_journal(journal)
        trial_spans = {
            r["args"]["trial"]: r for r in recs if r["name"] == "trial"
        }
        # one complete (start+end → single "X" record) span per trial
        assert set(trial_spans) == set(exp.trials)
        for name, rec in trial_spans.items():
            assert rec["dur"] >= 0 and rec["ts"] >= 0
            assert rec["args"]["condition"] == exp.trials[name].condition.value
            assert rec["args"]["experiment"] == "trace-e2e"
        # train_fn spans nest inside trial spans (whitebox path)
        assert sum(1 for r in recs if r["name"] == "train_fn") == len(exp.trials)
        # suggestion-service spans + the terminal experiment span
        assert any(r["name"] == "suggest" for r in recs)
        exp_spans = [r for r in recs if r["name"] == "experiment"]
        assert len(exp_spans) == 1
        assert exp_spans[0]["args"]["trials"] == len(exp.trials)
        # ambient tracer is cleaned up after the run
        assert tracing.current_tracer() is None

        # exported Chrome trace is valid and complete
        out = str(tmp_path / "trace.json")
        assert tracing.export_chrome_trace(journal, out) == len(recs)
        doc = json.loads(open(out).read())
        names = [e["name"] for e in doc["traceEvents"]]
        assert "experiment" in names and "trial" in names

        # duration histograms on the global registry (cross-test counts can
        # only grow, so assert >= via the rendered series)
        text = obs.REGISTRY.render()
        assert "katib_trial_duration_seconds_bucket" in text
        assert "katib_suggestion_latency_seconds_bucket" in text
        assert obs.trial_duration.get_count(condition="Succeeded") >= len(exp.trials)

    def test_journal_survives_resume(self, tmp_path):
        """A resumed experiment appends to the same journal with a monotonic
        elapsed base: a second experiment span lands after the first."""
        from katib_tpu.core.types import ResumePolicy
        from katib_tpu.orchestrator.orchestrator import Orchestrator

        spec = _spec("trace-resume", n_trials=2)
        spec.resume_policy = ResumePolicy.LONG_RUNNING
        orch = Orchestrator(workdir=str(tmp_path))
        orch.run(spec)

        spec2 = _spec("trace-resume", n_trials=4)
        spec2.resume_policy = ResumePolicy.LONG_RUNNING
        orch2 = Orchestrator(workdir=str(tmp_path))
        exp2 = orch2.run(spec2, resume=True)
        assert len(exp2.trials) == 4

        recs = tracing.read_journal(tracing.trace_path(str(tmp_path), "trace-resume"))
        exp_spans = [r for r in recs if r["name"] == "experiment"]
        assert len(exp_spans) == 2
        # second run's span starts at or after the first run's span end
        assert (
            exp_spans[1]["ts"]
            >= exp_spans[0]["ts"] + exp_spans[0]["dur"] - 1e-6
        )
        assert len([r for r in recs if r["name"] == "trial"]) == 4
