"""KatibConfig: loading, defaulting, env overrides, runtime merging —
parity coverage for the reference's config loader + scheme defaulting
(``pkg/util/v1beta1/katibconfig/config_test.go``, ``defaults.go``)."""

from __future__ import annotations

import pytest

from katib_tpu.core.config import ConfigError, KatibConfig, StoreConfig
from katib_tpu.core.types import (
    EarlyStoppingSpec,
    ExperimentCondition,
    MetricsCollectorKind,
    MetricsCollectorSpec,
)
from katib_tpu.store.base import MemoryObservationStore
from katib_tpu.store.sqlite import SqliteObservationStore

from helpers import make_spec


YAML = """
apiVersion: config.katib-tpu.dev/v1
init:
  workdir: /tmp/kt-test-runs
  parallel_trial_count: 5
runtime:
  algorithms:
    darts:
      settings: {num_epochs: "50", w_lr: "0.025"}
      mesh_axes: {data: 8}
    random: {}
  early_stopping:
    medianstop: {min_trials_required: "4"}
  metrics_collectors:
    StdOut:
      filter: "([\\\\w|-]+)=((?:[+-]?\\\\d+)(?:\\\\.\\\\d+)?)"
store:
  backend: sqlite
  path: /tmp/kt-test-obs.db
"""


class TestLoading:
    def test_defaults(self):
        cfg = KatibConfig.load(env={})
        assert cfg.init.workdir == "katib_runs"
        assert cfg.store.backend == "memory"
        assert cfg.runtime.algorithms == {}

    def test_yaml_roundtrip(self, tmp_path):
        p = tmp_path / "config.yaml"
        p.write_text(YAML)
        cfg = KatibConfig.load(str(p), env={})
        assert cfg.init.workdir == "/tmp/kt-test-runs"
        assert cfg.init.parallel_trial_count == 5
        assert cfg.runtime.algorithms["darts"].settings["num_epochs"] == "50"
        assert cfg.runtime.algorithms["darts"].mesh_axes == {"data": 8}
        assert cfg.store.backend == "sqlite"

    def test_unknown_key_rejected(self, tmp_path):
        p = tmp_path / "bad.yaml"
        p.write_text("init:\n  no_such_flag: 1\n")
        with pytest.raises(ConfigError, match="no_such_flag"):
            KatibConfig.load(str(p), env={})

    def test_bad_api_version(self):
        with pytest.raises(ConfigError, match="apiVersion"):
            KatibConfig.from_dict({"apiVersion": "config.kubeflow.org/v1beta1"})

    def test_bad_backend(self):
        with pytest.raises(ConfigError, match="backend"):
            KatibConfig.from_dict({"store": {"backend": "oracle"}})

    def test_env_overrides(self):
        cfg = KatibConfig.load(
            env={
                "KATIB_TPU_WORKDIR": "/tmp/elsewhere",
                "KATIB_TPU_STORE_BACKEND": "sqlite",
                "KATIB_TPU_DB_PORT": "7000",
            }
        )
        assert cfg.init.workdir == "/tmp/elsewhere"
        assert cfg.store.backend == "sqlite"
        assert cfg.store.port == 7000

    def test_env_override_bad_int(self):
        with pytest.raises(ConfigError, match="KATIB_TPU_DB_PORT"):
            KatibConfig.load(env={"KATIB_TPU_DB_PORT": "not-a-port"})

    def test_env_override_bad_backend(self):
        with pytest.raises(ConfigError, match="backend"):
            KatibConfig.load(env={"KATIB_TPU_STORE_BACKEND": "oracle"})


class TestStoreFactory:
    def test_memory(self):
        assert isinstance(StoreConfig(backend="memory").make_store(), MemoryObservationStore)

    def test_sqlite(self, tmp_path):
        store = StoreConfig(backend="sqlite", path=str(tmp_path / "o.db")).make_store()
        assert isinstance(store, SqliteObservationStore)
        store.close()

    def test_native_or_fallback(self):
        store = StoreConfig(backend="native").make_store()
        # native engine when the toolchain exists, memory fallback otherwise
        assert store.get("nothing") == []


class TestApplyTo:
    def _config(self):
        return KatibConfig.from_dict(
            {
                "runtime": {
                    "algorithms": {
                        "random": {"settings": {"seed": "7", "shared": "config"}}
                    },
                    "early_stopping": {"medianstop": {"min_trials_required": "4"}},
                    "metrics_collectors": {"StdOut": {"filter": "custom-regex"}},
                }
            }
        )

    def test_settings_merge_experiment_wins(self):
        spec = make_spec("random", settings={"shared": "experiment"})
        merged = self._config().apply_to(spec)
        assert merged.algorithm.settings["seed"] == "7"
        assert merged.algorithm.settings["shared"] == "experiment"
        # original untouched
        assert "seed" not in spec.algorithm.settings

    def test_early_stopping_merge(self):
        spec = make_spec("random")
        spec.early_stopping = EarlyStoppingSpec(name="medianstop", settings={})
        merged = self._config().apply_to(spec)
        assert merged.early_stopping.settings["min_trials_required"] == "4"

    def test_collector_defaults_fill_unset(self):
        spec = make_spec("random")
        spec.metrics_collector = MetricsCollectorSpec(kind=MetricsCollectorKind.STDOUT)
        merged = self._config().apply_to(spec)
        assert merged.metrics_collector.filter == "custom-regex"
        spec.metrics_collector = MetricsCollectorSpec(
            kind=MetricsCollectorKind.STDOUT, filter="mine"
        )
        assert self._config().apply_to(spec).metrics_collector.filter == "mine"

    def test_mesh_axes_for(self):
        cfg = KatibConfig.from_dict(
            {
                "init": {"mesh_axes": {"data": 2}},
                "runtime": {"algorithms": {"darts": {"mesh_axes": {"data": 8}}}},
            }
        )
        assert cfg.mesh_axes_for("darts") == {"data": 8}
        assert cfg.mesh_axes_for("random") == {"data": 2}


class TestOrchestratorWiring:
    def test_config_driven_run(self, tmp_path):
        cfg = KatibConfig.from_dict(
            {
                "init": {"workdir": str(tmp_path), "poll_interval": 0.01},
                "store": {"backend": "memory"},
            }
        )
        orch = cfg.make_orchestrator()
        assert orch.workdir == str(tmp_path)

        def train(ctx):
            ctx.report(loss=(ctx.params["x"] - 1.0) ** 2)

        spec = make_spec("random", train_fn=train, max_trial_count=3,
                         parallel_trial_count=1)
        exp = orch.run(spec)
        assert exp.condition is ExperimentCondition.MAX_TRIALS_REACHED
        assert len(exp.trials) == 3


class TestReviewRegressions:
    def test_suggester_crash_balances_gauge_and_fails_status(self, tmp_path):
        """A persistently crashing suggester must wind down cleanly: the
        circuit breaker absorbs ``suggester_max_errors - 1`` exceptions,
        then the experiment fails (no raise) with the bug's traceback in
        its message, the gauge balanced, and the journal showing Failed."""
        from katib_tpu.core.types import ExperimentCondition
        from katib_tpu.orchestrator.orchestrator import Orchestrator
        from katib_tpu.orchestrator.status import read_status
        from katib_tpu.suggest import base as suggest_base
        from katib_tpu.utils import observability as obs

        class Boom(Exception):
            pass

        class BoomSuggester:
            def get_suggestions(self, exp, n):
                raise Boom("bug")

        spec = make_spec("random", max_trial_count=4, suggester_max_errors=2)
        orig = suggest_base.make_suggester
        suggest_base.make_suggester = lambda s: BoomSuggester()
        # the orchestrator imports the symbol directly; patch there too
        import katib_tpu.orchestrator.orchestrator as orch_mod

        orch_orig = orch_mod.make_suggester
        orch_mod.make_suggester = lambda s: BoomSuggester()
        try:
            orch = Orchestrator(workdir=str(tmp_path))
            exp = orch.run(spec)
        finally:
            suggest_base.make_suggester = orig
            orch_mod.make_suggester = orch_orig
        assert exp.condition is ExperimentCondition.FAILED
        assert "Boom" in exp.message  # the bug's traceback surfaces
        assert obs.experiments_current.get() == 0
        status = read_status(str(tmp_path), spec.name)
        assert status["condition"] == "Failed"
        assert "suggester failed 2 consecutive times" in status["message"]

    def test_per_algorithm_mesh_resolution(self):
        from katib_tpu.orchestrator.orchestrator import Orchestrator
        from katib_tpu.parallel.mesh import DATA_AXIS

        cfg = KatibConfig.from_dict(
            {
                "init": {"mesh_axes": {"data": 2}},
                "runtime": {"algorithms": {"tpe": {"mesh_axes": {"data": 4}}}},
            }
        )
        orch = Orchestrator(config=cfg)
        mesh = orch._resolve_mesh(make_spec("tpe"))
        assert mesh.shape[DATA_AXIS] == 4
        mesh = orch._resolve_mesh(make_spec("random"))
        assert mesh.shape[DATA_AXIS] == 2
